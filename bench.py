"""Benchmark: SL + RL learner throughput on the real chip.

Prints JSON result lines ``{"metric", "value", "unit", "vs_baseline", ...}``;
the LAST line printed is always the freshest complete result, so a harness
that records the tail of stdout gets the best measurement even if the
process is killed mid-sweep.

Metrics
  * main:  supervised-learning replay-frames/sec/chip with the FULL flagship
    model (fwd+loss+bwd+adam). Reference headline: ~384 frames/s per A100
    (56xA100, total batch 336 x traj 64 at ~1 s/iter; BASELINE.md).
  * extra: RL learner steps/sec and frames/sec on the full RL train step
    (T+1 layout, 6 value heads, teacher-KL). Reference: 0.67 steps/s per
    32-GPU learner at batch 192 x traj 64 => ~256 frames/s per A100.

Environment lessons baked in (rounds 1-2 postmortems):
  * round 1: TPU backend init died => run the measurement in a child process,
    retry with backoff, ALWAYS print a parseable JSON line.
  * round 2: the sweep timed out with zero configs done and the timeout
    handler discarded the child's stderr, so the BENCH-STAGE breadcrumbs
    never reached the artifact. Root cause found in round 3: claiming the
    tunneled chip (`jax.devices()`) can block for many minutes when the
    shared relay is contended. Fixes:
      - the parent STREAMS child stdout/stderr (no capture-at-exit): result
        lines are re-printed the moment they appear, and the last BENCH-STAGE
        breadcrumb is always available for the diagnostic;
      - a tiny always-lands probe config runs before the baseline-regime
        config, so *some* frames/s number survives even if the big config
        cannot compile in budget;
      - the child heartbeats its current stage every 20 s so a stall is
        attributable (claim vs trace vs compile vs step);
      - measurement is AOT: trace once, flop-count + compile the SAME
        lowering (persistent-cache-aware), step the compiled executable —
        no duplicate trace for the MFU estimate.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

SL_BASELINE_FRAMES = 384.0   # frames/s per A100, reference large-scale SL
RL_BASELINE_STEPS = 0.67     # learner steps/s, reference large-scale RL
RL_BASELINE_FRAMES = 256.0   # frames/s per A100 (192*64/1.5s / 32 GPUs)

# shared smoke-dims flagship-shaped model config (distill + anakin cases):
# full architecture, tiny widths — CPU-compilable in seconds, flagged
# in-band wherever it appears so a smoke number is never quoted as real
SMOKE_MODEL_CFG = {
    "encoder": {
        "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16, "head_dim": 8},
        "spatial": {"down_channels": [4, 4, 8], "project_dim": 4,
                    "resblock_num": 1, "fc_dim": 16},
        "scatter": {"output_dim": 4},
        "core_lstm": {"hidden_size": 32, "num_layers": 1},
    },
    "policy": {
        "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
        "delay_head": {"decode_dim": 16},
        "queued_head": {"decode_dim": 16},
        "selected_units_head": {"func_dim": 16},
        "target_unit_head": {"func_dim": 16},
        "location_head": {"res_dim": 8, "res_num": 1,
                          "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
    },
    "value": {"res_dim": 8, "res_num": 1},
}

# peak-flops table + cost/memory introspection live in obs/perf.py now —
# ONE code path shared by bench, tools/memstats.py and the live learner
# gauges (obs imports no jax, so the parent process stays jax-free)
from distar_tpu.obs.perf import (  # noqa: E402
    flops_of_compiled as _flops_of_compiled,
    flops_of_lowered as _flops_of_lowered,
    memory_report as _memory_report,
    peak_flops as _peak_flops,
)


# --------------------------------------------------------------------- child

_CURRENT_STAGE = ["start"]


def _stage(name: str) -> None:
    _CURRENT_STAGE[0] = name
    print(f"BENCH-STAGE {name} t={time.time():.0f}", file=sys.stderr, flush=True)


_HEARTBEAT_STARTED = []
_HEARTBEAT_STOP = threading.Event()


def _start_heartbeat() -> None:
    _HEARTBEAT_STOP.clear()
    if _HEARTBEAT_STARTED:  # once per process: in-process callers (tests)
        return              # must not accumulate immortal printer threads
    _HEARTBEAT_STARTED.append(True)

    def beat():
        t0 = time.time()
        while True:
            time.sleep(20)
            if _HEARTBEAT_STOP.is_set():
                # an in-process bench (tests) finished: stay quiet instead of
                # stamping unrelated later output with stale BENCH-STAGE lines
                continue
            print(
                f"BENCH-STAGE {_CURRENT_STAGE[0]} (heartbeat +{time.time() - t0:.0f}s)",
                file=sys.stderr,
                flush=True,
            )

    threading.Thread(target=beat, daemon=True).start()


def _stop_heartbeat() -> None:
    _HEARTBEAT_STOP.set()


# ------------------------------------------------------------- replay bench

# no external reference number exists for this path; results are normalised
# against a nominal 1k trajectories/s so vs_baseline stays comparable
# across rounds of OUR artifacts (BENCH_r* trend, not a paper claim)
REPLAY_BASELINE_ITEMS = 1000.0


def _measure_replay_clients(make_insert_client, make_sample_client, payload,
                            seconds, writers, readers, batch,
                            table: str = "bench") -> dict:
    """Shared replay measurement loop: ``writers`` threads ack inserts while
    ``readers`` drain batched samples for ``seconds``; every thread owns its
    client (its own connections), so concurrency is real, not lock-shared."""
    stop = threading.Event()
    counts = {"inserted": 0, "sampled": 0}
    lock = threading.Lock()

    def writer():
        client = make_insert_client()
        n = 0
        while not stop.is_set():
            client.insert(table, payload, timeout_s=5.0)
            n += 1
        with lock:
            counts["inserted"] += n
        client.close()

    def reader():
        client = make_sample_client()
        n = 0
        while not stop.is_set():
            try:
                items, _info = client.sample(table, batch_size=batch, timeout_s=1.0)
                n += len(items)
            except Exception:
                continue  # startup races before min_size is reached
        with lock:
            counts["sampled"] += n
        client.close()

    threads = [threading.Thread(target=writer, daemon=True) for _ in range(writers)]
    threads += [threading.Thread(target=reader, daemon=True) for _ in range(readers)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(10.0)
    elapsed = time.perf_counter() - t0
    insert_rate = counts["inserted"] / elapsed
    sample_rate = counts["sampled"] / elapsed
    mb = len(payload) / (1024.0 * 1024.0)
    return {
        "insert_items_per_s": round(insert_rate, 2),
        "sample_items_per_s": round(sample_rate, 2),
        "aggregate_items_per_s": round(insert_rate + sample_rate, 2),
        "insert_mb_per_s": round(insert_rate * mb, 2),
        "sample_mb_per_s": round(sample_rate * mb, 2),
        "writers": writers,
        "readers": readers,
        "batch": batch,
        "seconds": round(elapsed, 2),
    }


def _spawn_shard_fleet(n: int, batch: int, compress: bool = True,
                       transport: str = "tcp"):
    """``n`` real replay-shard subprocesses (``python -m
    distar_tpu.replay.server`` — jax-free, own GIL, own sockets). Returns
    ``(procs, addrs)``; closing a proc's stdin reaps it. ``transport``
    defaults to tcp so the historical sweep rows keep measuring the wire
    (the dedicated transport row opts into shm explicitly)."""
    import subprocess

    procs, addrs = [], []
    for i in range(n):
        cmd = [sys.executable, "-m", "distar_tpu.replay.server", "--port", "0",
               "--min-size", str(batch), "--shard-id", f"s{i}",
               "--transport", transport]
        if not compress:
            cmd.append("--no-compress")
        proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)
        parts = proc.stdout.readline().split()
        if len(parts) < 3 or parts[0] != "REPLAY-SHARD":
            raise RuntimeError(f"shard {i} failed to start: {parts}")
        addrs.append(f"{parts[1]}:{parts[2]}")
        procs.append(proc)
    return procs, addrs


def _reap_shard_fleet(procs) -> None:
    for proc in procs:
        try:
            proc.stdin.close()
            proc.wait(timeout=10)
        except Exception:
            proc.kill()


def _registry_sum(prefix: str) -> float:
    from distar_tpu.obs import get_registry

    return float(sum(v for k, v in get_registry().snapshot().items()
                     if k.startswith(prefix)))


def bench_replay() -> dict:
    """Replay data-plane throughput on loopback (BENCH_MODE=replay;
    CPU-only — never claims the chip). Four cases:

      * legacy single in-process store over framed TCP (the PR 5 point,
        unchanged, so the round-over-round trend is unbroken);
      * sharded scaling sweep (BENCH_REPLAY_SHARDS, default 1,2,4): real
        shard SUBPROCESSES behind consistent-hash routing + fan-in
        sampling. NOTE the honest physics: the fleet needs host cores to
        scale onto — a 1-core host time-shares every shard, so the sweep
        there proves the fleet executes at every width, not that it
        scales (``host_cores``/``scaling_valid`` travel in-band, the
        multichip-bench precedent);
      * compression on/off row on a compressible payload: negotiated wire
        compression's byte ratio (from the tx/rx raw/wire counters) and
        its throughput cost/benefit;
      * zero-copy colocated fast path (LocalReplayClient): the same
        workload with no socket and no serialization, vs the TCP path;
      * transport three-way (its own artifact line, SHM_r*): shm rings
        vs framed TCP over REAL shard subprocesses (distinct PIDs) with
        the fast path as in-process ceiling, wall AND cpu-per-item rates
        (on a 1-core host the wall ratio is context-switch-bound — the
        in-band flags say when it is a real separation claim).

    Payloads are BENCH_REPLAY_PAYLOAD_KB of incompressible bytes (the
    serializer's worst case, like real trajectory tensors) except the
    compression row, which uses a 75%%-zeros payload (like zero-padded
    entity tensors). Emits one BENCH JSON line per case; the LAST line is
    the full sharded artifact."""
    _stage("replay-setup")
    from distar_tpu.replay import (
        InsertClient, LocalReplayClient, ReplayServer, ReplayStore,
        SampleClient, ShardMap, ShardedInsertClient, ShardedSampleClient,
        TableConfig,
    )

    seconds = float(os.environ.get("BENCH_REPLAY_SECONDS", 5.0))
    payload_kb = int(os.environ.get("BENCH_REPLAY_PAYLOAD_KB", 64))
    writers = int(os.environ.get("BENCH_REPLAY_WRITERS", 2))
    readers = int(os.environ.get("BENCH_REPLAY_READERS", 2))
    batch = int(os.environ.get("BENCH_REPLAY_BATCH", 4))
    shard_counts = [int(s) for s in
                    os.environ.get("BENCH_REPLAY_SHARDS", "1,2,4").split(",")]
    host_cores = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") \
        else (os.cpu_count() or 1)

    payload = os.urandom(payload_kb * 1024)

    def table_cfg(_name):
        return TableConfig(max_size=4096, sampler="uniform",
                           samples_per_insert=None, min_size_to_sample=batch)

    # ---- legacy case: one in-process store over framed TCP (PR 5 shape).
    # transport pinned to tcp — colocated clients negotiate shm by default
    # now, and this row's whole point is the unchanged TCP trend line
    server = ReplayServer(ReplayStore(table_factory=table_cfg), port=0).start()
    _stage("replay-run-legacy")
    legacy = _measure_replay_clients(
        lambda: InsertClient(server.host, server.port, transport="tcp"),
        lambda: SampleClient(server.host, server.port, transport="tcp"),
        payload, seconds, writers, readers, batch)
    server.stop()
    point = {
        "metric": "replay-store sample throughput (framed TCP, loopback)",
        "value": legacy["sample_items_per_s"],
        "unit": "items/s",
        "vs_baseline": round(legacy["sample_items_per_s"] / REPLAY_BASELINE_ITEMS, 3),
        "replay": {**legacy, "payload_kb": payload_kb},
    }
    print(json.dumps(point), flush=True)

    # ---- sharded scaling sweep: real shard subprocesses, hash routing in,
    # fan-in sampling out. Each width runs under the tools/pin.py harness:
    # when the host has cores, every shard gets its own and the client side
    # the reserved remainder (provenance lands in the artifact, verified by
    # perf_gate's scaling gate); a refused plan keeps scaling_valid false
    from distar_tpu.fleet import pinning

    orig_affinity = (os.sched_getaffinity(0)
                     if hasattr(os, "sched_getaffinity") else None)
    sweep = []
    sweep_pinning = {}
    for n in shard_counts:
        _stage(f"replay-shards-{n}")
        procs, addrs = _spawn_shard_fleet(n, batch)
        sweep_pinning[n] = pinning.pin_fleet([p.pid for p in procs],
                                             reserve_client=1)
        try:
            shard_map = ShardMap(addrs)
            row = _measure_replay_clients(
                lambda: ShardedInsertClient(shard_map, transport="tcp"),
                lambda: ShardedSampleClient(shard_map, transport="tcp"),
                payload, seconds, writers, readers, batch)
        finally:
            _reap_shard_fleet(procs)
            if orig_affinity is not None:  # un-pin the client between cases
                os.sched_setaffinity(0, orig_affinity)
        row["shards"] = n
        row["pinning"] = sweep_pinning[n]
        if sweep:
            row["scaling_vs_1"] = round(
                row["aggregate_items_per_s"] / sweep[0]["aggregate_items_per_s"], 3)
        sweep.append(row)
        print(json.dumps({"metric": "replay sharded aggregate throughput",
                          "value": row["aggregate_items_per_s"],
                          "unit": "items/s", "shards": n}), flush=True)

    # ---- compression on/off row (compressible payload: 75% zeros, like
    # zero-padded entity tensors) — ratio comes from the server-side
    # raw/wire byte counters, which is why this row runs in-process
    _stage("replay-compression")
    soft_payload = bytes(payload_kb * 1024 // 4) * 3 + os.urandom(payload_kb * 1024 // 4)
    compression = {}
    for mode, compress in (("on", True), ("off", False)):
        server = ReplayServer(ReplayStore(table_factory=table_cfg), port=0,
                              compress=compress).start()
        before = {k: _registry_sum(f"distar_replay_{k}_total")
                  for k in ("tx_bytes_raw", "tx_bytes_wire",
                            "rx_bytes_raw", "rx_bytes_wire")}
        row = _measure_replay_clients(
            lambda: InsertClient(server.host, server.port, compress=compress,
                                 transport="tcp"),
            lambda: SampleClient(server.host, server.port, compress=compress,
                                 transport="tcp"),
            soft_payload, seconds / 2, writers, readers, batch)
        deltas = {k: _registry_sum(f"distar_replay_{k}_total") - v
                  for k, v in before.items()}
        server.stop()
        raw = deltas["tx_bytes_raw"] + deltas["rx_bytes_raw"]
        wire = deltas["tx_bytes_wire"] + deltas["rx_bytes_wire"]
        row["wire_ratio"] = round(wire / raw, 4) if raw else None
        compression[mode] = row
    compression["throughput_delta"] = round(
        compression["on"]["aggregate_items_per_s"]
        / max(compression["off"]["aggregate_items_per_s"], 1e-9), 3)
    # ---- zstd column: the second negotiated codec. Gated on the host
    # having a zstandard binding — when absent the row says so in-band
    # instead of silently vanishing (honesty-flag convention)
    from distar_tpu.comm import serializer as _ser

    if _ser.zstd_available():
        _stage("replay-compression-zstd")
        server = ReplayServer(ReplayStore(table_factory=table_cfg), port=0).start()
        before = {k: _registry_sum(f"distar_replay_{k}_total")
                  for k in ("tx_bytes_raw", "tx_bytes_wire",
                            "rx_bytes_raw", "rx_bytes_wire")}
        row = _measure_replay_clients(
            lambda: InsertClient(server.host, server.port, codec="zstd",
                                 transport="tcp"),
            lambda: SampleClient(server.host, server.port, codec="zstd",
                                 transport="tcp"),
            soft_payload, seconds / 2, writers, readers, batch)
        deltas = {k: _registry_sum(f"distar_replay_{k}_total") - v
                  for k, v in before.items()}
        server.stop()
        raw = deltas["tx_bytes_raw"] + deltas["rx_bytes_raw"]
        wire = deltas["tx_bytes_wire"] + deltas["rx_bytes_wire"]
        row["wire_ratio"] = round(wire / raw, 4) if raw else None
        row["codec"] = "zstd"
        compression["zstd"] = row
    else:
        compression["zstd"] = {"unavailable": True,
                               "reason": "no zstandard binding in this image"}
    print(json.dumps({"metric": "replay wire-compression ratio (75% zeros)",
                      "value": compression["on"]["wire_ratio"],
                      "unit": "wire/raw bytes",
                      "throughput_on_vs_off": compression["throughput_delta"],
                      "zstd": compression["zstd"].get("wire_ratio",
                                                      "unavailable")}),
          flush=True)

    # ---- zero-copy colocated fast path: same workload, no socket, no
    # serialization (the --replay-fast-path data plane)
    _stage("replay-fast-path")
    local_store = ReplayStore(table_factory=table_cfg)
    fast = _measure_replay_clients(
        lambda: LocalReplayClient(local_store),
        lambda: LocalReplayClient(local_store),
        payload, seconds / 2, writers, readers, batch)
    fast["vs_tcp_loopback"] = round(
        fast["aggregate_items_per_s"] / max(legacy["aggregate_items_per_s"], 1e-9), 3)

    # ---- transport three-way: shm rings vs framed TCP over REAL shard
    # subprocesses (distinct PIDs — the claim the in-process rows cannot
    # make), with the in-process fast path as the ceiling reference. Both
    # subprocess rows run the identical store config; only the negotiated
    # transport differs, so the ratio isolates the transport itself.
    from distar_tpu.comm.shm_ring import shm_available

    def _proc_cpu_s(pid: int) -> float:
        """utime+stime of a child process in seconds (/proc/<pid>/stat)."""
        try:
            with open(f"/proc/{pid}/stat") as f:
                parts = f.read().rsplit(")", 1)[1].split()
            hz = os.sysconf("SC_CLK_TCK")
            return (int(parts[11]) + int(parts[12])) / hz  # utime, stime
        except (OSError, IndexError, ValueError):
            return 0.0

    transport_rows = {}
    for mode in ("tcp", "shm"):
        if mode == "shm" and not shm_available():
            transport_rows["shm"] = {
                "unavailable": True,
                "reason": "no multiprocessing.shared_memory on this host"}
            continue
        _stage(f"replay-transport-{mode}")
        procs, addrs = _spawn_shard_fleet(1, batch, transport=mode)
        transport_pinning = pinning.pin_fleet([p.pid for p in procs],
                                              reserve_client=1)
        host, port = addrs[0].rsplit(":", 1)
        t_client0 = sum(os.times()[:2])
        t_server0 = _proc_cpu_s(procs[0].pid)
        try:
            row = _measure_replay_clients(
                lambda: InsertClient(host, int(port), transport=mode),
                lambda: SampleClient(host, int(port), transport=mode),
                payload, seconds / 2, writers, readers, batch)
            cpu_s = (sum(os.times()[:2]) - t_client0
                     + _proc_cpu_s(procs[0].pid) - t_server0)
        finally:
            _reap_shard_fleet(procs)
            if orig_affinity is not None:
                os.sched_setaffinity(0, orig_affinity)
        row["transport"] = mode
        row["pinning"] = transport_pinning
        # CPU-seconds per item across BOTH processes: core-count
        # independent, so it stays an honest efficiency number on a host
        # whose wall-clock is context-switch-bound (see scaling_valid)
        items = row["seconds"] * row["aggregate_items_per_s"]
        row["cpu_s_total"] = round(cpu_s, 3)
        row["cpu_us_per_item"] = round(cpu_s / items * 1e6, 1) if items else None
        transport_rows[mode] = row
    transport_rows["fast_path_inproc"] = dict(fast)
    shm_row = transport_rows.get("shm", {})
    if "aggregate_items_per_s" in shm_row:
        transport_rows["shm_vs_tcp"] = round(
            shm_row["aggregate_items_per_s"]
            / max(transport_rows["tcp"]["aggregate_items_per_s"], 1e-9), 3)
        tcp_cpu = transport_rows["tcp"].get("cpu_us_per_item") or 0.0
        shm_cpu = shm_row.get("cpu_us_per_item") or 0.0
        if tcp_cpu and shm_cpu:
            transport_rows["shm_vs_tcp_cpu"] = round(tcp_cpu / shm_cpu, 3)
    shm_artifact = {
        "metric": "replay transport three-way (shm ring vs framed TCP, real "
                  "subprocesses; in-process fast path as ceiling)",
        "value": shm_row.get("aggregate_items_per_s", 0.0),
        "unit": "items/s",
        "vs_baseline": round(
            shm_row.get("aggregate_items_per_s", 0.0) / REPLAY_BASELINE_ITEMS, 3),
        "device": "cpu",
        "cpu_derived": True,
        "host_cores": host_cores,
        # A 1-core host serializes client, server AND the kernel's wake
        # path onto one core, so BOTH legs are bound by the same context-
        # switch budget and the wall-clock ratio collapses toward 1 —
        # exactly the physics the multichip/sharded sweeps already flag.
        # The transport ratio is only a *throughput* claim when the
        # tools/pin.py harness actually separated the processes (provenance
        # below — perf_gate's scaling gate verifies it); cpu_us_per_item
        # remains the core-count-independent efficiency number.
        "scaling_valid": pinning.scaling_valid(
            transport_rows.get("shm", {}).get(
                "pinning", transport_rows.get("tcp", {}).get("pinning", {}))),
        "pinning": transport_rows.get("shm", {}).get(
            "pinning", transport_rows.get("tcp", {}).get("pinning", {})),
        "distinct_pids": True,
        "payload_kb": payload_kb,
        "shm_vs_tcp": transport_rows.get("shm_vs_tcp"),
        "shm_vs_tcp_cpu": transport_rows.get("shm_vs_tcp_cpu"),
        "replay_transport": transport_rows,
    }
    print(json.dumps(shm_artifact), flush=True)

    two = next((r for r in sweep if r.get("shards") == 2), None)
    artifact = {
        "metric": "replay sharded fleet aggregate throughput (framed TCP, loopback)",
        "value": sweep[-1]["aggregate_items_per_s"],
        "unit": "items/s",
        "vs_baseline": round(sweep[-1]["aggregate_items_per_s"] / REPLAY_BASELINE_ITEMS, 3),
        "device": "cpu",
        "cpu_derived": True,
        "host_cores": host_cores,
        # scaling is only a *claim* when the tools/pin.py harness actually
        # gave every shard of the WIDEST sweep its own core (per-width
        # provenance rides each sweep row; the widest one is the artifact's
        # claim). On a smaller host the sweep still proves the sharded path
        # executes at every width (the multichip-bench precedent), refused
        # in-band so no reader quotes a serialized number as scaling.
        "scaling_valid": pinning.scaling_valid(
            sweep_pinning.get(max(shard_counts), {}),
            min_cores=max(shard_counts) + 1),
        "pinning": sweep_pinning.get(max(shard_counts), {}),
        "payload_kb": payload_kb,
        "replay": {**legacy, "payload_kb": payload_kb},
        "replay_shard_sweep": sweep,
        "replay_compression": compression,
        "replay_fast_path": fast,
        "replay_transport": transport_rows,
    }
    if two is not None:
        artifact["two_shard_scaling"] = two.get("scaling_vs_1")
    print(json.dumps(artifact), flush=True)
    return artifact


# ------------------------------------------------------------ rollout bench

# no external reference number for this path either; normalise against a
# nominal 1k env-steps/s so vs_baseline trends across OUR rounds
ROLLOUT_BASELINE_STEPS = 1000.0


def bench_rollout() -> dict:
    """Rollout-plane env-steps/s: inline (per-actor engine replica) vs
    local (one shared batched gateway) vs remote (framed TCP) at 1/4/16
    actors (``BENCH_MODE=rollout``; mock engine + mock env, CPU-only —
    never claims the chip).

    The device economics are modelled honestly: every mock engine instance
    shares ONE device lock (per-actor replicas serialise on the same chip,
    exactly like N jitted forwards dispatched to one TPU), and a forward
    costs ``base + per_slot * active`` seconds (a batched flush amortises
    the base cost over its occupancy). What this measures is therefore the
    plane's dispatch/batching machinery — the Sebulba claim — not model
    math. The 16-actor remote case additionally kills and restarts the
    gateway mid-run: throughput must survive (ServeClient reconnect under
    the resilience policy) and the carries re-materialize from zero
    (``distar_actor_carry_resets_total``)."""
    _stage("rollout-setup")
    import numpy as np

    from distar_tpu.actor.rollout_plane import RolloutPlane
    from distar_tpu.obs import get_registry
    from distar_tpu.serve import InferenceGateway, MockModelEngine, ServeTCPServer

    seconds = float(os.environ.get("BENCH_ROLLOUT_SECONDS", 3.0))
    base_s = float(os.environ.get("BENCH_ROLLOUT_FWD_BASE_S", 0.002))
    per_slot_s = float(os.environ.get("BENCH_ROLLOUT_FWD_PER_SLOT_S", 0.00005))
    env_s = float(os.environ.get("BENCH_ROLLOUT_ENV_S", 0.001))
    actor_counts = [int(x) for x in
                    os.environ.get("BENCH_ROLLOUT_ACTORS", "1,4,16").split(",")]

    device_lock = threading.Lock()  # one chip: replica forwards serialise

    def factory(player_id, num_slots, params, teacher_params, model, seed):
        return MockModelEngine(
            num_slots, params={"version": "v1", "bias": 0.0},
            delay_s=base_s, per_slot_delay_s=per_slot_s,
            device_lock=device_lock, teacher_params=teacher_params,
        )

    obs = {"x": np.ones((8,), np.float32)}

    def run_actors(mk_client, n_actors, on_half=None):
        """N actor threads, one env lane each: sample -> mock env step."""
        counts = [0] * n_actors
        stop = threading.Event()
        half_fired = threading.Event()
        t_half = time.perf_counter() + seconds / 2

        def loop(w, client):
            try:
                while not stop.is_set():
                    client.sample([obs], [True])
                    if env_s:
                        time.sleep(env_s)  # the mock env step
                    counts[w] += 1
                    if (on_half is not None and not half_fired.is_set()
                            and time.perf_counter() >= t_half and w == 0):
                        half_fired.set()
                        on_half()
            finally:
                client.close()

        clients = [mk_client(w) for w in range(n_actors)]
        threads = [threading.Thread(target=loop, args=(w, c), daemon=True)
                   for w, c in enumerate(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in threads:
            t.join(15.0)
        elapsed = time.perf_counter() - t0
        return sum(counts) / elapsed

    cases = {}
    for n in actor_counts:
        _stage(f"rollout-inline-{n}")
        plane = RolloutPlane(backend="inline", engine_factory=factory)
        cases[f"inline@{n}"] = round(run_actors(
            lambda w: plane.client_for(f"bench{w}", num_slots=1), n), 2)
    for n in actor_counts:
        _stage(f"rollout-local-{n}")
        plane = RolloutPlane(backend="local", slots=n, engine_factory=factory,
                             max_delay_s=0.002)
        cases[f"local@{n}"] = round(run_actors(
            lambda w: plane.client_for("bench", num_slots=1), n), 2)
        plane.shutdown()

    # remote: a real TCP gateway on loopback, killed + restarted mid-run at
    # the largest actor count (the chaos acceptance case)
    def make_server(port=0):
        eng = MockModelEngine(
            max(actor_counts), params={"version": "v1", "bias": 0.0},
            delay_s=base_s, per_slot_delay_s=per_slot_s, device_lock=device_lock,
        )
        gw = InferenceGateway(eng, max_delay_s=0.002, default_timeout_s=10.0).start()
        gw.load_version("v1", params={"version": "v1", "bias": 0.0}, activate=True)
        srv = ServeTCPServer(gw, host="127.0.0.1", port=port).start()
        return gw, srv

    carry_resets = 0.0
    for n in actor_counts:
        _stage(f"rollout-remote-{n}")
        gw, srv = make_server()
        port = srv.port
        holder = {"gw": gw, "srv": srv}
        # transport pinned to tcp: this row's trend predates the shm leg,
        # and a colocated in-process gateway would otherwise negotiate
        # rings and silently change what the row measures
        plane = RolloutPlane(backend="remote", addr=f"127.0.0.1:{port}",
                             timeout_s=10.0, transport="tcp")

        def restart():
            # kill the gateway hard mid-run, rebind the same port: clients
            # must ride reconnect+retry, carries re-materialize from zero
            holder["srv"].stop()
            holder["gw"].drain_and_stop(timeout=2.0)
            holder["gw"], holder["srv"] = make_server(port)

        inject = restart if n == max(actor_counts) else None
        reg0 = get_registry().snapshot().get(
            "distar_actor_carry_resets_total{player=bench}", 0.0)
        cases[f"remote@{n}"] = round(run_actors(
            lambda w: plane.client_for("bench", num_slots=1), n,
            on_half=inject), 2)
        if inject is not None:
            carry_resets = get_registry().snapshot().get(
                "distar_actor_carry_resets_total{player=bench}", 0.0) - reg0
        holder["srv"].stop()
        holder["gw"].drain_and_stop(timeout=2.0)

    hi = max(actor_counts)

    # transport A/B at the highest actor count: the SAME remote workload
    # against a REAL gateway subprocess (distinct PID), once per transport
    # leg — what the actor fleet actually pays per env-step to cross the
    # process boundary on one host (the Sebulba colocation recipe)
    import subprocess

    def spawn_gateway(transport):
        cmd = [sys.executable, "-m", "distar_tpu.serve.fleet.gateway_proc",
               "--port", "0", "--http-port", "0", "--slots", str(max(hi, 32)),
               "--mock-delay-s", str(base_s), "--transport", transport]
        proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)
        parts = proc.stdout.readline().split()
        if len(parts) < 4 or parts[0] != "SERVE-GATEWAY":
            raise RuntimeError(f"gateway failed to start: {parts}")
        return proc, f"{parts[1]}:{parts[2]}"

    transport_cases = {}
    for mode in ("tcp", "shm"):
        _stage(f"rollout-transport-{mode}")
        proc, addr = spawn_gateway(mode)
        try:
            plane = RolloutPlane(backend="remote", addr=addr, timeout_s=10.0,
                                 transport=mode)
            transport_cases[mode] = round(run_actors(
                lambda w: plane.client_for("bench", num_slots=1), hi), 2)
        finally:
            try:
                proc.stdin.close()
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
    transport_cases["shm_vs_tcp"] = round(
        transport_cases["shm"] / max(transport_cases["tcp"], 1e-9), 3)
    print(json.dumps({
        "metric": f"rollout remote transport A/B @{hi} actors "
                  "(real gateway subprocess)",
        "value": transport_cases["shm_vs_tcp"], "unit": "x tcp",
        "env_steps_per_s": transport_cases,
    }), flush=True)
    speedup = round(cases[f"local@{hi}"] / max(cases[f"inline@{hi}"], 1e-9), 2)
    out = {
        "metric": f"rollout plane env-steps/s, local vs inline @{hi} actors "
                  "(shared batched gateway vs per-actor replica, mock engine)",
        "value": speedup,
        "unit": "x inline",
        "vs_baseline": round(cases[f"local@{hi}"] / ROLLOUT_BASELINE_STEPS, 3),
        "device": "cpu",
        "note": (
            "CPU-derived (impossible-timing policy: no chip claim): mock "
            "engine + mock env measure the plane's dispatch/batching "
            "machinery only; per-actor replicas serialise on one shared "
            "device lock, the shared gateway amortises the base forward "
            "cost across its flush occupancy"
        ),
        "rollout": {
            "env_steps_per_s": cases,
            "local_vs_inline": {
                str(n): round(cases[f"local@{n}"] / max(cases[f"inline@{n}"], 1e-9), 2)
                for n in actor_counts
            },
            "remote_restart": {
                "actors": hi,
                "env_steps_per_s": cases[f"remote@{hi}"],
                "carry_resets": carry_resets,
            },
            "remote_transport": transport_cases,
            "fwd_base_s": base_s,
            "fwd_per_slot_s": per_slot_s,
            "env_step_s": env_s,
            "seconds": seconds,
        },
    }
    print(json.dumps(out), flush=True)
    return out


# ------------------------------------------------------------ distill bench

#: ROADMAP item 2's acceptance bar: the student must cost at most half a
#: teacher step (FLOPs-derived — the committed artifact's ratio is checked
#: against this in tests/test_distill.py)
DISTILL_TARGET_RATIO = 0.5


def bench_distill() -> dict:
    """BENCH_MODE=distill: the distillation tier's two numbers.

    * **student/teacher per-step cost ratio** — FLOP counts off the SAME
      jitted train steps both tiers actually run (teacher: full RL step,
      fwd+loss+bwd+adam on ``default_model_config``; student: distill step
      on ``student_model_config``), at the same (batch, unroll). A ratio
      of flop counts is physics-coherent on ANY host — no chip timing is
      claimed, which is exactly why this is the number the serve-side
      capacity multiplier can honestly quote from a CPU CI box (the DD-PPO
      precedent: keep the scaling story honest while the policy shrinks).
    * **toy distill run** — a fixed-batch DistillLearner loop whose masked
      KL vs the teacher must fall MONOTONICALLY over the window (the
      signal trains; curve committed in-band).

    ``BENCH_DISTILL_SMOKE=1`` shrinks both tiers to smoke dims for the
    harness test (flagged in-band — a smoke artifact can never be quoted
    as the real ratio)."""
    _stage("distill-setup")
    import itertools

    import jax
    import jax.numpy as jnp

    from distar_tpu.learner import DistillLearner, RLLearner
    from distar_tpu.learner.data import fake_rl_batch

    B = int(os.environ.get("BENCH_DISTILL_BATCH", 2))
    T = int(os.environ.get("BENCH_DISTILL_UNROLL", 8))
    iters = int(os.environ.get("BENCH_DISTILL_ITERS", 24))
    smoke = _env_truthy("BENCH_DISTILL_SMOKE")
    host_cores = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") \
        else (os.cpu_count() or 1)

    smoke_model = SMOKE_MODEL_CFG
    model_cfg = smoke_model if smoke else {}
    common = {"save_freq": 10 ** 9, "log_freq": 10 ** 9}

    # ---- teacher FLOPs: the full RL train step, traced once (no compile,
    # no timing — the flop count is a property of the lowering)
    _stage("distill-teacher-trace")
    teacher = RLLearner({
        "common": {"experiment_name": "bench_distill_teacher"},
        "learner": {"batch_size": B, "unroll_len": T,
                    "value_pretrain_iters": -1, **common},
        "model": model_cfg,
    })
    data = dict(next(teacher._dataloader))
    data.pop("model_last_iter", None)
    t_batch = teacher.shard_batch(teacher._cap(data))
    t_args = (teacher.state["params"], teacher.state["opt_state"], t_batch,
              jnp.asarray(False))
    teacher_flops = _flops_of_lowered(teacher._train_step.lower(*t_args))
    teacher_core = dict(teacher.model_cfg.encoder.core_lstm)
    teacher_entity = {k: teacher.model_cfg.encoder.entity[k]
                      for k in ("hidden_dim", "output_dim", "head_num", "layer_num")}
    del teacher, t_batch, t_args

    # ---- student FLOPs: the distill train step on the shrunk config
    _stage("distill-student-trace")
    student = DistillLearner({
        "common": {"experiment_name": "bench_distill_student"},
        "learner": {"batch_size": B, "unroll_len": T, **common},
        "model": model_cfg,
    })
    s_data = dict(next(student._dataloader))
    s_data.pop("model_last_iter", None)
    s_batch = jax.tree.map(jnp.asarray,
                           student._strip_batch(student._cap(s_data)))
    student_flops = _flops_of_lowered(student._train_step.lower(
        student.state["params"], student.state["opt_state"], s_batch))
    student_core = dict(student.model_cfg.encoder.core_lstm)
    student_entity = {k: student.model_cfg.encoder.entity[k]
                      for k in ("hidden_dim", "output_dim", "head_num", "layer_num")}
    del s_batch

    ratio = round(student_flops / teacher_flops, 4) \
        if (teacher_flops and student_flops) else None

    # ---- toy distill loop: fixed batch, KL must fall monotonically
    _stage("distill-toy-run")
    toy = DistillLearner({
        "common": {"experiment_name": "bench_distill_toy"},
        "learner": {"batch_size": 2, "unroll_len": 3, **common},
        "model": smoke_model,
    })
    toy_batch = fake_rl_batch(2, 3)
    toy.set_dataloader(itertools.repeat(toy_batch))
    kl_curve = []
    for _ in range(iters):
        kl_curve.append(round(toy._train(dict(next(toy._dataloader)))["divergence"], 5))
    monotone = all(b < a for a, b in zip(kl_curve, kl_curve[1:]))
    del toy, student

    out = {
        "metric": "distill student/teacher per-step cost ratio "
                  "(FLOPs-derived, same jitted train steps)",
        "value": ratio,
        "unit": "x teacher step",
        "vs_baseline": ratio,
        "device": "cpu",
        "cpu_derived": True,
        "flops_derived": True,
        "host_cores": host_cores,
        "scaling_valid": False,
        "pinning": {"pinned": False,
                    "refused_reason": "single-process FLOP counting — "
                                      "nothing to pin",
                    "host_cores": host_cores},
        "smoke_model": smoke,
        "target_ratio": DISTILL_TARGET_RATIO,
        "meets_target": bool(ratio is not None
                             and ratio <= DISTILL_TARGET_RATIO) and not smoke,
        "distill": {
            "batch": B,
            "unroll": T,
            "teacher_flops_per_step": teacher_flops,
            "student_flops_per_step": student_flops,
            "teacher_config": {"core_lstm": teacher_core, "entity": teacher_entity},
            "student_config": {"core_lstm": student_core, "entity": student_entity},
            "toy_run": {
                "iters": iters,
                "kl_curve": kl_curve,
                "kl_first": kl_curve[0] if kl_curve else None,
                "kl_last": kl_curve[-1] if kl_curve else None,
                "monotone_decrease": monotone,
            },
        },
    }
    print(json.dumps(out), flush=True)
    return out


# ------------------------------------------------------------- anakin bench

# no external reference number for the fused rollout either; normalise
# against a nominal 1k env-steps/s (same convention as the rollout plane)
# so vs_baseline trends across OUR rounds without tripping the >20x gate
ANAKIN_BASELINE_STEPS = 1000.0


def bench_anakin() -> dict:
    """BENCH_MODE=anakin: fused Anakin rollout vs the classic host actor
    loop over the SAME pure-JAX micro-battle world and the SAME policy.

    * **fused leg** — ``AnakinRunner``: env step + ``sample_action`` +
      LSTM carry fused into one jitted ``lax.scan`` over B vmapped lanes;
      measured in env-steps/s across whole windows (one deliberate host
      sync per window, the loader's own timing discipline).
    * **host leg** — ``JaxMicroBattleEnv`` driven one env step at a time:
      jitted ``sample_action`` at batch 1, device->host action fetch,
      host-side env adapter per step. A deliberately charitable floor:
      no actor machinery at all, just the irreducible per-step crossing.
    * **actor leg** — the REAL mock-env actor path: ``Actor.run_job``
      (env worker pool, rollout plane, per-step policy+teacher forwards,
      trajectory assembly + adapter push) over the mock env with the
      same policy. This is the production path the fused tier replaces,
      warmed by a full compile job before the timed job.

    HONEST PHYSICS: the ratios measure what Podracer-style fusion buys —
    per-step dispatch, host<->device boundary crossings, actor machinery
    and B-lane vectorization amortised into one XLA program. It is NOT a
    silicon claim (CPU, smoke model dims, flagged in-band), and on a
    1-core host it is NOT Podracer's orders-of-magnitude claim either:
    the B vmapped lanes serialize onto the same core that runs the host
    legs, so only the dispatch/machinery amortization is expressible —
    the separation refusal rides in-band, same policy as SHM_r11 /
    FLEET_r12. Device purity of the fused program is asserted and
    shipped in the artifact."""
    _stage("anakin-setup")
    import jax

    # never claims the chip: the fused-vs-host A/B is architecture
    # arithmetic, valid on any backend — pin to host CPU like the other
    # host-side modes (sitecustomize pins via jax.config, env alone is late)
    jax.config.update("jax_platforms", os.environ.get("BENCH_PLATFORM", "cpu"))
    import jax.numpy as jnp
    import numpy as np

    from distar_tpu.envs.jaxenv import (
        AnakinDataLoader, AnakinRunner, EnvConfig, JaxMicroBattleEnv,
        ScenarioConfig, micro_legal_mask,
    )
    from distar_tpu.model import Model, default_model_config
    from distar_tpu.utils import deep_merge_dicts

    B = int(os.environ.get("BENCH_ANAKIN_BATCH", 256))
    T = int(os.environ.get("BENCH_ANAKIN_UNROLL", 16))
    windows = int(os.environ.get("BENCH_ANAKIN_WINDOWS", 3))
    units = int(os.environ.get("BENCH_ANAKIN_UNITS", 4))
    host_steps = int(os.environ.get("BENCH_ANAKIN_HOST_STEPS", 48))
    host_cores = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") \
        else (os.cpu_count() or 1)

    env_cfg = EnvConfig(units_per_squad=units)
    scn_cfg = ScenarioConfig(units_per_squad=units, max_units=units,
                             episode_len=32)
    model = Model(deep_merge_dicts(default_model_config(), SMOKE_MODEL_CFG))
    runner = AnakinRunner(model, batch_size=B, unroll_len=T,
                          env_cfg=env_cfg, scenario_cfg=scn_cfg, seed=0)
    loader = AnakinDataLoader(runner)

    # ---- fused leg: first window pays trace+compile (reported separately),
    # then whole windows are timed through the loader's own host-sync path
    _stage(f"anakin-fused-compile B{B}xT{T}")
    t0 = time.perf_counter()
    next(loader)
    compile_s = time.perf_counter() - t0
    _stage(f"anakin-fused-steps B{B}xT{T}")
    t0 = time.perf_counter()
    for _ in range(windows):
        next(loader)
    fused_dt = time.perf_counter() - t0
    fused_rate = B * T * windows / fused_dt

    _stage("anakin-purity")
    purity = runner.purity_report(loader._params(), runner.init_carry())

    # ---- host leg: the same policy and world, one env lane, one jitted
    # forward + one host env step at a time (what the Anakin loop replaces)
    _stage("anakin-host-leg")
    env = JaxMicroBattleEnv(env_cfg, scn_cfg, seed=0)
    legal = jnp.asarray(micro_legal_mask())
    lstm = model.cfg["encoder"]["core_lstm"]
    z = jnp.zeros((1, int(lstm["hidden_size"])), jnp.float32)
    hidden0 = tuple((z, z) for _ in range(int(lstm["num_layers"])))
    params = loader._params()

    @jax.jit
    def sample(params, spatial, entity, scalar, en, hidden, key):
        return model.apply(params, spatial, entity, scalar, en, hidden, key,
                           legal, method=model.sample_action)

    def host_step(obs, hidden, key):
        key, k = jax.random.split(key)
        ob = obs[0]
        b1 = {k2: jax.tree.map(lambda x: jnp.asarray(x)[None], ob[k2])
              for k2 in ("spatial_info", "entity_info", "scalar_info")}
        b1["entity_num"] = jnp.asarray(int(ob["entity_num"]))[None]
        out = sample(params, b1["spatial_info"], b1["entity_info"],
                     b1["scalar_info"], b1["entity_num"], hidden, k)
        act = {k2: np.asarray(v)[0] for k2, v in out["action_info"].items()}
        act["selected_units_num"] = np.asarray(out["selected_units_num"])[0]
        obs, _rew, done, _info = env.step({0: act})
        if done:
            obs = env.reset()
        return obs, out["hidden_state"], key

    obs = env.reset()
    hidden, key = hidden0, jax.random.PRNGKey(1)
    for _ in range(3):  # warmup: compiles the batch-1 forward
        obs, hidden, key = host_step(obs, hidden, key)
    t0 = time.perf_counter()
    for _ in range(host_steps):
        obs, hidden, key = host_step(obs, hidden, key)
    host_dt = time.perf_counter() - t0
    host_rate = host_steps / host_dt

    # ---- actor leg: the mock-env actor path (the ISSUE/ROADMAP baseline).
    # One env lane through the full production machinery: EnvWorkerPool,
    # rollout plane (shared local gateway so the timed job reuses the
    # warmup job's compilations), per-step policy + frozen-teacher
    # forwards, trajectory assembly and adapter push. The mock env's own
    # obs generation is near-free, so this leg prices exactly what the
    # fused loop deletes: per-step actor machinery + batch-1 crossings.
    _stage("anakin-actor-leg")
    actor_steps = int(os.environ.get("BENCH_ANAKIN_ACTOR_STEPS", 24))
    from distar_tpu.actor import Actor
    from distar_tpu.comm import Adapter, Coordinator
    from distar_tpu.envs.mock_env import MockEnv

    counted = {"n": 0}

    class _CountedMockEnv(MockEnv):
        """Mock env that ends an episode after exactly ``actor_steps``
        env steps, so one run_job == one measurable fixed-length window."""

        def __init__(self):
            super().__init__(seed=0, episode_game_loops=1 << 30)

        def step(self, actions):
            counted["n"] += 1
            if counted["n"] % actor_steps == 0:
                self._game_loop = self._episode_game_loops
            return super().step(actions)

    actor_job = {
        "player_ids": ["MP0", "BOT"],
        "send_data_players": ["MP0"],
        "update_players": ["MP0"],
        "teacher_player_ids": ["T", "none"],
        "pipelines": ["default", "scripted.random"],
        "branch": "standalone",
        "env_info": {"map_name": "mock"},
    }
    actor = Actor(
        cfg={"actor": {"env_num": 1, "traj_len": T,
                       "plane": {"backend": "local", "addr": "", "slots": 4}}},
        league=None,
        adapter=Adapter(coordinator=Coordinator()),
        model_cfg=SMOKE_MODEL_CFG,
        env_fn=_CountedMockEnv,
    )
    actor.run_job(episodes=1, job=dict(actor_job))  # warmup: compiles
    base = counted["n"]
    t0 = time.perf_counter()
    actor.run_job(episodes=1, job=dict(actor_job))
    actor_dt = time.perf_counter() - t0
    actor_rate = (counted["n"] - base) / actor_dt

    ratio = round(fused_rate / max(host_rate, 1e-9), 1)
    actor_ratio = round(fused_rate / max(actor_rate, 1e-9), 1)
    out = {
        "metric": "anakin fused rollout env-steps/s (pure-JAX micro-battle, "
                  "one jitted scan over vmapped lanes)",
        "value": round(fused_rate, 1),
        "unit": "env-steps/s",
        "vs_baseline": round(fused_rate / ANAKIN_BASELINE_STEPS, 3),
        "device": "cpu",
        "cpu_derived": True,
        "host_cores": host_cores,
        "smoke_model": True,
        "scaling_valid": False,
        "pinning": {"pinned": False,
                    "refused_reason": "single-process fused-vs-host A/B — "
                                      "nothing to pin",
                    "host_cores": host_cores},
        "note": (
            "CPU-derived, smoke model dims (flagship architecture, tiny "
            "widths): the ratios price Podracer-style fusion — per-step "
            "dispatch, host<->device crossings, actor machinery and "
            "B-lane vectorization amortised into one XLA program — "
            "against (a) a charitable one-lane tight host loop over the "
            "SAME world (fused_vs_host floor) and (b) the REAL mock-env "
            "actor path (fused_vs_actor: Actor.run_job with env pool, "
            "rollout plane, policy+teacher forwards, trajectory push). "
            "Not a silicon claim."
        ),
        "anakin": {
            "batch_lanes": B,
            "unroll": T,
            "windows": windows,
            "units_per_squad": units,
            "fused_env_steps_per_s": round(fused_rate, 1),
            "fused_window_seconds": round(fused_dt / windows, 4),
            "fused_compile_s": round(compile_s, 1),
            "host_env_steps_per_s": round(host_rate, 2),
            "host_steps_timed": host_steps,
            "fused_vs_host": ratio,
            "actor_env_steps_per_s": round(actor_rate, 2),
            "actor_steps_timed": actor_steps,
            "fused_vs_actor": actor_ratio,
            "separation_refusal": (
                f"host_cores={host_cores}: the B vmapped lanes serialize "
                "onto the same core(s) running the host legs, so "
                "Podracer's orders-of-magnitude separation is not "
                "expressible here — only dispatch/machinery amortization "
                "is; the full claim needs parallel silicon "
                "(ROADMAP item 2b)."
            ) if host_cores <= 2 else "",
            "device_pure": purity["pure"],
            "purity_offending": purity["offending"],
        },
    }
    print(json.dumps(out), flush=True)
    return out


def _calibrate_matmul(jax):
    """Timing/peak sanity anchor: a dependency-chained bf16 matmul of KNOWN
    FLOPs (8 x 4096^3 = 1.1 TFLOP per call). Every model-step timing rides
    the same dispatch + block_until_ready path; if this anchor measures above
    the chip's datasheet peak, the device label or the readiness signalling
    is wrong and the model numbers inherit that — the JSON then carries the
    evidence either way. ~5 s of chip time."""
    import jax.numpy as jnp

    try:
        # full-size anchor only where it's fast; tiny elsewhere (CPU smoke)
        n = 4096 if jax.default_backend() == "tpu" else 256
        x = jnp.ones((n, n), jnp.bfloat16)
        w = jnp.ones((n, n), jnp.bfloat16) * 1e-4

        @jax.jit
        def chain(x, w):
            for _ in range(8):
                x = jnp.dot(x, w, preferred_element_type=jnp.bfloat16)
            return x

        out = chain(x, w)
        jax.block_until_ready(out)
        reps = 4
        t0 = time.perf_counter()
        for _ in range(reps):
            out = chain(out, w)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / reps
        return {
            "matmul_chain_s": round(dt, 5),
            "measured_tflops": round(8 * 2 * n ** 3 / dt / 1e12, 1),
            "what": f"8x chained {n}^3 bf16 matmul vs datasheet peak",
        }
    except Exception as e:  # calibration must never cost the sweep
        print(f"BENCH-STAGE calibration-failed {e!r}"[:300], file=sys.stderr, flush=True)
        return None


def _measure(kind, label, train_step, args, feedback, frames, peak, iters=4):
    """AOT measurement: trace ONCE, take the flop count off the lowering
    (and, post-compile, the optimized executable — the honest MFU
    numerator), compile that same lowering (persistent-cache-aware), then
    time the compiled executable directly. Avoids the duplicate trace a
    post-hoc ``jit_fn.lower()`` MFU estimate would cost (minutes for the
    full model)."""
    import jax

    _stage(f"{kind}-trace {label}")
    t0 = time.perf_counter()
    lowered = train_step.lower(*args)
    trace_s = time.perf_counter() - t0
    flops_unoptimized = _flops_of_lowered(lowered)
    _stage(f"{kind}-compile {label}")
    t0 = time.perf_counter()
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t0
    # post-optimization executable-level count, when the backend offers it
    flops_optimized = _flops_of_compiled(compiled)
    memory = _memory_report(compiled)
    # MFU numerator: the optimized executable count when present (honest —
    # what actually runs), else the HLO count. The impossible-timing check
    # below uses the MAX of the two: a backend reporting an erroneously low
    # optimized count must not be able to both deflate MFU and defeat the
    # physics recheck, and both counts land in the JSON as evidence.
    flops = flops_optimized or flops_unoptimized
    check_flops = max(flops_optimized, flops_unoptimized)
    _stage(f"{kind}-warmup {label}")
    out = compiled(*args)
    jax.block_until_ready(out)
    def timed(n):
        nonlocal args, out
        t0 = time.perf_counter()
        for _ in range(n):
            args = feedback(args, out)
            out = compiled(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / n

    _stage(f"{kind}-steps {label}")
    step_time = timed(iters)
    point = {
        "frames_per_sec": round(frames / step_time, 2),
        "step_time_s": round(step_time, 4),
        "trace_s": round(trace_s, 1),
        "compile_s": round(compile_s, 1),
    }
    if memory:
        point["memory"] = memory  # XLA memory_analysis via obs/perf.py
    if flops:
        point["flops_per_step"] = flops
        if flops_unoptimized:
            point["flops_unoptimized"] = flops_unoptimized
        if flops_optimized:
            point["flops_optimized"] = flops_optimized
        point["implied_tflops"] = round(flops / step_time / 1e12, 1)
        if peak:
            point["mfu"] = round(flops / step_time / peak, 4)
        if peak and check_flops / step_time > 1.1 * peak:
            # physically impossible number: the flop count says this step
            # cannot run this fast on this chip. Re-time over an 8x longer
            # window and make THAT the point's headline numbers — a timing
            # the code itself disproved must not win best-point selection.
            # The short window stays in the JSON as evidence.
            _stage(f"{kind}-steps-recheck {label}")
            long_time = timed(iters * 8)
            point["step_time_short_s"] = point["step_time_s"]
            point["implied_tflops_short"] = point["implied_tflops"]
            point["suspect_timing"] = bool(check_flops / long_time > 1.1 * peak)
            step_time = long_time
            point["step_time_s"] = round(step_time, 4)
            point["frames_per_sec"] = round(frames / step_time, 2)
            point["implied_tflops"] = round(flops / step_time / 1e12, 1)
            point["mfu"] = round(flops / step_time / peak, 4)
    return point


def _env_truthy(name):
    return os.environ.get(name, "").lower() in ("1", "true", "yes")


def _env_int(name):
    try:
        return int(os.environ.get(name, 0))
    except ValueError:  # exported-but-empty / junk: degrade, don't abort
        return 0


def _env_entity_cap():
    return _env_int("BENCH_MAX_ENTITIES") or None


def _bench_model_cfg():
    """Flagship model config for the bench: bf16 on the MXU, with the hot-op
    implementations switchable for on-silicon A/B
    (BENCH_ATTN_IMPL=pallas|xla|ring,
    BENCH_SCATTER_IMPL=pallas|pallas_onehot|xla)."""
    cfg = {"dtype": "bfloat16"}
    if _env_truthy("BENCH_REMAT"):
        cfg["remat"] = True  # trade recompute for HBM: bigger batches fit
    attn = os.environ.get("BENCH_ATTN_IMPL")
    scatter = os.environ.get("BENCH_SCATTER_IMPL")
    enc = {}
    if attn:
        enc["entity"] = {"attention_impl": attn}
    if scatter:
        enc["scatter"] = {"impl": scatter}
    core_lstm = {}
    if _env_int("BENCH_LSTM_UNROLL") > 1:
        # fuse N timesteps per scan iteration: the 64-step core-LSTM loop's
        # per-step matmuls are too small to fill the MXU at batch ~6
        core_lstm["scan_unroll"] = _env_int("BENCH_LSTM_UNROLL")
    if os.environ.get("BENCH_LSTM_LAYER_MAJOR", "") == "0":
        core_lstm["layer_major"] = False  # A/B the hoisted-projection split
    if core_lstm:
        enc["core_lstm"] = core_lstm
    if enc:
        cfg["encoder"] = enc
    return cfg


def _bench_sl(batch_size, unroll_len, peak, iters=4, remat=False, cap=None):
    import jax

    from distar_tpu.learner import SLLearner

    model_cfg = _bench_model_cfg()
    if remat:
        model_cfg = dict(model_cfg, remat=True)
    remat = bool(model_cfg.get("remat", False))  # env-driven runs tag too
    cfg = {
        "common": {"experiment_name": "bench_sl"},
        "learner": {
            "batch_size": batch_size,
            "unroll_len": unroll_len,
            "save_freq": 10 ** 9,
            "log_freq": 10 ** 9,
            # pad-to-bucket entity cap (learner/data.cap_entities): the
            # entity transformer + pointer decode are O(N^2)/O(N) in the
            # PADDED count; real frames rarely exceed ~300 entities
            "max_entities": cap if cap is not None else _env_entity_cap(),
        },
        # bfloat16 matmuls/convs on the MXU (params stay f32)
        "model": model_cfg,
    }
    cap = cfg["learner"]["max_entities"]
    label = (
        f"b{batch_size}xt{unroll_len}"
        + ("-remat" if remat else "")
        + (f"-e{cap}" if cap else "")
    )
    _stage(f"sl-init {label}")
    learner = SLLearner(cfg)
    data = dict(next(learner._dataloader))
    data.pop("new_episodes", None)
    data.pop("traj_lens", None)
    data = learner._cap(data)  # the MEASURED batch must carry the cap too
    batch = jax.tree.map(jax.numpy.asarray, data)
    args = (learner.state["params"], learner.state["opt_state"], batch, learner._hidden)

    def feedback(args, out):
        params, opt_state, out_state, _ = out
        # carry the LSTM state forward like the SL loop does
        return (params, opt_state, args[2], out_state)

    point = _measure(
        "sl", label, learner._train_step, args, feedback,
        batch_size * unroll_len, peak, iters,
    )
    point.update(batch=batch_size, unroll=unroll_len)
    if remat:
        point["remat"] = True
    if cap:
        point["max_entities"] = cap
    del learner
    return point


def _bench_sl_real(batch_size, unroll_len, peak, iters=6, cap=None):
    """SL throughput through the PRODUCTION data path: disk-backed
    ReplayDataset (synthetically generated decoded steps, same frozen
    contract as SC2 decode output) -> SLDataloader windowing/collate ->
    DevicePrefetcher double-buffer -> train step. Reports the host-side
    data_time share alongside frames/s — the number a fake in-memory
    dataloader overstates (reference: the sl_training dataloader path,
    SURVEY.md §2.3)."""
    import shutil
    import statistics
    import tempfile

    from distar_tpu.learner import SLLearner
    from distar_tpu.learner.hooks import LambdaHook
    from distar_tpu.learner.sl_dataloader import ReplayDataset, SLDataloader, make_fake_dataset

    cap = cap if cap is not None else _env_entity_cap()
    label = f"b{batch_size}xt{unroll_len}" + (f"-e{cap}" if cap else "")
    _stage(f"sl-real-dataset {label}")
    root = tempfile.mkdtemp(prefix="bench_sl_realdata_")
    try:
        make_fake_dataset(
            root,
            n_trajectories=max(2, batch_size // 2),
            steps_per_traj=unroll_len * 2,
            seed=0,
        )
        cfg = {
            "common": {"experiment_name": "bench_sl_real"},
            "learner": {
                "batch_size": batch_size,
                "unroll_len": unroll_len,
                "save_freq": 10 ** 9,
                "log_freq": 10 ** 9,
                "prefetch_depth": 2,
                "max_entities": cap if cap is not None else _env_entity_cap(),
            },
            "model": _bench_model_cfg(),
        }
        _stage(f"sl-real-init {label}")
        learner = SLLearner(cfg)
        learner.set_dataloader(SLDataloader(ReplayDataset(root), batch_size, unroll_len))
        # Host->device transfer probe: on the tunneled dev chip the fresh-batch
        # stream (not compute) can bound this point — measure it explicitly so
        # the frames/s number is interpretable. A real TPU host's local PCIe
        # moves the same bytes 1-2 orders of magnitude faster. The probe batch
        # comes off the learner's own dataloader (the dataset loops, so one
        # consumed batch costs nothing) rather than a duplicate pipeline.
        import jax
        import numpy as _np

        probe = dict(next(learner._dataloader))
        probe.pop("new_episodes", None)
        probe.pop("traj_lens", None)
        probe = learner._cap(probe)
        batch_bytes = sum(_np.asarray(x).nbytes for x in jax.tree.leaves(probe))
        t0 = time.perf_counter()
        placed = jax.device_put(probe)
        jax.block_until_ready(placed)
        h2d_s = time.perf_counter() - t0
        del placed, probe
        times = {"data": [], "train": []}

        def rec(lrn):
            # LogReduceHook (priority 10) folds log_buffer into the meters
            # and clears it before priority-50 hooks run; read the meters
            vr = lrn.variable_record
            times["data"].append(float(vr.get("data_time").val))
            times["train"].append(float(vr.get("train_time").val))

        learner.hooks.add(LambdaHook("bench_rec", "after_iter", rec, freq=1))
        _stage(f"sl-real-steps {label} (first iter compiles)")
        learner.run(max_iterations=iters)
        # drop compile/warmup iterations
        keep = slice(2, None) if len(times["train"]) > 3 else slice(1, None)
        data_t = statistics.fmean(times["data"][keep])
        train_t = statistics.fmean(times["train"][keep])
        total = data_t + train_t
        point = {
            "frames_per_sec": round(batch_size * unroll_len / total, 2),
            "step_time_s": round(train_t, 4),
            "data_time_s": round(data_t, 4),
            "data_time_share": round(data_t / total, 4),
            "batch": batch_size,
            "unroll": unroll_len,
            "iters_measured": len(times["train"][keep]),
            "batch_mb": round(batch_bytes / 1e6, 1),
            "h2d_s": round(h2d_s, 4),
            "h2d_mb_s": round(batch_bytes / 1e6 / max(h2d_s, 1e-9), 1),
            # the prefetcher overlaps H2D with compute, so per-iter wall is
            # max(compute, transfer) — the point is transfer-bound only when
            # the transfer time explains (nearly all of) the measured wall
            "transfer_bound": bool(h2d_s > 0.9 * train_t),
        }
        if cap:
            point["max_entities"] = cap
        del learner
        return point
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _bench_rl(batch_size, unroll_len, peak, iters=4, cap=None):
    import jax.numpy as jnp

    from distar_tpu.learner import RLLearner

    cfg = {
        "common": {"experiment_name": "bench_rl"},
        "learner": {
            "batch_size": batch_size,
            "unroll_len": unroll_len,
            "save_freq": 10 ** 9,
            "log_freq": 10 ** 9,
            "value_pretrain_iters": -1,
            "max_entities": cap if cap is not None else _env_entity_cap(),
        },
        "model": _bench_model_cfg(),
    }
    cap = cfg["learner"]["max_entities"]
    label = f"b{batch_size}xt{unroll_len}" + (f"-e{cap}" if cap else "")
    _stage(f"rl-init {label}")
    learner = RLLearner(cfg)
    data = dict(next(learner._dataloader))
    data.pop("model_last_iter", None)
    batch = learner.shard_batch(learner._cap(data))
    args = (learner.state["params"], learner.state["opt_state"], batch, jnp.asarray(False))

    def feedback(args, out):
        params, opt_state, _ = out
        return (params, opt_state, args[2], args[3])

    point = _measure(
        "rl", label, learner._train_step, args, feedback,
        batch_size * unroll_len, peak, iters,
    )
    point.update(
        batch=batch_size,
        unroll=unroll_len,
        steps_per_sec=round(1.0 / point["step_time_s"], 4),
    )
    if cap:
        point["max_entities"] = cap
    del learner
    return point


def _run_child_simulated(spec: str) -> None:
    """Harness-test seam (tests/test_bench.py): play back a scripted child —
    stages, sleeps, result lines — with no jax and no backend, so the
    parent's kill/extend/retry decisions are testable deterministically
    instead of via a real multi-minute cold compile (which is what made the
    round-4 harness test flaky under CPU oversubscription).

    ``spec``: ';'-separated per-attempt scripts, each a comma-separated op
    list — ``stage:<name>:<sleep_s>`` or ``result:<frames_per_sec>``. The
    attempt index persists in the BENCH_SIMULATE_STATE file (attempts past
    the last script replay the last one)."""
    scripts = spec.split(";")
    idx = 0
    state = os.environ.get("BENCH_SIMULATE_STATE")
    if state:
        try:
            with open(state) as f:
                idx = int(f.read().strip() or 0)
        except (OSError, ValueError):
            idx = 0
        with open(state, "w") as f:
            f.write(str(idx + 1))
    for op in filter(None, scripts[min(idx, len(scripts) - 1)].split(",")):
        parts = op.split(":")
        if parts[0] == "stage":
            _stage(parts[1])
            if len(parts) > 2:
                time.sleep(float(parts[2]))
        elif parts[0] == "result":
            fps = float(parts[1])
            print(
                json.dumps(
                    {
                        "metric": "SL replay-frames/sec/chip (simulated child)",
                        "value": fps,
                        "unit": "frames/s",
                        "vs_baseline": round(fps / SL_BASELINE_FRAMES, 3),
                        "sl": {"frames_per_sec": fps},
                        "sl_sweep": [],
                        "rl_sweep": [],
                    }
                ),
                flush=True,
            )


def bench_multichip() -> dict:
    """MULTICHIP scaling-efficiency case: step-time of the full executed
    sharded RL train step (live mesh, GSPMD, ShardFeeder) at dp=1 -> 2 -> 4
    on FORCED HOST DEVICES (``BENCH_MODE=multichip``; never claims the
    chip). Strong scaling at a fixed global batch: efficiency(k) =
    t(dp=1) / (k * t(dp=k)).

    SUSPECT-gated by construction, per the impossible-timing recheck
    policy: virtual CPU devices share the same host cores, so these numbers
    are STRUCTURAL evidence (the sharded path runs, collectives schedule,
    nothing serialises catastrophically) — never a silicon scaling claim.
    The artifact says so in-band (``suspect: true``) so no later reader can
    promote it."""
    # must precede the jax import/backend init in this child
    n_dev = int(os.environ.get("BENCH_MULTICHIP_DEVICES", 4))
    from distar_tpu.parallel.executor import force_host_devices, run_sharded_training

    force_host_devices(
        n_dev,
        cache_base=os.environ.get("BENCH_COMPILE_CACHE", "/tmp/jax_cache_distar_tpu_bench"),
    )
    iters = int(os.environ.get("BENCH_MULTICHIP_ITERS", 4))
    batch = int(os.environ.get("BENCH_MULTICHIP_BATCH", 4))
    unroll = int(os.environ.get("BENCH_MULTICHIP_UNROLL", 2))
    points = {}
    for dp in (1, 2, 4):
        if dp > n_dev:
            break
        _stage(f"multichip-dp{dp}")
        rep = run_sharded_training(
            f"dp={dp}", iters=iters, batch_size=batch, unroll_len=unroll,
            experiment_name=f"bench_multichip_dp{dp}", sharded_ckpt=False,
            max_devices=dp,
        )
        points[dp] = {
            "step_time_s": rep["step_time_s"],
            "step_times_s": rep["step_times_s"],
            "feeder_wait_s_mean": round(rep["feeder"].get("wait_s_mean", 0.0), 4),
            "mesh": rep["mesh"],
        }
    t1 = points.get(1, {}).get("step_time_s") or 0.0
    efficiency = {
        str(dp): round(t1 / (dp * p["step_time_s"]), 3)
        for dp, p in points.items()
        if p["step_time_s"]
    }
    out = {
        "metric": "MULTICHIP dp scaling efficiency (executed GSPMD step, host devices)",
        "value": efficiency.get("4", efficiency.get("2", 0.0)),
        "unit": "efficiency (1.0 = linear)",
        "vs_baseline": efficiency.get("4", efficiency.get("2", 0.0)),
        "suspect": True,
        "suspect_reason": (
            "CPU-derived: virtual host devices share the same cores, so "
            "scaling numbers are structural only (impossible-timing recheck "
            "policy) — a silicon claim needs the TPU campaign stages"
        ),
        "multichip": {
            "devices_forced": n_dev,
            "global_batch": batch,
            "unroll": unroll,
            "iters": iters,
            "points": points,
            "efficiency": efficiency,
        },
    }
    print(json.dumps(out), flush=True)
    return out


def run_child():
    if os.environ.get("BENCH_SIMULATE"):
        _run_child_simulated(os.environ["BENCH_SIMULATE"])
        return
    if os.environ.get("BENCH_MODE") == "multichip":
        # forced-host-device case: configures its own virtual platform
        # before the jax import — never claims the tunneled chip
        _start_heartbeat()
        try:
            bench_multichip()
        finally:
            _stop_heartbeat()
        return
    if os.environ.get("BENCH_MODE") == "replay":
        # pure host-side case: no jax import, no chip claim — the replay
        # plane is sockets + serializer and must be benchable anywhere
        _start_heartbeat()
        try:
            bench_replay()
        finally:
            _stop_heartbeat()
        return
    if os.environ.get("BENCH_MODE") == "distill":
        # FLOP-count case: traces on whatever backend jax gives this child
        # (CPU in CI) but never times it — the ratio is count arithmetic
        _start_heartbeat()
        try:
            bench_distill()
        finally:
            _stop_heartbeat()
        return
    if os.environ.get("BENCH_MODE") == "rollout":
        # pure host-side case too: mock engine + mock env measure the
        # rollout plane's dispatch/batching machinery, never the chip
        _start_heartbeat()
        try:
            bench_rollout()
        finally:
            _stop_heartbeat()
        return
    if os.environ.get("BENCH_MODE") == "anakin":
        # fused-vs-host A/B on host CPU (pins its own platform before any
        # device use) — architecture arithmetic, never claims the chip
        _start_heartbeat()
        try:
            bench_anakin()
        finally:
            _stop_heartbeat()
        return
    try:
        _run_child_real()
    finally:
        _stop_heartbeat()


def _run_child_real():
    _start_heartbeat()
    _stage("import-jax")
    import jax

    # persistent compile cache: the flagship train step is expensive to
    # compile; retries and later rounds must not pay it again. NOT when
    # called in-process from pytest: the harness tests must not repoint the
    # suite's live cache config mid-run (global jax state). A bench.py
    # SUBPROCESS spawned by a pytest-descended parent has its own jax state
    # and must still configure (argv distinguishes the two).
    in_pytest_process = (
        "PYTEST_CURRENT_TEST" in os.environ
        and os.path.basename(sys.argv[0]) != "bench.py"
    )
    if not in_pytest_process:
        # host-keyed: XLA:CPU AOT entries bake in the compiling machine's
        # features and this container migrates hosts (utils/compile_cache.py)
        from distar_tpu.utils.compile_cache import configure as _configure_cache

        _configure_cache(
            jax,
            os.environ.get("BENCH_COMPILE_CACHE", "/tmp/jax_cache_distar_tpu_bench"),
        )
    if os.environ.get("BENCH_PLATFORM"):
        # for CPU smoke tests of the harness itself: the image's
        # sitecustomize pins the platform via jax.config, so the
        # JAX_PLATFORMS env var alone is too late (see tests/conftest.py)
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    _stage("backend-init (chip claim; can block minutes when the relay is contended)")
    devices = jax.devices()
    device_kind = devices[0].device_kind
    _stage(f"devices-ok {device_kind}")
    peak = _peak_flops(device_kind)
    calibration = _calibrate_matmul(jax)

    budget = float(os.environ.get("BENCH_TIME_BUDGET", 10 ** 9))
    t0 = time.perf_counter()
    state = {
        "sl_best": None, "rl_best": None, "sl_real_best": None,
        "sl_sweep": [], "rl_sweep": [], "sl_real_sweep": [],
    }

    def emit():
        sl, rl = state["sl_best"], state["rl_best"]
        if sl is None and rl is None and state["sl_real_best"] is not None:
            # sl_real-only run: the real-data point IS a full train step —
            # make it the headline rather than a misleading 0.0
            point = state["sl_real_best"]
            headline_metric = "SL replay-frames/sec/chip (full model, real data path)"
            value = point["frames_per_sec"]
            vs = round(value / SL_BASELINE_FRAMES, 3)
        elif sl is not None or rl is None:
            headline_metric = "SL replay-frames/sec/chip (full model, fwd+loss+bwd+adam)"
            value = sl["frames_per_sec"] if sl else 0.0
            vs = round(value / SL_BASELINE_FRAMES, 3)
        else:
            # rl-only run: make the headline the RL number rather than a
            # misleading 0.0
            headline_metric = "RL learner frames/sec/chip (full train step)"
            value = rl["frames_per_sec"]
            vs = round(value / RL_BASELINE_FRAMES, 3)
        out = {
            "metric": headline_metric,
            "value": value,
            "unit": "frames/s",
            "vs_baseline": vs,
            "device": device_kind,
            "sl": sl,
            "sl_sweep": state["sl_sweep"],
            "rl_sweep": state["rl_sweep"],
        }
        if sl and "mfu" in sl:
            out["mfu"] = sl["mfu"]
        if calibration:
            out["calibration"] = calibration
        if state["sl_real_best"] is not None:
            out["sl_real_data"] = state["sl_real_best"]
        if rl:
            out["rl"] = dict(
                rl,
                vs_baseline_steps=round(rl["steps_per_sec"] / RL_BASELINE_STEPS, 3),
                vs_baseline_frames=round(rl["frames_per_sec"] / RL_BASELINE_FRAMES, 3),
            )
        print(json.dumps(out), flush=True)

    mode = os.environ.get("BENCH_MODE", "both")
    fns = {"sl": _bench_sl, "rl": _bench_rl, "sl_real": _bench_sl_real}
    if "BENCH_BATCH" in os.environ or "BENCH_UNROLL" in os.environ:
        kind = mode if mode in fns else "sl"
        plan = [(kind, int(os.environ.get("BENCH_BATCH", 6)), int(os.environ.get("BENCH_UNROLL", 64)))]
    else:
        plan = [
            # tiny probe first: lands a nonzero number before anything big
            ("sl", 2, 8),
            # baseline regime (reference per-A100 SL slice: batch 6 x traj
            # 64) at the 256-entity bucket — exact for real frame entity
            # counts and the strongest per-chip number (PERF.md) — then full
            ("sl", 6, 64, 256),
            ("sl", 6, 64),
            ("rl", 6, 64, 256),
            ("rl", 6, 64),
            # production data path: disk dataset + windowing + prefetch
            ("sl_real", 6, 64),
            # push batch toward the HBM limit (bucketed: bigger batches fit)
            ("sl", 16, 64, 256),
            # remat A/B at the same shape: if b16's ~0.65s/step cliff is
            # activation spill, recompute should step around it
            ("sl", 16, 64, 256, True),
            ("sl", 32, 64, 256),
            ("rl", 12, 64),
        ]
        if _env_entity_cap() is not None:
            # an explicit BENCH_MAX_ENTITIES governs every config: drop the
            # plan's own buckets (they would duplicate whole compiles). The
            # remat flag stays part of the identity — remat compiles differ.
            seen = set()
            deduped = []
            for p in plan:
                key = (p[0], p[1], p[2], bool(p[4]) if len(p) > 4 else False)
                if key in seen:
                    continue
                seen.add(key)
                deduped.append((p[0], p[1], p[2], None, key[3]))
            plan = deduped
        if mode in fns:
            plan = [p for p in plan if p[0] == mode]

    def out_of_budget():
        have_any = state["sl_best"] or state["rl_best"] or state["sl_real_best"]
        return bool(have_any) and time.perf_counter() - t0 > budget

    for entry in plan:
        kind, b, t = entry[:3]
        cap = entry[3] if len(entry) > 3 else None
        plan_remat = bool(entry[4]) if len(entry) > 4 else False
        if out_of_budget():
            break
        try:
            kwargs = {"cap": cap}
            if plan_remat and kind == "sl":
                kwargs["remat"] = True
            point = fns[kind](b, t, peak, **kwargs)
        except Exception as e:  # OOM at the top of the sweep is expected
            err = {"batch": b, "unroll": t, "error": repr(e)[:300]}
            if cap:
                err["max_entities"] = cap
            if plan_remat:
                err["remat"] = True
            state[f"{kind}_sweep"].append(err)
            print(f"BENCH-STAGE {kind}-failed b{b}xt{t}: {e!r}"[:400], file=sys.stderr, flush=True)
            already_remat = _env_truthy("BENCH_REMAT") or plan_remat
            if (
                kind == "sl"
                and "RESOURCE_EXHAUSTED" in repr(e)
                and not already_remat  # retry would rebuild the same config
                and not out_of_budget()  # a fresh trace+compile won't fit
            ):
                # HBM edge: retry once with rematerialization — recompute
                # buys the activations back and the config may fit
                try:
                    point = _bench_sl(b, t, peak, remat=True, cap=cap)
                except Exception as e2:
                    retry_err = {"batch": b, "unroll": t, "remat": True,
                                 "error": repr(e2)[:300]}
                    if cap:
                        retry_err["max_entities"] = cap
                    state["sl_sweep"].append(retry_err)
                    continue
            else:
                continue
        state[f"{kind}_sweep"].append(point)
        best = state[f"{kind}_best"]
        if best is None or point["frames_per_sec"] > best["frames_per_sec"]:
            state[f"{kind}_best"] = point
        emit()

    if not (state["sl_best"] or state["rl_best"] or state["sl_real_best"]):
        raise RuntimeError(f"no config completed: {state}")


# -------------------------------------------------------------------- parent


def main():
    # Defaults are sized to the DRIVER's observed kill window (~600 s,
    # BENCH_r03 rc=124): finish under it with margin. A local long-haul run
    # overrides via env (e.g. BENCH_DEADLINE=7200).
    deadline = time.monotonic() + float(os.environ.get("BENCH_DEADLINE", 540.0))
    # per-attempt cap so one child hung in the chip claim doesn't eat the
    # whole deadline — a lingering previous holder needs time to expire, and
    # a fresh claim sometimes lands where the stuck one never will
    attempt_timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", 240.0))
    backoff = 20.0
    last_result = [None]  # last full result line relayed from a child
    last_stage = ["(no stage reached)"]
    stderr_tail = []
    stdout_lock = threading.Lock()  # pump + heartbeat both write result lines

    def emit_line(line):
        with stdout_lock:
            sys.stdout.write(line + "\n")
            sys.stdout.flush()

    def pump(stream, is_stdout, first_line_t):
        # first_line_t is THIS attempt's stamp cell: a pump surviving its
        # child (grandchild holding the pipe) must not stamp a later
        # attempt's clock
        for line in iter(stream.readline, ""):
            line = line.rstrip("\n")
            if not line:
                continue
            if first_line_t[0] is None:
                first_line_t[0] = time.monotonic()
            if is_stdout:
                try:
                    parsed = json.loads(line)
                except (json.JSONDecodeError, ValueError):
                    continue
                if isinstance(parsed, dict) and "metric" in parsed:
                    last_result[0] = line
                    # re-print immediately: the harness keeps the tail
                    emit_line(line)
            else:
                if line.startswith("BENCH-STAGE"):
                    last_stage[0] = line
                stderr_tail.append(line[:500])
                del stderr_tail[:-40]
        stream.close()

    def parent_heartbeat():
        # Print a parseable JSON line every ~60 s: if the driver SIGKILLs the
        # whole tree, the artifact tail still carries a diagnostic (or the
        # freshest real result) instead of being empty (BENCH_r03 postmortem).
        n = 0
        while True:
            time.sleep(60)
            n += 1
            # decide under the lock: a real result landing between the check
            # and the write must never be followed by a fake 0.0 tail line
            with stdout_lock:
                if last_result[0] is not None:
                    line = last_result[0]
                else:
                    line = json.dumps(
                        {
                            "metric": "SL replay-frames/sec/chip (full model, fwd+loss+bwd+adam)",
                            "value": 0.0,
                            "unit": "frames/s",
                            "vs_baseline": 0.0,
                            "heartbeat": n,
                            "stage": last_stage[0],
                        }
                    )
                sys.stdout.write(line + "\n")
                sys.stdout.flush()

    threading.Thread(target=parent_heartbeat, daemon=True).start()

    attempt = 0
    while time.monotonic() < deadline - 30:
        attempt += 1
        # judge each child on its own progress, not its predecessor's
        last_stage[0] = "(no stage reached)"
        first_line_t = [None]  # fresh cell per attempt (see pump)
        child_env = dict(os.environ)
        # respect an explicit user budget; otherwise hand the child what's
        # left of the parent deadline so its sweep self-limits
        child_env.setdefault(
            "BENCH_TIME_BUDGET", str(max(60.0, deadline - time.monotonic() - 60.0))
        )
        proc = subprocess.Popen(
            [sys.executable, "-u", os.path.abspath(__file__), "--run"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=child_env,
        )
        threads = [
            threading.Thread(target=pump, args=(proc.stdout, True, first_line_t),
                             daemon=True),
            threading.Thread(target=pump, args=(proc.stderr, False, first_line_t),
                             daemon=True),
        ]
        for th in threads:
            th.start()
        # the attempt clock starts when the child first SPEAKS, not when it
        # forks: on a saturated host interpreter startup alone can exceed
        # the attempt timeout, and killing a child that never got to run
        # wastes claim attempts. A silent child gets a bounded boot grace —
        # capped so a wedged-before-output child still leaves retry budget
        # inside the deadline (3x matters for test-scale timeouts, +60 s
        # for driver-scale ones).
        attempt_start = time.monotonic()
        silent_grace = min(3 * attempt_timeout, attempt_timeout + 60.0)
        timed_out = False
        while True:
            try:
                proc.wait(timeout=1.0)
                break
            except subprocess.TimeoutExpired:
                now = time.monotonic()
                if now >= deadline - 10:
                    timed_out = True
                    break
                base = first_line_t[0]
                expiry = (
                    base + attempt_timeout
                    if base is not None
                    else attempt_start + silent_grace
                )
                if now >= expiry:
                    timed_out = True
                    break
        if timed_out:
            # a child stuck in the chip claim should die fast (a FRESH claim
            # sometimes lands where the stuck one never will) — but one that
            # is past backend-init is tracing/compiling: killing it mid-
            # compile caches nothing and the retry repeats the same compile
            # (livelock). Let progressing children use the whole deadline.
            stuck = last_result[0] is None and (
                last_stage[0] == "(no stage reached)"
                or "import-jax" in last_stage[0]
                or "backend-init" in last_stage[0]
            )
            if not stuck:
                try:
                    proc.wait(timeout=max(5.0, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    pass
            proc.kill()
            proc.wait()
        for th in threads:
            th.join(timeout=5)
        if last_result[0] is not None:
            return  # best result already on stdout (streamed by pump)
        if time.monotonic() >= deadline - 30:
            break
        time.sleep(min(backoff, max(0.0, deadline - time.monotonic() - 30)))
        backoff *= 2

    if last_result[0] is None:
        emit_line(
            json.dumps(
                {
                    "metric": "SL replay-frames/sec/chip (full model, fwd+loss+bwd+adam)",
                    "value": 0.0,
                    "unit": "frames/s",
                    "vs_baseline": 0.0,
                    "error": f"no config completed in {attempt} attempt(s); "
                    f"last stage: {last_stage[0]}",
                    "stderr_tail": stderr_tail[-12:],
                }
            )
        )


if __name__ == "__main__":
    if "--run" in sys.argv:
        run_child()
    else:
        main()
