"""Benchmark: SL learner throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Metric: supervised-learning replay-frames/sec on a single chip with the FULL
flagship model (the reference's headline SL number is ~384 frames/s per A100
— 56xA100, total batch 336 x traj 64 at ~1s/iter; see BASELINE.md). A frame
is one (obs, action) trajectory step through forward+loss+backward+adam.

Robustness (round-1 postmortem: BENCH_r01 died in TPU backend init with no
number at all): the measurement runs in a child process; the parent retries
with backoff on init failures (the single tunneled chip admits one client at
a time and a previous holder may linger) and ALWAYS prints a parseable JSON
line — a diagnostic one with value 0 if every attempt fails.

The child sweeps batch sizes at trajectory length 64 (the regime the
baseline numbers live in) up to a time budget and reports the best
operating point, plus an MFU estimate from XLA's own cost analysis.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_FRAMES_PER_SEC_PER_CHIP = 384.0  # A100, reference large-scale SL

# peak bf16 matmul throughput per chip, for the MFU estimate
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v5": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def _peak_flops(device_kind: str):
    kind = device_kind.lower()
    best = None
    for name, peak in _PEAK_FLOPS.items():
        if name in kind and (best is None or len(name) > best[0]):
            best = (len(name), peak)
    return best[1] if best else None


def _bench_config(batch_size: int, unroll_len: int, iters: int = 4):
    import jax

    from distar_tpu.learner import SLLearner

    cfg = {
        "common": {"experiment_name": "bench_sl"},
        "learner": {
            "batch_size": batch_size,
            "unroll_len": unroll_len,
            "save_freq": 10 ** 9,
            "log_freq": 10 ** 9,
        },
        # bfloat16 matmuls/convs on the MXU (params stay f32)
        "model": {"dtype": "bfloat16"},
    }
    learner = SLLearner(cfg)

    data = next(learner._dataloader)
    learner._train(dict(data))  # warmup (compile)
    jax.block_until_ready(learner.state["params"])

    start = time.perf_counter()
    for _ in range(iters):
        learner._train(dict(data))
    jax.block_until_ready(learner.state["params"])
    elapsed = time.perf_counter() - start
    frames_per_sec = batch_size * unroll_len * iters / elapsed

    flops_per_step = None
    try:
        batch = {k: v for k, v in dict(data).items() if k not in ("new_episodes", "traj_lens")}
        batch = jax.tree.map(jax.numpy.asarray, batch)
        lowered = learner._train_step.lower(
            learner.state["params"], learner.state["opt_state"], batch, learner._hidden
        )
        # unoptimized-HLO flops straight off the Lowered — adequate for an
        # MFU estimate and avoids a second multi-minute XLA compile
        cost = lowered.cost_analysis()
        if cost:
            flops_per_step = float(cost.get("flops", 0.0)) or None
    except Exception:
        pass

    del learner
    return frames_per_sec, elapsed / iters, flops_per_step


def _stage(name: str) -> None:
    # breadcrumbs on stderr: when an attempt times out, the parent reports
    # the LAST stage reached so the diagnostic says where it stalled
    # (round-1 postmortem: "rc=1" with no location)
    print(f"BENCH-STAGE {name} t={time.time():.0f}", file=sys.stderr, flush=True)


def run_child():
    _stage("import-jax")
    import jax

    # persistent compile cache: the flagship train step costs minutes to
    # compile through the tunneled chip; retries and later rounds must not
    # pay it again
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_distar_tpu_bench")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    _stage("backend-init")
    devices = jax.devices()
    device_kind = devices[0].device_kind
    _stage(f"devices-ok {device_kind}")
    peak = _peak_flops(device_kind)

    if "BENCH_BATCH" in os.environ or "BENCH_UNROLL" in os.environ:
        configs = [(int(os.environ.get("BENCH_BATCH", 6)), int(os.environ.get("BENCH_UNROLL", 64)))]
    else:
        # sweep toward the HBM-limited batch; baseline regime is traj 64
        # (reference per-A100 slice: batch 6 x traj 64)
        configs = [(6, 64), (16, 64), (32, 64)]
    budget = float(os.environ.get("BENCH_TIME_BUDGET", 420.0))

    t0 = time.perf_counter()
    best = None
    sweep = []

    def emit(b):
        # one full result line per completed config: if the parent kills us
        # mid-sweep, the best-so-far measurement still reaches stdout
        out = {
            "metric": "SL replay-frames/sec/chip (full model, fwd+loss+bwd+adam)",
            "value": b["frames_per_sec"],
            "unit": "frames/s",
            "vs_baseline": round(b["frames_per_sec"] / BASELINE_FRAMES_PER_SEC_PER_CHIP, 3),
            "device": device_kind,
            "batch": b["batch"],
            "unroll": b["unroll"],
            "sweep": list(sweep),
        }
        if "mfu" in b:
            out["mfu"] = b["mfu"]
        print(json.dumps(out), flush=True)

    for batch_size, unroll_len in configs:
        if best is not None and time.perf_counter() - t0 > budget:
            break
        try:
            fps, step_time, flops = _bench_config(batch_size, unroll_len)
        except Exception as e:  # OOM at the top of the sweep is expected
            sweep.append({"batch": batch_size, "unroll": unroll_len, "error": repr(e)[:200]})
            break
        point = {
            "batch": batch_size,
            "unroll": unroll_len,
            "frames_per_sec": round(fps, 2),
            "step_time_s": round(step_time, 4),
        }
        if flops and peak:
            point["mfu"] = round(flops / step_time / peak, 4)
        sweep.append(point)
        if best is None or fps > best["frames_per_sec"]:
            best = point
        emit(best)

    if best is None:
        raise RuntimeError(f"no config completed: {sweep}")


def main():
    deadline = time.monotonic() + float(os.environ.get("BENCH_DEADLINE", 1500.0))
    attempt_timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", 900.0))
    backoff = 20.0
    last_err = ""

    def scan_for_result(stdout) -> bool:
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", "replace")
        for line in reversed((stdout or "").strip().splitlines()):
            try:
                parsed = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(parsed, dict) and "metric" in parsed:
                print(line)
                return True
        return False

    for attempt in range(4):
        remaining = deadline - time.monotonic()
        if remaining <= 60:
            break
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--run"],
                capture_output=True,
                text=True,
                timeout=min(attempt_timeout, remaining),
            )
        except subprocess.TimeoutExpired as e:
            # the child emits a result line per completed config — salvage
            # the best-so-far even when the sweep hung partway
            if scan_for_result(e.stdout):
                return
            last_err = f"attempt {attempt}: timeout after {e.timeout}s"
            continue
        if scan_for_result(proc.stdout):
            return
        last_err = (
            f"attempt {attempt}: rc={proc.returncode} "
            f"stderr_tail={proc.stderr[-1500:]!r} stdout_tail={proc.stdout[-300:]!r}"
        )
        if attempt < 3:
            time.sleep(min(backoff, max(0.0, deadline - time.monotonic())))
            backoff *= 2
    print(
        json.dumps(
            {
                "metric": "SL replay-frames/sec/chip (full model, fwd+loss+bwd+adam)",
                "value": 0.0,
                "unit": "frames/s",
                "vs_baseline": 0.0,
                "error": last_err[-2000:],
            }
        )
    )


if __name__ == "__main__":
    if "--run" in sys.argv:
        run_child()
    else:
        main()
