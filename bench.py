"""Benchmark: SL learner throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: supervised-learning replay-frames/sec on a single chip with the FULL
flagship model (the reference's headline SL number is ~384 frames/s per A100
— 56xA100, total batch 336 x traj 64 at ~1s/iter; see BASELINE.md). A frame
is one (obs, action) trajectory step through forward+loss+backward+adam.
"""
from __future__ import annotations

import json
import time


def main():
    import jax

    from distar_tpu.learner import SLLearner

    BASELINE_FRAMES_PER_SEC_PER_CHIP = 384.0  # A100, reference large-scale SL

    import os

    batch_size = int(os.environ.get("BENCH_BATCH", 4))
    unroll_len = int(os.environ.get("BENCH_UNROLL", 16))
    cfg = {
        "common": {"experiment_name": "bench_sl"},
        "learner": {
            "batch_size": batch_size,
            "unroll_len": unroll_len,
            "save_freq": 10 ** 9,
            "log_freq": 10 ** 9,
        },
        # bfloat16 matmuls/convs on the MXU (params stay f32)
        "model": {"dtype": "bfloat16"},
    }
    learner = SLLearner(cfg)

    # warmup (compile)
    data = next(learner._dataloader)
    learner._train(dict(data))
    jax.block_until_ready(learner.state["params"])

    iters = 4
    start = time.perf_counter()
    for _ in range(iters):
        learner._train(dict(data))
    jax.block_until_ready(learner.state["params"])
    elapsed = time.perf_counter() - start

    frames_per_sec = batch_size * unroll_len * iters / elapsed
    print(
        json.dumps(
            {
                "metric": "SL replay-frames/sec/chip (full model, fwd+loss+bwd+adam)",
                "value": round(frames_per_sec, 2),
                "unit": "frames/s",
                "vs_baseline": round(frames_per_sec / BASELINE_FRAMES_PER_SEC_PER_CHIP, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
