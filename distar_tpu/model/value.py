"""Per-baseline value towers (role of reference model/value.py:9-39)."""
from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from ..ops import FCBlock
from ..ops.blocks import ResFCBlock2

PI = 3.141592653589793


class ValueBaseline(nn.Module):
    """fc -> res_num x post-norm ResFC2 -> scalar; optional atan squash into
    (-1, 1). The tower uses the reference's ResFCBlock2 topology
    (LN(x + fc(fc_relu(x))), res_block.py:110-139)."""

    res_dim: int = 256
    res_num: int = 16
    norm_type: str = "LN"
    atan: bool = False
    dtype: object = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = FCBlock(self.res_dim, "relu", dtype=self.dtype)(x)
        for _ in range(self.res_num):
            x = ResFCBlock2(self.res_dim, "relu", dtype=self.dtype)(x)
        v = nn.Dense(
            1,
            dtype=self.dtype,
            kernel_init=nn.initializers.variance_scaling(0.01, "fan_in", "truncated_normal"),
        )(x)
        v = v[..., 0].astype(jnp.float32)
        if self.atan:
            v = (2.0 / PI) * jnp.arctan((PI / 2.0) * v)
        return v
