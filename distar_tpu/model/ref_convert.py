"""Reference (torch) state_dict -> Flax params conversion.

Maps the reference model's recorded weights onto this framework's modules so
(a) golden parity tests can pin our numerics to the reference's
(tests/test_golden_parity.py; VERDICT round-1 weak #7) and (b) reference
pretrained checkpoints can seed training here.

Layout rules:
  torch Linear weight [out, in]      -> flax Dense kernel [in, out] (transpose)
  torch Conv2d weight [O, I, kh, kw] -> flax Conv kernel [kh, kw, I, O]
  torch LayerNorm weight/bias        -> flax LayerNorm scale/bias
  torch NCHW flatten (view(B, -1))   -> our NHWC flatten: fc kernels over
                                        flattened conv maps are re-ordered
                                        (C,H,W) -> (H,W,C) row-wise
  reference one-hot-concat @ W       -> our per-field Embed/Dense params are
                                        ROW SLICES of W^T at each field's
                                        column offset (entity encoder)
"""
from __future__ import annotations

from typing import Dict

import numpy as np

__all__ = [
    "convert_lnlstm",
    "convert_entity_encoder",
    "convert_scalar_encoder",
    "convert_spatial_encoder",
    "convert_action_type_head",
    "convert_delay_head",
    "convert_queued_head",
    "convert_selected_units_head",
    "convert_target_unit_head",
    "convert_location_head",
    "convert_value_baseline",
]


def _t(w) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(w).T)


def _ln(sd: Dict, prefix: str) -> Dict:
    return {"scale": np.asarray(sd[f"{prefix}.weight"]), "bias": np.asarray(sd[f"{prefix}.bias"])}


def _fc(sd: Dict, prefix: str) -> Dict:
    """reference fc_block -> {FCBlock}/Dense_0 params."""
    return {
        "Dense_0": {
            "kernel": _t(sd[f"{prefix}.0.weight"]),
            "bias": np.asarray(sd[f"{prefix}.0.bias"]),
        }
    }


def _dense(sd: Dict, prefix: str) -> Dict:
    return {"kernel": _t(sd[f"{prefix}.0.weight"]), "bias": np.asarray(sd[f"{prefix}.0.bias"])}


def _conv(sd: Dict, prefix: str) -> Dict:
    """reference conv2d_block -> flax Conv params (inside Conv2DBlock)."""
    w = np.asarray(sd[f"{prefix}.0.weight"])  # [O, I, kh, kw]
    return {
        "Conv_0": {
            "kernel": np.ascontiguousarray(w.transpose(2, 3, 1, 0)),
            "bias": np.asarray(sd[f"{prefix}.0.bias"]),
        }
    }


def _nchw_fc_kernel(w: np.ndarray, c: int, h: int, wdt: int) -> np.ndarray:
    """torch fc over an NCHW flatten -> kernel for our NHWC flatten."""
    out_dim = w.shape[0]
    k = w.reshape(out_dim, c, h, wdt).transpose(2, 3, 1, 0)  # H, W, C, out
    return np.ascontiguousarray(k.reshape(h * wdt * c, out_dim))


def _transformer_layer(sd: Dict, prefix: str, mlp_num: int = 2) -> Dict:
    out = {
        "Attention_0": {
            "Dense_0": {
                "kernel": _t(sd[f"{prefix}.attention.attention_pre.0.weight"]),
                "bias": np.asarray(sd[f"{prefix}.attention.attention_pre.0.bias"]),
            },
            "Dense_1": {
                "kernel": _t(sd[f"{prefix}.attention.project.0.weight"]),
                "bias": np.asarray(sd[f"{prefix}.attention.project.0.bias"]),
            },
        },
        "LayerNorm_0": _ln(sd, f"{prefix}.layernorm1"),
        "LayerNorm_1": _ln(sd, f"{prefix}.layernorm2"),
    }
    for i in range(mlp_num):
        out[f"FCBlock_{i}"] = _fc(sd, f"{prefix}.mlp.{i}")
    return out


def _transformer(sd: Dict, prefix: str, layer_num: int = 3, mlp_num: int = 2) -> Dict:
    """reference module_utils.Transformer (embedding fc + layers) -> our
    ops.Transformer params (FCBlock_0 embedding + TransformerLayer_i)."""
    out = {"FCBlock_0": _fc(sd, f"{prefix}.embedding")}
    for i in range(layer_num):
        out[f"TransformerLayer_{i}"] = _transformer_layer(sd, f"{prefix}.layers.{i}", mlp_num)
    return out


def _fc_ln(sd: Dict, prefix: str) -> Dict:
    """reference fc_block with norm -> FCBlock{Dense_0, LayerNorm_0}."""
    out = _fc(sd, prefix)
    out["LayerNorm_0"] = _ln(sd, f"{prefix}.1")
    return out


def _res_fc(sd: Dict, prefix: str) -> Dict:
    """reference ResFCBlock (norm per fc) -> ops.ResFCBlock."""
    return {"FCBlock_0": _fc_ln(sd, f"{prefix}.fc1"), "FCBlock_1": _fc_ln(sd, f"{prefix}.fc2")}


def _glu(sd: Dict, prefix: str) -> Dict:
    """reference GLU (layer1 = context gate, layer2 = output) -> ops.GLU."""
    return {
        "Dense_0": {"kernel": _t(sd[f"{prefix}.layer1.0.weight"]), "bias": np.asarray(sd[f"{prefix}.layer1.0.bias"])},
        "Dense_1": {"kernel": _t(sd[f"{prefix}.layer2.0.weight"]), "bias": np.asarray(sd[f"{prefix}.layer2.0.bias"])},
    }


def convert_lnlstm(sd: Dict, num_layers: int) -> Dict:
    """reference script_lnlstm state_dict -> ops.lstm.StackedLSTM params."""
    params = {}
    for i in range(num_layers):
        p = f"layers.{i}.cell"
        params[f"layer{i}"] = {
            "ih": {"kernel": _t(sd[f"{p}.weight_ih"])},
            "hh": {"kernel": _t(sd[f"{p}.weight_hh"])},
            "ln_ih": _ln(sd, f"{p}.layernorm_i"),
            "ln_hh": _ln(sd, f"{p}.layernorm_h"),
            "ln_c": _ln(sd, f"{p}.layernorm_c"),
        }
    return {"params": params}


def convert_entity_encoder(sd: Dict, cfg) -> Dict:
    """reference EntityEncoder state_dict -> model.encoders.EntityEncoder.

    The reference materialises each entity as a 997-wide one-hot/binary/raw
    concat and projects with transformer.embedding (fc 997->256,
    entity_encoder.py:59-80); our per-field embedding-sum is the same map
    with W^T split row-wise at each field's column offset."""
    ent = cfg.encoder.entity
    W = np.asarray(sd["transformer.embedding.0.weight"])  # [width, total]
    bias = np.asarray(sd["transformer.embedding.0.bias"])
    params = {"ent_embed_bias": bias}
    off = 0
    for key, arc, n in ent.fields:
        span = {"one_hot": n, "binary": n, "float": 1}[arc]
        block = _t(W[:, off : off + span])  # [span, width]
        if arc == "one_hot":
            params[f"ent_{key}"] = {"embedding": block}
        else:
            params[f"ent_{key}"] = {"kernel": block}
        off += span
    assert off == W.shape[1], f"field widths {off} != embedding input {W.shape[1]}"

    for i in range(ent.layer_num):
        params[f"TransformerLayer_{i}"] = _transformer_layer(
            sd, f"transformer.layers.{i}", ent.mlp_num
        )
    params["entity_fc"] = _fc(sd, "entity_fc")
    params["embed_fc"] = _fc(sd, "embed_fc")
    return {"params": params}


def _bo_encoder(sd: Dict, prefix: str) -> Dict:
    return {
        "Transformer_0": _transformer(sd, f"{prefix}.transformer"),
        "FCBlock_0": _fc(sd, f"{prefix}.embedd_fc"),
    }


def convert_scalar_encoder(sd: Dict, cfg) -> Dict:
    """reference ScalarEncoder state_dict -> model.encoders.ScalarEncoder."""
    params = {}
    for key, arc, _n, _out, _ctx, _base in cfg.encoder.scalar.fields:
        if arc == "one_hot":
            params[f"embed_{key}"] = {
                "embedding": np.asarray(sd[f"encode_modules.{key}.weight"])
            }
        elif arc == "fc":
            params[f"fc_{key}"] = _fc(sd, f"encode_modules.{key}")
        elif arc == "bo_transformer":
            params["bo_encoder"] = _bo_encoder(sd, f"encode_modules.{key}")
    return {"params": params}


def convert_spatial_encoder(sd: Dict, cfg) -> Dict:
    """reference SpatialEncoder state_dict -> model.encoders.SpatialEncoder.

    Ours auto-names blocks in call order: Conv2DBlock_0 (project), then one
    Conv2DBlock per downsample, then ResBlock_i, then FCBlock_0 (head). The
    fc head's kernel is re-ordered for the NHWC flatten."""
    sp = cfg.encoder.spatial
    params = {"Conv2DBlock_0": _conv(sd, "project")}
    for i in range(len(sp.down_channels)):
        params[f"Conv2DBlock_{i + 1}"] = _conv(sd, f"downsample.{i}")
    for i in range(sp.resblock_num):
        params[f"ResBlock_{i}"] = {
            "Conv2DBlock_0": _conv(sd, f"res.{i}.conv1"),
            "Conv2DBlock_1": _conv(sd, f"res.{i}.conv2"),
        }
    c = sp.down_channels[-1]
    h = cfg.static.spatial_y // (2 ** len(sp.down_channels)) if hasattr(cfg, "static") else None
    # head fc: torch flattens NCHW, ours NHWC
    w = np.asarray(sd["fc.0.weight"])
    hw = w.shape[1] // c
    # infer H from the known aspect (H/W ratio preserved through /8 pooling)
    from ..lib.features import SPATIAL_SIZE

    H = SPATIAL_SIZE[0] // (2 ** len(sp.down_channels))
    W_ = SPATIAL_SIZE[1] // (2 ** len(sp.down_channels))
    assert H * W_ == hw, (H, W_, hw)
    params["FCBlock_0"] = {
        "Dense_0": {"kernel": _nchw_fc_kernel(w, c, H, W_), "bias": np.asarray(sd["fc.0.bias"])}
    }
    return {"params": params}


def convert_action_type_head(sd: Dict, cfg) -> Dict:
    """reference ActionTypeHead -> model.heads.ActionTypeHead."""
    hc = cfg.policy.action_type_head
    params = {"FCBlock_0": _fc(sd, "project")}
    for i in range(hc.res_num):
        params[f"ResFCBlock_{i}"] = _res_fc(sd, f"res.{i}")
    params["action_glu"] = _glu(sd, "action_fc")
    params["FCBlock_1"] = _fc(sd, "action_map_fc1")
    params["FCBlock_2"] = _fc(sd, "action_map_fc2")
    params["glu1"] = _glu(sd, "glu1")
    params["glu2"] = _glu(sd, "glu2")
    return {"params": params}


def _fc_chain(sd: Dict, names) -> Dict:
    return {f"FCBlock_{i}": _fc(sd, name) for i, name in enumerate(names)}


def convert_delay_head(sd: Dict, cfg) -> Dict:
    return {"params": _fc_chain(sd, ["fc1", "fc2", "fc3", "embed_fc1", "embed_fc2"])}


def convert_queued_head(sd: Dict, cfg) -> Dict:
    return {"params": _fc_chain(sd, ["fc1", "fc2", "fc3", "embed_fc1", "embed_fc2"])}


def convert_selected_units_head(sd: Dict, cfg) -> Dict:
    hc = cfg.policy.selected_units_head
    params = {
        "key_fc": _fc(sd, "key_fc"),
        "query_fc1": _fc(sd, "query_fc1"),
        "query_fc2": _fc(sd, "query_fc2"),
        "embed_fc1": _fc(sd, "embed_fc1"),
        "embed_fc2": _fc(sd, "embed_fc2"),
        "end_embedding": np.asarray(sd["end_embedding"]).reshape(-1),
    }
    for i in range(hc.get("num_layers", 1)):
        p = f"lstm.layers.{i}.cell"
        params[f"lstm{i}"] = {
            "ih": {"kernel": _t(sd[f"{p}.weight_ih"])},
            "hh": {"kernel": _t(sd[f"{p}.weight_hh"])},
            "ln_ih": _ln(sd, f"{p}.layernorm_i"),
            "ln_hh": _ln(sd, f"{p}.layernorm_h"),
            "ln_c": _ln(sd, f"{p}.layernorm_c"),
        }
    return {"params": params}


def convert_target_unit_head(sd: Dict, cfg) -> Dict:
    return {"params": _fc_chain(sd, ["key_fc", "query_fc1", "query_fc2"])}


def convert_location_head(sd: Dict, cfg) -> Dict:
    """reference LocationHead (gate=True, bilinear upsample) ->
    model.heads.LocationHead. project_embed's output feeds a channel-FIRST
    reshape in the reference and channel-LAST in ours, so its rows are
    re-ordered (C,H,W) -> (H,W,C)."""
    hc = cfg.policy.location_head
    from ..lib.features import SPATIAL_SIZE

    H8, W8 = SPATIAL_SIZE[0] // 8, SPATIAL_SIZE[1] // 8
    c = hc.reshape_channel
    w = np.asarray(sd["project_embed.0.weight"])  # [C*H8*W8, in]
    b = np.asarray(sd["project_embed.0.bias"])
    w = w.reshape(c, H8, W8, -1).transpose(1, 2, 0, 3).reshape(c * H8 * W8, -1)
    b = b.reshape(c, H8, W8).transpose(1, 2, 0).reshape(-1)
    params = {
        "FCBlock_0": {"Dense_0": {"kernel": _t(w), "bias": b}},
        "Conv2DBlock_0": _conv(sd, "conv1"),
    }
    for i in range(hc.res_num):
        block = {
            "Conv2DBlock_0": _conv(sd, f"res.{i}.conv1"),
            "Conv2DBlock_1": _conv(sd, f"res.{i}.conv2"),
            "update_sp": np.asarray(sd[f"res.{i}.UpdateSP"]),
        }
        for g in range(4):
            block[f"Conv2DBlock_{g + 2}"] = _conv(sd, f"res.{i}.GateWeightG.{g}")
        params[f"GatedResBlock_{i}"] = block
    for i in range(len(hc.upsample_dims)):
        params[f"Conv2DBlock_{i + 1}"] = _conv(sd, f"upsample.{i}")
    return {"params": params}


def convert_value_baseline(sd: Dict, res_num: int) -> Dict:
    params = {"FCBlock_0": _fc(sd, "project")}
    for i in range(res_num):
        params[f"ResFCBlock2_{i}"] = {
            "FCBlock_0": _fc(sd, f"res.{i}.fc1"),
            "FCBlock_1": _fc(sd, f"res.{i}.fc2"),
            "LayerNorm_0": _ln(sd, f"res.{i}.norm"),
        }
    params["Dense_0"] = {
        "kernel": _t(sd["value_fc.0.weight"]),
        "bias": np.asarray(sd["value_fc.0.bias"]),
    }
    return {"params": params}


def _subdict(sd: Dict, prefix: str) -> Dict:
    p = prefix + "."
    return {k[len(p):]: v for k, v in sd.items() if k.startswith(p)}


def convert_model(sd: Dict, cfg) -> Dict:
    """Full reference Model state_dict -> our Model params.

    Accepts raw reference checkpoints: 'model.'/'module.' prefixes are
    stripped. Value towers present in the state dict are converted under
    their value_<name> modules; the value encoder is not yet mapped."""
    for strip in ("model.", "module."):
        if any(k.startswith(strip) for k in sd):
            sd = {k[len(strip):] if k.startswith(strip) else k: v for k, v in sd.items()}

    params = {
        "encoder": {
            "scalar_encoder": convert_scalar_encoder(_subdict(sd, "encoder.scalar_encoder"), cfg)["params"],
            "entity_encoder": convert_entity_encoder(_subdict(sd, "encoder.entity_encoder"), cfg)["params"],
            "spatial_encoder": convert_spatial_encoder(_subdict(sd, "encoder.spatial_encoder"), cfg)["params"],
            "FCBlock_0": _fc(_subdict(sd, "encoder"), "scatter_project"),
        },
        "core_lstm": convert_lnlstm(_subdict(sd, "core_lstm"), cfg.encoder.core_lstm.num_layers)["params"],
        "policy": {
            "action_type_head": convert_action_type_head(_subdict(sd, "policy.action_type_head"), cfg)["params"],
            "delay_head": convert_delay_head(_subdict(sd, "policy.delay_head"), cfg)["params"],
            "queued_head": convert_queued_head(_subdict(sd, "policy.queued_head"), cfg)["params"],
            "selected_units_head": convert_selected_units_head(_subdict(sd, "policy.selected_units_head"), cfg)["params"],
            "target_unit_head": convert_target_unit_head(_subdict(sd, "policy.target_unit_head"), cfg)["params"],
            "location_head": convert_location_head(_subdict(sd, "policy.location_head"), cfg)["params"],
        },
    }
    for name in cfg.enable_baselines:
        sub = _subdict(sd, f"value_networks.{name}")
        if sub:
            params[f"value_{name}"] = convert_value_baseline(sub, cfg.value.res_num)["params"]
    return {"params": params}


def convert_value_encoder(sd: Dict, cfg) -> Dict:
    """reference ValueEncoder state_dict -> model.encoders.ValueEncoder."""
    vc = cfg.value.encoder
    params = {}
    for key, _in, _out in vc.fc_fields:
        ref_key = "cumulative_stat" if key == "enemy_cumulative_stat" else key
        params[f"fc_{key}"] = _fc(sd, f"encode_modules.{ref_key}")
    for key, _n, _dim in vc.unit_fields:
        params[f"embed_{key}"] = {"embedding": np.asarray(sd[f"encode_modules.{key}.weight"])}
    params["bo_encoder"] = _bo_encoder(sd, "encode_modules.beginning_order")
    params["scatter_project"] = _fc(sd, "scatter_project")
    params["Conv2DBlock_0"] = _conv(sd, "project")
    # downsample Sequential alternates MaxPool2d (no params) and conv blocks
    for i in range(len(vc.spatial.down_channels)):
        params[f"Conv2DBlock_{i + 1}"] = _conv(sd, f"downsample.{2 * i + 1}")
    for i in range(vc.spatial.resblock_num):
        params[f"ResBlock_{i}"] = {
            "Conv2DBlock_0": _conv(sd, f"res.{i}.conv1"),
            "Conv2DBlock_1": _conv(sd, f"res.{i}.conv2"),
        }
    c = vc.spatial.down_channels[-1]
    from ..lib.features import SPATIAL_SIZE

    H = SPATIAL_SIZE[0] // (2 ** len(vc.spatial.down_channels))
    W_ = SPATIAL_SIZE[1] // (2 ** len(vc.spatial.down_channels))
    w = np.asarray(sd["spatial_fc.0.weight"])
    params["spatial_fc"] = {
        "Dense_0": {"kernel": _nchw_fc_kernel(w, c, H, W_), "bias": np.asarray(sd["spatial_fc.0.bias"])}
    }
    return {"params": params}
