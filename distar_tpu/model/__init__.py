from .config import default_model_config, student_model_config
from .core import Model

__all__ = ["Model", "default_model_config", "student_model_config"]
