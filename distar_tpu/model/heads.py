"""Autoregressive policy heads.

Role parity with the reference heads (reference: distar/agent/default/model/
head/action_type_head.py, action_arg_head.py). The autoregressive chain is
action_type -> delay -> queued -> selected_units -> target_unit -> location,
each head consuming and extending a 1024-d autoregressive embedding.

TPU-first reformulations:
* Every sampling path takes an explicit PRNG key and uses
  jax.random.categorical — no in-place logit mutation; temperature is a
  static config scalar folded into the logits once.
* SelectedUnitsHead runs a fixed MAX_SELECTED_UNITS_NUM-step `lax.scan` for
  BOTH teacher-forced training and sampling inference (the reference's
  dynamic-length Python loops, action_arg_head.py:168-313, cannot compile to
  a single XLA program). Ended lanes are masked no-ops, preserving the
  reference's semantics with static shapes.
* LocationHead upsamples with jax.image.resize (bilinear) over NHWC maps.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from .config import cdtype, static_cfg
from ..lib.features import MAX_ENTITY_NUM, MAX_SELECTED_UNITS_NUM
from ..ops import GLU, Conv2DBlock, FCBlock, GatedResBlock, ResBlock, ResFCBlock, sequence_mask
from ..ops.blocks import build_activation
from ..ops.lstm import LayerNormLSTMCell

NEG_INF = -1e9


class ActionTypeHead(nn.Module):
    """ResFC tower + GLU logits over 327 action types; emits the initial
    autoregressive embedding (role of reference action_type_head.py:18-67)."""

    cfg: dict

    @nn.compact
    def __call__(
        self,
        lstm_output: jnp.ndarray,
        scalar_context: jnp.ndarray,
        action_type: Optional[jnp.ndarray] = None,
        rng: Optional[jax.Array] = None,
        legal_mask: Optional[jnp.ndarray] = None,
    ):
        hc = static_cfg(self.cfg).policy.action_type_head
        x = FCBlock(hc.res_dim, "relu", dtype=cdtype(self.cfg))(lstm_output)
        for _ in range(hc.res_num):
            x = ResFCBlock(hc.res_dim, "relu", hc.norm_type, dtype=cdtype(self.cfg))(x)
        logits = GLU(hc.action_num, dtype=cdtype(self.cfg), name="action_glu")(x, scalar_context)
        # logits leave every head in f32: log-prob differences (CE, vtrace
        # rhos) are too quantized in bf16
        logits = logits.astype(jnp.float32) / static_cfg(self.cfg).temperature
        if legal_mask is not None:
            logits = jnp.where(legal_mask.astype(bool), logits, NEG_INF)
        if action_type is None:
            action_type = jax.random.categorical(rng, logits, axis=-1)
        one_hot_action = jax.nn.one_hot(action_type, hc.action_num, dtype=jnp.float32)
        e1 = FCBlock(hc.action_map_dim, "relu", dtype=cdtype(self.cfg))(one_hot_action)
        e1 = FCBlock(hc.action_map_dim, None, dtype=cdtype(self.cfg))(e1)
        e1 = GLU(hc.gate_dim, dtype=cdtype(self.cfg), name="glu1")(e1, scalar_context)
        e2 = GLU(hc.gate_dim, dtype=cdtype(self.cfg), name="glu2")(lstm_output, scalar_context)
        return logits, action_type, e1 + e2


class DelayHead(nn.Module):
    """128-way delay logits; no temperature (reference action_arg_head.py:27-53)."""

    cfg: dict

    @nn.compact
    def __call__(self, embedding, delay=None, rng=None):
        hc = static_cfg(self.cfg).policy.delay_head
        x = FCBlock(hc.decode_dim, "relu", dtype=cdtype(self.cfg))(embedding)
        x = FCBlock(hc.decode_dim, "relu", dtype=cdtype(self.cfg))(x)
        logits = FCBlock(hc.delay_dim, None, dtype=cdtype(self.cfg))(x).astype(jnp.float32)
        if delay is None:
            delay = jax.random.categorical(rng, logits, axis=-1)
        dh = jax.nn.one_hot(delay, hc.delay_dim, dtype=jnp.float32)
        e = FCBlock(hc.delay_map_dim, "relu", dtype=cdtype(self.cfg))(dh)
        e = FCBlock(embedding.shape[-1], None, dtype=cdtype(self.cfg))(e)
        return logits, delay, embedding + e


class QueuedHead(nn.Module):
    """Binary queued flag (reference action_arg_head.py:56-86)."""

    cfg: dict

    @nn.compact
    def __call__(self, embedding, queued=None, rng=None):
        hc = static_cfg(self.cfg).policy.queued_head
        x = FCBlock(hc.decode_dim, "relu", dtype=cdtype(self.cfg))(embedding)
        x = FCBlock(hc.decode_dim, "relu", dtype=cdtype(self.cfg))(x)
        logits = FCBlock(hc.queued_dim, None, dtype=cdtype(self.cfg))(x).astype(
            jnp.float32
        ) / static_cfg(self.cfg).temperature
        if queued is None:
            queued = jax.random.categorical(rng, logits, axis=-1)
        qh = jax.nn.one_hot(queued, hc.queued_dim, dtype=jnp.float32)
        e = FCBlock(hc.queued_map_dim, "relu", dtype=cdtype(self.cfg))(qh)
        e = FCBlock(embedding.shape[-1], None, dtype=cdtype(self.cfg))(e)
        return logits, queued, embedding + e


class SelectedUnitsHead(nn.Module):
    """LSTM pointer network selecting <=64 units with an end-flag token.

    Fixed-length scan over MAX_SELECTED_UNITS_NUM steps; per-step the query
    LSTM attends over entity keys (+1 end slot at index entity_num). Masking
    schedule matches the reference (action_arg_head.py:151-314): step 0
    disables the end slot, steps >=1 enable it and disable already-selected
    units; after a lane selects the end token all its updates become no-ops.
    """

    cfg: dict

    def setup(self):
        hc = static_cfg(self.cfg).policy.selected_units_head
        # the query LSTM's output dots against the keys, so widths must match
        assert hc.hidden_dim == hc.key_dim, "selected_units_head: hidden_dim must equal key_dim"
        self.key_fc = FCBlock(hc.key_dim, None, dtype=cdtype(self.cfg), name="key_fc")
        self.query_fc1 = FCBlock(hc.func_dim, "relu", dtype=cdtype(self.cfg), name="query_fc1")
        self.query_fc2 = FCBlock(hc.key_dim, None, dtype=cdtype(self.cfg), name="query_fc2")
        self.embed_fc1 = FCBlock(hc.func_dim, "relu", dtype=cdtype(self.cfg), name="embed_fc1")
        self.embed_fc2 = FCBlock(
            static_cfg(self.cfg).policy.action_type_head.gate_dim, None, dtype=cdtype(self.cfg), name="embed_fc2"
        )
        # the reference hardcodes script_lnlstm for the pointer decoder
        # (action_arg_head.py:108), overriding its own lstm_type config
        self.lstm_cells = [
            LayerNormLSTMCell(hc.hidden_dim, dtype=cdtype(self.cfg), name=f"lstm{i}")
            for i in range(hc.get("num_layers", 1))
        ]

        self.end_embedding = self.param(
            "end_embedding", nn.initializers.uniform(scale=2.0 / (32 ** 0.5)), (hc.key_dim,)
        )

    def _scan_unroll(self) -> int:
        # lax.scan unroll for the 64-step pointer decode (pure scheduling
        # knob, same as encoder.core_lstm.scan_unroll)
        return int(static_cfg(self.cfg).policy.selected_units_head.get("scan_unroll", 1))

    def _keys(self, entity_embedding, entity_num):
        """Per-entity keys with the end token written at index entity_num.
        Returns key [B, N+1, K] and validity mask [B, N+1]."""
        B, N, _ = entity_embedding.shape
        key = self.key_fc(entity_embedding)  # B, N, K
        key = jnp.concatenate([key, jnp.zeros_like(key[:, :1])], axis=1)  # B, N+1, K
        is_end = jnp.arange(N + 1)[None, :] == entity_num[:, None]  # B, N+1
        key = jnp.where(is_end[..., None], self.end_embedding[None, None, :], key)
        mask = sequence_mask(entity_num + 1, N + 1)
        return key, mask

    def _lstm(self, x, states):
        """Stacked LN-LSTM step; ``states`` is a tuple of (h, c) per layer."""
        new_states = []
        for cell, st in zip(self.lstm_cells, states):
            x, st = cell(x, st)
            new_states.append(st)
        return x, tuple(new_states)

    def _ae_update(self, base_ae, key, sel_onehot, count):
        """ae = base + embed(mean of selected keys). The MLP applies even to
        a zero selection (the reference feeds the raw zero sum through
        embed_fc1/2, whose biases contribute — action_arg_head.py:193-200);
        only step 0 uses the raw base ae (handled by callers)."""
        s = (key * sel_onehot[..., None]).sum(axis=1)
        denom = jnp.maximum(count, 1.0)[:, None]
        return base_ae + self.embed_fc2(self.embed_fc1(s / denom))

    def _su_step(self, carry, result_fn, temperature: float = 1.0):
        """One pointer-decode step; ``result_fn(logits)`` picks the unit."""
        key, valid, entity_num = carry["key"], carry["valid"], carry["entity_num"]
        N1 = key.shape[1]
        q = self.query_fc2(self.query_fc1(carry["ae"]))
        out, lstm_state = self._lstm(q, carry["lstm_state"])
        logits = (out[:, None, :] * key).sum(-1).astype(jnp.float32)  # B, N+1
        logits = jnp.where(carry["logit_mask"], logits, NEG_INF) / temperature
        result = result_fn(logits)
        picked_end = result == entity_num
        newly_end = picked_end & ~carry["end_flag"]
        num = jnp.where(newly_end, carry["i"] + 1, carry["num"])
        end_flag = carry["end_flag"] | picked_end
        slot = jnp.arange(N1)[None, :] == result[:, None]
        add = (~end_flag)[:, None] & slot
        sel_onehot = jnp.maximum(carry["sel_onehot"], add.astype(jnp.float32))
        count = sel_onehot.sum(axis=1)
        ae = self._ae_update(carry["base_ae"], key, sel_onehot, count)
        is_end_slot = jnp.arange(N1)[None, :] == entity_num[:, None]
        logit_mask = carry["logit_mask"] | (is_end_slot & valid)  # end selectable from step 1
        logit_mask = logit_mask & ~(slot & ~picked_end[:, None])  # chosen unit now off
        new_carry = dict(
            carry,
            lstm_state=lstm_state,
            ae=ae,
            logit_mask=logit_mask,
            sel_onehot=sel_onehot,
            end_flag=end_flag,
            num=num,
            i=carry["i"] + 1,
        )
        return new_carry, (logits, result)

    def _train_forward_parallel(
        self, base_ae, key, valid, entity_num, labels, selected_units_num, states0
    ):
        """Teacher-forced decode with everything except the tiny query LSTM
        batched over the 64 steps.

        Under teacher forcing the per-step state (selected one-hots, masks,
        autoregressive embeddings) is a pure function of the *labels*, so the
        reference's step-by-step recomputation (and the scan path's 64
        sequential 1024-wide matmuls) collapses into cumulative ops + three
        big MXU matmuls; only the 32-dim pointer LSTM stays sequential.
        Produces logits identical to the scan path (equivalence-tested)."""
        B, N1, K = key.shape
        S = MAX_SELECTED_UNITS_NUM
        slot = jax.nn.one_hot(labels, N1, dtype=jnp.float32)  # [B, S, N+1]
        picked_end = labels == entity_num[:, None]  # [B, S]
        end_before = jnp.concatenate(
            [jnp.zeros((B, 1), bool), jnp.cumsum(picked_end, axis=1)[:, :-1] > 0], axis=1
        )
        # selection accumulated AFTER each step i (ended lanes stop adding)
        add = slot * (~(end_before | picked_end))[..., None]
        sel_after = jnp.minimum(jnp.cumsum(add, axis=1), 1.0)  # [B, S, N+1]
        # ae at step i uses selections from steps < i
        sel_before = jnp.concatenate(
            [jnp.zeros((B, 1, N1), jnp.float32), sel_after[:, :-1]], axis=1
        )
        count_before = sel_before.sum(-1)  # [B, S]
        pooled = jnp.einsum("bsn,bnk->bsk", sel_before, key) / jnp.maximum(
            count_before, 1.0
        )[..., None]
        emb = self.embed_fc2(self.embed_fc1(pooled))  # [B, S, 1024] one batched matmul
        # step 0 queries the raw base ae; every later step adds the selection
        # MLP (incl. its bias for empty selections — see _ae_update)
        ae_all = base_ae[:, None, :] + jnp.where(
            (jnp.arange(S) > 0)[None, :, None], emb, 0.0
        )
        # per-step logits mask: end slot off at step 0, on after; previously
        # selected units off (the end pick itself stays maskable)
        picked_slots_before = jnp.concatenate(
            [
                jnp.zeros((B, 1, N1), jnp.float32),
                jnp.cumsum(slot * (~picked_end)[..., None], axis=1)[:, :-1],
            ],
            axis=1,
        )
        is_end_slot = (jnp.arange(N1)[None, :] == entity_num[:, None])[:, None, :]
        step_idx = jnp.arange(S)[None, :, None]
        mask_all = (
            valid[:, None, :]
            & ((step_idx > 0) | ~is_end_slot)  # init_mask semantics at step 0
            & (picked_slots_before == 0)
        )
        # tiny pointer LSTM over the precomputed query inputs
        q_in = self.query_fc2(self.query_fc1(ae_all))  # [B, S, K]
        _, lstm_out = nn.transforms.scan(
            lambda mdl, carry, x: tuple(reversed(mdl._lstm(x, carry))),
            variable_broadcast="params",
            split_rngs={"params": False},
            unroll=self._scan_unroll(),
        )(self, states0, q_in.transpose(1, 0, 2))
        lstm_out = lstm_out.transpose(1, 0, 2)  # [B, S, K]
        logits = jnp.einsum("bsk,bnk->bsn", lstm_out, key).astype(jnp.float32)
        logits = jnp.where(mask_all, logits, NEG_INF)
        # final ae (feeds target_unit/location heads) = ae after step S-1
        count_after = sel_after[:, -1].sum(-1)
        pooled_final = jnp.einsum(
            "bn,bnk->bk", sel_after[:, -1], key
        ) / jnp.maximum(count_after, 1.0)[:, None]
        emb_final = self.embed_fc2(self.embed_fc1(pooled_final))
        ae_final = base_ae + emb_final
        end_flag = end_before[:, -1] | picked_end[:, -1]
        last_logits = logits[:, -1, :]
        end_logit = jnp.take_along_axis(last_logits, entity_num[:, None], axis=1)
        extra_units = ((last_logits > end_logit) & ~end_flag[:, None]).astype(jnp.float32)
        return logits, labels, ae_final, selected_units_num, extra_units

    def _su_step_train(self, carry, label):
        return self._su_step(carry, lambda logits: label)

    def _su_step_sample(self, carry, step_rng):
        # temperature folds into the *returned* logits so action_logp is
        # computed under the same distribution that sampled (the reference's
        # in-place logit.div_ in _get_pred_with_logit has the same effect,
        # action_arg_head.py:145-149)
        return self._su_step(
            carry,
            lambda logits: jax.random.categorical(step_rng, logits, axis=-1),
            temperature=static_cfg(self.cfg).temperature,
        )

    def __call__(
        self,
        embedding: jnp.ndarray,  # [B, 1024] autoregressive embedding
        entity_embedding: jnp.ndarray,  # [B, N, 256]
        entity_num: jnp.ndarray,  # [B]
        selected_units: Optional[jnp.ndarray] = None,  # [B, S] teacher labels
        selected_units_num: Optional[jnp.ndarray] = None,  # [B]
        su_mask: Optional[jnp.ndarray] = None,  # [B] does this action select units
        rng: Optional[jax.Array] = None,
    ):
        hc = static_cfg(self.cfg).policy.selected_units_head
        B, N, _ = entity_embedding.shape
        S = MAX_SELECTED_UNITS_NUM
        key, valid = self._keys(entity_embedding, entity_num)
        base_ae = embedding
        h0 = jnp.zeros((B, hc.hidden_dim), jnp.float32)  # carry stays f32
        states0 = tuple((h0, h0) for _ in self.lstm_cells)
        init_mask = valid & (jnp.arange(N + 1)[None, :] != entity_num[:, None])  # end off at step 0

        train = selected_units is not None
        if train:
            labels = selected_units[:, :S].astype(jnp.int32)
            if labels.shape[1] < S:
                labels = jnp.pad(labels, ((0, 0), (0, S - labels.shape[1])))
            if (
                hc.get("train_impl", "parallel") != "scan"
                and not self.is_initializing()
            ):
                return self._train_forward_parallel(
                    base_ae, key, valid, entity_num, labels, selected_units_num, states0
                )
            xs = labels.T  # [S, B]
        else:
            xs = jax.random.split(rng, S)

        end0 = jnp.zeros((B,), bool)
        num0 = jnp.full((B,), S, jnp.int32)
        if su_mask is not None:
            end0 = ~su_mask.astype(bool)
            num0 = jnp.where(su_mask.astype(bool), num0, 0)
        carry0 = dict(
            lstm_state=states0,
            # step 0 queries the RAW base ae (the selection MLP only joins
            # from step 1, reference :188-200)
            ae=base_ae,
            logit_mask=init_mask,
            sel_onehot=jnp.zeros((B, N + 1), jnp.float32),
            end_flag=end0,
            num=num0,
            i=jnp.zeros((), jnp.int32),
            # loop-invariant context, threaded through the carry so the
            # lifted-scan step sees it without closures
            key=key,
            valid=valid,
            base_ae=base_ae,
            entity_num=entity_num,
        )

        step_method = self._su_step_train if train else self._su_step_sample
        if self.is_initializing():
            carry, (logits0, result0) = step_method(carry0, jax.tree.map(lambda a: a[0], xs))
            logits_seq = jnp.broadcast_to(logits0[None], (S, B, N + 1))
            results_seq = jnp.broadcast_to(result0[None], (S, B))
            final = carry
        else:
            final, (logits_seq, results_seq) = nn.transforms.scan(
                type(self)._su_step_train if train else type(self)._su_step_sample,
                variable_broadcast="params",
                split_rngs={"params": False},
                unroll=self._scan_unroll(),
            )(self, carry0, xs)

        ae = final["ae"]
        end_flag = final["end_flag"]
        num = final["num"]
        logits_seq = logits_seq.transpose(1, 0, 2)  # B, S, N+1
        results_seq = results_seq.transpose(1, 0)  # B, S
        if train:
            out_num = selected_units_num
        else:
            out_num = num
        # extra-units proposal: entities scoring above the end token at the
        # final step, for lanes that never ended (reference :307-309)
        last_logits = logits_seq[:, -1, :]
        end_logit = jnp.take_along_axis(last_logits, entity_num[:, None], axis=1)
        extra_units = ((last_logits > end_logit) & ~end_flag[:, None]).astype(jnp.float32)
        return logits_seq, results_seq, ae, out_num, extra_units


class TargetUnitHead(nn.Module):
    """Key-query attention over entities (reference action_arg_head.py:331-363)."""

    cfg: dict

    @nn.compact
    def __call__(self, embedding, entity_embedding, entity_num, target_unit=None, rng=None):
        hc = static_cfg(self.cfg).policy.target_unit_head
        key = FCBlock(hc.key_dim, None, dtype=cdtype(self.cfg))(entity_embedding)
        q = FCBlock(hc.key_dim, "relu", dtype=cdtype(self.cfg))(embedding)
        q = FCBlock(hc.key_dim, None, dtype=cdtype(self.cfg))(q)
        logits = (q[:, None, :] * key).sum(-1).astype(jnp.float32)
        mask = sequence_mask(entity_num, entity_embedding.shape[1])
        logits = jnp.where(mask, logits, NEG_INF) / static_cfg(self.cfg).temperature
        if target_unit is None:
            target_unit = jax.random.categorical(rng, logits, axis=-1)
        return logits, target_unit


class LocationHead(nn.Module):
    """Gated res stack over map_skip + 3x bilinear upsample to 152x160 logits
    (reference action_arg_head.py:366-450; gate=True, film/unet off)."""

    cfg: dict

    @nn.compact
    def __call__(self, embedding, map_skip: List[jnp.ndarray], location=None, rng=None):
        hc = static_cfg(self.cfg).policy.location_head
        H8, W8 = static_cfg(self.cfg).spatial_y // 8, static_cfg(self.cfg).spatial_x // 8
        proj = FCBlock(H8 * W8 * hc.reshape_channel, "relu", dtype=cdtype(self.cfg))(embedding)
        proj = proj.reshape(-1, H8, W8, hc.reshape_channel)
        x = jnp.concatenate([proj, map_skip[-1]], axis=-1)
        x = jax.nn.relu(x)
        x = Conv2DBlock(hc.res_dim, 1, 1, "SAME", "relu", dtype=cdtype(self.cfg))(x)
        for i in range(hc.res_num):
            x = x + map_skip[len(map_skip) - i - 1]
            if hc.gate:
                x = GatedResBlock(hc.res_dim, "relu", dtype=cdtype(self.cfg))(x, x)
            else:
                x = ResBlock(hc.res_dim, "relu", dtype=cdtype(self.cfg))(x)
        for i, ch in enumerate(hc.upsample_dims):
            B, h, w, c = x.shape
            x = jax.image.resize(x, (B, h * 2, w * 2, c), "bilinear")
            act = "relu" if i < len(hc.upsample_dims) - 1 else None
            x = Conv2DBlock(ch, 3, 1, "SAME", act, dtype=cdtype(self.cfg))(x)
        logits = x.reshape(x.shape[0], -1).astype(jnp.float32) / static_cfg(self.cfg).temperature
        if location is None:
            location = jax.random.categorical(rng, logits, axis=-1)
        return logits, location
