"""Observation encoders: scalar, spatial, entity, and value-feature.

Role parity with the reference encoders
(reference: distar/agent/default/model/obs_encoder/*.py, encoder.py) with
TPU-first reformulations:

* Entity features are *not* materialised as a 997-wide one-hot concat then
  projected (entity_encoder.py:59-78); each categorical field gets its own
  embedding table into the transformer width and the contributions are
  summed — mathematically identical to concat->Dense (split the kernel by
  rows) but lowers to gathers + adds instead of a huge sparse matmul.
* Spatial maps are NHWC (TPU conv layout); effect coordinate lists are
  scattered into planes with one fused scatter.
* All fixed shapes: entities padded to MAX_ENTITY_NUM, map fixed 152x160.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from .config import cdtype, static_cfg
from ..ops import (
    Conv2DBlock,
    FCBlock,
    ResBlock,
    Transformer,
    AttentionPool,
    binary_encode,
    one_hot,
    scatter_connection,
    sequence_mask,
)
from ..ops.transformer import TransformerLayer
from ..ops.blocks import build_activation


def _field_sum_embed(mdl_prefix: str, fields, x: Dict[str, jnp.ndarray], width: int, dtype):
    """Sum of per-field projections into ``width`` (== concat->Dense)."""
    total = None
    for key, arc, n in fields:
        v = x[key]
        if arc == "one_hot":
            emb = nn.Embed(n, width, dtype=dtype, name=f"{mdl_prefix}_{key}")(
                jnp.clip(v.astype(jnp.int32), 0, n - 1)
            )
        elif arc == "binary":
            emb = nn.Dense(width, use_bias=False, dtype=dtype, name=f"{mdl_prefix}_{key}")(
                binary_encode(v, n)
            )
        elif arc == "float":
            w = nn.Dense(width, use_bias=False, dtype=dtype, name=f"{mdl_prefix}_{key}")(
                v.astype(jnp.float32)[..., None]
            )
            emb = w
        else:
            raise NotImplementedError(arc)
        total = emb if total is None else total + emb
    return total


class BeginningBuildOrderEncoder(nn.Module):
    """Transformer over the 20-slot build-order sequence with positional
    one-hot and binary-encoded (x, y) of each order location
    (role of reference scalar_encoder.py:19-53)."""

    action_num: int
    binary_dim: int = 10
    head_dim: int = 8
    output_dim: int = 64
    spatial_x: int = 160
    dtype: object = jnp.float32

    @nn.compact
    def __call__(self, bo: jnp.ndarray, bo_location: jnp.ndarray):
        B, L = bo.shape
        a = one_hot(bo, self.action_num)
        pos = jnp.broadcast_to(jnp.eye(L, dtype=jnp.float32)[None], (B, L, L))
        loc_x = binary_encode(bo_location.astype(jnp.int32) % self.spatial_x, self.binary_dim)
        loc_y = binary_encode(bo_location.astype(jnp.int32) // self.spatial_x, self.binary_dim)
        x = jnp.concatenate([a, pos, loc_x, loc_y], axis=-1)
        x = Transformer(
            head_dim=self.head_dim,
            hidden_dim=self.output_dim * 2,
            output_dim=self.output_dim,
            head_num=2,
            mlp_num=2,
            layer_num=3,
            ln_type="pre",
            dtype=self.dtype,
        )(x)
        x = x.mean(axis=1)
        return FCBlock(self.output_dim, "relu", dtype=self.dtype)(x)


class ScalarEncoder(nn.Module):
    """Per-field scalar embeddings -> (embedded_scalar, scalar_context,
    baseline_feature) triple (role of reference scalar_encoder.py:56-132).
    Output layout: concat of field outputs in config order, then the sin/cos
    time embedding last."""

    cfg: dict  # model config Config

    @nn.compact
    def __call__(self, x: Dict[str, jnp.ndarray]):
        sc = static_cfg(self.cfg).encoder.scalar
        outs, ctx, base = [], [], []
        for key, arc, n, out_dim, is_ctx, is_base in sc.fields:
            if arc == "time":
                continue
            if arc == "one_hot":
                v = jnp.clip(x[key].astype(jnp.int32), 0, n - 1)
                emb = jax.nn.relu(nn.Embed(n, out_dim, dtype=cdtype(self.cfg), name=f"embed_{key}")(v))
            elif arc == "fc":
                emb = FCBlock(out_dim, "relu", dtype=cdtype(self.cfg), name=f"fc_{key}")(
                    x[key].astype(jnp.float32)
                )
            elif arc == "bo_transformer":
                emb = BeginningBuildOrderEncoder(
                    action_num=sc.bo.action_num,
                    binary_dim=sc.bo.binary_dim,
                    head_dim=sc.bo.head_dim,
                    output_dim=sc.bo.output_dim,
                    spatial_x=static_cfg(self.cfg).spatial_x,
                    dtype=cdtype(self.cfg),
                    name="bo_encoder",
                )(x[key].astype(jnp.float32), x["bo_location"].astype(jnp.int32))
            else:
                raise NotImplementedError(arc)
            outs.append(emb)
            if is_ctx:
                ctx.append(emb)
            if is_base:
                base.append(emb)
        outs.append(self._time_embedding(x["time"].astype(jnp.float32)))
        return (
            jnp.concatenate(outs, axis=-1),
            jnp.concatenate(ctx, axis=-1),
            jnp.concatenate(base, axis=-1),
        )

    def _time_embedding(self, t: jnp.ndarray, dim: int = 32):
        idx = jnp.arange(dim, dtype=jnp.float32)
        denom = 1.0 / jnp.power(10000.0, (idx // 2 * 2) / dim)
        ang = t[:, None] * denom[None, :]
        even = jnp.sin(ang)
        odd = jnp.cos(ang)
        return jnp.where((jnp.arange(dim) % 2 == 0)[None, :], even, odd)


class SpatialEncoder(nn.Module):
    """One-hot planes + effect scatters + entity scatter_map -> conv stack.

    Returns (embedded_spatial [B, fc_dim], map_skip pyramid list) — the skip
    list feeds LocationHead (role of reference spatial_encoder.py:51-90;
    downsample 'maxpool', head 'fc', norm none per the default config).
    """

    cfg: dict

    @nn.compact
    def __call__(self, x: Dict[str, jnp.ndarray], scatter_map: jnp.ndarray):
        sp = static_cfg(self.cfg).encoder.spatial
        H, W = static_cfg(self.cfg).spatial_y, static_cfg(self.cfg).spatial_x
        planes = []
        for key, arc, n in sp.fields:
            v = x[key]
            if arc == "float":
                planes.append(v.astype(jnp.float32)[..., None] / 256.0)
            elif arc == "one_hot":
                planes.append(one_hot(v, n))
            elif arc == "scatter":
                # v: [B, EFFECT_LEN] flat indices into H*W
                B, L = v.shape
                idx = jnp.clip(v.astype(jnp.int32), 0, H * W - 1)
                plane = jnp.zeros((B, H * W), jnp.float32)
                plane = plane.at[jnp.arange(B)[:, None], idx].set(1.0)
                planes.append(plane.reshape(B, H, W, 1))
            else:
                raise NotImplementedError(arc)
        planes.append(scatter_map)
        h = jnp.concatenate(planes, axis=-1)
        h = Conv2DBlock(sp.project_dim, 1, 1, "SAME", "relu", dtype=cdtype(self.cfg))(h)
        map_skip: List[jnp.ndarray] = []
        for ch in sp.down_channels:
            map_skip.append(h)
            h = nn.max_pool(h, (2, 2), strides=(2, 2))
            h = Conv2DBlock(ch, 3, 1, "SAME", "relu", dtype=cdtype(self.cfg))(h)
        for _ in range(sp.resblock_num):
            map_skip.append(h)
            h = ResBlock(h.shape[-1], "relu", dtype=cdtype(self.cfg))(h)
        h = h.reshape(h.shape[0], -1)
        h = FCBlock(sp.fc_dim, "relu", dtype=cdtype(self.cfg))(h)
        return h, map_skip


class EntityEncoder(nn.Module):
    """Per-field embedding-sum -> 3-layer set transformer -> per-entity
    embeddings + masked-mean pooled embedding
    (role of reference entity_encoder.py:20-96)."""

    cfg: dict

    @nn.compact
    def __call__(self, x: Dict[str, jnp.ndarray], entity_num: jnp.ndarray):
        ent = static_cfg(self.cfg).encoder.entity
        width = ent.output_dim
        # field-sum embedding == reference's concat(one-hots) @ W_embed
        h = _field_sum_embed("ent", ent.fields, x, width, cdtype(self.cfg))
        bias = self.param("ent_embed_bias", nn.initializers.zeros_init(), (width,))
        h = jax.nn.relu(h + bias)
        mask = sequence_mask(entity_num, h.shape[1])
        # transformer layers only (embedding fc already applied above);
        # remat recomputes each layer in the backward instead of keeping its
        # [B*T, 512, C] activations live (model cfg `remat`)
        layer_cls = (
            nn.remat(TransformerLayer)
            if static_cfg(self.cfg).get("remat", False)
            else TransformerLayer
        )
        for i in range(ent.layer_num):
            h = layer_cls(
                ent.head_dim,
                ent.hidden_dim,
                ent.output_dim,
                ent.head_num,
                ent.mlp_num,
                "relu",
                ent.ln_type,
                cdtype(self.cfg),
                attn_impl=ent.get("attention_impl", "xla"),
                # explicit name: params stay loadable across the remat toggle
                # (nn.remat's auto-name prefix would otherwise differ)
                name=f"TransformerLayer_{i}",
            )(h, mask)
        # the reference's build_activation returns an INPLACE ReLU, so its
        # `entity_fc(act(x))` also rewrites x before the pooling branch
        # (entity_encoder.py:82-96 + activation.py:85) — the pooled embedding
        # therefore reduces relu(x), and so do we (golden-parity verified)
        h = jax.nn.relu(h)
        entity_embeddings = FCBlock(width, "relu", dtype=cdtype(self.cfg), name="entity_fc")(h)
        reduce_type = static_cfg(self.cfg).entity_reduce_type
        masked = h * mask[..., None]
        if reduce_type in ("entity_num", "selected_units_num"):
            pooled = masked.sum(axis=1) / jnp.maximum(entity_num, 1)[:, None]
        elif reduce_type == "constant":
            pooled = masked.sum(axis=1) / 512.0
        elif reduce_type == "attention_pool":
            pooled = AttentionPool(head_num=2, output_dim=width, dtype=cdtype(self.cfg))(
                h, mask=mask[..., None]
            )
        else:
            raise NotImplementedError(reduce_type)
        embedded_entity = FCBlock(width, "relu", dtype=cdtype(self.cfg), name="embed_fc")(pooled)
        return entity_embeddings, embedded_entity, mask


class ValueEncoder(nn.Module):
    """Centralized-critic feature encoder over opponent stats and both sides'
    unit scatter maps (role of reference value_encoder.py:12-77).

    Expects a value_feature dict with keys: the configured fc fields,
    unit_alliance/unit_type/unit_x/unit_y/total_unit_count per unit,
    own_units_spatial/enemy_units_spatial [B,H,W] {0,1} maps, and
    enemy beginning_order/bo_location.
    """

    cfg: dict

    @nn.compact
    def __call__(self, x: Dict[str, jnp.ndarray]):
        vc = static_cfg(self.cfg).value.encoder
        fc_parts = [
            FCBlock(out, "relu", dtype=cdtype(self.cfg), name=f"fc_{key}")(x[key].astype(jnp.float32))
            for key, _in, out in vc.fc_fields
        ]
        unit_emb = None
        for key, n, dim in vc.unit_fields:
            e = nn.Embed(n, dim, dtype=cdtype(self.cfg), name=f"embed_{key}")(
                jnp.clip(x[key].astype(jnp.int32), 0, n - 1)
            )
            unit_emb = e if unit_emb is None else jnp.concatenate([unit_emb, e], axis=-1)
        proj = FCBlock(vc.scatter_dim, "relu", dtype=cdtype(self.cfg), name="scatter_project")(unit_emb)
        unit_mask = sequence_mask(x["total_unit_count"], proj.shape[1])
        proj = proj * unit_mask[..., None]
        loc = jnp.stack([x["unit_x"].astype(jnp.int32), x["unit_y"].astype(jnp.int32)], axis=-1)
        H, W = x["own_units_spatial"].shape[-2:]
        smap = scatter_connection(proj, loc, (H, W), "add")
        spatial = jnp.concatenate(
            [
                smap,
                x["own_units_spatial"].astype(jnp.float32)[..., None],
                x["enemy_units_spatial"].astype(jnp.float32)[..., None],
            ],
            axis=-1,
        )
        h = Conv2DBlock(vc.spatial.project_dim, 1, 1, "SAME", "relu", dtype=cdtype(self.cfg))(spatial)
        for ch in vc.spatial.down_channels:
            h = nn.max_pool(h, (2, 2), strides=(2, 2))
            h = Conv2DBlock(ch, 3, 1, "SAME", "relu", dtype=cdtype(self.cfg))(h)
        for _ in range(vc.spatial.resblock_num):
            h = ResBlock(h.shape[-1], "relu", dtype=cdtype(self.cfg))(h)
        h = FCBlock(vc.spatial.fc_dim, "relu", dtype=cdtype(self.cfg), name="spatial_fc")(
            h.reshape(h.shape[0], -1)
        )
        bo = BeginningBuildOrderEncoder(
            action_num=vc.bo.action_num,
            binary_dim=vc.bo.binary_dim,
            head_dim=vc.bo.head_dim,
            output_dim=vc.bo.output_dim,
            spatial_x=static_cfg(self.cfg).spatial_x,
            dtype=cdtype(self.cfg),
            name="bo_encoder",
        )(x["beginning_order"].astype(jnp.float32), x["bo_location"].astype(jnp.int32))
        return jnp.concatenate(fc_parts + [h, bo], axis=-1)
