"""The full policy/value network and its four forward modes.

Role parity with the reference Model (reference: distar/agent/default/model/
model.py:22-189, encoder.py:15-45, policy.py): Encoder (scalar+spatial+entity
with entity->map scatter connection) -> 3x384 LN-LSTM core -> autoregressive
policy heads -> per-baseline value towers.

TPU-first structure: the network is a pure Flax module; time handling for the
learner modes reshapes [(T+1)*B, ...] flat batches around a `lax.scan` LSTM
exactly once (reference model.py:117-129 does the same reshape around its
TorchScript LSTM). Sampling modes take explicit PRNG keys. All shapes static.

Forward modes (mirroring model.py):
  * sample_action        — actor inference: sample every head, return
                           actions + per-head log-probs + new hidden state
                           (reference compute_logp_action :56).
  * teacher_logits       — teacher-forced logits for a given action
                           (reference compute_teacher_logit :76).
  * rl_forward           — (T+1, B) learner forward: policy logits on the
                           first T steps, six baselines on all T+1
                           (reference rl_learner_forward :95).
  * sl_forward           — supervised teacher-forced forward over [T, B]
                           windows with carried hidden state
                           (reference sl_train :170).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..lib.features import MAX_SELECTED_UNITS_NUM
from ..ops import FCBlock, StackedLSTM, scatter_connection
from .config import cdtype, static_cfg
from .encoders import EntityEncoder, ScalarEncoder, SpatialEncoder, ValueEncoder
from .heads import (
    ActionTypeHead,
    DelayHead,
    LocationHead,
    QueuedHead,
    SelectedUnitsHead,
    TargetUnitHead,
)
from .value import ValueBaseline

NEG_INF = -1e9


class Encoder(nn.Module):
    """Fuse the three observation encoders; scatter entity embeddings onto
    the map before the spatial conv stack (reference encoder.py:28-45)."""

    cfg: dict

    @nn.compact
    def __call__(self, spatial_info, entity_info, scalar_info, entity_num):
        embedded_scalar, scalar_context, baseline_feature = ScalarEncoder(
            static_cfg(self.cfg), name="scalar_encoder"
        )(scalar_info)
        entity_embeddings, embedded_entity, entity_mask = EntityEncoder(
            static_cfg(self.cfg), name="entity_encoder"
        )(entity_info, entity_num)
        proj = FCBlock(static_cfg(self.cfg).encoder.scatter.output_dim, "relu", dtype=cdtype(self.cfg))(
            entity_embeddings
        )
        proj = proj * entity_mask[..., None]
        locations = jnp.stack(
            [entity_info["x"].astype(jnp.int32), entity_info["y"].astype(jnp.int32)], axis=-1
        )
        scatter_map = scatter_connection(
            proj,
            locations,
            (static_cfg(self.cfg).spatial_y, static_cfg(self.cfg).spatial_x),
            static_cfg(self.cfg).encoder.scatter.type,
            impl=static_cfg(self.cfg).encoder.scatter.get("impl", "xla"),
        )
        spatial_cls = (
            nn.remat(SpatialEncoder)
            if static_cfg(self.cfg).get("remat", False)
            else SpatialEncoder
        )
        embedded_spatial, map_skip = spatial_cls(static_cfg(self.cfg), name="spatial_encoder")(
            spatial_info, scatter_map
        )
        lstm_input = jnp.concatenate(
            [embedded_scalar, embedded_entity, embedded_spatial], axis=-1
        )
        return lstm_input, scalar_context, baseline_feature, entity_embeddings, map_skip


class Policy(nn.Module):
    """The six-head autoregressive chain (reference policy.py)."""

    cfg: dict

    def setup(self):
        self.action_type_head = ActionTypeHead(static_cfg(self.cfg))
        self.delay_head = DelayHead(static_cfg(self.cfg))
        self.queued_head = QueuedHead(static_cfg(self.cfg))
        self.selected_units_head = SelectedUnitsHead(static_cfg(self.cfg))
        self.target_unit_head = TargetUnitHead(static_cfg(self.cfg))
        self.location_head = LocationHead(static_cfg(self.cfg))

    def sample(self, lstm_output, entity_embeddings, map_skip, scalar_context, entity_num,
               rng, legal_mask=None):
        r = jax.random.split(rng, 6)
        logit: Dict[str, jnp.ndarray] = {}
        action: Dict[str, jnp.ndarray] = {}
        logit["action_type"], action["action_type"], emb = self.action_type_head(
            lstm_output, scalar_context, None, r[0], legal_mask
        )
        logit["delay"], action["delay"], emb = self.delay_head(emb, None, r[1])
        logit["queued"], action["queued"], emb = self.queued_head(emb, None, r[2])
        # whether this action type selects units at all (contract table)
        from ..lib.actions import SELECTED_UNITS_MASK

        su_mask = jnp.asarray(SELECTED_UNITS_MASK)[action["action_type"]]
        (
            logit["selected_units"],
            action["selected_units"],
            emb,
            selected_units_num,
            extra_units,
        ) = self.selected_units_head(
            emb, entity_embeddings, entity_num, None, None, su_mask, r[3]
        )
        logit["target_unit"], action["target_unit"] = self.target_unit_head(
            emb, entity_embeddings, entity_num, None, r[4]
        )
        logit["target_location"], action["target_location"] = self.location_head(
            emb, map_skip, None, r[5]
        )
        return action, selected_units_num, logit, extra_units

    def train_forward(self, lstm_output, entity_embeddings, map_skip, scalar_context,
                      entity_num, action_info, selected_units_num):
        logit: Dict[str, jnp.ndarray] = {}
        logit["action_type"], _, emb = self.action_type_head(
            lstm_output, scalar_context, action_info["action_type"]
        )
        logit["delay"], _, emb = self.delay_head(emb, action_info["delay"])
        logit["queued"], _, emb = self.queued_head(emb, action_info["queued"])
        logit["selected_units"], _, emb, _, _ = self.selected_units_head(
            emb,
            entity_embeddings,
            entity_num,
            action_info["selected_units"],
            selected_units_num,
        )
        logit["target_unit"], _ = self.target_unit_head(
            emb, entity_embeddings, entity_num, action_info["target_unit"]
        )
        logit["target_location"], _ = self.location_head(
            emb, map_skip, action_info["target_location"]
        )
        return logit


class Model(nn.Module):
    """Encoder + LSTM core + Policy + value baselines."""

    cfg: dict

    def setup(self):
        self.encoder = Encoder(static_cfg(self.cfg))
        self.policy = Policy(static_cfg(self.cfg))
        core = static_cfg(self.cfg).encoder.core_lstm
        self.core_lstm = StackedLSTM(
            hidden_size=core.hidden_size, num_layers=core.num_layers, norm="LN",
            dtype=cdtype(self.cfg),
            scan_unroll=int(core.get("scan_unroll", 1)),
            layer_major=bool(core.get("layer_major", True)),
        )
        if static_cfg(self.cfg).use_value_network:
            self.value_networks = {
                name: ValueBaseline(
                    res_dim=static_cfg(self.cfg).value.res_dim,
                    res_num=static_cfg(self.cfg).value.res_num,
                    norm_type=static_cfg(self.cfg).value.norm_type,
                    atan=static_cfg(self.cfg).value.baselines[name].atan,
                    dtype=cdtype(self.cfg),
                    name=f"value_{name}",
                )
                for name in static_cfg(self.cfg).enable_baselines
            }
            if static_cfg(self.cfg).use_value_feature:
                self.value_encoder = ValueEncoder(static_cfg(self.cfg))

    # ---------------------------------------------------------------- actor
    def sample_action(self, spatial_info, entity_info, scalar_info, entity_num,
                      hidden_state, rng, legal_mask=None):
        """Single-step batched inference (reference compute_logp_action)."""
        lstm_input, scalar_context, baseline_feature, entity_embeddings, map_skip = self.encoder(
            spatial_info, entity_info, scalar_info, entity_num
        )
        lstm_output, out_state = self.core_lstm(lstm_input[None], hidden_state)
        lstm_output = lstm_output[0]
        action, selected_units_num, logit, extra_units = self.policy.sample(
            lstm_output, entity_embeddings, map_skip, scalar_context, entity_num,
            rng, legal_mask,
        )
        logp = {k: _log_prob(logit[k], action[k]) for k in action}
        return {
            "action_info": action,
            "action_logp": logp,
            "selected_units_num": selected_units_num,
            "entity_num": entity_num,
            "hidden_state": out_state,
            "logit": logit,
            "extra_units": extra_units,
        }

    # -------------------------------------------------------------- teacher
    def teacher_logits(self, spatial_info, entity_info, scalar_info, entity_num,
                       hidden_state, action_info, selected_units_num):
        lstm_input, scalar_context, _, entity_embeddings, map_skip = self.encoder(
            spatial_info, entity_info, scalar_info, entity_num
        )
        lstm_output, out_state = self.core_lstm(lstm_input[None], hidden_state)
        logit = self.policy.train_forward(
            lstm_output[0], entity_embeddings, map_skip, scalar_context, entity_num,
            action_info, selected_units_num,
        )
        return {
            "logit": logit,
            "hidden_state": out_state,
            "entity_num": entity_num,
            "selected_units_num": selected_units_num,
        }

    # ------------------------------------------------------------- learner
    def _learner_logits(self, spatial_info, entity_info, scalar_info,
                        entity_num, hidden_state, action_info,
                        selected_units_num, batch_size, unroll_len):
        """Shared logits half of the learner forwards: encoder -> LSTM over
        the [T+1, B] window -> teacher-forced policy logits on the first T
        steps. Returns (logits [T, B, ...] dict with the selected-units S
        axis padded static, flat LSTM outputs, baseline_feature) — the
        value-tower consumers take the last two."""
        flat_action = {k: v.reshape((-1,) + v.shape[2:]) for k, v in action_info.items()}
        flat_sun = selected_units_num.reshape(-1)

        lstm_input, scalar_context, baseline_feature, entity_embeddings, map_skip = self.encoder(
            spatial_info, entity_info, scalar_info, entity_num
        )
        seq = lstm_input.reshape(-1, batch_size, lstm_input.shape[-1])  # [T+1, B, D]
        lstm_output, _ = self.core_lstm(seq, hidden_state)
        flat_out = lstm_output.reshape(-1, lstm_output.shape[-1])  # [(T+1)*B, H]

        n_policy = unroll_len * batch_size
        logits = self.policy.train_forward(
            flat_out[:n_policy],
            entity_embeddings[:n_policy],
            [m[:n_policy] for m in map_skip],
            scalar_context[:n_policy],
            entity_num[:n_policy],
            flat_action,
            flat_sun,
        )
        logits = {
            k: v.reshape((unroll_len, batch_size) + v.shape[1:]) for k, v in logits.items()
        }
        # pad selected-units logits to the fixed S axis so downstream shapes
        # are static (reference model.py:156-158)
        su = logits["selected_units"]
        if su.shape[2] < MAX_SELECTED_UNITS_NUM:
            su = jnp.pad(
                su,
                ((0, 0), (0, 0), (0, MAX_SELECTED_UNITS_NUM - su.shape[2]), (0, 0)),
                constant_values=NEG_INF,
            )
        logits["selected_units"] = su
        return logits, flat_out, baseline_feature

    def policy_forward(self, spatial_info, entity_info, scalar_info, entity_num,
                       hidden_state, action_info, selected_units_num, batch_size,
                       unroll_len):
        """``rl_forward``'s policy half without the value towers — the
        distillation student's train-time forward (student models carry no
        baselines; their training signal is the teacher's logits, not
        returns). Same flat [(T+1)*B, ...] input layout, returns
        ``{"target_logit": [T, B, ...]}``."""
        logits, _, _ = self._learner_logits(
            spatial_info, entity_info, scalar_info, entity_num, hidden_state,
            action_info, selected_units_num, batch_size, unroll_len,
        )
        return {"target_logit": logits}

    def rl_forward(self, spatial_info, entity_info, scalar_info, entity_num,
                   hidden_state, action_info, selected_units_num, batch_size,
                   unroll_len, value_feature=None):
        """Flat [(T+1)*B, ...] inputs -> policy logits [T, B, ...] and six
        baseline values [T+1, B] (reference rl_learner_forward :95-168).

        ``hidden_state`` is the per-trajectory initial state, tuple of
        (h, c) pairs each [B, H].
        """
        logits, flat_out, baseline_feature = self._learner_logits(
            spatial_info, entity_info, scalar_info, entity_num, hidden_state,
            action_info, selected_units_num, batch_size, unroll_len,
        )

        if not static_cfg(self.cfg).use_value_network:
            raise ValueError(
                "rl_forward requires cfg.use_value_network=True (the RL learner "
                "constructs its model with value towers; the default config ships "
                "False for actor-side models, mirroring the reference's "
                "use_value_network ctor flag, model.py:23)"
            )
        critic_input = flat_out
        if static_cfg(self.cfg).only_update_baseline:
            critic_input = jax.lax.stop_gradient(critic_input)
            baseline_feature = jax.lax.stop_gradient(baseline_feature)
        if static_cfg(self.cfg).use_value_feature:
            if value_feature is None:
                raise ValueError(
                    "cfg.use_value_feature=True but the batch carries no "
                    "value_feature — the data source (actor collect_data / "
                    "fake_rl_batch) must include the centralized-critic "
                    "features (lib.features.VALUE_FEATURE_INFO)"
                )
            vf = self.value_encoder(value_feature)
            critic_input = jnp.concatenate([critic_input, vf, baseline_feature], axis=1)
        values = {
            k: v(critic_input).reshape(unroll_len + 1, batch_size)
            for k, v in self.value_networks.items()
        }
        return {"target_logit": logits, "value": values}

    # ------------------------------------------------------------------ SL
    def sl_forward(self, spatial_info, entity_info, scalar_info, entity_num,
                   action_info, selected_units_num, hidden_state, batch_size):
        """Teacher-forced forward over flat [B*T, ...] batches; carries and
        returns the LSTM state (reference sl_train :170-189; note the
        reference lays SL batches out batch-major [B, T])."""
        lstm_input, scalar_context, _, entity_embeddings, map_skip = self.encoder(
            spatial_info, entity_info, scalar_info, entity_num
        )
        seq = lstm_input.reshape(batch_size, -1, lstm_input.shape[-1]).transpose(1, 0, 2)
        lstm_output, out_state = self.core_lstm(seq, hidden_state)
        flat_out = lstm_output.transpose(1, 0, 2).reshape(-1, lstm_output.shape[-1])
        logits = self.policy.train_forward(
            flat_out, entity_embeddings, map_skip, scalar_context, entity_num,
            action_info, selected_units_num,
        )
        return logits, out_state

    def __call__(self, spatial_info, entity_info, scalar_info, entity_num, hidden_state, rng):
        """Default apply target == actor sampling (used for init)."""
        return self.sample_action(
            spatial_info, entity_info, scalar_info, entity_num, hidden_state, rng
        )


def _log_prob(logits: jnp.ndarray, action: jnp.ndarray) -> jnp.ndarray:
    """Categorical log-prob of ``action`` under ``logits`` (last axis)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, action[..., None].astype(jnp.int32), axis=-1)[..., 0]
