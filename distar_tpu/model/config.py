"""Default architecture configuration for the AlphaStar-style policy/value net.

Dimensions reproduce the reference architecture spec
(reference: distar/agent/default/model/actor_critic_default_config.yaml) —
NUM_ACTIONS=327, spatial 152x160, LSTM 1536->384x3, six value baselines —
reorganised as a Python Config so user configs can cascade over it with
deep_merge_dicts. Field *semantics* (which arc each feature uses) live with
the encoders; this file only carries sizes and switches.
"""
from __future__ import annotations

from typing import Any, Mapping

from ..lib import actions as A
from ..lib.features import BEGINNING_ORDER_LENGTH, MAX_DELAY, SPATIAL_SIZE
from ..utils import Config, deep_merge_dicts

SPATIAL_Y, SPATIAL_X = SPATIAL_SIZE


class StaticConfig:
    """Attribute-access view over any Mapping (incl. the FrozenDict flax
    converts Module dict fields into). Not itself a Mapping, so flax leaves
    it alone when passed between modules."""

    def __init__(self, data: Mapping):
        object.__setattr__(self, "_data", data)

    @staticmethod
    def _wrap(v: Any) -> Any:
        return StaticConfig(v) if isinstance(v, Mapping) else v

    def __getattr__(self, k: str) -> Any:
        try:
            return self._wrap(self._data[k])
        except KeyError as e:
            raise AttributeError(k) from e

    def __getitem__(self, k) -> Any:
        return self._wrap(self._data[k])

    def get(self, k, default=None) -> Any:
        v = self._data.get(k, default)
        return self._wrap(v) if isinstance(v, Mapping) else v

    def __contains__(self, k) -> bool:
        return k in self._data


def static_cfg(cfg) -> StaticConfig:
    """Wrap a Mapping (or pass a StaticConfig through) for attribute access."""
    return cfg if isinstance(cfg, StaticConfig) else StaticConfig(cfg)


def cdtype(cfg):
    """Compute dtype from the model config: 'bfloat16' puts every matmul/conv
    on the MXU's native precision (params stay float32; flax's Dense/Conv
    dtype= casts inputs+params for compute only)."""
    import jax.numpy as jnp

    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        static_cfg(cfg).get("dtype", "float32")
    ]


def default_model_config() -> Config:
    bo_encoder = {
        "action_num": A.NUM_BEGINNING_ORDER_ACTIONS,  # 174
        "binary_dim": 10,
        "head_dim": 8,
        "output_dim": 64,
    }
    return Config(
        {
            "spatial_y": SPATIAL_Y,
            "spatial_x": SPATIAL_X,
            "temperature": 1.0,
            "use_value_network": False,
            "use_value_feature": False,
            "only_update_baseline": False,
            "enable_baselines": [
                "winloss", "build_order", "built_unit", "effect", "upgrade", "battle",
            ],
            # entity pooled-embedding reduction: 'selected_units_num' divides the
            # masked sum by entity_num (reference default), 'constant' by 512.
            "entity_reduce_type": "selected_units_num",
            "dtype": "float32",  # compute dtype for matmuls; 'bfloat16' on TPU
            # rematerialize the activation-heavy encoder blocks in the
            # backward pass (jax.checkpoint): trades ~1 extra forward of
            # those blocks for a large cut in live activations — the HBM
            # knob that buys bigger batches on-chip
            "remat": False,
            "encoder": {
                "scalar": {
                    # ordered: (key, arc, in_dim_or_classes, out_dim, context?, baseline?)
                    "fields": [
                        ("agent_statistics", "fc", 10, 64, False, True),
                        ("home_race", "one_hot", 5, 32, True, False),
                        ("away_race", "one_hot", 5, 32, True, False),
                        ("upgrades", "fc", A.NUM_UPGRADES, 128, False, True),
                        ("time", "time", None, 32, False, False),
                        ("unit_counts_bow", "fc", A.NUM_UNIT_TYPES, 128, False, True),
                        ("last_delay", "one_hot", MAX_DELAY + 1, 64, False, False),
                        ("last_queued", "one_hot", 2, 32, False, False),
                        ("last_action_type", "one_hot", A.NUM_ACTIONS, 128, False, False),
                        ("cumulative_stat", "fc", A.NUM_CUMULATIVE_STAT_ACTIONS, 128, True, True),
                        ("beginning_order", "bo_transformer", None, 64, True, True),
                        ("unit_type_bool", "fc", A.NUM_UNIT_TYPES, 64, True, False),
                        ("enemy_unit_type_bool", "fc", A.NUM_UNIT_TYPES, 64, True, False),
                        ("unit_order_type", "fc", A.NUM_UNIT_MIX_ABILITIES, 64, True, False),
                    ],
                    "bo": bo_encoder,
                    # concat of outputs = 1024; context subset = 448; baseline = 512
                },
                "spatial": {
                    # (key, arc, classes) — 'float' divides by 256, 'scatter' is a
                    # coordinate-list effect plane
                    "fields": [
                        ("height_map", "float", None),
                        ("visibility_map", "one_hot", 4),
                        ("creep", "one_hot", 2),
                        ("player_relative", "one_hot", 5),
                        ("alerts", "one_hot", 2),
                        ("pathable", "one_hot", 2),
                        ("buildable", "one_hot", 2),
                        ("effect_PsiStorm", "scatter", None),
                        ("effect_NukeDot", "scatter", None),
                        ("effect_LiberatorDefenderZone", "scatter", None),
                        ("effect_BlindingCloud", "scatter", None),
                        ("effect_CorrosiveBile", "scatter", None),
                        ("effect_LurkerSpines", "scatter", None),
                    ],
                    "project_dim": 32,
                    "down_channels": [64, 128, 128],
                    "resblock_num": 4,
                    "fc_dim": 256,
                },
                "entity": {
                    # (key, arc, classes_or_bits); 'float' appends the raw value
                    "fields": [
                        ("unit_type", "one_hot", A.NUM_UNIT_TYPES),
                        ("alliance", "one_hot", 5),
                        ("cargo_space_taken", "one_hot", 9),
                        ("build_progress", "float", None),
                        ("health_ratio", "float", None),
                        ("shield_ratio", "float", None),
                        ("energy_ratio", "float", None),
                        ("display_type", "one_hot", 5),
                        ("x", "binary", 11),
                        ("y", "binary", 11),
                        ("cloak", "one_hot", 5),
                        ("is_blip", "one_hot", 2),
                        ("is_powered", "one_hot", 2),
                        ("mineral_contents", "float", None),
                        ("vespene_contents", "float", None),
                        ("cargo_space_max", "one_hot", 9),
                        ("assigned_harvesters", "one_hot", 24),
                        ("weapon_cooldown", "one_hot", 32),
                        ("order_length", "one_hot", 9),
                        ("order_id_0", "one_hot", A.NUM_ACTIONS),
                        ("order_id_1", "one_hot", A.QUEUE_ACTION_EMBEDDING_DIM),
                        ("is_hallucination", "one_hot", 2),
                        ("buff_id_0", "one_hot", A.NUM_BUFFS),
                        ("buff_id_1", "one_hot", A.NUM_BUFFS),
                        ("addon_unit_type", "one_hot", A.NUM_ADDON),
                        ("is_active", "one_hot", 2),
                        ("order_progress_0", "float", None),
                        ("order_progress_1", "float", None),
                        ("order_id_2", "one_hot", A.QUEUE_ACTION_EMBEDDING_DIM),
                        ("order_id_3", "one_hot", A.QUEUE_ACTION_EMBEDDING_DIM),
                        ("is_in_cargo", "one_hot", 2),
                        ("attack_upgrade_level", "one_hot", 4),
                        ("armor_upgrade_level", "one_hot", 4),
                        ("shield_upgrade_level", "one_hot", 4),
                        ("last_selected_units", "one_hot", 2),
                        ("last_targeted_unit", "one_hot", 2),
                    ],
                    "head_dim": 128,
                    "hidden_dim": 1024,
                    "output_dim": 256,
                    "head_num": 2,
                    "mlp_num": 2,
                    "layer_num": 3,
                    "ln_type": "post",
                },
                "scatter": {"output_dim": 32, "type": "add"},
                "core_lstm": {"input_size": 1536, "hidden_size": 384, "num_layers": 3},
            },
            "policy": {
                "action_type_head": {
                    "input_dim": 384,
                    "res_dim": 256,
                    "res_num": 2,
                    "action_num": A.NUM_ACTIONS,
                    "action_map_dim": 256,
                    "gate_dim": 1024,
                    "context_dim": 448,
                    "norm_type": "LN",
                },
                "delay_head": {"decode_dim": 256, "delay_dim": MAX_DELAY + 1, "delay_map_dim": 256},
                "queued_head": {"decode_dim": 256, "queued_dim": 2, "queued_map_dim": 256},
                "selected_units_head": {
                    "key_dim": 32,
                    "func_dim": 256,
                    "hidden_dim": 32,
                    "num_layers": 1,
                    "extra_units": True,
                    # teacher-forced decode: 'parallel' (batched, default) or
                    # 'scan' (step-by-step, the sampling path's structure)
                    "train_impl": "parallel",
                },
                "target_unit_head": {"key_dim": 32, "func_dim": 256},
                "location_head": {
                    "reshape_channel": 4,
                    "res_dim": 128,
                    "res_num": 4,
                    "map_skip_dim": 128,
                    "upsample_dims": [64, 32, 1],
                    "gate": True,
                },
            },
            "value": {
                # per-baseline tower params; atan squash only on winloss
                "baselines": {
                    "winloss": {"atan": True},
                    "build_order": {"atan": False},
                    "built_unit": {"atan": False},
                    "effect": {"atan": False},
                    "upgrade": {"atan": False},
                    "battle": {"atan": False},
                },
                "input_dim": 384,
                "res_dim": 256,
                "res_num": 16,
                "norm_type": "LN",
                "encoder": {
                    # value_feature fields (centralized critic; opponent info)
                    "fc_fields": [
                        ("enemy_unit_counts_bow", A.NUM_UNIT_TYPES, 64),
                        ("enemy_unit_type_bool", A.NUM_UNIT_TYPES, 64),
                        ("enemy_agent_statistics", 10, 64),
                        ("enemy_upgrades", A.NUM_UPGRADES, 32),
                        ("enemy_cumulative_stat", A.NUM_CUMULATIVE_STAT_ACTIONS, 128),
                    ],
                    "unit_fields": [("unit_alliance", 2, 16), ("unit_type", A.NUM_UNIT_TYPES, 48)],
                    "bo": bo_encoder,
                    "scatter_dim": 8,
                    "spatial": {"project_dim": 16, "down_channels": [16, 32, 32], "resblock_num": 4, "fc_dim": 128},
                },
            },
        }
    )


#: The distillation student's shrink overlay (cascaded over the teacher's
#: config by :func:`student_model_config`). Every head keeps its STRUCTURE
#: — same six heads, same action vocabularies, same logit axes — so the
#: student's wire outputs (logits, actions, versions) are drop-in
#: replacements for the teacher's on every serving surface; only widths,
#: depths and the LSTM carry dims shrink. Dims that derive from the
#: observation contract (scalar-field vocabularies, context_dim 448, the
#: spatial grid) are untouched: shrinking them would change semantics, not
#: just capacity.
STUDENT_SHRINK = {
    "encoder": {
        "entity": {
            # the entity transformer is the FLOP center: half the width,
            # quarter the MLP, one less block
            "head_dim": 64,
            "hidden_dim": 256,
            "output_dim": 128,
            "layer_num": 2,
        },
        "spatial": {
            "project_dim": 16,
            "down_channels": [32, 64, 64],
            "resblock_num": 2,
            "fc_dim": 128,
        },
        "scatter": {"output_dim": 16},
        # half the carry width; SAME layer count, so the (h, c)-tuple
        # structure the serve plane snapshots/restores is isomorphic
        # (input = 1024 scalar concat + 128 entity + 128 spatial)
        "core_lstm": {"input_size": 1280, "hidden_size": 192, "num_layers": 3},
    },
    "policy": {
        "action_type_head": {
            "input_dim": 192, "res_dim": 128, "res_num": 1,
            "action_map_dim": 128, "gate_dim": 256,
        },
        "delay_head": {"decode_dim": 128, "delay_map_dim": 128},
        "queued_head": {"decode_dim": 128, "queued_map_dim": 128},
        "selected_units_head": {"func_dim": 128},
        "target_unit_head": {"func_dim": 128},
        "location_head": {
            "res_dim": 64, "res_num": 2, "map_skip_dim": 64,
            "upsample_dims": [32, 16, 1],
        },
    },
    "value": {"input_dim": 192, "res_dim": 128, "res_num": 4},
}


def student_model_config(overrides: Mapping = None) -> Config:
    """The distillation student: :func:`default_model_config` with
    :data:`STUDENT_SHRINK` cascaded over it, then any user ``overrides``
    (so a smoke config shrinks the student the same way it shrinks the
    teacher). Head structure is identical to the teacher's by construction
    — only capacity differs — which is what lets student checkpoints roll
    through the same gateways, canary splits and player muxes as teacher
    ones (docs/serving.md, model tiering)."""
    cfg = deep_merge_dicts(default_model_config(), STUDENT_SHRINK)
    if overrides:
        cfg = deep_merge_dicts(cfg, overrides)
    return cfg
