from .distill_loss import DistillLossConfig, compute_distill_loss
from .rl_loss import ReinforcementLossConfig, compute_rl_loss
from .sl_loss import SupervisedLossConfig, compute_sl_loss

__all__ = [
    "DistillLossConfig",
    "compute_distill_loss",
    "ReinforcementLossConfig",
    "compute_rl_loss",
    "SupervisedLossConfig",
    "compute_sl_loss",
]
