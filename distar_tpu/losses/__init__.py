from .rl_loss import ReinforcementLossConfig, compute_rl_loss
from .sl_loss import SupervisedLossConfig, compute_sl_loss

__all__ = [
    "ReinforcementLossConfig",
    "compute_rl_loss",
    "SupervisedLossConfig",
    "compute_sl_loss",
]
