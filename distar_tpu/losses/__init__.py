from .distill_loss import DistillLossConfig, compute_distill_loss
from .rl_loss import (
    HEADS,
    LOSS_TERMS,
    REWARD_FIELDS,
    ReinforcementLossConfig,
    compute_rl_loss,
)
from .sl_loss import SL_METRIC_KEYS, SupervisedLossConfig, compute_sl_loss

__all__ = [
    "DistillLossConfig",
    "compute_distill_loss",
    "HEADS",
    "LOSS_TERMS",
    "REWARD_FIELDS",
    "ReinforcementLossConfig",
    "compute_rl_loss",
    "SL_METRIC_KEYS",
    "SupervisedLossConfig",
    "compute_sl_loss",
]
