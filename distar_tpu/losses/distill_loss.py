"""Actor-learner distillation loss: masked per-head KL against the teacher.

The student tier ("Efficient Transformers in Reinforcement Learning using
Actor-Learner Distillation", PAPERS.md) trains on the SAME trajectory
batches the RL learner consumes — the teacher logits already ride every
rollout flush (the serve plane's ``want_teacher`` leg), so distillation
costs zero extra teacher forwards on the hot path. The loss is the
forward KL ``KL(teacher || student)`` per action head, with exactly the
mask semantics of :mod:`losses.rl_loss`'s ``_kl_terms``:

  * ``selected_units``: per-lane KL over the pointer decode, summed over
    the S axis under ``selected_units_mask`` (a step with zero active
    lanes contributes nothing);
  * heads outside ``ALWAYS_ON`` gate on ``actions_mask[head]`` (a step
    whose action type takes no target unit must not distill one);
  * every head multiplies ``step_mask`` so pad steps after a mid-window
    episode end contribute to no term.

Input layout (time-major, the RL batch's own shapes):
  student_logit[head]   [T, B, ...]
  teacher_logit[head]   [T, B, ...]
  mask:
    actions_mask[head]  [T, B]
    selected_units_mask [T, B, S]
    step_mask           [T, B]   (optional; 1 real / 0 pad)
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from .rl_loss import ALWAYS_ON, HEADS, _default_head_weights


@dataclasses.dataclass(frozen=True)
class DistillLossConfig:
    """Head weights mirror the RL loss's (selected_units down-weighted the
    same way); ``temperature`` softens BOTH distributions (T > 1 transfers
    more of the teacher's dark knowledge; the KL is computed at the
    softened temperature, standard distillation practice)."""

    temperature: float = 1.0
    selected_units_head_weight: float = 0.01

    def head_weights(self) -> Dict[str, float]:
        return _default_head_weights(self.selected_units_head_weight)


def compute_distill_loss(
    inputs: Dict,
    cfg: DistillLossConfig = DistillLossConfig(),
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """(total, info): weighted masked KL summed over heads. ``info`` carries
    ``kl/<head>`` per-head means, the weighted ``kl/total``, and
    ``divergence`` — the UNWEIGHTED sum of head means, the drift gauge the
    distill learner publishes (weight-independent, so retuning head weights
    never silently moves the health rule's input)."""
    student = inputs["student_logit"]
    teacher = inputs["teacher_logit"]
    masks = inputs["mask"]
    su_mask = masks["selected_units_mask"]
    tau = cfg.temperature

    any_head = student["action_type"]
    step_mask = masks.get("step_mask")
    if step_mask is None:
        step_mask = jnp.ones(any_head.shape[:2], dtype=jnp.float32)
    else:
        step_mask = step_mask.astype(jnp.float32)

    info: Dict[str, jnp.ndarray] = {}
    head_w = cfg.head_weights()
    total = 0.0
    divergence = 0.0
    for head in HEADS:
        t_logp = jax.nn.log_softmax(teacher[head] / tau, axis=-1)
        s_logp = jax.nn.log_softmax(student[head] / tau, axis=-1)
        kl = (jnp.exp(t_logp) * (t_logp - s_logp)).sum(-1)
        if head == "selected_units":
            kl = (kl * su_mask).sum(-1)
        kl = kl * step_mask
        if head not in ALWAYS_ON:
            kl = kl * masks["actions_mask"][head]
        kl_mean = kl.mean()
        info[f"kl/{head}"] = kl_mean
        total += kl_mean * head_w[head]
        divergence += kl_mean
    info["kl/total"] = total
    info["divergence"] = divergence
    info["total_loss"] = total
    return total, info
