"""League-RL loss: per-head V-trace PG + UPGO + TD(lambda) critics + entropy
+ teacher-KL (+ optional DAPO successive-policy KL).

Pure-jnp equivalent of the reference ReinforcementLoss
(reference: distar/agent/default/rl_training/rl_loss.py:33-185 and
as_rl_utils.py:1-127), jit-safe end to end: every branch in the reference's
Python control flow is either a static config switch or a masked arithmetic
path here. Default weights mirror default_reinforcement_loss.yaml.

Input layout (time-major):
  target_logit[head]      [T, B, ...]      learner policy logits
  value[field]            [T+1, B]         baseline values
  action_log_prob[head]   [T, B] / [T,B,S] behaviour log-probs (actor-side)
  teacher_logit[head]     [T, B, ...]
  action[head]            [T, B] / [T,B,S]
  reward[field]           [T, B]
  step                    [T, B]           game steps
  mask:
    actions_mask[head]    [T, B]   per-step head applicability
    selected_units_mask   [T, B, S]
    step_mask             [T, B]   1 real step / 0 pad step (optional)
    build_order_mask, built_unit_mask, effect_mask, cum_action_mask  [T, B]
  done                    [T, B]   1 from the terminal step onward (optional)
  entity_num              [T, B]   for entropy normalisation
  selected_units_num      [T, B]
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import upgo_returns, vtrace_advantages, generalized_lambda_returns

HEADS = ("action_type", "delay", "queued", "selected_units", "target_unit", "target_location")
# heads whose losses are always active (the rest gate on actions_mask)
ALWAYS_ON = ("action_type", "delay")
# the reward/value fields of the info grid (pg/{field}/{head}, td/{field},
# reward/{field}, value/{field}) — the obs layer's bounded label vocabulary
# for the distar_train_loss_* gauges lives HERE, next to the keys it names
REWARD_FIELDS = ("winloss", "build_order", "built_unit", "effect", "upgrade",
                 "battle")
# loss-term prefixes the info dict produces ("{term}/total" and, for the
# per-head terms, "{term}/{head}")
LOSS_TERMS = ("pg", "upgo", "td", "entropy", "kl", "dapo")
FIELD_MASKS = {"build_order": "build_order_mask", "built_unit": "built_unit_mask", "effect": "effect_mask"}


def _default_head_weights(selected_units: float = 0.01) -> Dict[str, float]:
    return {h: (selected_units if h == "selected_units" else 1.0) for h in HEADS}


@dataclasses.dataclass(frozen=True)
class ReinforcementLossConfig:
    """Mirrors default_reinforcement_loss.yaml."""

    baseline_weights: Tuple[Tuple[str, float], ...] = (
        ("winloss", 10.0), ("build_order", 0.0), ("built_unit", 0.0),
        ("effect", 0.0), ("upgrade", 0.0), ("battle", 0.0),
    )
    pg_weights: Tuple[Tuple[str, float], ...] = (
        ("winloss", 1.0), ("build_order", 0.0), ("built_unit", 0.0),
        ("effect", 0.0), ("upgrade", 0.0), ("battle", 0.0),
    )
    upgo_weight: float = 1.0
    kl_weight: float = 0.02
    action_type_kl_weight: float = 0.1
    entropy_weight: float = 1e-4
    dapo_weight: float = 0.0
    gammas: Tuple[Tuple[str, float], ...] = (
        ("winloss", 1.0), ("build_order", 1.0), ("built_unit", 1.0),
        ("effect", 1.0), ("upgrade", 1.0), ("battle", 0.997),
    )
    td_lambda: float = 0.8
    vtrace_lambda: float = 1.0
    pg_gamma: float = 1.0  # reference passes gamma=1.0 into the PG vtrace
    action_type_kl_steps: int = 2400
    dapo_steps: int = 2400
    use_dapo: bool = False
    only_update_value: bool = False
    selected_units_head_weight: float = 0.01

    def head_weights(self) -> Dict[str, float]:
        return _default_head_weights(self.selected_units_head_weight)


def _log_softmax(logits):
    return jax.nn.log_softmax(logits, axis=-1)


def _gather(logp, action):
    return jnp.take_along_axis(logp, action[..., None].astype(jnp.int32), axis=-1)[..., 0]


def compute_rl_loss(
    inputs: Dict,
    cfg: ReinforcementLossConfig = ReinforcementLossConfig(),
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    target_logit = inputs["target_logit"]
    values = dict(inputs["value"])
    behaviour_logp = inputs["action_log_prob"]
    teacher_logit = inputs["teacher_logit"]
    masks = inputs["mask"]
    actions = inputs["action"]
    rewards = inputs["reward"]
    steps = inputs["step"]
    entity_num = inputs["entity_num"]
    su_mask = masks["selected_units_mask"]

    info: Dict[str, jnp.ndarray] = {}

    vdtype = values[next(iter(values))].dtype
    # step_mask: 1 on real steps, 0 on the pad steps that fill a trajectory
    # window after a mid-window episode end. Padded steps must contribute to
    # NO loss term (incl. the always-on action_type/delay heads) and their
    # post-terminal values are 0 by definition.
    step_mask = masks.get("step_mask")
    if step_mask is None:
        step_mask = jnp.ones_like(rewards["winloss"], dtype=vdtype)
    else:
        step_mask = step_mask.astype(vdtype)
    # explicit done flag [T, B] (1 from the terminal step onward): zero the
    # bootstrap value when the episode ended anywhere in this window — the
    # reference zeroes it on done (rl_loss.py:47-49); inferring done from
    # reward[-1]==0 breaks when the terminal +-1 sits mid-window before pads.
    done = inputs.get("done")
    if done is None:
        not_done = (rewards["winloss"][-1] == 0).astype(vdtype)
    else:
        not_done = 1.0 - done[-1].astype(vdtype)
    for field in values:
        v = values[field]
        v = v.at[:-1].multiply(step_mask)  # post-terminal states have value 0
        v = v.at[-1].multiply(not_done)
        values[field] = v

    # per-head distribution prep
    target_logp_full: Dict[str, jnp.ndarray] = {}
    target_prob_full: Dict[str, jnp.ndarray] = {}
    target_action_logp: Dict[str, jnp.ndarray] = {}
    clipped_rhos: Dict[str, jnp.ndarray] = {}
    for head in HEADS:
        logp_full = _log_softmax(target_logit[head])
        target_logp_full[head] = logp_full
        target_prob_full[head] = jnp.exp(logp_full)
        alogp = _gather(logp_full, actions[head])
        blogp = behaviour_logp[head]
        if head == "selected_units":
            alogp = jnp.where(su_mask, alogp, 0.0).sum(-1)
            log_rho = jax.lax.stop_gradient(
                (jnp.where(su_mask, _gather(logp_full, actions[head]) - blogp, 0.0)).sum(-1)
            )
        else:
            log_rho = jax.lax.stop_gradient(alogp - blogp)
        target_action_logp[head] = alogp
        clipped_rhos[head] = jnp.minimum(jnp.exp(log_rho), 1.0)

    head_w = cfg.head_weights()
    gammas = dict(cfg.gammas)

    # ------------------------------------------------ policy gradient (vtrace)
    total_pg = 0.0
    for field, field_w in cfg.pg_weights:
        if field not in values or field not in rewards:
            continue
        reward = rewards[field].astype(jnp.float32)
        baseline = values[field]
        field_pg = 0.0
        for head in HEADS:
            adv = jax.lax.stop_gradient(
                vtrace_advantages(
                    clipped_rhos[head], clipped_rhos[head], reward, baseline,
                    gammas=cfg.pg_gamma, lambda_=cfg.vtrace_lambda,
                )
            )
            pg = -adv * target_action_logp[head] * step_mask
            if head not in ALWAYS_ON:
                pg = pg * masks["actions_mask"][head]
            if field in FIELD_MASKS:
                pg = pg * masks[FIELD_MASKS[field]]
            pg = pg.mean()
            field_pg += pg * head_w[head]
            info[f"pg/{field}/{head}"] = pg
        total_pg += field_w * field_pg
    info["pg/total"] = total_pg

    # ------------------------------------------------------------------ UPGO
    total_upgo = 0.0
    upgo_adv_base = jax.lax.stop_gradient(
        upgo_returns(rewards["winloss"].astype(jnp.float32), values["winloss"])
        - values["winloss"][:-1]
    )
    for head in HEADS:
        adv = clipped_rhos[head] * upgo_adv_base
        ug = -adv * target_action_logp[head] * step_mask
        if head not in ALWAYS_ON:
            ug = ug * masks["actions_mask"][head]
        ug = ug.mean()
        total_upgo += ug * head_w[head]
        info[f"upgo/{head}"] = ug
    total_upgo = total_upgo * cfg.upgo_weight
    info["upgo/total"] = total_upgo

    # ---------------------------------------------------------------- critic
    total_critic = 0.0
    for field, field_w in cfg.baseline_weights:
        if field not in values or field not in rewards:
            continue
        reward = rewards[field].astype(jnp.float32)
        baseline = values[field]
        returns = jax.lax.stop_gradient(
            generalized_lambda_returns(reward, gammas[field], baseline, cfg.td_lambda)
        )
        td = 0.5 * jnp.square(returns - baseline[:-1]) * step_mask
        if field in FIELD_MASKS:
            td = td * masks[FIELD_MASKS[field]]
        td = td.mean()
        total_critic += field_w * td
        info[f"td/{field}"] = td
        info[f"reward/{field}"] = reward.mean()
        info[f"value/{field}"] = baseline.mean()
    info["td/total"] = total_critic

    # --------------------------------------------------------------- entropy
    total_entropy_loss = 0.0
    for head in HEADS:
        ent = -target_prob_full[head] * target_logp_full[head]
        if head == "selected_units":
            # normalise by log(valid candidates + 1) and average over real steps
            norm = jnp.log(entity_num.astype(jnp.float32) + 1.0 + 1e-9)[..., None]
            ent = ent.sum(-1) / norm
            ent = (ent * su_mask).sum(-1) / (su_mask.sum(-1) + 1e-9)
        elif head == "target_unit":
            # log(num_valid_targets + 1) (reference as_rl_utils.py:59-61);
            # the +1 inside the log also guards entity_num == 1
            ent = ent.sum(-1) / (jnp.log(entity_num.astype(jnp.float32) + 1.0) + 1e-9)
        else:
            ent = ent.sum(-1) / jnp.log(float(ent.shape[-1]))
        ent = ent * step_mask
        if head not in ALWAYS_ON:
            ent = ent * masks["actions_mask"][head]
        ent_mean = ent.mean()
        info[f"entropy/{head}"] = ent_mean
        total_entropy_loss += -ent_mean * head_w[head]
    total_entropy_loss = total_entropy_loss * cfg.entropy_weight
    info["entropy/total"] = total_entropy_loss

    # -------------------------------------------------------------------- KL
    def _kl_terms(ref_logit):
        out = {}
        for head in HEADS:
            ref_logp = _log_softmax(ref_logit[head])
            kl = (jnp.exp(ref_logp) * (ref_logp - target_logp_full[head])).sum(-1)
            if head == "selected_units":
                kl = (kl * su_mask).sum(-1)
            kl = kl * step_mask
            if head not in ALWAYS_ON:
                kl = kl * masks["actions_mask"][head]
            out[head] = kl
        return out

    kls = _kl_terms(teacher_logit)
    total_kl = 0.0
    for head, kl in kls.items():
        kl_mean = kl.mean()
        total_kl += kl_mean * head_w[head]
        info[f"kl/{head}"] = kl_mean
    at_kl = (
        kls["action_type"]
        * (steps < cfg.action_type_kl_steps)
        * masks["cum_action_mask"]
    ).mean()
    total_kl = total_kl * cfg.kl_weight
    at_kl = at_kl * cfg.action_type_kl_weight
    info["kl/total"] = total_kl
    info["kl/extra_at"] = at_kl

    # ------------------------------------------------------------------ DAPO
    total_dapo = 0.0
    if cfg.use_dapo:
        dapo_kls = _kl_terms(inputs["successive_logit"])
        flag = steps < cfg.dapo_steps
        for head, kl in dapo_kls.items():
            kl_mean = (kl * flag).mean()
            total_dapo += kl_mean * head_w[head]
            info[f"dapo/{head}"] = kl_mean
        total_dapo = total_dapo * cfg.dapo_weight
        info["dapo/total"] = total_dapo

    if cfg.only_update_value:
        total = total_critic
    else:
        total = (
            total_pg + total_upgo + total_critic + total_entropy_loss
            + total_kl + at_kl + total_dapo
        )
    info["total_loss"] = total
    return total, info
