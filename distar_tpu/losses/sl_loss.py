"""Supervised (behaviour-cloning) loss: per-head cross entropy + metrics.

Pure-jnp equivalent of the reference SupervisedLoss
(reference: distar/agent/default/sl_training/sl_loss.py). Per-head CE with
optional label smoothing, per-head applicability masks, the selected-units
candidate masking trick (su_mask: at step i every *other* ground-truth unit
is removed from the softmax so order permutations aren't penalised,
sl_loss.py:176-192), end-flag loss, and the accuracy metric grid
(action_type_acc, delay L1, queued acc, selected-units IoU, target_unit acc,
location L2). Default weights mirror default_supervised_loss.yaml.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import sequence_mask

NEG_INF = -1e9

# every non-loss scalar the SL info dict produces — the obs layer's bounded
# label vocabulary for the distar_train_sl_metric gauge family (a name not
# listed here is never published as a labelled series)
SL_METRIC_KEYS = (
    "action_type_acc",
    "delay_distance_L1",
    "queued_acc",
    "target_unit_acc",
    "target_location_distance_L2",
    "selected_units_iou",
    "selected_units_loss_norm",
    "selected_units_end_flag_loss",
)


@dataclasses.dataclass(frozen=True)
class SupervisedLossConfig:
    action_type: float = 30.0
    delay: float = 9.0
    queued: float = 1.0
    selected_units: float = 4.0
    target_unit: float = 4.0
    target_location: float = 8.0
    label_smooth: float = 0.0  # 0.1 in the reference when label_smooth: True
    su_candidate_mask: bool = True
    spatial_x: int = 160

    def weights(self) -> Dict[str, float]:
        return {
            "action_type": self.action_type,
            "delay": self.delay,
            "queued": self.queued,
            "selected_units": self.selected_units,
            "target_unit": self.target_unit,
            "target_location": self.target_location,
        }


def _ce(logits, labels, smoothing: float = 0.0):
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    if smoothing > 0.0:
        smooth = -logp.mean(axis=-1)
        return (1.0 - smoothing) * nll + smoothing * smooth
    return nll


def _masked_mean(x, mask):
    valid = mask.sum()
    return jnp.where(valid > 0, (x * mask).sum() / jnp.maximum(valid, 1), 0.0)


def compute_sl_loss(
    logits: Dict[str, jnp.ndarray],
    actions: Dict[str, jnp.ndarray],
    action_masks: Dict[str, jnp.ndarray],
    selected_units_num: jnp.ndarray,  # [B]
    entity_num: jnp.ndarray,  # [B]
    cfg: SupervisedLossConfig = SupervisedLossConfig(),
    infer_selected_units: Optional[jnp.ndarray] = None,  # [B, S] sampled, for IoU
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    info: Dict[str, jnp.ndarray] = {}
    w = cfg.weights()
    total = 0.0

    # ------------------------------------------------------------ flat heads
    for head in ("action_type", "delay", "queued", "target_unit", "target_location"):
        lab = actions[head].astype(jnp.int32)
        mask = action_masks[head].astype(jnp.float32)
        ce = _ce(logits[head], lab, cfg.label_smooth)
        loss = _masked_mean(ce, mask)
        info[f"{head}_loss"] = loss
        total += loss * w[head]
        pred = logits[head].argmax(-1)
        if head == "action_type":
            info["action_type_acc"] = (pred == lab).mean()
        elif head == "delay":
            info["delay_distance_L1"] = _masked_mean(jnp.abs(pred - lab), mask)
        elif head == "queued":
            info["queued_acc"] = _masked_mean((pred == lab).astype(jnp.float32), mask)
        elif head == "target_unit":
            info["target_unit_acc"] = _masked_mean((pred == lab).astype(jnp.float32), mask)
        elif head == "target_location":
            W = cfg.spatial_x
            d2 = (pred % W - lab % W) ** 2 + (pred // W - lab // W) ** 2
            info["target_location_distance_L2"] = _masked_mean(jnp.sqrt(d2.astype(jnp.float32)), mask)

    # --------------------------------------------------------- selected units
    su_logits = logits["selected_units"]  # [B, S, N+1]
    B, S, N1 = su_logits.shape
    labels = actions["selected_units"].astype(jnp.int32)[:, :S]  # [B, S]
    lengths = selected_units_num.astype(jnp.int32)
    mask = action_masks["selected_units"].astype(jnp.float32)  # [B]

    if cfg.su_candidate_mask:
        # at step i mask out every ground-truth unit except the step's own
        # label (end-flag positions use a dummy class so they mask nothing)
        len_wo_end = jnp.maximum(lengths - 1, 0)
        real_pos = sequence_mask(len_wo_end, S)  # [B, S] non-end label slots
        dummy = N1  # one-past-last class
        eff_labels = jnp.where(real_pos, labels, dummy)
        labeled_any = jax.nn.one_hot(eff_labels, N1 + 1, dtype=jnp.float32).sum(1) > 0  # [B, N+2)
        labeled_any = labeled_any[:, :N1]  # drop dummy
        step_own = jax.nn.one_hot(eff_labels, N1 + 1, dtype=jnp.float32)[..., :N1].astype(bool)
        allowed = ~labeled_any[:, None, :] | step_own  # [B, S, N+1]
        su_logits = jnp.where(allowed, su_logits, NEG_INF)

    ce = _ce(su_logits, labels)  # [B, S]
    select_mask = sequence_mask(lengths, S)
    ce = jnp.where(select_mask, ce, 0.0) * mask[:, None]
    su_loss = ce.sum() / B
    info["selected_units_loss"] = su_loss
    info["selected_units_loss_norm"] = ce.sum() / (lengths.sum() + 1e-6)
    end_idx = jnp.clip(lengths - 1, 0, S - 1)
    info["selected_units_end_flag_loss"] = jnp.take_along_axis(ce, end_idx[:, None], axis=1).mean()
    total += su_loss * w["selected_units"]

    # IoU between sampled and labelled unit sets (ignoring order)
    if infer_selected_units is not None:
        preds = infer_selected_units.astype(jnp.int32)[:, :S]
        # count predicted steps up to (and incl.) the first end token
        is_end = preds == entity_num[:, None]
        any_end = is_end.any(axis=1)
        first_end = jnp.argmax(is_end, axis=1)
        pred_len = jnp.where(any_end, first_end, S)
        pred_mask = sequence_mask(pred_len, S)
        lab_mask = sequence_mask(len_wo_end if cfg.su_candidate_mask else lengths, S)
        pred_bag = (jax.nn.one_hot(preds, N1, dtype=jnp.float32) * pred_mask[..., None]).sum(1) > 0
        lab_bag = (jax.nn.one_hot(labels, N1, dtype=jnp.float32) * lab_mask[..., None]).sum(1) > 0
        inter = (pred_bag & lab_bag).sum(-1)
        union = (pred_bag | lab_bag).sum(-1)
        info["selected_units_iou"] = _masked_mean(inter / jnp.maximum(union, 1), mask)
    else:
        info["selected_units_iou"] = jnp.zeros(())

    info["total_loss"] = total
    return total, info
