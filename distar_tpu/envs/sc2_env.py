"""SC2 environment orchestration over abstract game controllers.

Role parity with the reference SC2Env (reference: distar/envs/env.py:96-455):
per-agent variable ``skip_steps`` delays (the AlphaStar delay-action model —
each agent names the game loop of its next observation, the env advances to
the earliest one, :333-375), simulated inference-latency noise
(`random_delay_weights`, :350-354), win/loss extraction from player_result
(:411-424), per-agent {obs, opponent_obs, action_result} returns (:443-455),
and episode-length cutoffs.

The controller is abstract (`GameController`): the reference's
RemoteController (websocket+protobuf to the SC2 binary,
pysc2/lib/remote_controller.py) slots in unchanged once the proto package is
available; `FakeController` (dummy protos) makes the whole orchestration
testable without the game — the reference's mock_sc2_env strategy applied
one layer lower.
"""
from __future__ import annotations

import random
from typing import Dict, List, Optional, Protocol, Sequence

import numpy as np

from .env import BaseEnv
from .features import ProtoFeatures

# sc_pb.Result: Victory=1, Defeat=2, Tie=3, Undecided=4
POSSIBLE_RESULTS = {1: 1, 2: -1, 3: 0, 4: 0}
MAX_STEP_COUNT = 524_000  # SC2 hard limit 2^19, minus margin (reference :427)


class GameController(Protocol):
    """Subset of the reference RemoteController the env drives."""

    status_ended: bool

    def step(self, loops: int) -> None: ...

    def acts(self, raw_cmds: List[dict]): ...  # returns result-like or None

    def observe(self, target_game_loop: int = 0): ...  # raw proto obs


class SC2Env(BaseEnv):
    def __init__(
        self,
        controllers: Sequence[GameController],
        features: Sequence[ProtoFeatures],
        episode_length: int = 100_000,
        random_delay_weights: Optional[Sequence[float]] = None,
        realtime: bool = False,
        both_obs: bool = True,
        seed: int = 0,
    ):
        assert len(controllers) == len(features)
        self._controllers = list(controllers)
        self._features = list(features)
        self.num_agents = len(controllers)
        self._episode_length = min(episode_length, MAX_STEP_COUNT)
        self._random_delay_weights = list(random_delay_weights or [])
        self._realtime = realtime
        self._both_obs = both_obs and self.num_agents == 2
        self._rng = random.Random(seed)
        self._episode_steps = 0
        self._episode_count = 0
        self._next_obs_step = [0] * self.num_agents
        self._action_result: List[List[int]] = [[1] for _ in range(self.num_agents)]
        self._last_tags: List[list] = [[] for _ in range(self.num_agents)]
        self._done = True

    # ------------------------------------------------------------------ api
    def reset(self) -> Dict[int, dict]:
        self._episode_steps = 0
        self._episode_count += 1
        self._next_obs_step = [0] * self.num_agents
        self._action_result = [[1] for _ in range(self.num_agents)]
        self._done = False
        # restart the underlying game (reference restarts via the
        # controller's restart_game / create+join, env.py:298-330)
        for c in self._controllers:
            if hasattr(c, "reset"):
                c.reset()
        obs, _, _, _ = self._observe(0)
        return obs

    def step(self, actions: Dict[int, dict]):
        assert not self._done, "step() after episode end; call reset()"
        # issue raw commands + register each agent's requested delay
        for idx, action in actions.items():
            delay = max(int(np.asarray(action["delay"]).reshape(-1)[0]), 1)
            self._next_obs_step[idx] = self._episode_steps + delay
            cmd = self._features[idx].transform_action(
                action, self._last_tags[idx],
                selected_units_num=action.get("selected_units_num"),
            )
            c = self._controllers[idx]
            if not c.status_ended:
                result = c.acts([cmd])
                if result is not None:
                    self._action_result[idx] = (
                        list(result) if isinstance(result, (list, tuple)) else [result]
                    )

        # simulated inference/network latency for short delays (reference
        # :350-354, fires only when EVERY acting agent requested a short
        # delay): the game runs on while the "agents think"
        if not self._realtime and self._random_delay_weights and actions:
            max_delay = max(
                self._next_obs_step[i] - self._episode_steps for i in actions
            )
            if max_delay < 4:
                lag = self._rng.choices(
                    range(len(self._random_delay_weights)),
                    weights=self._random_delay_weights,
                )[0]
                self._advance(lag)
                self._episode_steps += lag

        target = min(self._next_obs_step)
        step_mul = max(target - self._episode_steps, 0)
        self._advance(step_mul)
        # dueness is judged inside _observe against the ACTUAL game loop —
        # a latency lag may have overshot some agents' schedules
        return self._observe(max(target, self._episode_steps))

    def close(self) -> None:
        for c in self._controllers:
            if hasattr(c, "close"):
                c.close()

    # ------------------------------------------------------------- internals
    def _advance(self, loops: int) -> None:
        if loops <= 0:
            return
        for c in self._controllers:
            if not c.status_ended:
                c.step(loops)

    def _observe(self, target_game_loop: int):
        raw = [c.observe(target_game_loop=target_game_loop) for c in self._controllers]
        game_loop = int(raw[0].observation.game_loop)
        self._episode_steps = game_loop
        due = [i for i in range(self.num_agents) if self._next_obs_step[i] <= game_loop]

        outcome = [0] * self.num_agents
        episode_complete = any(
            getattr(o, "player_result", None) for o in raw if o is not None
        )
        if episode_complete:
            for i, o in enumerate(raw):
                if o is None:
                    continue
                pid = o.observation.player_common.player_id
                for result in o.player_result:
                    if result.player_id == pid:
                        outcome[i] = POSSIBLE_RESULTS.get(result.result, 0)
                    elif self.num_agents == 2:
                        outcome[1 - i] = POSSIBLE_RESULTS.get(result.result, 0)
        if game_loop >= self._episode_length:
            episode_complete = True
        self._done = episode_complete

        obs: Dict[int, dict] = {}
        indices = range(self.num_agents) if episode_complete else due
        for i in indices:
            opponent = raw[1 - i] if self._both_obs else None
            f_obs = self._features[i].transform_obs(raw[i], opponent_obs=opponent)
            f_obs["action_result"] = self._action_result[i]
            self._last_tags[i] = f_obs["game_info"]["tags"]
            obs[i] = f_obs
        rewards = {i: float(outcome[i]) for i in range(self.num_agents)}
        info = {"game_loop": game_loop, "outcome": outcome}
        return obs, rewards, episode_complete, info


class FakeController:
    """Dummy-proto controller: advances a loop counter, serves synthetic
    observations, ends with a victory/defeat pair after ``end_at`` loops."""

    def __init__(self, player_id: int = 1, end_at: int = 1000, n_units: int = 8,
                 map_y: int = 120, map_x: int = 120, seed: int = 0,
                 winner_player: int = 1):
        from .dummy_obs import build_dummy_obs, make_unit
        from ..lib import actions as ACT

        self._build = build_dummy_obs
        self._make_unit = make_unit
        self._unit_type = ACT.UNIT_TYPES[10]
        self.player_id = player_id
        self._end_at = end_at
        self._n_units = n_units
        self._map = (map_y, map_x)
        self._rng = np.random.default_rng(seed)
        self._winner = winner_player
        self.game_loop = 0
        self.status_ended = False
        self.acts_log: List[list] = []

    def reset(self) -> None:
        """Restart the fake game (role of restart_game in the real client)."""
        self.game_loop = 0
        self.status_ended = False

    def step(self, loops: int) -> None:
        self.game_loop += loops

    def acts(self, raw_cmds: List[dict]):
        self.acts_log.append(raw_cmds)
        return [1]

    def observe(self, target_game_loop: int = 0):
        if target_game_loop > self.game_loop:
            self.game_loop = target_game_loop
        units = [
            self._make_unit(100 + i, self._unit_type, x=5 + i, y=10)
            for i in range(self._n_units)
        ]
        obs = self._build(
            units=units, game_loop=self.game_loop, player_id=self.player_id,
            map_y=self._map[0], map_x=self._map[1], rng=self._rng,
        )
        if self.game_loop >= self._end_at:
            self.status_ended = True
            from types import SimpleNamespace as NS

            obs.player_result = [
                NS(player_id=1, result=1 if self._winner == 1 else 2),
                NS(player_id=2, result=1 if self._winner == 2 else 2),
            ]
        else:
            obs.player_result = []
        return obs
