"""SC2 environment orchestration over abstract game controllers.

Role parity with the reference SC2Env (reference: distar/envs/env.py:96-455):
per-agent variable ``skip_steps`` delays (the AlphaStar delay-action model —
each agent names the game loop of its next observation, the env advances to
the earliest one, :333-375), simulated inference-latency noise
(`random_delay_weights`, :350-354), win/loss extraction from player_result
(:411-424), per-agent {obs, opponent_obs, action_result} returns (:443-455),
and episode-length cutoffs.

The controller is abstract (`GameController`): the reference's
RemoteController (websocket+protobuf to the SC2 binary,
pysc2/lib/remote_controller.py) slots in unchanged once the proto package is
available; `FakeController` (dummy protos) makes the whole orchestration
testable without the game — the reference's mock_sc2_env strategy applied
one layer lower.
"""
from __future__ import annotations

import random
from typing import Dict, List, Optional, Protocol, Sequence

import numpy as np

from .env import BaseEnv
from .features import ProtoFeatures

# sc_pb.Result: Victory=1, Defeat=2, Tie=3, Undecided=4
POSSIBLE_RESULTS = {1: 1, 2: -1, 3: 0, 4: 0}
MAX_STEP_COUNT = 524_000  # SC2 hard limit 2^19, minus margin (reference :427)


class GameController(Protocol):
    """Subset of the reference RemoteController the env drives."""

    status_ended: bool

    def step(self, loops: int) -> None: ...

    def acts(self, raw_cmds: List[dict]): ...  # returns result-like or None

    def observe(self, target_game_loop: int = 0): ...  # raw proto obs


class SC2Env(BaseEnv):
    def __init__(
        self,
        controllers: Sequence[GameController],
        features: Sequence[ProtoFeatures],
        episode_length: int = 100_000,
        random_delay_weights: Optional[Sequence[float]] = None,
        realtime: bool = False,
        both_obs: bool = True,
        seed: int = 0,
        human_indices: Optional[Sequence[int]] = None,
        save_replay_episodes: int = 0,
        replay_saver=None,
    ):
        assert len(controllers) == len(features)
        self._controllers = list(controllers)
        self._features = list(features)
        self.num_agents = len(controllers)
        self._episode_length = min(episode_length, MAX_STEP_COUNT)
        self._random_delay_weights = list(random_delay_weights or [])
        self._realtime = realtime
        # a human plays through their own full-screen client: the env never
        # observes or acts their controller (reference env.py:315-316,384-385)
        self._human = set(human_indices or [])
        self._both_obs = both_obs and self.num_agents == 2 and not self._human
        self._save_replay_episodes = save_replay_episodes
        self._replay_saver = replay_saver
        self._rng = random.Random(seed)
        self._episode_steps = 0
        self._episode_count = 0
        self._next_obs_step = [0] * self.num_agents
        self._action_result: List[List[int]] = [[1] for _ in range(self.num_agents)]
        self._last_tags: List[list] = [[] for _ in range(self.num_agents)]
        self._raw_obs: List = [None] * self.num_agents
        self._born_locations: List = [None] * self.num_agents
        self._done = True

    # ------------------------------------------------------------------ api
    def reset(self) -> Dict[int, dict]:
        self._episode_steps = 0
        self._episode_count += 1
        self._next_obs_step = [
            MAX_STEP_COUNT + 1 if i in self._human else 0
            for i in range(self.num_agents)
        ]
        self._action_result = [[1] for _ in range(self.num_agents)]
        self._raw_obs = [None] * self.num_agents
        self._born_locations: List = [None] * self.num_agents
        self._done = False
        # restart the underlying game (reference restarts via the
        # controller's restart_game / create+join, env.py:298-330)
        for c in self._controllers:
            if hasattr(c, "reset"):
                c.reset()
        obs, _, _, _ = self._observe(0)
        return obs

    def step(self, actions: Dict[int, dict]):
        assert not self._done, "step() after episode end; call reset()"
        # issue raw commands + register each agent's requested delay
        for idx, action in actions.items():
            delay = max(int(np.asarray(action["delay"]).reshape(-1)[0]), 1)
            self._next_obs_step[idx] = self._episode_steps + delay
            cmd = self._features[idx].transform_action(
                action, self._last_tags[idx],
                selected_units_num=action.get("selected_units_num"),
            )
            c = self._controllers[idx]
            if not c.status_ended:
                result = c.acts([cmd])
                if result is not None:
                    self._action_result[idx] = (
                        list(result) if isinstance(result, (list, tuple)) else [result]
                    )

        # simulated inference/network latency for short delays (reference
        # :350-354, fires only when EVERY acting agent requested a short
        # delay): the game runs on while the "agents think"
        if not self._realtime and self._random_delay_weights and actions:
            max_delay = max(
                self._next_obs_step[i] - self._episode_steps for i in actions
            )
            if max_delay < 4:
                lag = self._rng.choices(
                    range(len(self._random_delay_weights)),
                    weights=self._random_delay_weights,
                )[0]
                self._advance(lag)
                self._episode_steps += lag

        target = min(self._next_obs_step)
        step_mul = max(target - self._episode_steps, 0)
        self._advance(step_mul)
        # dueness is judged inside _observe against the ACTUAL game loop —
        # a latency lag may have overshot some agents' schedules
        return self._observe(max(target, self._episode_steps))

    def close(self) -> None:
        for c in self._controllers:
            if hasattr(c, "close"):
                c.close()

    # ------------------------------------------------------------- internals
    def _advance(self, loops: int) -> None:
        # realtime games advance on SC2's own clock — no step requests
        # (upstream pysc2 sc2_env gates exactly this way); observe() blocks
        # until the target game loop instead
        if loops <= 0 or self._realtime:
            return
        for c in self._controllers:
            if not c.status_ended:
                c.step(loops)

    def _observe(self, target_game_loop: int):
        # observe only the agents that are due (or every non-human agent in
        # both-obs critic mode) — the reference's selective parallel observe
        # (env.py:377-390); a human's controller is never queried
        due = [
            i for i in range(self.num_agents)
            if self._next_obs_step[i] <= target_game_loop and i not in self._human
        ]
        query = [
            i for i in range(self.num_agents)
            if i not in self._human and (self._both_obs or i in due)
        ] or due
        for i in query:
            self._raw_obs[i] = self._controllers[i].observe(
                target_game_loop=target_game_loop
            )
        game_loop = int(self._raw_obs[query[0]].observation.game_loop)
        self._episode_steps = game_loop
        due = [
            i for i in range(self.num_agents)
            if self._next_obs_step[i] <= game_loop and i not in self._human
        ]

        outcome = [0] * self.num_agents
        episode_complete = any(
            getattr(o, "player_result", None) for o in self._raw_obs if o is not None
        )
        if episode_complete:
            for i, o in enumerate(self._raw_obs):
                if o is None:
                    continue
                pid = o.observation.player_common.player_id
                for result in o.player_result:
                    if result.player_id == pid:
                        outcome[i] = POSSIBLE_RESULTS.get(result.result, 0)
                    elif self.num_agents == 2:
                        outcome[1 - i] = POSSIBLE_RESULTS.get(result.result, 0)
        if game_loop >= self._episode_length:
            episode_complete = True
        self._done = episode_complete
        if episode_complete:
            self._maybe_save_replay(outcome)

        obs: Dict[int, dict] = {}
        if episode_complete:
            indices = [i for i in range(self.num_agents) if i not in self._human]
        else:
            indices = due
        for i in indices:
            # a non-due agent's cached obs may be stale (or absent) — e.g.
            # the terminal frame, or a realtime overshoot making an
            # unqueried agent due; serve it the current frame
            cached = self._raw_obs[i]
            if cached is None or int(cached.observation.game_loop) < game_loop:
                self._raw_obs[i] = self._controllers[i].observe(
                    target_game_loop=target_game_loop
                )
            opponent = self._raw_obs[1 - i] if self._both_obs else None
            f_obs = self._features[i].transform_obs(
                self._raw_obs[i], opponent_obs=opponent
            )
            f_obs["action_result"] = self._action_result[i]
            self._last_tags[i] = f_obs["game_info"]["tags"]
            # born locations key the Z-library sampling (reference
            # agent.py:183-187 reads them off the first observation)
            if self._born_locations[i] is None:
                try:
                    self._born_locations[i] = self._features[i].born_locations(
                        self._raw_obs[i]
                    )
                except Exception:
                    self._born_locations[i] = (0, 0)
            f_obs["game_info"]["born_location"] = self._born_locations[i][0]
            f_obs["game_info"]["away_born_location"] = self._born_locations[i][1]
            obs[i] = f_obs
        rewards = {i: float(outcome[i]) for i in range(self.num_agents)}
        info = {"game_loop": game_loop, "outcome": outcome}
        return obs, rewards, episode_complete, info

    def _maybe_save_replay(self, outcome) -> None:
        """Save the finished game's replay every N episodes (reference
        env.py:435-438)."""
        if (
            self._replay_saver is None
            or self._save_replay_episodes <= 0
            or self._episode_count % self._save_replay_episodes != 0
        ):
            return
        try:
            self._replay_saver(f"outcome_{outcome}")
        except Exception:  # replay saving must never kill training
            import logging

            logging.exception("save_replay failed")


class FakeController:
    """Dummy-proto controller: advances a loop counter, serves synthetic
    observations, ends with a victory/defeat pair after ``end_at`` loops."""

    def __init__(self, player_id: int = 1, end_at: int = 1000, n_units: int = 8,
                 map_y: int = 120, map_x: int = 120, seed: int = 0,
                 winner_player: int = 1):
        from .dummy_obs import build_dummy_obs, make_unit
        from ..lib import actions as ACT

        self._build = build_dummy_obs
        self._make_unit = make_unit
        self._unit_type = ACT.UNIT_TYPES[10]
        self.player_id = player_id
        self._end_at = end_at
        self._n_units = n_units
        self._map = (map_y, map_x)
        self._rng = np.random.default_rng(seed)
        self._winner = winner_player
        self.game_loop = 0
        self.status_ended = False
        self.acts_log: List[list] = []

    def reset(self) -> None:
        """Restart the fake game (role of restart_game in the real client)."""
        self.game_loop = 0
        self.status_ended = False

    def step(self, loops: int) -> None:
        self.game_loop += loops

    def acts(self, raw_cmds: List[dict]):
        self.acts_log.append(raw_cmds)
        return [1]

    def observe(self, target_game_loop: int = 0):
        if target_game_loop > self.game_loop:
            self.game_loop = target_game_loop
        units = [
            self._make_unit(100 + i, self._unit_type, x=5 + i, y=10)
            for i in range(self._n_units)
        ]
        obs = self._build(
            units=units, game_loop=self.game_loop, player_id=self.player_id,
            map_y=self._map[0], map_x=self._map[1], rng=self._rng,
        )
        if self.game_loop >= self._end_at:
            self.status_ended = True
            from types import SimpleNamespace as NS

            obs.player_result = [
                NS(player_id=1, result=1 if self._winner == 1 else 2),
                NS(player_id=2, result=1 if self._winner == 2 else 2),
            ]
        else:
            obs.player_result = []
        return obs
