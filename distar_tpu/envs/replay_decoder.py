"""Two-pass SC2 replay decoder -> SL training trajectories.

Role parity with the reference ReplayDecoder (reference: distar/agent/
default/replay_decoder.py:37-435):

  pass 1 (:236-278)  start the replay with a 1x1 minimap (actions need no
                     spatial data), step at 50-loop strides, harvest the raw
                     action stream (camera moves dropped), running the
                     keyboard-spam ``FilterActions`` dedup (:70-214) to build
                     the *filtered* stream used for Z extraction;
  pass 2 (:281-330+) restart with the full map-sized minimap, observe
                     BEFORE each action, step its recorded delay, emit
                     (obs, action) pairs via ProtoFeatures.transform_obs +
                     reverse_raw_action with last-action augmentation and
                     the missed-tag fixup (:44-60);
  version routing (:361-400)  a replay's base_build picks the binary via
                     run_configs.version_for_build (BUILD2VERSION); the
                     client relaunches on version change or every 10 replays.

Output steps follow the frozen ReplayDataset contract
(learner/sl_dataloader.py): feature-schema obs + action_info + action_mask +
selected_units_num, with the replay's Z written into every step's
scalar_info.

The client is injectable: production uses StarcraftProcess via run_configs;
tests connect to fake_sc2.FakeSC2Server through the same RemoteController.
"""
from __future__ import annotations

import logging
import random
import time
import traceback
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..lib import actions as ACT
from ..lib import features as F
from ..obs import get_registry
from .features import ProtoFeatures, extract_z

RESULT_DICT = {1: "W", 2: "L", 3: "D", 4: "U"}
RACE_DICT = {1: "terran", 2: "zerg", 3: "protoss", 4: "random"}
# crawler/uprooted/burrowed variants whose tags vanish mid-morph
# (reference get_tags :62-67)
MORPHING_UNIT_TYPES = {
    665, 666, 341, 1961, 483, 884, 885, 796, 797, 146, 147, 608, 880, 344, 881, 342,
}


def get_tags(obs) -> Dict[int, List[float]]:
    tags = {}
    for u in obs.observation.raw_data.units:
        if u.unit_type in MORPHING_UNIT_TYPES:
            tags[u.tag] = [u.pos.x, u.pos.y]
    return tags


def find_missed_tag(obs, action, saved_tags):
    """Remap a target tag that morphed away to the unit now standing at its
    recorded position (reference :44-60)."""
    ar = action.action_raw
    if ar.HasField("unit_command") and ar.unit_command.HasField("target_unit_tag"):
        target_tag = ar.unit_command.target_unit_tag
        live = {u.tag for u in obs.observation.raw_data.units}
        if target_tag not in live and target_tag in saved_tags:
            x, y = saved_tags[target_tag]
            for u in obs.observation.raw_data.units:
                if u.pos.x == x and u.pos.y == y:
                    action.action_raw.unit_command.target_unit_tag = u.tag
                    break
    return action


class FilterActions:
    """De-duplicate keyboard-spam action bursts (reference :70-214): runs of
    the same train/research/morph/bile ability within <=4 loops collapse to
    the number of effects actually observed between observations."""

    def __init__(self, flag: bool = False):
        def gids(pred):
            return {
                a["general_ability_id"] for a in ACT.ACTIONS if pred(a["name"]) and a["general_ability_id"]
            }

        zerg_morphs = {
            "Train_Baneling_quick", "Train_Corruptor_quick", "Train_Drone_quick",
            "Train_Hydralisk_quick", "Train_Infestor_quick", "Train_Mutalisk_quick",
            "Train_Overlord_quick", "Train_Roach_quick", "Train_SwarmHost_quick",
            "Train_Ultralisk_quick", "Train_Zergling_quick",
        }
        self.morph_abilities = gids(lambda n: n in zerg_morphs or "Morph" in n)
        self.train_abilities = gids(lambda n: "Train" in n and n not in zerg_morphs)
        self.research_abilities = gids(lambda n: "Research" in n)
        self.corrosivebile = {2338}
        self.target_abilities = (
            self.train_abilities | self.research_abilities
            | self.corrosivebile | self.morph_abilities
        )
        self.max_loop = 4
        self.filter_flag = flag

    @staticmethod
    def gen_ability_id(action):
        ar = action.action_raw
        if ar.HasField("unit_command"):
            return ar.unit_command.ability_id
        if ar.HasField("toggle_autocast"):
            return ar.toggle_autocast.ability_id
        return None

    @staticmethod
    def gen_unit_tags(action):
        ar = action.action_raw
        if ar.HasField("unit_command"):
            return ar.unit_command.unit_tags
        if ar.HasField("toggle_autocast"):
            return ar.toggle_autocast.unit_tags
        return []

    def _count_real(self, actions, a_id, pre_obs, post_obs) -> Optional[int]:
        """How many of this burst's commands visibly took effect; None keeps
        the burst unfiltered."""
        unit_tags = self.gen_unit_tags(actions[0])
        if a_id in self.morph_abilities:
            pre = {u.tag: u.unit_type for u in pre_obs.units}
            post = {u.tag: u.unit_type for u in post_obs.units}
            count = 0
            for t in unit_tags:
                if t not in pre or t not in post:
                    count += 1
                elif pre[t] != post[t]:
                    count += 1
            return count
        if a_id in self.corrosivebile:
            pre = {u.tag: u.unit_type for u in pre_obs.units}
            count = 0
            for t in unit_tags:
                if t not in pre or pre[t] == 688:  # Ravager
                    count += 1
            return count
        if a_id in self.train_abilities:
            pre = {u.tag: len(u.orders) for u in pre_obs.units}
            post = {u.tag: len(u.orders) for u in post_obs.units}
            pre_len = post_len = 0
            for t in unit_tags:
                if t not in pre or t not in post:
                    return None  # tag vanished: keep everything
                pre_len += pre[t]
                post_len += post[t]
            return post_len - pre_len
        return None

    def filter(self, actions, a_id, last_last_ob, last_ob, ob):
        if a_id not in self.target_abilities or len(actions) == 1:
            return actions
        if a_id in self.research_abilities:
            return [actions[0]]  # research can't repeat
        if actions[0].game_loop >= last_ob.observation.game_loop:
            pre_obs = last_ob.observation.raw_data
        else:
            pre_obs = last_last_ob.observation.raw_data
        count = self._count_real(actions, a_id, pre_obs, ob.observation.raw_data)
        if count is None:
            return actions
        count = min(count, len(actions))
        # spread the kept commands across the burst, always keeping the last
        new_actions = []
        for i in range(count):
            index = -1 if i == count - 1 else (len(actions) // count) * i
            new_actions.append(actions[index])
        return new_actions

    def run(self, last_last_ob, last_ob, ob, cached_actions):
        """Consume completed same-ability bursts from ``cached_actions``;
        returns (still_cached, filtered_out_now)."""
        if not self.filter_flag or ob.observation.game_loop > 8000:  # ~6 min
            return [], cached_actions
        if not cached_actions:
            return [], []
        out = []
        burst = []
        for idx, a in enumerate(cached_actions[:-1]):
            burst.append(a)
            a_id = self.gen_ability_id(a)
            next_id = self.gen_ability_id(cached_actions[idx + 1])
            gap = cached_actions[idx + 1].game_loop - a.game_loop
            if a_id != next_id or gap > self.max_loop:
                out += self.filter(burst, a_id, last_last_ob, last_ob, ob)
                burst = []
        return burst + [cached_actions[-1]], out


class ReplayDecoder:
    """Decode one replay-player into an SL trajectory (step-dict list)."""

    def __init__(
        self,
        cfg: Optional[dict] = None,
        controller_provider: Optional[Callable[[Optional[str]], object]] = None,
        stride: int = 50,
    ):
        cfg = cfg or {}
        self._stride = stride
        self._parse_race = cfg.get("parse_race", "ZTP")
        self._minimum_action_length = cfg.get("minimum_action_length", 128)
        self._filter = FilterActions(cfg.get("filter_action", False))
        self._relaunch_every = cfg.get("relaunch_every_replays", 10)
        # external endpoints (an SC2 we didn't launch) must not be killed by
        # RequestQuit, and gain nothing from periodic relaunch
        self._external = bool(cfg.get("external_endpoint", False))
        if self._external:
            self._relaunch_every = 10 ** 9
        self._provider = controller_provider or _SC2ProcessProvider()
        self._controller = None
        self._version: Optional[str] = None
        self._decoded_since_launch = 0

    # ---------------------------------------------------------------- client
    def _ensure_client(self, version: Optional[str]) -> None:
        relaunch = (
            self._controller is None
            or (version is not None and version != self._version)
            or self._decoded_since_launch >= self._relaunch_every
        )
        if not relaunch:
            return
        self.close()
        self._controller = self._provider(version)
        self._version = version
        self._decoded_since_launch = 0

    def close(self) -> None:
        if self._controller is not None:
            try:
                if self._external:
                    self._controller.close()  # drop the socket, leave SC2 up
                else:
                    self._controller.quit()
            except Exception:
                pass
            self._controller = None
        closer = getattr(self._provider, "close", None)
        if closer:
            closer()

    # ------------------------------------------------------------------- run
    def run(self, replay_path: str, player_index: int) -> Optional[List[dict]]:
        """Decode ``replay_path`` from ``player_index``'s (0/1) perspective;
        None for computer players / off-race / too-short replays / errors
        (reference run :361-412)."""
        try:
            start_time = time.time()
            info = self._replay_info(replay_path)
            if info is None:
                return None
            if info["player_type"][player_index] == 2:  # Computer
                return None
            if info["race"][player_index][0].upper() not in self._parse_race.upper():
                return None
            self._ensure_client(info["version"])
            self._decoded_since_launch += 1
            data = self._parse_replay(replay_path, player_index, info)
            if data is None or len(data) < self._minimum_action_length:
                return None
            elapsed = time.time() - start_time
            reg = get_registry()
            reg.counter("distar_replay_decoded_total", "replays decoded").inc()
            reg.counter(
                "distar_replay_decoded_steps_total", "training steps emitted"
            ).inc(len(data))
            reg.histogram(
                "distar_replay_decode_seconds", "wall time per decoded replay"
            ).observe(elapsed)
            if elapsed > 0:
                reg.gauge(
                    "distar_replay_decode_steps_per_s", "decode throughput (last replay)"
                ).set(len(data) / elapsed)
            logging.info(
                "decoded %s player %d: %d steps in %.1fs",
                replay_path, player_index, len(data), elapsed,
            )
            return data
        except Exception as e:
            get_registry().counter(
                "distar_replay_decode_errors_total", "replay decode failures"
            ).inc()
            logging.error("parse replay error %r\n%s", e, traceback.format_exc())
            self.close()
            self._version = None
            return None

    def _replay_info(self, replay_path: str) -> Optional[dict]:
        """Replay metadata + version routing. The version is routed from the
        replay's own MPQ header (``sc2.replay_header`` — same source the
        reference reads via mpyq, replay_decoder.py:366-377) so the FIRST
        client launch is already the right binary; the running client then
        serves the player/race/map metadata."""
        from .sc2.run_configs import VERSIONS, version_for_build

        base_build = None
        try:
            from .sc2.replay_header import parse_replay_header

            base_build = parse_replay_header(replay_path)["base_build"]
        except Exception as e:
            # unreadable OR structurally-unexpected header (e.g. field 1 not a
            # struct raises AttributeError inside parse_replay_header): any
            # failure here must fall through to client-served replay_info, not
            # fail the whole replay decode
            # unreadable header: fall back to asking whatever client is up
            # (any version serves replay_info)
            logging.warning("replay header parse failed for %s: %r", replay_path, e)
        if base_build is not None:
            self._ensure_client(version_for_build(base_build).game_version)
        else:
            self._ensure_client(self._version)
        info = self._controller.replay_info(replay_path=replay_path)
        version = version_for_build(base_build if base_build is not None else info.base_build).game_version
        if version not in VERSIONS:
            logging.warning("no game version for build %s; using current", info.base_build)
            version = self._version
        from .sc2.maps import LOCALIZED_BNET_NAME_TO_NAME_LUT

        return {
            "race": [RACE_DICT.get(p.player_info.race_actual, "random") for p in info.player_info],
            "result": [RESULT_DICT.get(p.player_result.result, "U") for p in info.player_info],
            "player_type": [p.player_info.type for p in info.player_info],
            "mmr": [p.player_mmr for p in info.player_info],
            "map_name": LOCALIZED_BNET_NAME_TO_NAME_LUT.get(info.map_name, info.map_name),
            "game_steps": info.game_duration_loops,
            "version": version,
        }

    # ----------------------------------------------------------------- parse
    def _start_replay(self, replay_path: str, player: int, minimap_xy) -> None:
        from .sc2.proto import sc_pb

        interface = sc_pb.InterfaceOptions(
            raw=True, score=False, raw_crop_to_playable_area=True,
        )
        interface.feature_layer.width = 1
        interface.feature_layer.resolution.x = 1
        interface.feature_layer.resolution.y = 1
        interface.feature_layer.minimap_resolution.x = minimap_xy[0]
        interface.feature_layer.minimap_resolution.y = minimap_xy[1]
        interface.feature_layer.crop_to_playable_area = True
        self._controller.start_replay(
            sc_pb.RequestStartReplay(
                replay_path=replay_path, options=interface, observed_player_id=player,
            )
        )

    def _harvest(self, replay_path: str, player: int, game_loops: int):
        """Pass 1: action stream at ``stride``-loop strides with the spam
        filter running alongside (reference :236-278). Returns
        (player_actions, filtered_actions, first_ob)."""
        self._start_replay(replay_path, player, (1, 1))
        # game_info is only legal while in_game/in_replay: fetch it now, the
        # harvest may run the replay to Status.ended
        game_info = self._controller.game_info()
        cur_loop = 0
        player_actions: List = []
        filtered_actions: List = []
        cached: List = []
        first_ob = last_last_ob = last_ob = self._controller.observe()
        while cur_loop < game_loops:
            next_loop = min(game_loops, cur_loop + self._stride)
            self._controller.step(next_loop - cur_loop)
            cur_loop = next_loop
            ob = self._controller.observe()
            for a in ob.actions:
                if a.HasField("action_raw") and not a.action_raw.HasField("camera_move"):
                    cached.append(a)
                    player_actions.append(a)
            cached, fresh = self._filter.run(last_last_ob, last_ob, ob, cached)
            last_last_ob, last_ob = last_ob, ob
            filtered_actions += fresh
            if len(ob.player_result):
                filtered_actions += cached
                break
        return player_actions, filtered_actions, first_ob, game_info

    def decode_z(self, replay_path: str, player_index: int) -> Optional[dict]:
        """Z-only decode (pass 1 alone): one episode summary for
        lib.z_library.build_z_library (role of the reference gen_z
        _parse_replay, distar/bin/gen_z.py:240-300)."""
        try:
            info = self._replay_info(replay_path)
            if info is None or info["player_type"][player_index] == 2:
                return None
            if info["race"][player_index][0].upper() not in self._parse_race.upper():
                return None
            self._ensure_client(info["version"])
            self._decoded_since_launch += 1
            player = player_index + 1
            actions, filtered, first_ob, game_info = self._harvest(
                replay_path, player, info["game_steps"]
            )
            if not actions:
                return None
            feature = ProtoFeatures(game_info)
            home_loc, away_loc = feature.born_locations(first_ob)
            race = info["race"][player_index]
            opp_race = info["race"][1 - player_index] if len(info["race"]) > 1 else race
            mix_race = race if race == opp_race else race + opp_race
            filtered_infos = _z_action_infos(feature, filtered)
            bo, cum, _, bo_loc = extract_z(filtered_infos, home_loc, away_loc)
            return {
                "map_name": info["map_name"],
                "mix_race": mix_race,
                "born_location": home_loc,
                "winloss": 1 if info["result"][player_index] == "W" else -1,
                "beginning_order": bo.tolist(),
                "bo_location": bo_loc.tolist(),
                "cumulative_stat": cum.tolist(),
                "game_loop": int(actions[-1].game_loop),
                "mmr": info["mmr"][player_index],
            }
        except Exception as e:
            logging.error("decode_z error %r\n%s", e, traceback.format_exc())
            self.close()
            self._version = None
            return None

    def _parse_replay(self, replay_path: str, player_index: int, info: dict) -> Optional[List[dict]]:
        player = player_index + 1
        player_actions, filtered_actions, _, _ = self._harvest(
            replay_path, player, info["game_steps"]
        )
        if not player_actions:
            return None

        # ---------------- pass 2: (obs, action) pairs (full minimap, :281-330)
        try:
            from .sc2.maps import get_map_size

            map_size = tuple(get_map_size(info["map_name"]))  # (x, y)
        except KeyError:
            # unknown map: the feature contract's full (x, y) = (160, 152)
            map_size = (F.SPATIAL_SIZE[1], F.SPATIAL_SIZE[0])
        self._start_replay(replay_path, player, map_size)
        raw_ob = self._controller.observe()
        saved_tags = get_tags(raw_ob)
        game_info = self._controller.game_info()
        feature = ProtoFeatures(game_info)
        home_loc, away_loc = feature.born_locations(raw_ob)

        last_selected_tags: Optional[Sequence[int]] = None
        last_target_tag: Optional[int] = None
        last_delay = np.asarray(0, np.int16)
        last_action_type = np.asarray(0, np.int16)
        last_queued = np.asarray(0, np.int16)
        enemy_unit_type_bool = np.zeros(ACT.NUM_UNIT_TYPES, np.uint8)

        self._controller.step(max(player_actions[0].game_loop - 2, 0))
        traj_data: List[dict] = []
        for idx, action in enumerate(player_actions):
            if idx == len(player_actions) - 1:
                delay = random.randint(0, F.MAX_DELAY)
            else:
                delay = player_actions[idx + 1].game_loop - action.game_loop
            raw_ob = self._controller.observe()
            if len(raw_ob.player_result):
                break
            if delay > 0:
                self._controller.step(delay)
            # accumulate morphing-unit positions as they appear (crawlers
            # etc. don't exist at game start)
            saved_tags.update(get_tags(raw_ob))
            action = find_missed_tag(raw_ob, action, saved_tags)

            step_data = feature.transform_obs(raw_ob)
            entity_num = int(step_data["entity_num"])
            tags = step_data["game_info"]["tags"]
            tag_index = {t: i for i, t in enumerate(tags)}
            last_selected_units = np.zeros(F.MAX_ENTITY_NUM, np.int8)
            last_targeted_unit = np.zeros(F.MAX_ENTITY_NUM, np.int8)
            for t in last_selected_tags or []:
                if t in tag_index:
                    last_selected_units[tag_index[t]] = 1
            if last_target_tag is not None and last_target_tag in tag_index:
                last_targeted_unit[tag_index[last_target_tag]] = 1
            step_data["entity_info"]["last_selected_units"] = last_selected_units
            step_data["entity_info"]["last_targeted_unit"] = last_targeted_unit
            step_data["scalar_info"]["last_delay"] = last_delay
            step_data["scalar_info"]["last_action_type"] = last_action_type
            step_data["scalar_info"]["last_queued"] = last_queued
            # enemy composition accumulates across fog (reference :318-319)
            enemy_unit_type_bool = (
                enemy_unit_type_bool | step_data["scalar_info"]["enemy_unit_type_bool"]
            ).astype(np.uint8)
            step_data["scalar_info"]["enemy_unit_type_bool"] = enemy_unit_type_bool

            rev = feature.reverse_raw_action(action.action_raw, tags)
            if rev["invalid"]:
                continue
            act_info = rev["action"]
            act_info["delay"] = np.asarray(min(delay, F.MAX_DELAY - 1), np.int64)
            last_action_type = act_info["action_type"].astype(np.int16)
            last_delay = act_info["delay"].astype(np.int16)
            last_queued = act_info["queued"].astype(np.int16)
            last_selected_tags = rev["selected_tags"]
            last_target_tag = rev["target_tag"]
            step_data.pop("game_info")
            step_data.pop("value_feature", None)
            step_data.update(
                {
                    "action_info": act_info,
                    "action_mask": rev["mask"],
                    "selected_units_num": rev["selected_units_num"],
                }
            )
            traj_data.append(step_data)

        # ---------------- Z targets from the FILTERED stream (:341-351)
        filtered_infos = _z_action_infos(feature, filtered_actions)
        beginning_order, cumulative_stat, _, bo_location = extract_z(
            filtered_infos, home_loc, away_loc
        )
        for step_data in traj_data:
            step_data["scalar_info"]["beginning_order"] = beginning_order
            step_data["scalar_info"]["cumulative_stat"] = cumulative_stat.astype(np.uint8)
            step_data["scalar_info"]["bo_location"] = bo_location
        return traj_data


def _z_action_infos(feature: ProtoFeatures, actions) -> List[dict]:
    """Action stream -> action_info dicts for extract_z. Out-of-set abilities
    decode to action_type 0 == BEGINNING_ORDER_ACTIONS[0]; letting them
    through would misalign beginning_order/bo_location in the Z targets
    (unresolvable selections are fine here — Z only reads type+location)."""
    infos = []
    for a in actions:
        rev = feature.reverse_raw_action(a.action_raw, [])
        if int(np.asarray(rev["action"]["action_type"])) == 0:
            continue
        infos.append({"action_info": rev["action"]})
    return infos


class _SC2ProcessProvider:
    """Production controller provider: one StarcraftProcess per version,
    launch retries x10 (reference _restart :414-427)."""

    def __init__(self):
        self._proc = None

    def __call__(self, version: Optional[str]):
        from .sc2 import run_configs

        self.close()
        last = None
        for attempt in range(10):
            try:
                run_config = run_configs.get(version=version)
                self._proc = run_config.start(want_rgb=False)
                return self._proc.controller
            except Exception as e:
                last = e
                logging.error("start sc2 failed (%r), retry %d", e, attempt)
                self.close()
        raise RuntimeError(f"could not launch SC2 for version {version}: {last!r}")

    def close(self) -> None:
        if self._proc is not None:
            try:
                self._proc.close()
            except Exception:
                pass
            self._proc = None
