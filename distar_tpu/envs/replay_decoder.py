"""Replay decoder interface (SC2-client binding point).

Role of the reference ReplayDecoder (reference: distar/agent/default/
replay_decoder.py:37-435): a two-pass decode per replay-player — pass 1
steps the client at 50-loop strides harvesting the action stream (with the
keyboard-spam FilterActions pass, :70-214), pass 2 re-steps requesting an
observation *before each action* and emits (obs, action) training pairs via
``Features.transform_obs`` + ``reverse_raw_action``; game-version routing
picks the right client build (BUILD2VERSION, :37-41).

This module freezes that contract for the framework: ``decode_replay``
yields step dicts in the ReplayDataset schema (sl_dataloader.ReplayDataset).
The concrete SC2 websocket/protobuf client is the remaining binding — it
slots in behind ``ReplayClient`` without touching the training stack, which
consumes only ReplayDataset files.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Protocol


class ReplayClient(Protocol):
    """Minimal client surface the decoder needs (subset of the reference
    RemoteController, remote_controller.py:127-330)."""

    def start_replay(self, replay_path: str, player_id: int, version: str) -> None: ...

    def observe(self, target_game_loop: int) -> dict: ...  # raw proto obs

    def step(self, loops: int) -> None: ...


class ReplayDecoder:
    def __init__(self, client: Optional[ReplayClient] = None, stride: int = 50):
        self._client = client
        self._stride = stride

    def decode(self, replay_path: str, player_id: int) -> List[dict]:
        if self._client is None:
            raise NotImplementedError(
                "SC2 replay decoding requires a game client; plug a ReplayClient "
                "implementation (websocket+protobuf binding) or use "
                "sl_dataloader.make_fake_dataset / an externally decoded "
                "ReplayDataset for SL training"
            )
        raise NotImplementedError("two-pass decode lands with the client binding")
