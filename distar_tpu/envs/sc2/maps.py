"""Map registry: size table, localized-name LUT, data access, auto-install.

Role parity with the reference map infrastructure (reference: distar/envs/
map_info.py:8-278 — MAPS size/name table + LOCALIZED_BNET_NAME_TO_NAME_LUT;
distar/pysc2/maps registry; the auto-install of bundled Ladder2019Season2
maps at distar/bin/rl_train.py:115-116). The table itself is game data,
extracted to ``data/map_info.json`` by tools/extract_map_info.py.
"""
from __future__ import annotations

import json
import logging
import os
import shutil
from typing import Dict, List, Optional, Tuple

_DATA = os.path.join(os.path.dirname(__file__), "..", "..", "data", "map_info.json")

with open(_DATA) as _f:
    _PAYLOAD = json.load(_f)
MAPS: Dict[str, dict] = _PAYLOAD["maps"]

# any known spelling (battle.net, localized, filename stem) -> canonical name
LOCALIZED_BNET_NAME_TO_NAME_LUT: Dict[str, str] = {}
for _name, _info in MAPS.items():
    LOCALIZED_BNET_NAME_TO_NAME_LUT[_name] = _name
    if _info["battle_net"]:
        LOCALIZED_BNET_NAME_TO_NAME_LUT[_info["battle_net"]] = _name
    for _loc in _info["localized_names"]:
        LOCALIZED_BNET_NAME_TO_NAME_LUT[_loc] = _name


class Map:
    """One playable map (role of pysc2 maps.lib.Map)."""

    def __init__(self, name: str):
        if name not in MAPS:
            name = LOCALIZED_BNET_NAME_TO_NAME_LUT.get(name, name)
        if name not in MAPS:
            raise KeyError(
                f"Unknown map '{name}'. Known: {sorted(MAPS)[:10]}... "
                "(see distar_tpu/data/map_info.json)"
            )
        self.name = name
        info = MAPS[name]
        self.battle_net = info["battle_net"]
        self.filename = info["map_path"]  # relative to <install>/Maps
        self.game_steps_per_episode = 0

    @property
    def path(self) -> Optional[str]:
        return self.filename

    def data(self, run_config) -> bytes:
        """Map bytes via the run config (reference lib.py map_data)."""
        if not self.filename:
            raise ValueError(f"Map '{self.name}' has no bundled path; install it first.")
        return run_config.map_data(self.filename)

    def __repr__(self) -> str:
        return f"Map({self.name!r}, {self.filename!r})"


def get(name: str) -> Map:
    return Map(name)


def get_map_size(map_name: str, cropped: bool = True) -> Tuple[int, int]:
    """(x, y) playable size (reference map_info.py:261-262)."""
    name = LOCALIZED_BNET_NAME_TO_NAME_LUT.get(map_name, map_name)
    info = MAPS[name]
    return tuple(info["map_size" if cropped else "uncropped_size"])


def get_localized_map_name(map_name: str) -> List[str]:
    name = LOCALIZED_BNET_NAME_TO_NAME_LUT.get(map_name, map_name)
    return MAPS[name]["localized_names"]


def bundled_maps_dir() -> str:
    """The Ladder2019Season2 .SC2Map bundle shipped with the package (role of
    the reference's distar/envs/maps/Ladder2019Season2/): offline hosts can
    play and decode without any network fetch. Integrity is pinned by
    MANIFEST.json (sha256 per file)."""
    return os.path.join(os.path.dirname(_DATA), "maps", "Ladder2019Season2")


def verify_bundled_maps(source_dir: Optional[str] = None) -> List[str]:
    """Check every bundled map against its MANIFEST.json sha256; returns the
    list of corrupt/missing filenames (empty == all good)."""
    import hashlib

    source_dir = source_dir or bundled_maps_dir()
    manifest_path = os.path.join(source_dir, "MANIFEST.json")
    with open(manifest_path) as f:
        manifest = json.load(f)["files"]
    bad = []
    for name, meta in manifest.items():
        path = os.path.join(source_dir, name)
        if not os.path.exists(path):
            bad.append(name)
            continue
        h = hashlib.sha256(open(path, "rb").read()).hexdigest()
        if h != meta["sha256"]:
            bad.append(name)
    return bad


def install_maps(source_dir: Optional[str] = None, sc2_dir: Optional[str] = None) -> int:
    """Copy .SC2Map files into the install's Maps dir (role of the
    auto-install at reference rl_train.py:115-116). ``source_dir`` defaults
    to the bundled Ladder2019Season2 set. Returns #installed."""
    if source_dir is None:
        source_dir = bundled_maps_dir()
    sc2_dir = os.path.expanduser(sc2_dir or os.environ.get("SC2PATH", "~/StarCraftII"))
    # maps sitting directly in source_dir install under Maps/<dirname>/ so
    # they land where map_data's primary 'Maps/Ladder2019Season2/<file>'
    # lookup (and a conventional install's idempotency check) expects them
    season = os.path.basename(os.path.normpath(source_dir))
    installed = 0
    for root, _, files in os.walk(source_dir):
        for f in files:
            if not f.lower().endswith(".sc2map"):
                continue
            rel = os.path.relpath(os.path.join(root, f), source_dir)
            season_prefixed = os.sep not in rel and bool(season)
            if season_prefixed:
                rel = os.path.join(season, rel)
            dst = os.path.join(sc2_dir, "Maps", rel)
            if os.path.exists(dst):
                continue
            # hosts that installed before the season-prefix change have the
            # map directly under Maps/ — treat that as already installed too
            if season_prefixed and os.path.exists(os.path.join(sc2_dir, "Maps", f)):
                continue
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            shutil.copyfile(os.path.join(root, f), dst)
            installed += 1
    if installed:
        logging.info("installed %d maps into %s/Maps", installed, sc2_dir)
    return installed
