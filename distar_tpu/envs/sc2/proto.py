"""SC2 protobuf module resolution.

The framework talks to the game in s2client-proto messages. Two providers:

1. The pip ``s2clientprotocol`` package (the reference's dependency,
   reference: distar/pysc2/lib/protocol.py:29) — byte-compatible with the
   retail binary by construction; preferred when importable.
2. The vendored subset under ``_proto_gen`` (built from ``protos/*.proto``
   by tools/build_protos.sh) — field numbers follow the public schema; keeps
   the full client stack importable and testable in environments without the
   pip package.

Consumers import ``sc_pb``/``raw_pb``/``common_pb``/``Status`` from here and
stay provider-agnostic (both expose the same message/field names).
"""
from __future__ import annotations

import enum

try:  # pragma: no cover - depends on environment
    from s2clientprotocol import common_pb2 as common_pb
    from s2clientprotocol import raw_pb2 as raw_pb
    from s2clientprotocol import sc2api_pb2 as sc_pb
    from s2clientprotocol import score_pb2 as score_pb
    from s2clientprotocol import spatial_pb2 as spatial_pb

    PROVIDER = "s2clientprotocol"
except ImportError:
    from ._proto_gen import common_pb2 as common_pb
    from ._proto_gen import raw_pb2 as raw_pb
    from ._proto_gen import sc2api_pb2 as sc_pb
    from ._proto_gen import score_pb2 as score_pb
    from ._proto_gen import spatial_pb2 as spatial_pb

    PROVIDER = "vendored"

# python enum over the proto Status values (reference protocol.py:42)
Status = enum.Enum("Status", sc_pb.Status.items())

__all__ = ["sc_pb", "raw_pb", "common_pb", "score_pb", "spatial_pb", "Status", "PROVIDER"]
