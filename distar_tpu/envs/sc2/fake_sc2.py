"""An in-process fake SC2: a real websocket server speaking real s2api
protos, backing the client stack's tests and game-free demos.

Role of the reference's recorded-protocol strategy (pysc2's mock_sc2_env +
dummy_observation, applied one layer LOWER): the full production path —
websocket framing, StarcraftProtocol, RemoteController status machine,
create/join port plumbing — runs byte-identically against this server; only
the simulation behind /sc2api is scripted.

The server hosts any number of client connections on one port, so the
multiplayer create/join handshake (host creates, everyone joins, the game
starts when all participants joined — reference distar/envs/env.py:211-274)
is exercised across connections exactly like against N real processes.

Replays: a "replay file" is a pickled dict
  {"base_build", "game_version", "data_version", "players":
   [{player_id, race, mmr, apm, result}], "game_duration_loops",
   "actions": [(game_loop, ability_id, unit_tags, target|None)], "map_name"}
start_replay plays its action stream back through ResponseObservation.actions
— the two-pass replay decoder runs against it unmodified.

Also launchable as a fake binary: ``python -m distar_tpu.envs.sc2.fake_sc2
-listen 127.0.0.1 -port N`` (SC2-style args), so StarcraftProcess's
launch/connect/retry path is testable end to end.
"""
from __future__ import annotations

import base64
import hashlib
import pickle
import socket
import struct
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .proto import sc_pb

_WS_MAGIC = b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


# ---------------------------------------------------------------- websocket
class _WSConn:
    """Server side of one websocket connection (RFC6455 subset: unfragmented
    binary frames, client->server masked, server->client unmasked)."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = b""

    def handshake(self) -> bool:
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = self._sock.recv(4096)
            if not chunk:
                return False
            data += chunk
        head, _, rest = data.partition(b"\r\n\r\n")
        self._buf = rest
        lines = head.decode("latin-1").split("\r\n")
        if "/sc2api" not in lines[0]:
            self._sock.sendall(b"HTTP/1.1 404 Not Found\r\n\r\n")
            return False
        key = ""
        for line in lines[1:]:
            name, _, value = line.partition(":")
            if name.strip().lower() == "sec-websocket-key":
                key = value.strip()
        accept = base64.b64encode(
            hashlib.sha1(key.encode("latin-1") + _WS_MAGIC).digest()
        ).decode()
        self._sock.sendall(
            (
                "HTTP/1.1 101 Switching Protocols\r\n"
                "Upgrade: websocket\r\n"
                "Connection: Upgrade\r\n"
                f"Sec-WebSocket-Accept: {accept}\r\n\r\n"
            ).encode("latin-1")
        )
        return True

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionResetError("client closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def recv(self) -> Optional[bytes]:
        """One message; None on close frame / disconnect."""
        while True:
            try:
                b1, b2 = self._read_exact(2)
            except (ConnectionResetError, OSError):
                return None
            opcode = b1 & 0x0F
            masked = b2 & 0x80
            length = b2 & 0x7F
            if length == 126:
                (length,) = struct.unpack(">H", self._read_exact(2))
            elif length == 127:
                (length,) = struct.unpack(">Q", self._read_exact(8))
            mask = self._read_exact(4) if masked else b""
            payload = self._read_exact(length)
            if mask:
                payload = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
            if opcode == 8:  # close
                return None
            if opcode == 9:  # ping -> pong
                self._send_frame(10, payload)
                continue
            if opcode in (1, 2):
                return payload
            # pong/continuation: ignore

    def _send_frame(self, opcode: int, payload: bytes) -> None:
        header = bytes([0x80 | opcode])
        n = len(payload)
        if n < 126:
            header += bytes([n])
        elif n < 2 ** 16:
            header += bytes([126]) + struct.pack(">H", n)
        else:
            header += bytes([127]) + struct.pack(">Q", n)
        self._sock.sendall(header + payload)

    def send(self, payload: bytes) -> None:
        self._send_frame(2, payload)

    def close(self) -> None:
        try:
            self._send_frame(8, b"")
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


# ------------------------------------------------------------------- game
class FakeGameCore:
    """The scripted simulation shared by all connections on one server."""

    def __init__(self, map_size=(120, 120), n_units: int = 8, end_at: int = 10_000,
                 winner: int = 1, seed: int = 0, game_version: str = "4.10.0",
                 base_build: int = 75689, replay_library: Optional[Dict[str, dict]] = None):
        self.lock = threading.RLock()
        self.map_size = map_size
        self.n_units = n_units
        self.end_at = end_at
        self.winner = winner
        self.game_version = game_version
        self.base_build = base_build
        self.replay_library = replay_library or {}
        self._rng = np.random.default_rng(seed)
        self.reset()
        self.saved_maps: Dict[str, bytes] = {}
        self.action_log: List = []

    def reset(self) -> None:
        self.game_loop = 0
        self.create_req = None
        self.joined: List[int] = []
        self.num_participants = 0
        self.started = False
        self.ended = False

    # ------------------------------------------------------------ lifecycle
    def create_game(self, req) -> None:
        self.reset()
        self.create_req = req
        self.num_participants = sum(
            1 for p in req.player_setup if p.type == sc_pb.Participant
        )

    def join(self, req) -> int:
        player_id = len(self.joined) + 1
        self.joined.append(player_id)
        if len(self.joined) >= max(self.num_participants, 1):
            self.started = True
        return player_id

    def advance(self, loops: int) -> None:
        if self.ended:
            return
        self.game_loop += loops
        if self.game_loop >= self.end_at:
            self.ended = True

    # ---------------------------------------------------------------- build
    def _image(self, bits: int) -> "sc_pb.ImageData":
        from .proto import common_pb

        y, x = self.map_size
        img = common_pb.ImageData()
        img.bits_per_pixel = bits
        img.size.x = x
        img.size.y = y
        if bits == 1:
            img.data = np.packbits(
                (self._rng.integers(0, 2, (y, x))).astype(np.uint8)
            ).tobytes()
        else:
            img.data = self._rng.integers(0, 4, (y, x), dtype=np.uint8).tobytes()
        return img

    def build_observation(self, player_id: int, with_result: bool = False,
                          actions: Optional[list] = None):
        res = sc_pb.ResponseObservation()
        obs = res.observation
        obs.game_loop = self.game_loop
        pc = obs.player_common
        pc.player_id = player_id
        pc.minerals = 50 + self.game_loop // 10
        pc.vespene = 25
        pc.food_cap = 15
        pc.food_used = 12
        pc.food_army = 4
        pc.food_workers = 8
        pc.idle_worker_count = 1
        pc.army_count = 4
        pc.warp_gate_count = 0
        pc.larva_count = 3

        sd = obs.score.score_details
        for cat in ("killed_minerals", "killed_vespene"):
            msg = getattr(sd, cat)
            msg.none = 0.0
            msg.army = float(self.game_loop // 100)
            msg.economy = 0.0
            msg.technology = 0.0
            msg.upgrade = 0.0

        raw = obs.raw_data
        # researched upgrades appear once the game has progressed (exercises
        # the scalar upgrades reorder-LUT path, features.py:350-353)
        if self.game_loop >= 100:
            raw.player.upgrade_ids.extend([1, 4])
        for side, alliance in ((player_id, 1), (3 - player_id, 4)):
            for i in range(self.n_units):
                u = raw.units.add()
                u.display_type = 1
                u.alliance = alliance
                u.tag = side * 10_000 + i
                u.unit_type = 104  # zerg drone
                u.owner = side
                u.pos.x = 5.0 + i + (0 if alliance == 1 else 40)
                u.pos.y = 10.0 + (0 if alliance == 1 else 40)
                u.health = 40.0
                u.health_max = 40.0
                u.is_powered = True
                u.build_progress = 1.0
                if i == 0:
                    # busy unit: queued orders with progress + a buff —
                    # real clients report these constantly (order_id_*,
                    # order_progress_*, buff_id_* entity fields)
                    o = u.orders.add()
                    o.ability_id = 1183  # zerg build ability (in contract)
                    o.progress = 0.5
                    o2 = u.orders.add()
                    o2.ability_id = 216  # in the queue-action vocabulary
                    # (ABILITY_TO_QUEUE_ACTION > 0) so order_id_1 remaps
                    # to a real class, not the 0 no-op
                    u.buff_ids.append(5)
                    u.energy = 25.0
                    u.energy_max = 50.0
                if i == 1 and self.n_units > 2:
                    # transport carrying a passenger: transform_obs emits the
                    # passenger as an is_in_cargo pseudo-entity
                    u.cargo_space_max = 8
                    u.cargo_space_taken = 1
                    p = u.passengers.add()
                    p.tag = side * 10_000 + 9000
                    p.unit_type = 104
                    p.health = 35.0
                    p.health_max = 40.0
                if i == 2 and self.n_units > 3:
                    u.add_on_tag = side * 10_000 + 3  # points at unit 3
                if i == 3 and self.n_units > 3:
                    u.unit_type = 5  # TechLab: a real addon type id so the
                    # addon_unit_type reorder LUT keeps it (others map to 0)
        # a transient battlefield effect (flat-index scatter plane path)
        if 50 <= self.game_loop < self.end_at:
            e = raw.effects.add()
            e.effect_id = 11  # CorrosiveBile
            p = e.pos.add()
            p.x, p.y = 30.0, 30.0

        fl = obs.feature_layer_data.minimap_renders
        for name, bits in (
            ("height_map", 8), ("visibility_map", 8), ("creep", 1),
            ("player_relative", 8), ("alerts", 8), ("pathable", 1),
            ("buildable", 1),
        ):
            getattr(fl, name).CopyFrom(self._image(bits))

        for a in actions or []:
            res.actions.add().CopyFrom(a)

        if with_result and (self.ended or self.game_loop >= self.end_at):
            for pid in (1, 2):
                pr = res.player_result.add()
                pr.player_id = pid
                pr.result = sc_pb.Victory if pid == self.winner else sc_pb.Defeat
        return res

    def build_game_info(self):
        gi = sc_pb.ResponseGameInfo()
        gi.map_name = "FakeMap"
        y, x = self.map_size
        gi.start_raw.map_size.x = x
        gi.start_raw.map_size.y = y
        n = max(self.num_participants, len(self.joined), 2)
        for pid in range(1, n + 1):
            pi = gi.player_info.add()
            pi.player_id = pid
            pi.type = sc_pb.Participant
            pi.race_requested = 2  # zerg
            pi.race_actual = 2
        return gi


class _ConnState:
    def __init__(self):
        self.status = sc_pb.launched
        self.player_id = 0
        self.in_replay = False
        self.replay: Optional[dict] = None
        self.replay_cursor = 0


class FakeSC2Server:
    """Accepts websocket connections on one port, dispatching /sc2api
    requests to a shared FakeGameCore."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 game: Optional[FakeGameCore] = None):
        self.game = game or FakeGameCore()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.host, self.port = self._listener.getsockname()[:2]
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def stop(self) -> None:
        self._stop.set()
        # closing an fd does NOT wake a thread blocked in accept() on Linux;
        # poke the listener so the loop observes _stop and exits instead of
        # parking forever as a leaked daemon thread
        poke_host = self.host if self.host not in ("0.0.0.0", "") else "127.0.0.1"
        try:
            with socket.create_connection((poke_host, self.port), timeout=1):
                pass
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        # reap the accept loop: the poke above guarantees it observes _stop,
        # so this join is fast — stop() returning with the loop still
        # between accept() and its _stop check would race a re-bind
        self._accept_thread.join(timeout=5.0)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            if self._stop.is_set():  # stop()'s wake-up poke, not a client
                sock.close()
                return
            t = threading.Thread(target=self._serve_client, args=(sock,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_client(self, sock: socket.socket) -> None:
        conn = _WSConn(sock)
        if not conn.handshake():
            conn.close()
            return
        state = _ConnState()
        # real SC2's status is process-global, not per-connection: a second
        # connection (e.g. bin/observe attaching to a live game) arrives
        # mid-game and may observe immediately
        with self.game.lock:
            if self.game.started and not self.game.ended:
                state.status = sc_pb.in_game
                state.player_id = 1
        while not self._stop.is_set():
            payload = conn.recv()
            if payload is None:
                break
            req = sc_pb.Request.FromString(payload)
            try:
                resp = self._dispatch(state, req)
            except Exception as e:  # bug in the fake -> protocol error
                resp = sc_pb.Response()
                resp.error.append(f"fake_sc2 internal error: {e!r}")
            if resp is None:  # quit
                break
            if req.HasField("id"):
                resp.id = req.id
            resp.status = state.status
            conn.send(resp.SerializeToString())
        conn.close()

    # ------------------------------------------------------------- dispatch
    def _dispatch(self, state: _ConnState, req) -> Optional["sc_pb.Response"]:
        which = req.WhichOneof("request")
        resp = sc_pb.Response()
        game = self.game
        if which == "join_game":
            # blocking call: returns when all participants joined (reference
            # join semantics, distar/envs/env.py:268-271) — waits OUTSIDE the
            # game lock so the other connections can join
            with game.lock:
                state.player_id = game.join(req.join_game)
            while not game.started and not self._stop.is_set():
                time.sleep(0.005)
            resp.join_game.player_id = state.player_id
            state.status = sc_pb.in_game
            return resp
        with game.lock:
            if which == "ping":
                resp.ping.game_version = game.game_version
                resp.ping.data_version = "FAKE"
                resp.ping.data_build = game.base_build
                resp.ping.base_build = game.base_build
            elif which == "create_game":
                game.create_game(req.create_game)
                resp.create_game.SetInParent()
                state.status = sc_pb.init_game
            elif which == "save_map":
                game.saved_maps[req.save_map.map_path] = req.save_map.map_data
                resp.save_map.SetInParent()
            elif which == "restart_game":
                game.reset()
                game.num_participants = 0
                game.joined = [state.player_id]
                game.started = True
                resp.restart_game.SetInParent()
                state.status = sc_pb.in_game
            elif which == "game_info":
                resp.game_info.CopyFrom(game.build_game_info())
            elif which == "observation":
                target = req.observation.game_loop
                if target > game.game_loop:
                    game.advance(target - game.game_loop)
                actions = None
                if state.in_replay and state.replay is not None:
                    actions, state.replay_cursor = _replay_actions_until(
                        state.replay, state.replay_cursor, game.game_loop
                    )
                resp.observation.CopyFrom(
                    game.build_observation(
                        max(state.player_id, 1), with_result=True, actions=actions
                    )
                )
                if game.ended:
                    state.status = sc_pb.ended
            elif which == "step":
                game.advance(req.step.count)
                resp.step.simulation_loop = game.game_loop
                if game.ended:
                    state.status = sc_pb.ended
            elif which == "action":
                game.action_log.append((state.player_id, req.action))
                for _ in req.action.actions:
                    resp.action.result.append(1)  # Success
            elif which == "replay_info":
                info = self._replay_info(req.replay_info)
                resp.replay_info.CopyFrom(info)
            elif which == "start_replay":
                rep = self._load_replay(req.start_replay)
                state.in_replay = True
                state.replay = rep
                state.replay_cursor = 0
                game.reset()
                game.started = True
                game.end_at = rep.get("game_duration_loops", game.end_at)
                state.player_id = req.start_replay.observed_player_id or 1
                resp.start_replay.SetInParent()
                state.status = sc_pb.in_replay
            elif which == "leave_game":
                resp.leave_game.SetInParent()
                state.status = sc_pb.launched
            elif which == "save_replay":
                resp.save_replay.data = pickle.dumps(
                    {"base_build": game.base_build, "actions": [],
                     "game_duration_loops": game.game_loop}
                )
            elif which == "available_maps":
                resp.available_maps.local_map_paths.extend(sorted(game.saved_maps))
            elif which == "data":
                resp.data.SetInParent()
            elif which == "quit":
                return None
            else:
                resp.error.append(f"unsupported request: {which}")
        return resp

    def _load_replay(self, req) -> dict:
        if req.HasField("replay_data") and req.replay_data:
            return pickle.loads(req.replay_data)
        name = req.replay_path
        if name in self.game.replay_library:
            return self.game.replay_library[name]
        with open(name, "rb") as f:
            return pickle.load(f)

    def _replay_info(self, req):
        rep = self._load_replay(req)
        info = sc_pb.ResponseReplayInfo()
        info.map_name = rep.get("map_name", "FakeMap")
        info.game_version = rep.get("game_version", self.game.game_version)
        info.data_version = rep.get("data_version", "FAKE")
        info.base_build = rep.get("base_build", self.game.base_build)
        info.data_build = info.base_build
        info.game_duration_loops = rep.get("game_duration_loops", 1000)
        info.game_duration_seconds = info.game_duration_loops / 22.4
        for p in rep.get("players", []):
            pie = info.player_info.add()
            pie.player_info.player_id = p.get("player_id", 1)
            pie.player_info.race_requested = p.get("race", 2)
            pie.player_info.race_actual = p.get("race", 2)
            pie.player_mmr = p.get("mmr", 4500)
            pie.player_apm = p.get("apm", 150)
            pr = pie.player_result
            pr.player_id = p.get("player_id", 1)
            pr.result = p.get("result", 1)
        return info


def _replay_actions_until(rep: dict, cursor: int, loop: int):
    """Actions whose recorded loop has been reached since the last observe."""
    out = []
    actions = rep.get("actions", [])
    while cursor < len(actions) and actions[cursor][0] <= loop:
        rec_loop, ability_id, unit_tags, target = actions[cursor]
        a = sc_pb.Action()
        a.game_loop = rec_loop
        uc = a.action_raw.unit_command
        uc.ability_id = ability_id
        uc.unit_tags.extend(unit_tags)
        if isinstance(target, (tuple, list)):
            uc.target_world_space_pos.x = float(target[0])
            uc.target_world_space_pos.y = float(target[1])
        elif isinstance(target, int):
            uc.target_unit_tag = target
        out.append(a)
        cursor += 1
    return out, cursor


def main(argv=None) -> None:
    """SC2-binary-compatible entry: -listen HOST -port N [ignored args]."""
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    host, port = "127.0.0.1", 0
    i = 0
    while i < len(argv):
        if argv[i] == "-listen":
            host = argv[i + 1]
            i += 2
        elif argv[i] == "-port":
            port = int(argv[i + 1])
            i += 2
        else:
            i += 1  # -dataDir/-tempDir/-dataVersion etc: accepted, ignored
    import logging

    logging.basicConfig(level=logging.INFO, format="%(message)s")
    server = FakeSC2Server(port=port, host=host)
    logging.info("fake_sc2 listening on %s:%s", server.host, server.port)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
