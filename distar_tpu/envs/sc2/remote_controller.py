"""RemoteController: the python interface to a running SC2 binary.

Role parity with the reference RemoteController (reference: distar/pysc2/
lib/remote_controller.py:127-386): blocking request/response calls with
status-gated validity, create/join/restart/start_replay lifecycle,
``observe(target_game_loop)`` with the stub-observation regurgitation, the
batched ``acts`` used by the env's hot loop, 'Game has already ended'
suppression, connect retries against a booting process.

Provenance: the status-gating decorator shapes (``valid_status`` /
``skip_status`` / ``decorate_check_error``) follow the request-validity
semantics of the SC2 api itself, which DeepMind's Apache-2.0 pysc2
(``pysc2/lib/remote_controller.py``) first codified as decorators — the
state machine they encode (which Status values make which request legal)
is fixed by the game protocol, so any correct client expresses the same
table. The implementations here are this repo's own.
"""
from __future__ import annotations

import copy
import functools
import logging
import os
import socket
import time

from . import protocol
from .proto import Status, sc_pb

DEFAULT_TIMEOUT_SECONDS = int(os.environ.get("DISTAR_SC2_TIMEOUT", "120"))


class ConnectError(Exception):
    pass


class RequestError(Exception):
    pass


def check_error(res, error_enum):
    """Raise RequestError if the response carries an error field."""
    if res.HasField("error"):
        enum_name = error_enum.DESCRIPTOR.full_name
        error_name = error_enum.Name(res.error)
        details = getattr(res, "error_details", "<none>")
        raise RequestError(f"{enum_name}.{error_name}: '{details}'")
    return res


def decorate_check_error(error_enum):
    def decorator(func):
        @functools.wraps(func)
        def _check_error(*args, **kwargs):
            return check_error(func(*args, **kwargs), error_enum)

        return _check_error

    return decorator


def skip_status(*skipped):
    """No-op the call when in one of the skipped states."""

    def decorator(func):
        @functools.wraps(func)
        def _skip_status(self, *args, **kwargs):
            if self.status not in skipped:
                return func(self, *args, **kwargs)

        return _skip_status

    return decorator


def valid_status(*valid):
    """Assert we are in a state where this request is legal."""

    def decorator(func):
        @functools.wraps(func)
        def _valid_status(self, *args, **kwargs):
            if self.status not in valid:
                raise protocol.ProtocolError(
                    f"`{func.__name__}` called while in state: {self.status}, "
                    f"valid: ({','.join(map(str, valid))})"
                )
            return func(self, *args, **kwargs)

        return _valid_status

    return decorator


def catch_game_end(func):
    """Suppress the spurious 'Game has already ended' protocol error that SC2
    can emit while our status is still in_game (reference :99-124)."""

    @functools.wraps(func)
    def _catch_game_end(self, *args, **kwargs):
        prev_status = self.status
        try:
            return func(self, *args, **kwargs)
        except protocol.ProtocolError as protocol_error:
            if prev_status == Status.in_game and (
                "Game has already ended" in str(protocol_error)
            ):
                logging.warning(
                    "Received a 'Game has already ended' error from SC2 whilst "
                    "status in_game. Suppressing the exception, returning None."
                )
                return None
            raise

    return _catch_game_end


class RemoteController:
    """Blocking python calls mapped onto SC2 api requests."""

    def __init__(self, host, port, proc=None, timeout_seconds=None, sock=None):
        timeout_seconds = timeout_seconds or DEFAULT_TIMEOUT_SECONDS
        if sock is None:
            sock = self._connect(host, port, proc, timeout_seconds)
        self._client = protocol.StarcraftProtocol(sock)
        self._last_obs = None
        self.ping()

    def _connect(self, host, port, proc, timeout_seconds):
        """Dial the binary's /sc2api websocket until the deadline lapses.

        A booting SC2 binary refuses TCP for a while, then serves 404 until
        the /sc2api endpoint registers — both mean "keep dialing". Two
        conditions end the wait early: the endpoint actively closing the
        handshake (another client owns the port — one controller per
        process), and the process dying after it was seen alive (or never
        appearing within the first quarter of the budget). Role parity with
        the reference's connect retry (reference remote_controller.py:147)."""
        import websocket

        wire_host = f"[{host}]" if ":" in host and not host.startswith("[") else host
        endpoint = f"ws://{wire_host}:{port}/sc2api"
        start = time.monotonic()
        boot_grace = timeout_seconds / 4  # how long a proc may take to appear
        seen_alive = False
        dials = 0
        while time.monotonic() - start < timeout_seconds:
            alive = bool(proc and proc.running)
            seen_alive = seen_alive or alive
            if not alive and (seen_alive or time.monotonic() - start >= boot_grace):
                raise ConnectError(
                    f"SC2 process is gone; stopped dialing {endpoint} after "
                    f"{dials} attempts"
                )
            dials += 1
            logging.info("dialing %s (attempt %d, proc alive: %s)", endpoint, dials, alive)
            try:
                return websocket.create_connection(endpoint, timeout=timeout_seconds)
            except websocket.WebSocketBadStatusException as err:
                if err.status_code != 404:  # 404 = listening, endpoint not up yet
                    raise
            except websocket.WebSocketConnectionClosedException:
                raise ConnectError(
                    f"{endpoint} closed the handshake — is another controller "
                    "already attached to this process?"
                )
            except socket.error:
                pass  # not listening yet
            time.sleep(1)
        raise ConnectError(f"no websocket at {endpoint} within {timeout_seconds}s")

    def close(self) -> None:
        self._client.close()

    @property
    def status(self) -> Status:
        return self._client.status

    @property
    def status_ended(self) -> bool:
        return self.status == Status.ended

    # -------------------------------------------------------- game lifecycle
    @valid_status(Status.launched, Status.ended, Status.in_game, Status.in_replay)
    @decorate_check_error(sc_pb.ResponseCreateGame.Error)
    def create_game(self, req_create_game):
        """Create a new game (host only)."""
        return self._client.send(create_game=req_create_game)

    @valid_status(Status.launched, Status.init_game)
    @decorate_check_error(sc_pb.ResponseSaveMap.Error)
    def save_map(self, map_path, map_data):
        """Save a map into the temp dir so multiplayer create can access it."""
        return self._client.send(
            save_map=sc_pb.RequestSaveMap(map_path=map_path, map_data=map_data)
        )

    @valid_status(Status.launched, Status.init_game)
    @decorate_check_error(sc_pb.ResponseJoinGame.Error)
    def join_game(self, req_join_game):
        """Join a game (all connected clients)."""
        return self._client.send(join_game=req_join_game)

    @valid_status(Status.ended, Status.in_game)
    @decorate_check_error(sc_pb.ResponseRestartGame.Error)
    def restart(self):
        """Restart the game (host only)."""
        return self._client.send(restart_game=sc_pb.RequestRestartGame())

    @valid_status(Status.launched, Status.ended, Status.in_game, Status.in_replay)
    @decorate_check_error(sc_pb.ResponseStartReplay.Error)
    def start_replay(self, req_start_replay):
        return self._client.send(start_replay=req_start_replay)

    @valid_status(Status.in_game, Status.ended)
    def leave(self):
        """Disconnect from a multiplayer game."""
        return self._client.send(leave_game=sc_pb.RequestLeaveGame())

    @skip_status(Status.quit)
    def quit(self):
        """Shut down the SC2 process."""
        try:
            # don't expect a response
            self._client.write(sc_pb.Request(quit=sc_pb.RequestQuit(), id=999999999))
        except protocol.ConnectionError:
            pass  # already (shutting) down
        finally:
            self.close()

    # ------------------------------------------------------------------ info
    @valid_status(Status.in_game, Status.in_replay)
    def game_info(self):
        return self._client.send(game_info=sc_pb.RequestGameInfo())

    @valid_status(Status.in_game, Status.in_replay)
    def data_raw(self, ability_id=True, unit_type_id=True, upgrade_id=True,
                 buff_id=True, effect_id=True):
        return self._client.send(
            data=sc_pb.RequestData(
                ability_id=ability_id, unit_type_id=unit_type_id,
                upgrade_id=upgrade_id, buff_id=buff_id, effect_id=effect_id,
            )
        )

    def ping(self):
        return self._client.send(ping=sc_pb.RequestPing())

    @decorate_check_error(sc_pb.ResponseReplayInfo.Error)
    def replay_info(self, replay_path=None, replay_data=None):
        req = sc_pb.RequestReplayInfo()
        if replay_data is not None:
            req.replay_data = replay_data
        else:
            req.replay_path = replay_path
        return self._client.send(replay_info=req)

    def available_maps(self):
        return self._client.send(available_maps=sc_pb.RequestAvailableMaps())

    # ---------------------------------------------------------- observe/step
    @valid_status(Status.in_game, Status.in_replay, Status.ended)
    def observe(self, disable_fog=False, target_game_loop=0):
        """Observation at an explicit target game loop (reference :241-272)."""
        obs = self._client.send(
            observation=sc_pb.RequestObservation(
                game_loop=target_game_loop, disable_fog=disable_fog
            )
        )
        if obs.observation.game_loop == 2 ** 32 - 1:
            logging.info("Received stub observation.")
            if not obs.player_result:
                raise ValueError("Expect a player result in a stub observation")
            if self._last_obs is None:
                raise RuntimeError("Received stub observation with no previous obs")
            # regurgitate the previous observation + the new result/actions
            new_obs = copy.deepcopy(self._last_obs)
            del new_obs.actions[:]
            new_obs.actions.extend(obs.actions)
            new_obs.player_result.extend(obs.player_result)
            obs = new_obs
            self._last_obs = None
        else:
            self._last_obs = obs
        return obs

    @valid_status(Status.in_game, Status.in_replay)
    @catch_game_end
    def step(self, count=1):
        """Step the engine forward by ``count`` game loops."""
        return self._client.send(step=sc_pb.RequestStep(count=count))

    # ---------------------------------------------------------------- actions
    @skip_status(Status.in_replay)
    @valid_status(Status.in_game)
    @catch_game_end
    def actions(self, req_action):
        """Send a RequestAction (may batch multiple actions)."""
        return self._client.send(action=req_action)

    def act(self, action):
        """Send a single action."""
        if action and action.ListFields():  # skip no-ops
            return self.actions(sc_pb.RequestAction(actions=[action]))

    def acts(self, act_list):
        """Batched actions — the env hot path (reference :330-333).

        Accepts sc_pb.Action protos OR the plain raw-command dicts emitted by
        ProtoFeatures.transform_action (converted here, keeping the feature
        layer proto-agnostic). Returns the per-action result list."""
        protos = [a if not isinstance(a, dict) else raw_cmd_to_action(a) for a in act_list]
        protos = [a for a in protos if a is not None]
        if not protos:
            return None
        res = self.actions(sc_pb.RequestAction(actions=protos))
        return list(res.result) if res is not None else None

    def chat(self, message, channel=None):
        if message:
            action = sc_pb.Action(
                action_chat=sc_pb.ActionChat(
                    channel=channel or sc_pb.ActionChat.Broadcast, message=message
                )
            )
            return self.act(action)

    # ----------------------------------------------------------------- misc
    @valid_status(Status.in_game, Status.in_replay, Status.ended)
    def save_replay(self):
        res = self._client.send(save_replay=sc_pb.RequestSaveReplay())
        return res.data


def raw_cmd_to_action(cmd: dict):
    """ProtoFeatures.transform_action dict -> sc_pb.Action raw unit command.

    The dict contract: {ability_id, queue_command, unit_tags,
    target_unit_tag?, target_world_space_pos?} (envs/features.py)."""
    if not cmd or not cmd.get("ability_id") and not cmd.get("unit_tags"):
        return None
    action = sc_pb.Action()
    uc = action.action_raw.unit_command
    uc.ability_id = int(cmd.get("ability_id", 0))
    uc.queue_command = bool(cmd.get("queue_command", False))
    uc.unit_tags.extend(int(t) for t in cmd.get("unit_tags", []))
    if cmd.get("target_unit_tag") is not None:
        uc.target_unit_tag = int(cmd["target_unit_tag"])
    elif cmd.get("target_world_space_pos") is not None:
        x, y = cmd["target_world_space_pos"]
        uc.target_world_space_pos.x = float(x)
        uc.target_world_space_pos.y = float(y)
    return action
