"""The SC2 client layer (the reference's pysc2-fork role, L1).

Modules:
  proto              — s2client protobuf resolution (pip package or vendored)
  protocol           — websocket request/response framing + status machine
  remote_controller  — blocking python calls onto the SC2 api
  sc_process         — binary launch / port / teardown
  run_configs        — version routing + platform install discovery
  maps               — map registry (sizes, localized names, install)
  launcher           — N-process create/join orchestration -> RealSC2Env
  fake_sc2           — in-process fake SC2 websocket server (tests/demos)
"""
from .proto import PROVIDER, Status, sc_pb  # noqa: F401
from .remote_controller import RemoteController, ConnectError, RequestError  # noqa: F401
from .protocol import ConnectionError, ProtocolError, StarcraftProtocol  # noqa: F401
