"""Launch the SC2 binary and set up its websocket endpoint.

Role parity with the reference StarcraftProcess (reference: distar/pysc2/
lib/sc_process.py:49-234): build the command line (-listen/-port/-dataDir/
-tempDir/-dataVersion), pick a free port, launch detached, connect a
RemoteController with boot-aware retries, and clean up (terminate -> kill,
temp dir removal, port return) on close.
"""
from __future__ import annotations

import logging
import os
import platform as _platform
import shutil
import subprocess
import tempfile
import time
from typing import Optional

from . import portpicker_compat as portpicker
from . import remote_controller

# the role of the reference's --sc2_port flag: connect to an already-running
# instance instead of launching one
FIXED_PORT = os.environ.get("DISTAR_SC2_PORT")


class SC2LaunchError(Exception):
    pass


class StarcraftProcess:
    """Launch an SC2 server, initialize a controller, clean up on close.

    Best used via run_configs (which resolves version and paths) and as a
    context manager — otherwise temp files and SC2 processes leak.
    """

    def __init__(self, run_config, exec_path, version, full_screen=False,
                 extra_args=None, verbose=False, host=None, port=None,
                 connect=True, timeout_seconds=None, window_size=(640, 480),
                 window_loc=(50, 50), **kwargs):
        self._proc = None
        self._controller = None
        self._check_exists(exec_path)
        self._tmp_dir = tempfile.mkdtemp(prefix="sc-", dir=run_config.tmp_dir)
        self._host = host or "127.0.0.1"
        self._port = int(FIXED_PORT) if FIXED_PORT else (port or portpicker.pick_unused_port())
        self._version = version

        args = [
            exec_path,
            "-listen", self._host,
            "-port", str(self._port),
            "-dataDir", os.path.join(run_config.data_dir, ""),
            "-tempDir", os.path.join(self._tmp_dir, ""),
        ]
        if ":" in self._host:
            args += ["-ipv6"]
        if _platform.system() != "Linux":
            if full_screen:
                args += ["-displayMode", "1"]
            else:
                args += [
                    "-displayMode", "0",
                    "-windowwidth", str(window_size[0]),
                    "-windowheight", str(window_size[1]),
                    "-windowx", str(window_loc[0]),
                    "-windowy", str(window_loc[1]),
                ]
        if verbose or os.environ.get("DISTAR_SC2_VERBOSE"):
            args += ["-verbose"]
        if self._version and self._version.data_version:
            args += ["-dataVersion", self._version.data_version.upper()]
        if extra_args:
            args += extra_args

        logging.info("Launching SC2: %s", " ".join(args))
        try:
            if not FIXED_PORT:
                self._proc = self._launch(run_config, args, **kwargs)
            if connect:
                self._controller = remote_controller.RemoteController(
                    self._host, self._port, self, timeout_seconds=timeout_seconds
                )
        except Exception:
            self.close()
            raise

    def close(self) -> None:
        """Shut down the game and clean up."""
        if hasattr(self, "_controller") and self._controller:
            self._controller.quit()
            self._controller.close()
            self._controller = None
        self._shutdown()
        if hasattr(self, "_port") and self._port:
            if not FIXED_PORT:
                portpicker.return_port(self._port)
            self._port = None
        if hasattr(self, "_tmp_dir") and os.path.exists(self._tmp_dir):
            shutil.rmtree(self._tmp_dir, ignore_errors=True)

    @property
    def controller(self):
        return self._controller

    @property
    def host(self):
        return self._host

    @property
    def port(self):
        return self._port

    @property
    def version(self):
        return self._version

    def __enter__(self):
        return self.controller

    def __exit__(self, exc_type, exc_value, tb):
        self.close()

    def __del__(self):
        self.close()

    def _check_exists(self, exec_path: str) -> None:
        if not os.path.isfile(exec_path):
            raise RuntimeError(f"Trying to run '{exec_path}', but it doesn't exist")
        if not os.access(exec_path, os.X_OK):
            raise RuntimeError(f"Trying to run '{exec_path}', but it isn't executable.")

    def _launch(self, run_config, args, **kwargs):
        del kwargs
        try:
            return subprocess.Popen(
                args,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                cwd=run_config.cwd,
                env=run_config.env,
            )
        except OSError:
            logging.exception("Failed to launch")
            raise SC2LaunchError(f"Failed to launch: {args}")

    def _shutdown(self) -> None:
        if self._proc:
            ret = _shutdown_proc(self._proc, 3)
            logging.info("Shutdown with return code: %s", ret)
            self._proc = None

    @property
    def running(self) -> bool:
        if FIXED_PORT:
            return True
        return bool(self._proc) and self._proc.poll() is None

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self.running else None


def _shutdown_proc(p, timeout: int):
    """Terminate politely, then kill after ``timeout`` seconds."""
    freq = 10
    for _ in range(1 + timeout * freq):
        p.terminate()
        ret = p.poll()
        if ret is not None:
            logging.info("Shutdown gracefully.")
            return ret
        time.sleep(1 / freq)
    logging.warning("Killing the process.")
    p.kill()
    return p.wait()
