"""portpicker shim: the real package when installed, stdlib fallback else.

The image this repo targets does not ship ``portpicker``; its hard import
made every sc2 client/launcher module (and the replay-decoder tests relying
on them) fail to import. The fallback picks a free port by binding port 0 —
the same OS mechanism portpicker uses, minus its cross-process reservation
bookkeeping, which the single-host launch paths here don't depend on.
"""
from __future__ import annotations

import socket

try:  # pragma: no cover - depends on optional dep
    from portpicker import pick_unused_port, return_port
except ImportError:

    def pick_unused_port() -> int:
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def return_port(port: int) -> None:
        return None
