"""Websocket request/response protocol for talking to the SC2 binary.

Role parity with the reference StarcraftProtocol (reference: distar/pysc2/
lib/protocol.py:72-192): synchronous write-request/read-response over one
websocket, status tracking from every response, request-id counting,
connection/protocol error taxonomy, optional packet logging.

The socket is duck-typed (``send(bytes)``/``recv() -> bytes``/``close()``),
so both a real ``websocket-client`` connection and an in-process test
transport satisfy it.
"""
from __future__ import annotations

import itertools
import logging
import os
import socket as _socket
import sys
import time
from typing import Optional

from .proto import Status, sc_pb

# set DISTAR_SC2_VERBOSE_PROTOCOL=N to print N lines per packet (-1 = all),
# the role of the reference's --sc2_verbose_protocol absl flag
VERBOSE = int(os.environ.get("DISTAR_SC2_VERBOSE_PROTOCOL", "0"))
MAX_WIDTH = int(os.environ.get("COLUMNS", 200))


class ConnectionError(Exception):  # noqa: A001 - mirrors the reference name
    """Failed to read/write a message, details in the error string."""


class ProtocolError(Exception):
    """SC2 responded with an error message likely due to a bad request or bug."""


def _translate_socket_errors(fn):
    def wrapped(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except ConnectionError:
            raise
        except _socket.error as e:
            raise ConnectionError(f"Socket error: {e}")
        except Exception as e:  # websocket-client exception classes
            name = type(e).__name__
            if "ConnectionClosed" in name:
                raise ConnectionError(
                    "Connection already closed. SC2 probably crashed. "
                    "Check the error log."
                )
            if "Timeout" in name:
                raise ConnectionError("Websocket timed out.")
            raise

    return wrapped


class StarcraftProtocol:
    """Synchronous request/response protocol over one websocket."""

    def __init__(self, sock):
        self._status = Status.launched
        self._sock = sock
        self._count = itertools.count(1)

    @property
    def status(self) -> Status:
        return self._status

    def close(self) -> None:
        if self._sock:
            try:
                self._sock.close()
            except Exception:
                pass
            self._sock = None
        self._status = Status.quit

    def read(self):
        """Read a Response, validate, track status (reference :92-113)."""
        if VERBOSE:
            self._log("-------------- Reading response --------------")
            start = time.time()
        response = self._read()
        if VERBOSE:
            self._log(
                f"-------------- Read {response.WhichOneof('response')} in "
                f"{1000 * (time.time() - start):.1f} msec --------------\n"
                f"{self._packet_str(response)}"
            )
        if not response.HasField("status"):
            raise ProtocolError("Got an incomplete response without a status.")
        prev_status = self._status
        self._status = Status(response.status)
        if response.error:
            err = (
                "Error in RPC response (likely a bug). "
                f"Prev status: {prev_status}, new status: {self._status}, error:\n"
                + "\n".join(response.error)
            )
            logging.error(err)
            raise ProtocolError(err)
        return response

    def write(self, request) -> None:
        if VERBOSE:
            self._log(
                f"-------------- Writing request: {request.WhichOneof('request')} "
                f"--------------\n{self._packet_str(request)}"
            )
        self._write(request)

    def send_req(self, request):
        self.write(request)
        return self.read()

    def send(self, **kwargs):
        """Build a Request from a single kwarg, send it, return the matching
        sub-response (reference :129-153)."""
        assert len(kwargs) == 1, "Must make a single request."
        name = next(iter(kwargs))
        req = sc_pb.Request(**kwargs)
        req.id = next(self._count)
        try:
            res = self.send_req(req)
        except ConnectionError as e:
            raise ConnectionError(f"Error during {name}: {e}")
        if res.HasField("id") and res.id != req.id:
            raise ConnectionError(
                f"Error during {name}: Got a response with a different id"
            )
        return getattr(res, name)

    # ------------------------------------------------------------- internals
    def _packet_str(self, packet) -> str:
        packet_str = str(packet).strip()
        if VERBOSE <= 0:
            return packet_str
        lines = packet_str.split("\n")
        count = len(lines)
        lines = [line[:MAX_WIDTH] for line in lines[: VERBOSE + 1]]
        if count > VERBOSE + 1:
            lines[-1] = f"***** {count - VERBOSE} lines skipped *****"
        return "\n".join(lines)

    def _log(self, s: str) -> None:
        sys.stderr.write(s + "\n")
        sys.stderr.flush()

    @_translate_socket_errors
    def _read(self):
        response_str = self._sock.recv()
        if not response_str:
            raise ProtocolError("Got an empty response from SC2.")
        return sc_pb.Response.FromString(response_str)

    @_translate_socket_errors
    def _write(self, request) -> None:
        self._sock.send(request.SerializeToString())
