"""LAN / remote human-play: an agent joins a game hosted on another machine.

Role parity with the reference's LAN envs (reference:
distar/pysc2/env/lan_sc2_env.py — agent side: fetch the host's port config
over TCP, launch a local SC2 client, join the remote game via host_ip;
distar/pysc2/env/remote_sc2_env.py — join an externally-created game;
distar/pysc2/bin/play_vs_agent.py — human side: host the LAN game and serve
the config). This is how a remote human showmatch runs: the human's machine
hosts and plays full-screen; the agent machine joins over the network.

Wire format: ONE length-prefixed serialized dict (the comm shuttle's frame —
same data plane as trajectories) carrying
``{map_name, ports: {server_game, server_base, client_game, client_base},
race, realtime}``.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Optional, Sequence

from ...comm import shuttle
from ...comm.serializer import dumps, loads
from ..features import ProtoFeatures
from ..sc2_env import SC2Env
from .proto import sc_pb
from .run_configs import get as get_run_config

RACES = {"zerg": 2, "terran": 1, "protoss": 3, "random": 4}


@dataclasses.dataclass
class LanPorts:
    server_game: int
    server_base: int
    client_game: int
    client_base: int

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def serve_handshake(info: dict, timeout_ms: int = 600_000) -> int:
    """Host side: serve the game config once on an ephemeral port; the agent
    machine connects and receives it (role of the reference's tcp_client /
    tcp_server pair, lan_sc2_env.py)."""
    return shuttle.serve(dumps(info, compress=False), accept_count=1, timeout_ms=timeout_ms)


def fetch_handshake(host: str, port: int, timeout_ms: int = 600_000) -> dict:
    return loads(shuttle.fetch(host, port, timeout_ms=timeout_ms))


def host_lan_game(
    map_name: str,
    race: str = "zerg",
    realtime: bool = True,
    version: Optional[str] = None,
    handshake_timeout_ms: int = 600_000,
    run_config=None,
    controller=None,
    ports: Optional[LanPorts] = None,
):
    """Human/host side: launch SC2 full screen, create a 2-participant LAN
    game, publish the config, and join as the human (in the background — the
    join completes once the remote agent joins). Returns
    (controller, handshake_port, proc, join_thread); the human then plays
    through the client UI while the remote agent joins via ``LanSC2Env``.

    ``controller``/``ports`` injectable for tests (fake server).
    """
    from . import portpicker_compat as portpicker
    from . import maps as map_registry

    if run_config is None and controller is None:
        run_config = get_run_config(version=version)
    proc = None
    if controller is None:
        proc = run_config.start(want_rgb=False, full_screen=True)
        controller = proc.controller
    if ports is None:
        ports = LanPorts(*[portpicker.pick_unused_port() for _ in range(4)])

    map_inst = map_registry.get(map_name)
    create = sc_pb.RequestCreateGame(realtime=realtime, disable_fog=False)
    create.local_map.map_path = map_inst.path or map_inst.name
    if run_config is not None and map_inst.path:
        create.local_map.map_data = map_inst.data(run_config)
    create.player_setup.add(type=sc_pb.Participant)
    create.player_setup.add(type=sc_pb.Participant)
    controller.create_game(create)

    handshake_port = serve_handshake(
        {
            "map_name": map_inst.name,
            "ports": ports.as_dict(),
            "race": race,
            "realtime": realtime,
        },
        timeout_ms=handshake_timeout_ms,
    )
    logging.info(
        "LAN game '%s' hosted; agent handshake on port %d", map_inst.name, handshake_port
    )

    join = sc_pb.RequestJoinGame(options=sc_pb.InterfaceOptions(raw=False, score=True))
    join.race = RACES.get(race, RACES["zerg"])
    join.server_ports.game_port = ports.server_game
    join.server_ports.base_port = ports.server_base
    join.client_ports.add(game_port=ports.client_game, base_port=ports.client_base)
    join.player_name = "human"
    # join_game blocks until EVERY participant joined (SC2 semantics) — the
    # agent connects later from another machine, so the host's join runs in
    # the background; wait on the returned thread before playing
    import threading

    join_thread = threading.Thread(
        target=lambda: controller.join_game(join), daemon=True
    )
    join_thread.start()
    return controller, handshake_port, proc, join_thread


class LanSC2Env(SC2Env):
    """Agent side: join a remote/LAN game created elsewhere and drive it as a
    one-agent SC2Env (the human is on their own machine, never observed or
    acted by us — exactly the reference lan_sc2_env contract)."""

    def __init__(
        self,
        host: str,
        config_port: int,
        agent_race: str = "zerg",
        version: Optional[str] = None,
        episode_length: int = 100_000,
        controller_factory: Optional[Callable[[], object]] = None,
        **env_kwargs,
    ):
        info = fetch_handshake(host, config_port)
        ports = info["ports"]
        self._proc = None
        if controller_factory is not None:
            controller = controller_factory()
        else:
            run_config = get_run_config(version=version)
            self._proc = run_config.start(want_rgb=False)
            controller = self._proc.controller

        interface = sc_pb.InterfaceOptions(
            raw=True,
            score=True,
            raw_affects_selection=True,  # a human shares this game
            raw_crop_to_playable_area=True,
        )
        interface.feature_layer.width = 24
        interface.feature_layer.resolution.x = 1
        interface.feature_layer.resolution.y = 1
        try:
            from . import maps as map_registry

            map_size = map_registry.get_map_size(info["map_name"])
        except KeyError:
            map_size = (152, 160)
        interface.feature_layer.minimap_resolution.x = map_size[0]
        interface.feature_layer.minimap_resolution.y = map_size[1]
        interface.feature_layer.crop_to_playable_area = True

        join = sc_pb.RequestJoinGame(options=interface)
        join.race = RACES.get(agent_race, RACES["zerg"])
        join.player_name = "agent"
        join.host_ip = host
        # reversed roles: the host's client ports are OUR server ports
        join.server_ports.game_port = ports["server_game"]
        join.server_ports.base_port = ports["server_base"]
        join.client_ports.add(game_port=ports["client_game"], base_port=ports["client_base"])
        controller.join_game(join)

        features = ProtoFeatures(controller.game_info())
        super().__init__(
            controllers=[controller],
            features=[features],
            episode_length=episode_length,
            realtime=bool(info.get("realtime", True)),
            both_obs=False,
            **env_kwargs,
        )

    def close(self) -> None:
        super().close()
        if self._proc is not None:
            try:
                self._proc.close()
            except Exception:
                pass
