"""Game launch orchestration: N SC2 processes, multiplayer create/join with
port plumbing, lifecycle (restart / periodic relaunch), -> a real SC2Env.

Role parity with the reference SC2Env's launch half (reference: distar/envs/
env.py:96-330): launch one process per agent with retries x10 (:181-209),
reserve 2 ports per agent and wire server/client PortSets into the join
requests (:211-274), save the map onto every controller for multiplayer
(:235-241), built-in-bot player setups, game relaunch every N episodes
against engine leaks (:309-311), restart-vs-recreate on reset (:290-311).

The step/observe orchestration half already lives in envs.sc2_env.SC2Env —
this module provisions the controllers/features it drives. A
``controller_factory`` hook swaps real processes for connections to
fake_sc2.FakeSC2Server in tests (same RemoteController code path).
"""
from __future__ import annotations

import logging
import random
import re
import threading
import time
from typing import Callable, List, Optional, Sequence

from . import portpicker_compat as portpicker
from ..features import ProtoFeatures
from ..sc2_env import SC2Env
from . import maps as map_registry
from . import run_configs
from .proto import sc_pb

RACES = {"terran": 1, "zerg": 2, "protoss": 3, "random": 4}
MAX_RETRY_TIMES = 10


def crop_and_deduplicate_names(names: Sequence[str], limit: int = 32) -> List[str]:
    """SC2 truncates long player names; keep them unique after cropping."""
    out, seen = [], {}
    for name in names:
        cropped = name[:limit]
        n = seen.get(cropped, 0)
        seen[cropped] = n + 1
        out.append(cropped if n == 0 else f"{cropped[: limit - 3]}({n})")
    return out


class Player:
    def __init__(self, race: str, name: str = "agent"):
        self.race = RACES[race.lower()]
        self.name = name


class Bot(Player):
    def __init__(self, race: str, difficulty: int, ai_build: int = 1):
        super().__init__(race, name=f"bot{difficulty}")
        self.difficulty = difficulty
        self.ai_build = ai_build


class Human(Player):
    """A human participant: gets their own (full-screen) SC2 client to play
    in; the env never observes or acts their controller (reference
    env.py:191-197, :315-316)."""

    def __init__(self, race: str, name: str = "human"):
        super().__init__(race, name=name)


class SC2GameLauncher:
    """Owns processes + controllers + per-agent features for one game."""

    def __init__(
        self,
        map_name: str = "KairosJunction",
        players: Optional[Sequence[Player]] = None,
        realtime: bool = False,
        version: Optional[str] = None,
        run_config=None,
        relaunch_every_episodes: int = 10,
        random_seed: Optional[int] = None,
        controller_factory: Optional[Callable[[int], object]] = None,
        game_steps_per_episode: int = 100_000,
    ):
        self._map_names = [m for m in ([map_name] if isinstance(map_name, str) else list(map_name))]
        self.players = list(players or [Player("zerg"), Player("zerg")])
        self.num_agents = sum(1 for p in self.players if not isinstance(p, Bot))
        self._realtime = realtime
        self._random_seed = random_seed
        self._relaunch_every = relaunch_every_episodes
        self._controller_factory = controller_factory
        self._run_config = run_config
        if run_config is None and controller_factory is None:
            self._run_config = run_configs.get(version=version)
        self.game_steps_per_episode = game_steps_per_episode

        self._procs: List = []
        self.controllers: List = []
        self.features: List[ProtoFeatures] = []
        self._ports: List[int] = []
        self._episodes_since_launch = 0
        self._launched = False
        self.map_name = None

    # -------------------------------------------------------------- launch
    def _launch_game(self) -> None:
        """Launch processes (or factory controllers) with retries x10
        (reference env.py:179-209)."""
        for attempt in range(MAX_RETRY_TIMES):
            try:
                if self.num_agents > 1:
                    self._ports = [
                        portpicker.pick_unused_port() for _ in range(self.num_agents * 2)
                    ]
                else:
                    self._ports = []
                if self._controller_factory is not None:
                    self._procs = []
                    self.controllers = [
                        self._controller_factory(i) for i in range(self.num_agents)
                    ]
                else:
                    agent_players = [
                        p for p in self.players if not isinstance(p, Bot)
                    ]
                    # the human's client launches full screen (reference
                    # env.py:191-197)
                    self._procs = [
                        self._run_config.start(
                            want_rgb=False, full_screen=isinstance(p, Human)
                        )
                        for p in agent_players
                    ]
                    self.controllers = [p.controller for p in self._procs]
                return
            except Exception as e:
                logging.error("start SC2 failed (%r), retry %d", e, attempt)
                self.close()
                if attempt == MAX_RETRY_TIMES - 1:
                    raise

    def _create_join(self) -> None:
        """Create the game on the host and join from every agent
        (reference env.py:211-274)."""
        map_inst = map_registry.get(random.choice(self._map_names))
        self.map_name = map_inst.name
        map_size = map_registry.get_map_size(map_inst.name)

        create = sc_pb.RequestCreateGame(
            disable_fog=False, realtime=self._realtime
        )
        if self._run_config is not None and map_inst.path:
            map_data = map_inst.data(self._run_config)
            create.local_map.map_path = map_inst.path
            if self.num_agents == 1:
                create.local_map.map_data = map_data
            else:
                # every client must see the map file (SC2 tmpdir quirk,
                # reference :235-241)
                for c in self.controllers:
                    c.save_map(map_inst.path, map_data)
        else:
            create.local_map.map_path = map_inst.path or map_inst.name
        if self._random_seed is not None:
            create.random_seed = self._random_seed
        for p in self.players:
            if isinstance(p, Bot):
                create.player_setup.add(
                    type=sc_pb.Computer, race=p.race, difficulty=p.difficulty,
                    ai_build=p.ai_build,
                )
            else:
                create.player_setup.add(type=sc_pb.Participant)
        host = self.controllers[1] if self.num_agents > 1 else self.controllers[0]
        host.create_game(create)

        # interface options: raw + score + map-sized minimap feature layers
        # (reference _setup_interface :150-177)
        agent_players = [p for p in self.players if not isinstance(p, Bot)]
        has_human = any(isinstance(p, Human) for p in agent_players)
        names = crop_and_deduplicate_names([p.name for p in agent_players])
        join_reqs = []
        for p, name in zip(agent_players, names):
            interface = sc_pb.InterfaceOptions(
                raw=True,
                score=True,
                show_cloaked=False,
                show_burrowed_shadows=False,
                show_placeholders=False,
                # a human drives the UI, so raw commands must respect their
                # selection (reference _setup_interface env.py:153-156)
                raw_affects_selection=has_human,
                raw_crop_to_playable_area=True,
            )
            interface.feature_layer.width = 24
            interface.feature_layer.resolution.x = 1
            interface.feature_layer.resolution.y = 1
            interface.feature_layer.minimap_resolution.x = map_size[0]
            interface.feature_layer.minimap_resolution.y = map_size[1]
            interface.feature_layer.crop_to_playable_area = True
            join = sc_pb.RequestJoinGame(options=interface)
            join.race = p.race
            join.player_name = name
            if self._ports:
                join.shared_port = 0  # unused
                join.server_ports.game_port = self._ports[0]
                join.server_ports.base_port = self._ports[1]
                for i in range(self.num_agents - 1):
                    join.client_ports.add(
                        game_port=self._ports[i * 2 + 2],
                        base_port=self._ports[i * 2 + 3],
                    )
            join_reqs.append(join)

        # join blocks until all clients joined -> run in parallel
        # (reference :268-271 via run_parallel)
        errors: List = [None] * len(join_reqs)

        def _join(i):
            try:
                self.controllers[i].join_game(join_reqs[i])
            except Exception as e:  # surfaced after the barrier
                errors[i] = e

        threads = [
            threading.Thread(target=_join, args=(i,)) for i in range(len(join_reqs))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for e in errors:
            if e is not None:
                raise e

        game_infos = [c.game_info() for c in self.controllers]
        self.features = [ProtoFeatures(gi) for gi in game_infos]
        self._launched = True

    @property
    def human_indices(self) -> List[int]:
        agent_players = [p for p in self.players if not isinstance(p, Bot)]
        return [i for i, p in enumerate(agent_players) if isinstance(p, Human)]

    def save_replay(self, replay_dir: str, prefix: Optional[str] = None) -> Optional[str]:
        """Pull the replay from the first controller and write it via the run
        config (reference env.py:485-496)."""
        if not self.controllers or self._run_config is None:
            return None
        data = self.controllers[0].save_replay()
        if not data:
            return None
        return self._run_config.save_replay(data, replay_dir, prefix)

    # ------------------------------------------------------------ lifecycle
    def ensure_game(self) -> None:
        """Called at every episode start: launch on first use, full relaunch
        every N episodes (memory leaks, reference :309-311), restart-or-
        recreate otherwise (:290-311)."""
        if not self._launched:
            self._launch_game()
            self._create_join()
            self._episodes_since_launch = 0
            return
        self._episodes_since_launch += 1
        if (
            self._relaunch_every
            and self._episodes_since_launch >= self._relaunch_every
            and self._controller_factory is None
        ):
            logging.info("relaunching SC2 after %d episodes", self._episodes_since_launch)
            self.close()
            self._launch_game()
            self._create_join()
            self._episodes_since_launch = 0
            return
        single = self.num_agents == 1 and len(self._map_names) == 1
        if single:
            try:
                self.controllers[0].restart()
                return
            except Exception as e:
                logging.warning("restart failed (%r); recreating the game", e)
        self._create_join()

    def close(self) -> None:
        for c in self.controllers:
            try:
                c.quit()
            except Exception:
                pass
        self.controllers = []
        for p in self._procs:
            try:
                p.close()
            except Exception:
                pass
        self._procs = []
        for port in self._ports:
            try:
                portpicker.return_port(port)
            except Exception:
                pass
        self._ports = []
        self._launched = False


class RealSC2Env(SC2Env):
    """SC2Env over a launcher's real controllers (the complete L2+L1 stack:
    orchestration from envs.sc2_env + the client layer underneath)."""

    def __init__(
        self,
        launcher: SC2GameLauncher,
        save_replay_episodes: int = 0,
        replay_dir: str = ".",
        **env_kwargs,
    ):
        self._launcher = launcher
        launcher.ensure_game()
        replay_saver = None
        if save_replay_episodes > 0:
            replay_saver = lambda prefix: launcher.save_replay(replay_dir, prefix)
        super().__init__(
            controllers=launcher.controllers,
            features=launcher.features,
            episode_length=launcher.game_steps_per_episode,
            realtime=env_kwargs.pop("realtime", launcher._realtime),
            human_indices=launcher.human_indices,
            save_replay_episodes=save_replay_episodes,
            replay_saver=replay_saver,
            **env_kwargs,
        )
        self._first_reset_done = False

    def reset(self):
        if self._first_reset_done:
            self._launcher.ensure_game()
            self._controllers = list(self._launcher.controllers)
            self._features = list(self._launcher.features)
        self._first_reset_done = True
        return super().reset()

    def close(self) -> None:
        self._launcher.close()


def make_sc2_env(cfg: Optional[dict] = None, controller_factory=None) -> RealSC2Env:
    """Config-driven construction (the actor's env_fn for real games).

    cfg.env keys (reference rl_user_config.yaml env block): map_name,
    player_ids (['agent','bot7']), races, realtime, game_steps_per_episode,
    random_delay_weights, update_both_obs, version, random_seed."""
    from ...utils import Config, deep_merge_dicts

    defaults = {
        "env": {
            "map_name": "KairosJunction",
            "player_ids": ["agent", "agent"],
            "races": ["zerg", "zerg"],
            "realtime": False,
            "game_steps_per_episode": 100_000,
            "random_delay_weights": [],
            "update_both_obs": True,
            "version": None,
            "random_seed": None,
            "relaunch_every_episodes": 10,
            "save_replay_episodes": 0,
            "replay_dir": ".",
        }
    }
    whole = deep_merge_dicts(Config(defaults), cfg or {})
    ec = whole.env
    players = []
    for pid, race in zip(ec.player_ids, ec.races):
        # exact forms only — agent ids derive from checkpoint basenames,
        # which may legitimately contain 'bot'/'human' as substrings
        bot_m = re.fullmatch(r"bot(\d+)", str(pid))
        if bot_m:
            players.append(Bot(race, int(bot_m.group(1))))
        elif str(pid) == "human":
            players.append(Human(race))
        else:
            players.append(Player(race, name=str(pid)))
    launcher = SC2GameLauncher(
        map_name=ec.map_name,
        players=players,
        realtime=ec.realtime,
        version=ec.get("version"),
        random_seed=ec.get("random_seed"),
        relaunch_every_episodes=ec.get("relaunch_every_episodes", 10),
        controller_factory=controller_factory,
        game_steps_per_episode=ec.game_steps_per_episode,
    )
    return RealSC2Env(
        launcher,
        random_delay_weights=list(ec.get("random_delay_weights") or []),
        both_obs=bool(ec.get("update_both_obs", True)),
        save_replay_episodes=int(ec.get("save_replay_episodes", 0) or 0),
        replay_dir=str(ec.get("replay_dir", ".")),
    )
