"""Game-free SC2Replay header parsing.

Reads the replay header (protocol version, build, elapsed game loops)
straight out of the .SC2Replay file, WITHOUT launching the game binary.
The reference obtains the same facts via ``RequestReplayInfo`` through a
running SC2 client (distar/agent/default/replay_decoder.py:379-388) — that
needs a binary install; this parser lets version routing
(``run_configs.version_for_build``), replay sharding, and tests run on
machines with no game.

File format (public, documented by Blizzard's s2client-proto / s2protocol):
a .SC2Replay is an MPQ archive whose *user-data* preamble (magic
``MPQ\\x1b``) carries the serialized replay header in Blizzard's
"versioned" tag encoding. The header struct's field 1 is the version
struct {0: flags, 1: major, 2: minor, 3: revision, 4: build, 5: baseBuild},
field 3 is elapsedGameLoops.
"""
from __future__ import annotations

import io
import os
from typing import Any, BinaryIO, Dict, Union


class CorruptReplayError(ValueError):
    pass


class _VersionedReader:
    """Generic reader for Blizzard's self-describing "versioned" encoding.

    Each value is introduced by a one-byte tag:
      0x00 array     vint count, then elements
      0x01 bitblob   vint bit-length, then ceil(n/8) bytes
      0x02 blob      vint byte-length, then bytes
      0x03 choice    vint alternative id, then value
      0x04 optional  u8 exists flag, then value if nonzero
      0x05 struct    vint field count, then (vint field id, value) pairs
      0x06 u8
      0x07 u32 (LE)
      0x08 u64 (LE)
      0x09 vint      zig-zag-style: bit0 of the first byte is the sign,
                     6 value bits, then 7-bit continuation groups
    """

    def __init__(self, data: bytes):
        self._d = data
        self._o = 0

    def _byte(self) -> int:
        if self._o >= len(self._d):
            raise CorruptReplayError("unexpected end of header blob")
        b = self._d[self._o]
        self._o += 1
        return b

    def _bytes(self, n: int) -> bytes:
        if self._o + n > len(self._d):
            raise CorruptReplayError("unexpected end of header blob")
        out = self._d[self._o : self._o + n]
        self._o += n
        return out

    def vint(self) -> int:
        b = self._byte()
        negative = b & 1
        result = (b >> 1) & 0x3F
        shift = 6
        while b & 0x80:
            b = self._byte()
            result |= (b & 0x7F) << shift
            shift += 7
        return -result if negative else result

    def value(self) -> Any:
        tag = self._byte()
        if tag == 0x00:  # array
            n = self.vint()
            return [self.value() for _ in range(n)]
        if tag == 0x01:  # bitblob
            bits = self.vint()
            return self._bytes((bits + 7) // 8)
        if tag == 0x02:  # blob
            return self._bytes(self.vint())
        if tag == 0x03:  # choice
            alt = self.vint()
            return {alt: self.value()}
        if tag == 0x04:  # optional
            return self.value() if self._byte() else None
        if tag == 0x05:  # struct
            n = self.vint()
            out: Dict[int, Any] = {}
            for _ in range(n):
                field = self.vint()  # field id must be read BEFORE the value
                out[field] = self.value()
            return out
        if tag == 0x06:
            return self._byte()
        if tag == 0x07:
            return int.from_bytes(self._bytes(4), "little")
        if tag == 0x08:
            return int.from_bytes(self._bytes(8), "little")
        if tag == 0x09:
            return self.vint()
        raise CorruptReplayError(f"unknown versioned tag 0x{tag:02x}")


def _user_data(data: bytes) -> bytes:
    """Extract the MPQ user-data payload (the serialized replay header)."""
    if data[:4] != b"MPQ\x1b":
        raise CorruptReplayError(
            "not an SC2 replay (missing MPQ user-data magic)"
        )
    # u32 @4: max user data size; u32 @8: archive header offset;
    # u32 @12: used user data size; payload starts at 16
    used = int.from_bytes(data[12:16], "little")
    if used <= 0 or 16 + used > len(data):
        raise CorruptReplayError("corrupt MPQ user-data header")
    return data[16 : 16 + used]


def parse_replay_header(replay: Union[bytes, str, os.PathLike, BinaryIO]) -> Dict[str, Any]:
    """Parse an .SC2Replay header into plain facts.

    Returns dict with keys: signature (str), version (str "a.b.c"),
    build, base_build, elapsed_game_loops, duration_seconds (at 22.4
    game loops / s, the SC2 "faster" speed the ladder uses).
    """
    if isinstance(replay, (str, os.PathLike)):
        with open(replay, "rb") as f:
            data = f.read(4096)
    elif isinstance(replay, bytes):
        data = replay
    else:
        data = replay.read(4096)
    header = _VersionedReader(_user_data(data)).value()
    if not isinstance(header, dict) or 1 not in header:
        raise CorruptReplayError("replay header missing version struct")
    ver = header[1]
    version = f"{ver.get(1, 0)}.{ver.get(2, 0)}.{ver.get(3, 0)}"
    loops = int(header.get(3, 0))
    return {
        "signature": header.get(0, b"").decode("utf-8", "replace"),
        "version": version,
        "build": int(ver.get(4, 0)),
        "base_build": int(ver.get(5, 0)),
        "elapsed_game_loops": loops,
        "duration_seconds": loops / 22.4,
    }
