"""Version routing + platform run configs for launching SC2.

Role parity with the reference run_configs (reference: distar/pysc2/
run_configs/lib.py:24-240, platforms.py:86-237, __init__.py:28-45) and the
decoder's BUILD2VERSION routing (distar/agent/default/replay_decoder.py:37-41):
resolve a game version string (or a replay's base_build) to the binary +
data-version to launch, find the install (SC2PATH), read map/replay data,
save replays.

VERSIONS is public Blizzard buildinfo
(github.com/Blizzard/s2client-proto/blob/master/buildinfo/versions.json) —
game facts, same data the reference vendors.
"""
from __future__ import annotations

import collections
import datetime
import os
import platform as _platform
import uuid
from typing import Dict, Optional

from . import sc_process

Version = collections.namedtuple(
    "Version", ["game_version", "build_version", "data_version", "binary"]
)


def version_dict(versions) -> Dict[str, Version]:
    return {ver.game_version: ver for ver in versions}


_V = Version
VERSIONS = version_dict([
    _V("3.16.1", 55958, "5BD7C31B44525DAB46E64C4602A81DC2", None),
    _V("3.17.0", 56787, "DFD1F6607F2CF19CB4E1C996B2563D9B", None),
    _V("3.17.1", 56787, "3F2FCED08798D83B873B5543BEFA6C4B", None),
    _V("3.17.2", 56787, "C690FC543082D35EA0AAA876B8362BEA", None),
    _V("3.18.0", 57507, "1659EF34997DA3470FF84A14431E3A86", None),
    _V("3.19.0", 58400, "2B06AEE58017A7DF2A3D452D733F1019", None),
    _V("3.19.1", 58400, "D9B568472880CC4719D1B698C0D86984", None),
    _V("4.0.0", 59587, "9B4FD995C61664831192B7DA46F8C1A1", None),
    _V("4.0.2", 59587, "B43D9EE00A363DAFAD46914E3E4AF362", None),
    _V("4.1.0", 60196, "1B8ACAB0C663D5510941A9871B3E9FBE", None),
    _V("4.1.1", 60321, "5C021D8A549F4A776EE9E9C1748FFBBC", None),
    _V("4.1.2", 60321, "33D9FE28909573253B7FC352CE7AEA40", None),
    _V("4.1.3", 60321, "F486693E00B2CD305B39E0AB254623EB", None),
    _V("4.1.4", 60321, "2E2A3F6E0BAFE5AC659C4D39F13A938C", None),
    _V("4.2.0", 62347, "C0C0E9D37FCDBC437CE386C6BE2D1F93", None),
    _V("4.2.1", 62848, "29BBAC5AFF364B6101B661DB468E3A37", None),
    _V("4.2.2", 63454, "3CB54C86777E78557C984AB1CF3494A0", None),
    _V("4.2.3", 63454, "5E3A8B21E41B987E05EE4917AAD68C69", None),
    _V("4.2.4", 63454, "7C51BC7B0841EACD3535E6FA6FF2116B", None),
    _V("4.3.0", 64469, "C92B3E9683D5A59E08FC011F4BE167FF", None),
    _V("4.3.1", 65094, "E5A21037AA7A25C03AC441515F4E0644", None),
    _V("4.3.2", 65384, "B6D73C85DFB70F5D01DEABB2517BF11C", None),
    _V("4.4.0", 65895, "BF41339C22AE2EDEBEEADC8C75028F7D", None),
    _V("4.4.1", 66668, "C094081D274A39219061182DBFD7840F", None),
    _V("4.5.0", 67188, "2ACF84A7ECBB536F51FC3F734EC3019F", None),
    _V("4.5.1", 67188, "6D239173B8712461E6A7C644A5539369", None),
    _V("4.6.0", 67926, "7DE59231CBF06F1ECE9A25A27964D4AE", None),
    _V("4.6.1", 67926, "BEA99B4A8E7B41E62ADC06D194801BAB", None),
    _V("4.6.2", 69232, "B3E14058F1083913B80C20993AC965DB", None),
    _V("4.7.0", 70154, "8E216E34BC61ABDE16A59A672ACB0F3B", None),
    _V("4.7.1", 70154, "94596A85191583AD2EBFAE28C5D532DB", None),
    _V("4.8.0", 71061, "760581629FC458A1937A05ED8388725B", None),
    _V("4.8.1", 71523, "FCAF3F050B7C0CC7ADCF551B61B9B91E", None),
    _V("4.8.2", 71663, "FE90C92716FC6F8F04B74268EC369FA5", None),
    _V("4.8.3", 72282, "0F14399BBD0BA528355FF4A8211F845B", None),
    _V("4.8.4", 73286, "CD040C0675FD986ED37A4CA3C88C8EB5", None),
    _V("4.8.5", 73559, "B2465E73AED597C74D0844112D582595", None),
    _V("4.8.6", 73620, "AA18FEAD6573C79EF707DF44ABF1BE61", None),
    _V("4.9.0", 74071, "70C74A2DCA8A0D8E7AE8647CAC68ACCA", None),
    _V("4.9.1", 74456, "218CB2271D4E2FA083470D30B1A05F02", None),
    _V("4.9.2", 74741, "614480EF79264B5BD084E57F912172FF", None),
    _V("4.9.3", 75025, "C305368C63621480462F8F516FB64374", None),
    _V("4.10.0", 75689, "B89B5D6FA7CBF6452E721311BFBC6CB2", None),
    _V("4.10.1", 75800, "DDFFF9EC4A171459A4F371C6CC189554", None),
    _V("4.10.2", 76052, "D0F1A68AA88BA90369A84CD1439AA1C3", None),
    _V("4.10.3", 76114, "CDB276D311F707C29BA664B7754A7293", None),
    _V("4.10.4", 76811, "FF9FA4EACEC5F06DEB27BD297D73ED67", None),
    _V("4.11.1", 77379, "F92D1127A291722120AC816F09B2E583", None),
    _V("4.11.2", 77535, "FC43E0897FCC93E4632AC57CBC5A2137", None),
    _V("4.11.3", 77661, "A15B8E4247434B020086354F39856C51", None),
    _V("4.11.4", 78285, "69493AFAB5C7B45DDB2F3442FD60F0CF", None),
    _V("4.12.0", 79998, "B47567DEE5DC23373BFF57194538DFD3", None),
    _V("4.12.1", 80188, "44DED5AED024D23177C742FC227C615A", None),
    _V("5.0.0", 80949, "9AE39C332883B8BF6AA190286183ED72", None),
    _V("5.0.1", 81009, "0D28678BC32E7F67A238F19CD3E0A2CE", None),
    _V("5.0.2", 81102, "DC0A1182FB4ABBE8E29E3EC13CF46F68", None),
    _V("5.0.3", 81433, "5FD8D4B6B52723B44862DF29F232CF31", None),
    _V("5.0.4", 82457, "D2707E265785612D12B381AF6ED9DBF4", None),
    _V("5.0.5", 82893, "D795328C01B8A711947CC62AA9750445", None),
    _V("5.0.6", 83830, "B4745D6A4F982A3143C183D8ACB6C3E3", None),
    _V("5.0.7", 84643, "A389D1F7DF9DD792FBE980533B7119FF", None),
    _V("5.0.8", 86383, "22EAC562CD0C6A31FB2C2C21E3AA3680", None),
    _V("5.0.9", 87702, "F799E093428D419FD634CCE9B925218C", None),
])

# build -> game version for replay routing; later point release wins for
# shared builds. The decoder's explicit pins (reference replay_decoder.py:
# 37-41) are applied on top.
BUILD2VERSION: Dict[int, str] = {}
for _ver in VERSIONS.values():
    BUILD2VERSION[_ver.build_version] = _ver.game_version
BUILD2VERSION.update({80188: "4.12.1", 81009: "5.0.0", 81433: "5.0.3"})


def version_for_build(base_build: int) -> Version:
    """Route a replay's base_build to a launchable Version (the decoder's
    BUILD2VERSION role). Unknown builds fall back to the closest known build
    at or below (the binary dirs are keyed by build)."""
    if base_build in BUILD2VERSION:
        return VERSIONS[BUILD2VERSION[base_build]]
    known = sorted(b for b in BUILD2VERSION)
    best = None
    for b in known:
        if b <= base_build:
            best = b
    if best is None:
        best = known[0]
    return VERSIONS[BUILD2VERSION[best]]


class RunConfig:
    """Base run config: directories + data access (reference lib.py:108-240)."""

    def __init__(self, replay_dir, data_dir, tmp_dir, version, cwd=None, env=None):
        self.replay_dir = replay_dir
        self.data_dir = data_dir
        self.tmp_dir = tmp_dir
        self.cwd = cwd
        self.env = env
        self.version = self._get_version(version)

    # ------------------------------------------------------------------ data
    def map_data(self, map_name: str, players: Optional[int] = None) -> bytes:
        """Map bytes by name or path; tries the (N)name player-count variant."""
        map_names = [map_name]
        if players:
            map_names.append(
                os.path.join(
                    os.path.dirname(map_name),
                    f"({players}){os.path.basename(map_name)}",
                )
            )
        for name in map_names:
            path = os.path.join(self.data_dir, "Maps", name)
            if os.path.exists(path):
                with open(path, "rb") as f:
                    return f.read()
        # not in the install: fall back to the package's bundled ladder maps
        # (distar_tpu/data/maps/...) so offline hosts play without installs;
        # match on normalized basenames (bundle files keep Blizzard's
        # punctuation, e.g. TurboCruise'84LE)
        from . import maps as map_registry

        def norm(s: str) -> str:
            return "".join(c for c in s.lower() if c.isalnum())

        bundle = map_registry.bundled_maps_dir()
        if os.path.isdir(bundle):
            by_norm = {
                norm(f[: -len(".SC2Map")]): f
                for f in os.listdir(bundle)
                if f.endswith(".SC2Map")
            }
            for name in map_names:
                stem = os.path.basename(name)
                if stem.endswith(".SC2Map"):
                    stem = stem[: -len(".SC2Map")]
                hit = by_norm.get(norm(stem))
                if hit:
                    with open(os.path.join(bundle, hit), "rb") as f:
                        return f.read()
        raise ValueError(f"Map '{map_name}' not found.")

    def abs_replay_path(self, replay_path: str) -> str:
        return os.path.join(self.replay_dir, replay_path)

    def replay_data(self, replay_path: str) -> bytes:
        with open(self.abs_replay_path(replay_path), "rb") as f:
            return f.read()

    def replay_paths(self, replay_dir: str):
        replay_dir = self.abs_replay_path(replay_dir)
        if replay_dir.lower().endswith(".sc2replay"):
            yield replay_dir
            return
        for f in os.listdir(replay_dir):
            if f.lower().endswith(".sc2replay"):
                yield os.path.join(replay_dir, f)

    def save_replay(self, replay_data: bytes, replay_dir: str, prefix=None) -> str:
        if not prefix:
            replay_filename = ""
        elif os.path.sep in prefix:
            raise ValueError(f"Prefix '{prefix}' contains '{os.path.sep}', use replay_dir instead.")
        else:
            replay_filename = prefix + "_"
        now = datetime.datetime.utcnow().replace(microsecond=0)
        replay_filename += "%s_%s.SC2Replay" % (
            now.isoformat("-").replace(":", "-"),
            str(uuid.uuid1()),
        )
        replay_dir = self.abs_replay_path(replay_dir)
        os.makedirs(replay_dir, exist_ok=True)
        replay_path = os.path.join(replay_dir, replay_filename)
        with open(replay_path, "wb") as f:
            f.write(replay_data)
        return replay_path

    # --------------------------------------------------------------- version
    def get_versions(self, containing: Optional[str] = None) -> Dict[str, Version]:
        if containing is not None and containing not in VERSIONS:
            raise ValueError(
                f"Unknown game version: {containing}. Known versions: "
                f"{sorted(VERSIONS.keys())}."
            )
        return VERSIONS

    def _get_version(self, game_version) -> Version:
        if isinstance(game_version, Version):
            if not game_version.game_version:
                raise ValueError(
                    f"Version '{game_version!r}' supplied without a game version."
                )
            if game_version.binary and game_version.build_version:
                return game_version
            game_version = game_version.game_version
        if game_version == "latest":
            return self._latest_installed_version()
        if game_version.count(".") == 1:
            game_version += ".0"
        return self.get_versions(containing=game_version)[game_version]

    def _latest_installed_version(self) -> Version:
        """Newest Versions/Base* under the install dir."""
        versions_dir = os.path.join(self.data_dir, "Versions")
        if os.path.isdir(versions_dir):
            builds = sorted(
                int(d[4:])
                for d in os.listdir(versions_dir)
                if d.startswith("Base") and d[4:].isdigit()
            )
            if builds:
                return version_for_build(builds[-1])
        # no install found; newest known (start() will raise a clear error)
        newest = max(VERSIONS.values(), key=lambda v: v.build_version)
        return newest

    def start(self, version=None, **kwargs):
        raise NotImplementedError


class LocalBase(RunConfig):
    """Run config for a public install (reference platforms.py:86-135)."""

    def __init__(self, base_dir, exec_name, version, cwd=None, env=None):
        base_dir = os.path.expanduser(base_dir)
        version = version or os.environ.get("DISTAR_SC2_VERSION") or "latest"
        cwd = cwd and os.path.join(base_dir, cwd)
        super().__init__(
            replay_dir=os.path.join(base_dir, "Replays"),
            data_dir=base_dir, tmp_dir=None, version=version, cwd=cwd, env=env,
        )
        if self.version.build_version < VERSIONS["3.16.1"].build_version:
            raise sc_process.SC2LaunchError(
                "SC2 Binaries older than 3.16.1 don't support the api."
            )
        self._exec_name = exec_name

    def exec_path(self) -> str:
        return os.path.join(
            self.data_dir,
            "Versions/Base%05d" % self.version.build_version,
            self._exec_name,
        )

    def start(self, version=None, want_rgb=False, **kwargs):
        del want_rgb
        if version:
            self.version = self._get_version(version)
        if not os.path.isdir(self.data_dir):
            raise sc_process.SC2LaunchError(
                f"Expected to find StarCraft II installed at '{self.data_dir}'. "
                "If it's not installed, do that and run it once so auto-detection "
                "works; if auto-detection fails, set the SC2PATH environment "
                "variable to the correct location."
            )
        exec_path = self.exec_path()
        if not os.path.exists(exec_path):
            raise sc_process.SC2LaunchError(f"No SC2 binary found at: {exec_path}")
        return sc_process.StarcraftProcess(
            self, exec_path=exec_path, version=self.version, **kwargs
        )


class Linux(LocalBase):
    """Linux install (headless SC2): SC2PATH or ~/StarCraftII."""

    def __init__(self, version=None):
        base_dir = os.environ.get("SC2PATH", "~/StarCraftII")
        env = dict(os.environ)
        # the Linux binary needs its libs (reference platforms.py Linux cfg)
        env["SC2_BASE_DIR"] = os.path.expanduser(base_dir)
        super().__init__(base_dir, "SC2_x64", version=version, cwd="Support64", env=env)


class Windows(LocalBase):
    def __init__(self, version=None):
        base_dir = os.environ.get("SC2PATH", "C:/Program Files (x86)/StarCraft II")
        super().__init__(base_dir, "SC2_x64.exe", version=version, cwd="Support64")


class MacOS(LocalBase):
    def __init__(self, version=None):
        base_dir = os.environ.get("SC2PATH", "/Applications/StarCraft II")
        super().__init__(base_dir, "SC2.app/Contents/MacOS/SC2", version=version)


def get(version=None) -> RunConfig:
    """Platform-routed run config (reference run_configs/__init__.py:28-45)."""
    system = _platform.system()
    if system == "Linux":
        return Linux(version=version)
    if system == "Windows":
        return Windows(version=version)
    if system == "Darwin":
        return MacOS(version=version)
    raise ValueError(f"Unsupported platform: {system}")
