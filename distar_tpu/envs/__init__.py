from .dummy_obs import build_dummy_game_info, build_dummy_obs
from .env import BaseEnv
from .features import ProtoFeatures, compute_battle_score, unpack_feature_layer
from .mock_env import MockEnv
from .sc2_env import FakeController, SC2Env

# jaxenv (the pure-JAX micro-battle world) is imported lazily by its users
# (bin/rl_train, serve/fleet, tests) — an eager import here would pull jax
# into every envs consumer including game-client-only paths.

__all__ = [
    "BaseEnv",
    "MockEnv",
    "SC2Env",
    "FakeController",
    "ProtoFeatures",
    "compute_battle_score",
    "unpack_feature_layer",
    "build_dummy_game_info",
    "build_dummy_obs",
]
