from .env import BaseEnv
from .mock_env import MockEnv

__all__ = ["BaseEnv", "MockEnv"]
