from .dummy_obs import build_dummy_game_info, build_dummy_obs
from .env import BaseEnv
from .features import ProtoFeatures, compute_battle_score, unpack_feature_layer
from .mock_env import MockEnv
from .sc2_env import FakeController, SC2Env

__all__ = [
    "BaseEnv",
    "MockEnv",
    "SC2Env",
    "FakeController",
    "ProtoFeatures",
    "compute_battle_score",
    "unpack_feature_layer",
    "build_dummy_game_info",
    "build_dummy_obs",
]
