"""Synthetic raw-observation builders (duck-typed protos).

Role parity with the reference's proto fixtures (reference: distar/pysc2/
tests/dummy_observation.py:15-50 — "build a dummy ResponseObservation ...
passed to features.transform_obs"): SimpleNamespace trees with the same
attribute surface as s2clientprotocol messages, so ProtoFeatures runs and is
tested without the game or even the proto package.
"""
from __future__ import annotations

from types import SimpleNamespace as NS
from typing import List, Optional, Sequence

import numpy as np


def pos(x, y):
    return NS(x=x, y=y)


def make_unit(
    tag: int,
    unit_type: int,
    alliance: int = 1,
    x: float = 10.0,
    y: float = 20.0,
    health: float = 50.0,
    health_max: float = 100.0,
    orders: Sequence[int] = (),
    buff_ids: Sequence[int] = (),
    passengers: Sequence = (),
    **kwargs,
):
    defaults = dict(
        cargo_space_taken=0, build_progress=1.0, shield_max=0.0, energy_max=0.0,
        display_type=1, owner=1 if alliance == 1 else 2, cloak=3, is_blip=False,
        is_powered=True, mineral_contents=0, vespene_contents=0, cargo_space_max=0,
        assigned_harvesters=0, weapon_cooldown=0, is_hallucination=False,
        add_on_tag=0, is_active=True, attack_upgrade_level=0, armor_upgrade_level=0,
        shield_upgrade_level=0, shield=0.0, energy=0.0,
    )
    defaults.update(kwargs)
    return NS(
        tag=tag, unit_type=unit_type, alliance=alliance, pos=pos(x, y),
        health=health, health_max=health_max,
        orders=[NS(ability_id=a, progress=0.5) for a in orders],
        buff_ids=list(buff_ids), passengers=list(passengers), **defaults,
    )


def make_passenger(tag: int, unit_type: int, health: float = 30.0):
    return NS(tag=tag, unit_type=unit_type, health=health, health_max=50.0,
              shield=0.0, shield_max=0.0, energy=0.0, energy_max=0.0)


def _packed_plane(arr: np.ndarray, bits: int):
    if bits == 1:
        data = np.packbits(arr.astype(bool).reshape(-1)).tobytes()
    else:
        data = arr.astype({8: np.uint8, 16: np.uint16, 32: np.int32}[bits]).tobytes()
    return NS(size=NS(y=arr.shape[0], x=arr.shape[1]), bits_per_pixel=bits, data=data)


def make_minimap(map_y: int = 120, map_x: int = 120, rng: Optional[np.random.Generator] = None):
    rng = rng or np.random.default_rng(0)
    layers = {
        "height_map": _packed_plane(rng.integers(0, 255, (map_y, map_x)), 8),
        "visibility_map": _packed_plane(rng.integers(0, 4, (map_y, map_x)), 8),
        "creep": _packed_plane(rng.integers(0, 2, (map_y, map_x)), 1),
        "player_relative": _packed_plane(rng.integers(0, 5, (map_y, map_x)), 8),
        "alerts": _packed_plane(rng.integers(0, 2, (map_y, map_x)), 1),
        "pathable": _packed_plane(rng.integers(0, 2, (map_y, map_x)), 1),
        "buildable": _packed_plane(rng.integers(0, 2, (map_y, map_x)), 1),
    }
    return NS(**layers)


def build_dummy_obs(
    units: Optional[List] = None,
    game_loop: int = 100,
    player_id: int = 1,
    upgrade_ids: Sequence[int] = (),
    effects: Sequence = (),
    map_y: int = 120,
    map_x: int = 120,
    minerals: int = 500,
    killed_minerals: float = 0.0,
    killed_vespene: float = 0.0,
    action_results: Sequence[int] = (1,),
    rng: Optional[np.random.Generator] = None,
):
    cat = NS(none=0.0, army=killed_minerals, economy=0.0, technology=0.0, upgrade=0.0)
    catv = NS(none=0.0, army=killed_vespene, economy=0.0, technology=0.0, upgrade=0.0)
    return NS(
        observation=NS(
            game_loop=game_loop,
            raw_data=NS(
                units=units or [],
                effects=list(effects),
                player=NS(upgrade_ids=list(upgrade_ids)),
            ),
            player_common=NS(
                player_id=player_id, minerals=minerals, vespene=100, food_used=20,
                food_cap=30, food_army=10, food_workers=10, idle_worker_count=1,
                army_count=5, warp_gate_count=0, larva_count=3,
            ),
            feature_layer_data=NS(minimap_renders=make_minimap(map_y, map_x, rng)),
            score=NS(score_details=NS(killed_minerals=cat, killed_vespene=catv)),
        ),
        action_errors=[NS(result=r) for r in action_results],
    )


def build_dummy_game_info(map_y: int = 120, map_x: int = 120, map_name: str = "DummyMap"):
    return NS(
        start_raw=NS(map_size=NS(x=map_x, y=map_y), start_locations=[pos(20, 30)]),
        map_name=map_name,
        player_info=[
            NS(player_id=1, race_requested=2, type=1),
            NS(player_id=2, race_requested=2, type=1),
        ],
    )


def make_effect(effect_id: int, positions: Sequence, owner: int = 2):
    return NS(effect_id=effect_id, owner=owner, pos=[pos(x, y) for x, y in positions])


def make_raw_action(ability_id: int, unit_tags: Sequence[int] = (),
                    target_unit_tag: Optional[int] = None,
                    target_pos=None, queue_command: bool = False):
    uc = NS(ability_id=ability_id, unit_tags=list(unit_tags), queue_command=queue_command)
    if target_unit_tag is not None:
        uc.target_unit_tag = target_unit_tag
    if target_pos is not None:
        uc.target_world_space_pos = pos(*target_pos)
    return NS(unit_command=uc)
