"""Synthetic raw-observation builders (duck-typed protos).

Role parity with the reference's proto fixtures (reference: distar/pysc2/
tests/dummy_observation.py:15-50 — "build a dummy ResponseObservation ...
passed to features.transform_obs"): SimpleNamespace trees with the same
attribute surface as s2clientprotocol messages, so ProtoFeatures runs and is
tested without the game or even the proto package.
"""
from __future__ import annotations

from types import SimpleNamespace as NS
from typing import List, Optional, Sequence

import numpy as np


def pos(x, y):
    return NS(x=x, y=y)


def make_unit(
    tag: int,
    unit_type: int,
    alliance: int = 1,
    x: float = 10.0,
    y: float = 20.0,
    health: float = 50.0,
    health_max: float = 100.0,
    orders: Sequence[int] = (),
    buff_ids: Sequence[int] = (),
    passengers: Sequence = (),
    **kwargs,
):
    defaults = dict(
        cargo_space_taken=0, build_progress=1.0, shield_max=0.0, energy_max=0.0,
        display_type=1, owner=1 if alliance == 1 else 2, cloak=3, is_blip=False,
        is_powered=True, mineral_contents=0, vespene_contents=0, cargo_space_max=0,
        assigned_harvesters=0, weapon_cooldown=0, is_hallucination=False,
        add_on_tag=0, is_active=True, attack_upgrade_level=0, armor_upgrade_level=0,
        shield_upgrade_level=0, shield=0.0, energy=0.0,
    )
    defaults.update(kwargs)
    return NS(
        tag=tag, unit_type=unit_type, alliance=alliance, pos=pos(x, y),
        health=health, health_max=health_max,
        orders=[NS(ability_id=a, progress=0.5) for a in orders],
        buff_ids=list(buff_ids), passengers=list(passengers), **defaults,
    )


def make_passenger(tag: int, unit_type: int, health: float = 30.0):
    return NS(tag=tag, unit_type=unit_type, health=health, health_max=50.0,
              shield=0.0, shield_max=0.0, energy=0.0, energy_max=0.0)


def _packed_plane(arr: np.ndarray, bits: int):
    if bits == 1:
        data = np.packbits(arr.astype(bool).reshape(-1)).tobytes()
    else:
        data = arr.astype({8: np.uint8, 16: np.uint16, 32: np.int32}[bits]).tobytes()
    return NS(size=NS(y=arr.shape[0], x=arr.shape[1]), bits_per_pixel=bits, data=data)


def make_minimap(map_y: int = 120, map_x: int = 120, rng: Optional[np.random.Generator] = None):
    rng = rng or np.random.default_rng(0)
    layers = {
        "height_map": _packed_plane(rng.integers(0, 255, (map_y, map_x)), 8),
        "visibility_map": _packed_plane(rng.integers(0, 4, (map_y, map_x)), 8),
        "creep": _packed_plane(rng.integers(0, 2, (map_y, map_x)), 1),
        "player_relative": _packed_plane(rng.integers(0, 5, (map_y, map_x)), 8),
        "alerts": _packed_plane(rng.integers(0, 2, (map_y, map_x)), 1),
        "pathable": _packed_plane(rng.integers(0, 2, (map_y, map_x)), 1),
        "buildable": _packed_plane(rng.integers(0, 2, (map_y, map_x)), 1),
    }
    return NS(**layers)


def build_dummy_obs(
    units: Optional[List] = None,
    game_loop: int = 100,
    player_id: int = 1,
    upgrade_ids: Sequence[int] = (),
    effects: Sequence = (),
    map_y: int = 120,
    map_x: int = 120,
    minerals: int = 500,
    killed_minerals: float = 0.0,
    killed_vespene: float = 0.0,
    action_results: Sequence[int] = (1,),
    rng: Optional[np.random.Generator] = None,
):
    cat = NS(none=0.0, army=killed_minerals, economy=0.0, technology=0.0, upgrade=0.0)
    catv = NS(none=0.0, army=killed_vespene, economy=0.0, technology=0.0, upgrade=0.0)
    return NS(
        observation=NS(
            game_loop=game_loop,
            raw_data=NS(
                units=units or [],
                effects=list(effects),
                player=NS(upgrade_ids=list(upgrade_ids)),
            ),
            player_common=NS(
                player_id=player_id, minerals=minerals, vespene=100, food_used=20,
                food_cap=30, food_army=10, food_workers=10, idle_worker_count=1,
                army_count=5, warp_gate_count=0, larva_count=3,
            ),
            feature_layer_data=NS(minimap_renders=make_minimap(map_y, map_x, rng)),
            score=NS(score_details=NS(killed_minerals=cat, killed_vespene=catv)),
        ),
        action_errors=[NS(result=r) for r in action_results],
    )


def build_dummy_game_info(map_y: int = 120, map_x: int = 120, map_name: str = "DummyMap"):
    return NS(
        start_raw=NS(map_size=NS(x=map_x, y=map_y), start_locations=[pos(20, 30)]),
        map_name=map_name,
        player_info=[
            NS(player_id=1, race_requested=2, type=1),
            NS(player_id=2, race_requested=2, type=1),
        ],
    )


def make_effect(effect_id: int, positions: Sequence, owner: int = 2):
    return NS(effect_id=effect_id, owner=owner, pos=[pos(x, y) for x, y in positions])


def make_raw_action(ability_id: int, unit_tags: Sequence[int] = (),
                    target_unit_tag: Optional[int] = None,
                    target_pos=None, queue_command: bool = False):
    uc = NS(ability_id=ability_id, unit_tags=list(unit_tags), queue_command=queue_command)
    if target_unit_tag is not None:
        uc.target_unit_tag = target_unit_tag
    if target_pos is not None:
        uc.target_world_space_pos = pos(*target_pos)
    return NS(unit_command=uc)


def make_autocast_action(ability_id: int, unit_tags: Sequence[int] = ()):
    return NS(toggle_autocast=NS(ability_id=ability_id, unit_tags=list(unit_tags)))


def build_parity_fixtures():
    """Deterministic proto fixtures shared by the obs-transform golden
    parity harness: tools/record_reference_obs_golden.py replays them
    through the REFERENCE Features.transform_obs / reverse_raw_action
    (reference features.py:463,854) on torch, tests/test_obs_golden_parity.py
    replays them through envs/features.ProtoFeatures — both sides see
    byte-identical inputs, so every output field is a cross-check.

    All ids are drawn from the extracted game-contract tables so every LUT
    lookup is in-vocabulary on both sides (out-of-vocabulary handling
    deliberately differs: the reference keeps -1 sentinels, we clamp to the
    no-op — envs/features.py _lut).
    """
    from ..lib import actions as ACT

    def valid(lut, n, skip=0):
        idxs = np.nonzero(np.asarray(lut) > 0)[0][skip:skip + n]
        assert len(idxs) == n, "contract table too small for fixtures"
        return [int(i) for i in idxs]

    unit_ab = valid(ACT.UNIT_ABILITY_REORDER, 2, skip=4)
    queue_ab = valid(ACT.ABILITY_TO_QUEUE_ACTION, 3)
    buff_ids = valid(ACT.BUFFS_REORDER_ARRAY, 2)
    addon_type = valid(ACT.ADDON_REORDER_ARRAY, 1, skip=2)[0]
    upgrade_ids = valid(ACT.UPGRADES_REORDER_ARRAY, 2, skip=3)

    def pick_ability(kind):
        """Smallest concrete ability whose canonical (gability, kind) decodes
        to an action with a selection (and a queued head, so the queued
        value round-trips on both sides)."""
        for a, g in sorted(ACT.ABILITY_TO_GABILITY.items()):
            idx = ACT.GAB_KIND_TO_ACTION.get((g, kind))
            if idx is None:
                continue
            spec = ACT.ACTIONS[idx]
            if spec["selected_units"] and (kind == "autocast" or spec["queued"]):
                return a
        raise AssertionError(f"no fixture ability for kind {kind}")

    quick_ab = pick_ability("quick")
    pt_ab = pick_ability("pt")
    unit_ab_cmd = pick_ability("unit")
    autocast_ab = pick_ability("autocast")

    map_y, map_x = 120, 112  # non-square: catches x/y transpositions
    game_info = NS(
        start_raw=NS(
            map_size=NS(x=map_x, y=map_y),
            start_locations=[pos(90.5, 100.5)],
        ),
        map_name="ParityMap",
        player_info=[
            NS(player_id=1, race_requested=2, type=1),
            NS(player_id=2, race_requested=3, type=1),
        ],
    )
    # exactly ONE base structure: the reference derives the born location
    # from it and asserts uniqueness (reference features.py:384-393)
    hatch = make_unit(101, 86, x=30.5, y=40.5, health=1450.0, health_max=1500.0,
                      energy=25.0, energy_max=200.0)
    first_obs = build_dummy_obs(
        units=[hatch], game_loop=0, map_y=map_y, map_x=map_x,
        rng=np.random.default_rng(11),
    )

    units = [
        hatch,
        make_unit(102, 104, x=31.2, y=44.9, health=40.0, health_max=40.0,
                  orders=[unit_ab[0]], weapon_cooldown=0.5,
                  assigned_harvesters=2),
        make_unit(103, 126, x=35.0, y=41.0, health=150.0, health_max=175.0,
                  energy=30.0, energy_max=200.0,
                  orders=[unit_ab[1], queue_ab[0], queue_ab[1], queue_ab[2]],
                  buff_ids=buff_ids),
        make_unit(104, 106, x=50.7, y=60.1, health=180.0, health_max=200.0,
                  cargo_space_max=8, cargo_space_taken=2,
                  passengers=[make_passenger(201, 105), make_passenger(202, 105)]),
        make_unit(105, 105, x=36.0, y=42.0, build_progress=0.55, health=20.0,
                  health_max=35.0),
        make_unit(106, 48, alliance=4, x=80.4, y=90.8, health=35.0,
                  health_max=45.0, display_type=2, owner=2,
                  attack_upgrade_level=1),
        make_unit(107, 74, alliance=4, x=82.0, y=95.0, health=80.0,
                  health_max=80.0, shield=60.0, shield_max=80.0, owner=2,
                  cloak=1, is_hallucination=True),
        make_unit(108, 21, alliance=4, x=100.0, y=30.0, health=900.0,
                  health_max=1000.0, owner=2, add_on_tag=109),
        make_unit(109, addon_type, alliance=4, x=102.0, y=30.0, health=400.0,
                  health_max=400.0, owner=2),
        make_unit(110, 341, alliance=3, x=25.0, y=35.0, health=0.0,
                  health_max=0.0, owner=16, mineral_contents=900,
                  is_active=False),
    ]
    effects = [
        make_effect(1, [(40.0, 50.0), (41.0, 50.0)], owner=2),   # PsiStorm
        make_effect(9, [(60.0, 70.0)], owner=1),                 # skipped: own Liberator zone
        make_effect(9, [(61.0, 71.0)], owner=2),                 # kept
        make_effect(12, [(62.0, 72.0)], owner=1),                # skipped: own LurkerSpines
    ]
    obs = build_dummy_obs(
        units=units, game_loop=4521, upgrade_ids=upgrade_ids, effects=effects,
        map_y=map_y, map_x=map_x, minerals=754, killed_minerals=600.0,
        killed_vespene=200.0, action_results=(2, 3),
        rng=np.random.default_rng(12),
    )
    opp_units = [
        make_unit(301, 59, x=90.5, y=100.5, health=1300.0, health_max=1500.0),
        make_unit(302, 48, x=91.0, y=99.0, health=45.0, health_max=45.0),
        make_unit(303, 48, x=92.5, y=98.0, health=30.0, health_max=45.0),
        make_unit(304, 105, alliance=4, x=30.0, y=40.0, health=35.0,
                  health_max=35.0, owner=1),  # OUR unit seen by the opponent
    ]
    opponent_obs = build_dummy_obs(
        units=opp_units, game_loop=4521, upgrade_ids=upgrade_ids[:1],
        map_y=map_y, map_x=map_x, minerals=310, killed_minerals=150.0,
        killed_vespene=75.0, player_id=2, rng=np.random.default_rng(13),
    )

    actions = [
        ("quick", make_raw_action(quick_ab, [102], queue_command=True)),
        ("pt", make_raw_action(pt_ab, [102, 103], target_pos=(37.6, 55.2))),
        ("unit", make_raw_action(unit_ab_cmd, [103, 105], target_unit_tag=106)),
        ("bad_target", make_raw_action(unit_ab_cmd, [103], target_unit_tag=999999)),
        ("cancel_slot", make_raw_action(305, [101])),
        ("unload", make_raw_action(410, [104])),
        ("frivolous", make_raw_action(6, [102])),
        ("autocast", make_autocast_action(autocast_ab, [103])),
        ("no_units", make_raw_action(quick_ab, [])),
    ]
    return {
        "game_info": game_info,
        "first_obs": first_obs,
        "obs": obs,
        "opponent_obs": opponent_obs,
        "actions": actions,
        "z_stream": build_z_stream(),
    }


def build_z_stream():
    """Decoded-action stream for the Z-extraction parity check (reference
    get_z, features.py:419-460 vs envs/features.extract_z): exercises the
    zergling-spam cap, the spine-crawler proximity filter (one build near
    our born location — dropped — and one near the enemy's — kept),
    cumulative-stat marking, the BO-only CUM_EXCLUDE family (build order
    advances, no cum bit), and BO-length truncation. Locations are flat
    spatial indices (y*160+x)."""
    from ..lib import actions as ACT

    def flat(x, y):
        return y * 160 + x

    zergling = 322  # Train_Zergling_quick on both tables
    spine = 54      # Build_SpineCrawler_pt
    assert zergling in ACT.BEGINNING_ORDER_ACTIONS
    assert spine in ACT.BEGINNING_ORDER_ACTIONS
    bo = [a for a in ACT.BEGINNING_ORDER_ACTIONS[1:]
          if a not in (zergling, spine)]
    # the cumulative set is a strict subset of the BO set (lib/actions.py
    # derivation), so the disjoint case to pin is BO-but-NOT-cum: static
    # defense & co. must enter the build order without setting a cum bit
    bo_not_cum = [a for a in ACT.BEGINNING_ORDER_ACTIONS[1:]
                  if a not in ACT.CUMULATIVE_STAT_ACTIONS and a != spine]
    assert bo_not_cum, "contract tables lost the CUM_EXCLUDE family"

    stream = []

    def add(action_type, location=0):
        stream.append({"action_info": {
            "action_type": action_type, "target_location": location,
        }})

    # ordinary build-order prefix
    for i, a in enumerate(bo[:6]):
        add(a, flat(30 + i, 40))
    # zergling spam: 12 trains, cap keeps 8 in the BO
    for i in range(12):
        add(zergling, flat(50, 60))
    # spine near OUR base (born location ~ (30, 79-ish)): filtered out
    add(spine, flat(31, 80))
    # spine near the ENEMY's start: kept
    add(spine, flat(90, 19))
    # BO-only actions (CUM_EXCLUDE family): BO slot advances, no cum bit
    for a in bo_not_cum[:3]:
        add(a, flat(70, 70))
    # overflow the 20-slot BO window
    for i, a in enumerate(bo[6:24]):
        add(a, flat(10 + i, 12))
    return stream
