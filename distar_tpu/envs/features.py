"""Proto-facing feature transforms: raw SC2 observations -> the fixed-shape
feature contract, and agent actions <-> raw game actions.

Role parity with the reference Features (reference: distar/agent/default/lib/
features.py:165-951): minimap feature-layer bit-unpacking (:282-304), per-unit
38-field rows incl. cargo passengers (:504-589), id-space remaps via the
reorder LUTs (:594-614), ratio/log normalisations (:619-648), bag-of-words
vectors (:664-676), the y-axis flip (:630), opponent-derived value features
(:691-765), transform_action (:770+) and reverse_raw_action (:854-951), and
compute_battle_score (:352-361).

Everything is duck-typed against s2clientprotocol attribute access (protobuf
objects and SimpleNamespace fixtures both satisfy it), so the transform logic
is fully testable without the game: `dummy_obs.build_dummy_obs` plays the
role of the reference's dummy_observation proto builders
(pysc2/tests/dummy_observation.py).

TPU-first divergence: entity arrays leave here already padded to
MAX_ENTITY_NUM (the reference pads per-batch in its dataloader) so every
consumer sees one static shape.
"""
from __future__ import annotations

import enum
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..lib import actions as ACT
from ..lib import features as F


class Effects(enum.IntEnum):
    none = 0
    PsiStorm = 1
    GuardianShield = 2
    TemporalFieldGrowing = 3
    TemporalField = 4
    ThermalLance = 5
    ScannerSweep = 6
    NukeDot = 7
    LiberatorDefenderZoneSetup = 8
    LiberatorDefenderZone = 9
    BlindingCloud = 10
    CorrosiveBile = 11
    LurkerSpines = 12


SCORE_CATEGORIES = ("none", "army", "economy", "technology", "upgrade")

MINIMAP_LAYERS = (
    "height_map", "visibility_map", "creep", "player_relative", "alerts",
    "pathable", "buildable",
)

_BIT_DTYPES = {1: np.uint8, 8: np.uint8, 16: np.uint16, 32: np.int32}


def unpack_feature_layer(plane) -> Optional[np.ndarray]:
    """Decode one bit-packed feature-layer image (reference :290-304)."""
    sy, sx = int(plane.size.y), int(plane.size.x)
    if (sy, sx) == (0, 0):
        return None
    data = np.frombuffer(plane.data, dtype=_BIT_DTYPES[plane.bits_per_pixel])
    if plane.bits_per_pixel == 1:
        data = np.unpackbits(data)
        if data.shape[0] != sx * sy:
            data = data[: sx * sy]
    return data.reshape(sy, sx)


def compute_battle_score(obs) -> float:
    """killed minerals + 1.5 * killed vespene, summed over score categories."""
    if obs is None:
        return 0.0
    details = obs.observation.score.score_details
    killed_mineral = sum(getattr(details.killed_minerals, s) for s in SCORE_CATEGORIES)
    killed_vespene = sum(getattr(details.killed_vespene, s) for s in SCORE_CATEGORIES)
    return float(killed_mineral + 1.5 * killed_vespene)


def _pad_to(arr: np.ndarray, n: int, value=0) -> np.ndarray:
    if arr.shape[0] >= n:
        return arr[:n]
    pad = [(0, n - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad, constant_values=value)


def _lut(lut: np.ndarray, ids) -> np.ndarray:
    """Reorder LUT lookup with out-of-range ids mapped to 0 (the reference
    prints an error for -1 entries and the encoders clamp; 0 is the no-op)."""
    ids = np.asarray(ids, dtype=np.int64)
    clipped = np.clip(ids, 0, len(lut) - 1)
    out = lut[clipped]
    return np.where((ids >= 0) & (ids < len(lut)) & (out >= 0), out, 0)


def extract_z(
    action_infos: Sequence[Dict],
    home_born_location: Optional[int] = None,
    away_born_location: Optional[int] = None,
    filter_spine: bool = True,
    bo_zergling_num: int = 8,
):
    """Strategy-statistics ("Z") extraction from a decoded action stream
    (reference get_z, features.py:419-460): beginning-order indices +
    locations (zergling spam capped at ``bo_zergling_num``, spine crawlers
    nearer our base than the enemy's dropped) and the dense cumulative-stat
    vector.

    Returns (beginning_order[20], cumulative_stat[dense], bo_len,
    bo_location[20]).
    """
    sx = F.SPATIAL_SIZE[1]
    own = (home_born_location % sx, home_born_location // sx) if home_born_location is not None else None
    away = (away_born_location % sx, away_born_location // sx) if away_born_location is not None else None

    zergling_count = 0
    beginning_order: List[int] = []
    bo_location: List[int] = []
    cumulative_stat = np.zeros(ACT.NUM_CUMULATIVE_STAT_ACTIONS, np.int8)
    for step in action_infos:
        action_type = int(np.asarray(step["action_info"]["action_type"]).reshape(-1)[0])
        if action_type == 322:  # Train_Zergling_quick
            zergling_count += 1
            if zergling_count > bo_zergling_num:
                continue
        if action_type in ACT.BEGINNING_ORDER_ACTIONS:
            location = int(np.asarray(step["action_info"]["target_location"]).reshape(-1)[0])
            if filter_spine and action_type == 54 and own and away:  # Build_SpineCrawler_pt
                x, y = location % sx, location // sx
                own_d = (own[0] - x) ** 2 + (own[1] - y) ** 2
                away_d = (away[0] - x) ** 2 + (away[1] - y) ** 2
                if own_d < away_d:
                    continue
            beginning_order.append(ACT.BEGINNING_ORDER_ACTIONS.index(action_type))
            bo_location.append(location)
        if action_type in ACT.CUMULATIVE_STAT_ACTIONS:
            cumulative_stat[ACT.CUMULATIVE_STAT_ACTIONS.index(action_type)] = 1

    bo_len = len(beginning_order)
    L = F.BEGINNING_ORDER_LENGTH
    beginning_order = (beginning_order + [0] * L)[:L]
    bo_location = (bo_location + [0] * L)[:L]
    return (
        np.asarray(beginning_order, np.int16),
        cumulative_stat,
        bo_len,
        np.asarray(bo_location, np.int16),
    )


class ProtoFeatures:
    """Per-game feature transformer bound to game_info (map size, races)."""

    def __init__(self, game_info, cfg: Optional[dict] = None):
        self.map_size = game_info.start_raw.map_size  # .x, .y
        self.map_name = getattr(game_info, "map_name", "unknown")
        self.start_locations = [
            (float(p.x), float(p.y))
            for p in getattr(game_info.start_raw, "start_locations", [])
        ]
        # 3 = observer type in sc_pb; duck-typed: anything with player_id +
        # race_requested and type != observer
        self.requested_races = {
            info.player_id: info.race_requested
            for info in game_info.player_info
            if getattr(info, "type", 1) != 3
        }

    def flat_location(self, x: float, y: float) -> int:
        """World (x, y) -> flat spatial index after the y flip."""
        xi = min(int(x), int(self.map_size.x) - 1)
        yi = min(int(self.map_size.y - y), int(self.map_size.y) - 1)
        return max(yi, 0) * F.SPATIAL_SIZE[1] + max(xi, 0)

    def born_locations(self, first_obs) -> (int, int):
        """(home, away) flat born locations from the initial observation:
        home = our first base structure, away = the farthest start location
        (reference Features keeps home/away_born_location for the Z spine
        filter, features.py:431-446)."""
        home_xy = None
        for u in first_obs.observation.raw_data.units:
            if u.alliance == 1 and u.unit_type in (59, 18, 86):  # nexus/cc/hatchery
                home_xy = (u.pos.x, u.pos.y)
                break
        if home_xy is None:
            return 0, 0
        away_xy = None
        best = -1.0
        for sx, sy in self.start_locations:
            d = (sx - home_xy[0]) ** 2 + (sy - home_xy[1]) ** 2
            if d > best:
                best, away_xy = d, (sx, sy)
        home = self.flat_location(*home_xy)
        away = self.flat_location(*away_xy) if away_xy else home
        return home, away

    # ------------------------------------------------------------------ obs
    def transform_obs(self, obs, padding_spatial: bool = True, opponent_obs=None) -> Dict:
        raw = obs.observation.raw_data
        spatial_info: Dict[str, np.ndarray] = {}

        # minimap planes, padded bottom/right to the fixed contract size
        for name in MINIMAP_LAYERS:
            plane = getattr(obs.observation.feature_layer_data.minimap_renders, name)
            d = unpack_feature_layer(plane)
            if d is None:
                d = np.zeros(F.SPATIAL_SIZE, np.uint8)
            if padding_spatial:
                d = np.pad(
                    d,
                    ((0, F.SPATIAL_SIZE[0] - d.shape[0]), (0, F.SPATIAL_SIZE[1] - d.shape[1])),
                )
            spatial_info[name] = d.astype(F.SPATIAL_INFO[name])

        # effect coordinate lists (flat indices, y flipped); enemy-owned
        # Liberator zones / lurker spines only (reference :479-485)
        effect_lists: Dict[str, List[int]] = {
            k: [] for k in F.SPATIAL_INFO if k.startswith("effect_")
        }
        for e in raw.effects:
            name = Effects(e.effect_id).name
            key = f"effect_{name}"
            if key not in effect_lists:
                continue
            if name in ("LiberatorDefenderZone", "LurkerSpines") and e.owner == 1:
                continue
            for p in e.pos:
                loc = int(p.x) + int(self.map_size.y - p.y) * F.SPATIAL_SIZE[1]
                effect_lists[key].append(loc)
        for k, lst in effect_lists.items():
            spatial_info[k] = _pad_to(
                np.asarray(lst[: F.EFFECT_LENGTH], np.int16), F.EFFECT_LENGTH
            )

        # ------------------------------------------------------------ units
        tag_types = {u.tag: u.unit_type for u in raw.units}
        tags: List[int] = []
        rows: List[List[float]] = []
        for u in raw.units:
            orders = list(u.orders)
            tags.append(u.tag)
            rows.append([
                u.unit_type, u.alliance, u.cargo_space_taken, u.build_progress,
                u.health_max, u.shield_max, u.energy_max, u.display_type, u.owner,
                u.pos.x, u.pos.y, u.cloak, u.is_blip, u.is_powered,
                u.mineral_contents, u.vespene_contents, u.cargo_space_max,
                u.assigned_harvesters, u.weapon_cooldown, len(orders),
                orders[0].ability_id if len(orders) > 0 else 0,
                orders[1].ability_id if len(orders) > 1 else 0,
                u.is_hallucination,
                u.buff_ids[0] if len(u.buff_ids) >= 1 else 0,
                u.buff_ids[1] if len(u.buff_ids) >= 2 else 0,
                tag_types.get(u.add_on_tag, 0) if u.add_on_tag else 0,
                u.is_active,
                orders[0].progress if len(orders) >= 1 else 0,
                orders[1].progress if len(orders) >= 2 else 0,
                orders[2].ability_id if len(orders) > 2 else 0,
                orders[3].ability_id if len(orders) > 3 else 0,
                0,  # is_in_cargo
                u.attack_upgrade_level, u.armor_upgrade_level, u.shield_upgrade_level,
                u.health, u.shield, u.energy,
            ])
            # cargo passengers become pseudo-entities at the carrier's position
            for v in u.passengers:
                tags.append(v.tag)
                rows.append([
                    v.unit_type, u.alliance, 0, 0, v.health_max, v.shield_max,
                    v.energy_max, 0, u.owner, u.pos.x, u.pos.y,
                    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
                    1,  # is_in_cargo
                    0, 0, 0, v.health, v.shield, v.energy,
                ])
        rows = rows[: F.MAX_ENTITY_NUM]
        tags = tags[: F.MAX_ENTITY_NUM]
        entity_num = len(rows)
        r = np.asarray(rows, np.float32) if rows else np.zeros((0, 38), np.float32)

        col = {
            name: i
            for i, name in enumerate([
                "unit_type", "alliance", "cargo_space_taken", "build_progress",
                "health_max", "shield_max", "energy_max", "display_type", "owner",
                "x", "y", "cloak", "is_blip", "is_powered", "mineral_contents",
                "vespene_contents", "cargo_space_max", "assigned_harvesters",
                "weapon_cooldown", "order_length", "order_id_0", "order_id_1",
                "is_hallucination", "buff_id_0", "buff_id_1", "addon_unit_type",
                "is_active", "order_progress_0", "order_progress_1", "order_id_2",
                "order_id_3", "is_in_cargo", "attack_upgrade_level",
                "armor_upgrade_level", "shield_upgrade_level", "health", "shield",
                "energy",
            ])
        }

        def c(name):
            return r[:, col[name]] if entity_num else np.zeros((0,), np.float32)

        entity_info: Dict[str, np.ndarray] = {}
        for k, dtype in F.ENTITY_INFO.items():
            if k.startswith("last_"):
                v = np.zeros((entity_num,), np.int64)
            elif k == "unit_type":
                v = _lut(ACT.UNIT_TYPES_REORDER_ARRAY, c(k))
            elif k == "order_id_0":
                v = _lut(ACT.UNIT_ABILITY_REORDER, c(k))
            elif k in ("order_id_1", "order_id_2", "order_id_3"):
                v = _lut(ACT.ABILITY_TO_QUEUE_ACTION, c(k))
            elif k in ("buff_id_0", "buff_id_1"):
                v = _lut(ACT.BUFFS_REORDER_ARRAY, c(k))
            elif k == "addon_unit_type":
                v = _lut(ACT.ADDON_REORDER_ARRAY, c(k))
            elif k in ("cargo_space_taken", "cargo_space_max"):
                v = np.clip(c(k), 0, 8)
            elif k == "health_ratio":
                v = c("health") / (c("health_max") + 1e-6)
            elif k == "shield_ratio":
                v = c("shield") / (c("shield_max") + 1e-6)
            elif k == "energy_ratio":
                v = c("energy") / (c("energy_max") + 1e-6)
            elif k == "mineral_contents":
                v = c(k) / 1800.0
            elif k == "vespene_contents":
                v = c(k) / 2500.0
            elif k == "y":
                v = self.map_size.y - c(k)
            else:
                v = c(k)
            entity_info[k] = _pad_to(np.asarray(v), F.MAX_ENTITY_NUM).astype(dtype)

        # ---------------------------------------------------------- scalars
        player = obs.observation.player_common
        scalar_info: Dict[str, np.ndarray] = {}
        scalar_info["time"] = np.asarray(obs.observation.game_loop, np.float32)
        stats = np.asarray(
            [
                player.minerals, player.vespene, player.food_used, player.food_cap,
                player.food_army, player.food_workers, player.idle_worker_count,
                player.army_count, player.warp_gate_count, player.larva_count,
            ],
            np.float32,
        )
        scalar_info["agent_statistics"] = np.log1p(stats)
        scalar_info["home_race"] = np.asarray(
            self.requested_races[player.player_id], np.uint8
        )
        away = [r_ for pid, r_ in self.requested_races.items() if pid != player.player_id]
        scalar_info["away_race"] = np.asarray(away[0] if away else 0, np.uint8)

        upgrades = np.zeros(ACT.NUM_UPGRADES, np.uint8)
        up_idx = _lut(ACT.UPGRADES_REORDER_ARRAY, list(raw.player.upgrade_ids)[: F.UPGRADE_LENGTH])
        upgrades[up_idx.astype(np.int64)] = 1
        scalar_info["upgrades"] = upgrades

        own = entity_info["alliance"][:entity_num] == 1
        own_types = entity_info["unit_type"][:entity_num][own].astype(np.int64)
        bow = np.zeros(ACT.NUM_UNIT_TYPES, np.int64)
        np.add.at(bow, own_types, 1)
        scalar_info["unit_counts_bow"] = np.clip(bow, 0, 255).astype(np.uint8)
        scalar_info["unit_type_bool"] = (bow > 0).astype(np.uint8)

        order_bool = np.zeros(ACT.NUM_UNIT_MIX_ABILITIES, np.uint8)
        own_orders = entity_info["order_id_0"][:entity_num][own].astype(np.int64)
        order_bool[own_orders] = 1
        scalar_info["unit_order_type"] = order_bool

        enemy = entity_info["alliance"][:entity_num] == 4
        enemy_types = entity_info["unit_type"][:entity_num][enemy].astype(np.int64)
        enemy_bool = np.zeros(ACT.NUM_UNIT_TYPES, np.uint8)
        enemy_bool[enemy_types] = 1
        scalar_info["enemy_unit_type_bool"] = enemy_bool

        # Z-conditioning fields are the AGENT's responsibility (pre_process);
        # zero here to keep the schema complete
        scalar_info["cumulative_stat"] = np.zeros(ACT.NUM_CUMULATIVE_STAT_ACTIONS, np.uint8)
        scalar_info["beginning_order"] = np.zeros(F.BEGINNING_ORDER_LENGTH, np.int16)
        scalar_info["bo_location"] = np.zeros(F.BEGINNING_ORDER_LENGTH, np.int16)
        scalar_info["last_queued"] = np.asarray(0, np.int16)
        scalar_info["last_delay"] = np.asarray(0, np.int16)
        scalar_info["last_action_type"] = np.asarray(0, np.int16)

        action_result = [o.result for o in obs.action_errors] or [1]
        battle_score = compute_battle_score(obs)
        opponent_battle_score = compute_battle_score(opponent_obs)
        ret = {
            "spatial_info": spatial_info,
            "scalar_info": scalar_info,
            "entity_info": entity_info,
            "entity_num": np.asarray(entity_num, np.int64),
            "game_info": {
                "map_name": self.map_name,
                "game_loop": int(obs.observation.game_loop),
                "tags": tags,
            },
            # top-level copies are the agent-facing contract (MockEnv shares it)
            "action_result": action_result,
            "battle_score": battle_score,
            "opponent_battle_score": opponent_battle_score,
        }

        if opponent_obs is not None:
            ret["value_feature"] = self._value_feature(ret, opponent_obs)
        return ret

    def _value_feature(self, ret: Dict, opponent_obs) -> Dict:
        """Opponent-derived centralized-critic features (reference :691-765)."""
        raw = opponent_obs.observation.raw_data
        entity_info = ret["entity_info"]
        n = int(ret["entity_num"])
        own_mask = entity_info["alliance"][:n] == 1

        enemy_x, enemy_y, enemy_types = [], [], []
        for u in raw.units:
            if u.alliance == 1:  # the OPPONENT's own units
                enemy_x.append(u.pos.x)
                enemy_y.append(self.map_size.y - u.pos.y)
                enemy_types.append(u.unit_type)
        enemy_types = _lut(ACT.UNIT_TYPES_REORDER_ARRAY, enemy_types).astype(np.int64)
        bow = np.zeros(ACT.NUM_UNIT_TYPES, np.int64)
        np.add.at(bow, enemy_types, 1)

        unit_type = np.concatenate(
            [enemy_types, entity_info["unit_type"][:n][own_mask].astype(np.int64)]
        )
        unit_x = np.concatenate([np.asarray(enemy_x), entity_info["x"][:n][own_mask]])
        unit_y = np.concatenate([np.asarray(enemy_y), entity_info["y"][:n][own_mask]])
        alliance = np.concatenate(
            [np.ones(len(enemy_types)), np.zeros(own_mask.sum())]
        )
        total = len(unit_y)

        player = opponent_obs.observation.player_common
        stats = np.asarray(
            [
                player.minerals, player.vespene, player.food_used, player.food_cap,
                player.food_army, player.food_workers, player.idle_worker_count,
                player.army_count, player.warp_gate_count, player.larva_count,
            ],
            np.float32,
        )
        upgrades = np.zeros(ACT.NUM_UPGRADES, np.uint8)
        up = _lut(ACT.UPGRADES_REORDER_ARRAY, list(raw.player.upgrade_ids)[: F.UPGRADE_LENGTH])
        upgrades[up.astype(np.int64)] = 1

        opp_rel = unpack_feature_layer(
            opponent_obs.observation.feature_layer_data.minimap_renders.player_relative
        )
        if opp_rel is None:
            opp_rel = np.zeros(F.SPATIAL_SIZE, np.uint8)
        opp_rel = np.pad(
            opp_rel,
            ((0, F.SPATIAL_SIZE[0] - opp_rel.shape[0]), (0, F.SPATIAL_SIZE[1] - opp_rel.shape[1])),
        )
        return {
            "unit_type": _pad_to(unit_type, F.MAX_ENTITY_NUM).astype(np.int16),
            "enemy_unit_counts_bow": np.clip(bow, 0, 255).astype(np.uint8),
            "enemy_unit_type_bool": (bow > 0).astype(np.uint8),
            "unit_x": _pad_to(unit_x, F.MAX_ENTITY_NUM).astype(np.uint8),
            "unit_y": _pad_to(unit_y, F.MAX_ENTITY_NUM).astype(np.uint8),
            "unit_alliance": _pad_to(alliance, F.MAX_ENTITY_NUM).astype(np.uint8),
            "total_unit_count": np.asarray(total, np.int64),
            "enemy_agent_statistics": np.log1p(stats),
            "enemy_upgrades": upgrades.astype(np.int16),
            "enemy_cumulative_stat": np.zeros(ACT.NUM_CUMULATIVE_STAT_ACTIONS, np.uint8),
            "own_units_spatial": (ret["spatial_info"]["player_relative"] == 1).astype(np.uint8),
            "enemy_units_spatial": (opp_rel == 1).astype(np.uint8),
            "beginning_order": np.zeros(F.BEGINNING_ORDER_LENGTH, np.int16),
            "bo_location": np.zeros(F.BEGINNING_ORDER_LENGTH, np.int16),
        }

    # --------------------------------------------------------------- action
    def transform_action(
        self, action: Dict, tags: Sequence[int], selected_units_num=None
    ) -> Dict:
        """Agent action dict -> raw-command dict the env/client executes
        (reference transform_action :770-850; emitting a plain dict keeps
        this independent of sc_pb — the client binding wraps it).

        ``selected_units_num`` (from the sampler output) bounds the selection;
        without it the scan stops at the end token — steps beyond it carry
        sampler garbage that must not become unit commands."""
        action_type = int(np.asarray(action["action_type"]).reshape(-1)[0])
        spec = ACT.ACTIONS[action_type]
        cmd: Dict = {
            "func_id": spec["func_id"],
            "ability_id": spec["general_ability_id"] or 0,
            "queue_command": bool(int(np.asarray(action["queued"]).reshape(-1)[0]))
            if spec["queued"]
            else False,
            "unit_tags": [],
        }
        if spec["selected_units"]:
            sel = np.asarray(action["selected_units"]).reshape(-1)
            n_tags = len(tags)
            if selected_units_num is not None:
                sel = sel[: int(np.asarray(selected_units_num).reshape(-1)[0])]
            else:
                end = np.nonzero(sel == n_tags)[0]
                if end.size:
                    sel = sel[: int(end[0]) + 1]
            seen = set()
            unit_tags = []
            for i in sel:
                i = int(i)
                if 0 <= i < n_tags and i not in seen:
                    seen.add(i)
                    unit_tags.append(int(tags[i]))
            cmd["unit_tags"] = unit_tags
        if spec["target_unit"]:
            tu = int(np.asarray(action["target_unit"]).reshape(-1)[0])
            if 0 <= tu < len(tags):
                cmd["target_unit_tag"] = int(tags[tu])
        if spec["target_location"]:
            loc = int(np.asarray(action["target_location"]).reshape(-1)[0])
            x = loc % F.SPATIAL_SIZE[1]
            y = loc // F.SPATIAL_SIZE[1]
            cmd["target_world_space_pos"] = (float(x), float(self.map_size.y - y))
        return cmd

    def _ability_to_action(self, ability_id: int, kind: str) -> Optional[int]:
        """Canonicalise an ability id and disambiguate pt/unit/quick/autocast
        variants (reference transfer_action_type :862-880)."""
        if ability_id in ACT.FRIVOLOUS_ABILITIES:
            return None
        if ability_id in ACT.UNLOAD_UNIT_ABILITIES:
            ability_id = ACT.UNLOAD_ALL_TARGET
        elif ability_id in ACT.CANCEL_SLOT_ABILITIES:
            ability_id = ACT.CANCEL_SLOT_TARGET
        gab = ACT.ABILITY_TO_GABILITY.get(ability_id, ability_id)
        return ACT.GAB_KIND_TO_ACTION.get((gab, kind))

    @staticmethod
    def _proto_field(msg, name):
        """Submessage presence: real protos need HasField (unset oneof
        members read as defaults); duck-typed fixtures use None/absence."""
        if hasattr(msg, "HasField"):
            try:
                return getattr(msg, name) if msg.HasField(name) else None
            except ValueError:
                return None
        return getattr(msg, name, None)

    def reverse_raw_action(self, raw_action, tags: Sequence[int]) -> Dict:
        """Replay raw action -> model action dict + per-head mask (reference
        reverse_raw_action :854-951): ability canonicalised (cancel/unload
        remaps) and disambiguated by command kind — unit_command as
        unit/pt/quick, toggle_autocast as autocast (reference :912-922) —
        selected tags mapped to entity indices with the end-flag appended,
        location clamped into the map after the y flip. Invalid/unknown
        actions come back as masked no_ops (invalid=True)."""
        uc = self._proto_field(raw_action, "unit_command")
        ac = self._proto_field(raw_action, "toggle_autocast")
        tag_index = {t: i for i, t in enumerate(tags)}
        entity_num = len(tags)
        S = F.MAX_SELECTED_UNITS_NUM
        invalid = False

        target_unit = 0
        location = 0
        queued = 0
        if ac is not None:
            kind = "autocast"
            ability_id = ac.ability_id
            unit_tags = ac.unit_tags
            action_type = self._ability_to_action(ability_id, kind)
        elif uc is not None:
            ability_id = uc.ability_id
            unit_tags = uc.unit_tags
            queued = int(getattr(uc, "queue_command", False) or 0)
            pos = self._proto_field(uc, "target_world_space_pos")
            target_tag = self._proto_field(uc, "target_unit_tag")
            if target_tag is not None:
                kind = "unit"
                if target_tag in tag_index:
                    target_unit = tag_index[target_tag]
                else:
                    invalid = True
            elif pos is not None:
                kind = "pt"
                x = int(pos.x) if hasattr(pos, "x") else int(pos[0])
                y = int(pos.y) if hasattr(pos, "y") else int(pos[1])
                x = min(x, int(self.map_size.x) - 1)
                y = min(int(self.map_size.y) - y, int(self.map_size.y) - 1)
                location = max(y, 0) * F.SPATIAL_SIZE[1] + max(x, 0)
            else:
                kind = "quick"
            action_type = self._ability_to_action(ability_id, kind)
            if action_type is None and kind == "quick":
                action_type = self._ability_to_action(ability_id, "autocast")
        else:
            unit_tags = []
            action_type = None
        if action_type is None:
            action_type = 0
            invalid = True
        spec = ACT.ACTIONS[action_type]

        selected = np.zeros(S, np.int64)
        sun = 0
        # tags matched against THIS obs (the reference collects only tags it
        # can resolve, :888-894) — kept for every unit-carrying command, not
        # just spec'd selections, and NOT capped (:930-931 caps the tensor)
        matched = [(tag_index[t], t) for t in unit_tags if t in tag_index]
        selected_tags: List[int] = [t for _, t in matched]
        if spec["selected_units"]:
            if matched:
                idxs = [i for i, _ in matched][: S - 1]
                selected[: len(idxs)] = idxs
                selected[len(idxs)] = entity_num  # end flag (reference :931)
                sun = len(idxs) + 1
            else:
                invalid = True
        action = {
            "action_type": np.asarray(action_type, np.int64),
            "delay": np.asarray(0, np.int64),
            "queued": np.asarray(queued, np.int64),
            "selected_units": selected,
            "target_unit": np.asarray(target_unit, np.int64),
            "target_location": np.asarray(location, np.int64),
        }
        head_valid = 0.0 if invalid else 1.0
        mask = {
            "action_type": head_valid,
            "delay": head_valid,
            # autocast commands carry no queue bit on the wire — the
            # reference leaves queued unset there (mask 0, :887 vs :915)
            "queued": head_valid * float(spec["queued"]) * (0.0 if ac is not None else 1.0),
            "selected_units": head_valid * float(spec["selected_units"]),
            "target_unit": head_valid * float(spec["target_unit"]),
            "target_location": head_valid * float(spec["target_location"]),
        }
        return {
            "action": action,
            "selected_units_num": np.asarray(sun, np.int64),
            "mask": mask,
            "invalid": invalid,
            # raw tags behind the selection, for last-action augmentation
            # (the decoder's last_selected_units; works for autocast too)
            "selected_tags": selected_tags,
            "target_tag": (
                int(tags[target_unit]) if (kind == "unit" and not invalid) else None
            ) if uc is not None and ac is None else None,
        }
