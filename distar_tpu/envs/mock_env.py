"""Game-free mock environment.

Role of the reference's mock env (reference: distar/pysc2/env/
mock_sc2_env.py:28-50 — constant timesteps per spec, no binary): produces
schema-complete feature-level observations, advances a game loop by each
agent's requested delay (the variable skip_steps model, env.py:333-375),
terminates after ``episode_game_loops`` with a deterministic winner rule so
league/actor plumbing sees every outcome path.

The observation evolves just enough to exercise the stack: entity counts
drift, the game-loop time advances, last-action fields reflect the previous
action (the reference's obs augmentation contract, agent.py:257-304).
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ..lib import features as F
from .env import BaseEnv


class MockEnv(BaseEnv):
    def __init__(
        self,
        num_agents: int = 2,
        episode_game_loops: int = 2000,
        seed: int = 0,
        # 'random' | 'first' (agent 0 always wins) | 'battle' (the agent
        # whose actions built more army wins — the LEARNABLE rule: policies
        # that shift probability onto cumulative-stat action types beat a
        # uniform-random opponent, so winrate/ELO curves can actually move
        # in the mock world)
        win_rule: str = "random",
        include_value_feature: bool = False,
    ):
        self.num_agents = num_agents
        self._episode_game_loops = episode_game_loops
        self._rng = np.random.default_rng(seed)
        self._win_rule = win_rule
        self._include_value_feature = include_value_feature
        self._game_loop = 0
        self._episode_count = 0
        self._scores = [0.0] * num_agents
        if win_rule == "battle":
            from ..lib import actions as ACT

            # ~half the action vocabulary counts as production: learnable
            # separation without being a needle-in-a-haystack. Slot 0 is the
            # z-target no-op convention, NOT a real build/train action —
            # counting it would score idling
            self._productive = frozenset(ACT.CUMULATIVE_STAT_ACTIONS) - {0}

    def _obs(self, idx: int) -> dict:
        obs = F.fake_step_data(train=False, rng=self._rng)
        obs["entity_num"] = np.asarray(
            int(self._rng.integers(8, 64)), dtype=np.int64
        )
        obs["scalar_info"]["time"] = np.asarray(float(self._game_loop), dtype=np.float32)
        obs["game_loop"] = self._game_loop
        # action feedback the agent's reward machinery reads
        obs["action_result"] = [1]
        obs["battle_score"] = float(self._rng.integers(0, 100)) + self._game_loop * 0.01
        obs["opponent_battle_score"] = float(self._rng.integers(0, 100)) + self._game_loop * 0.01
        if self._include_value_feature:
            obs["value_feature"] = F.fake_value_feature(self._rng)
        return obs

    def reset(self) -> Dict[int, dict]:
        self._game_loop = 0
        self._episode_count += 1
        self._scores = [0.0] * self.num_agents
        return {i: self._obs(i) for i in range(self.num_agents)}

    def step(self, actions: Dict[int, dict]):
        # advance to the earliest requested next observation (variable delay)
        delays = [int(np.asarray(a["delay"])) for a in actions.values()] or [1]
        self._game_loop += max(min(delays), 1)
        if self._win_rule == "battle":
            for i, a in actions.items():
                at = int(np.asarray(a["action_type"]).reshape(-1)[0])
                if at in self._productive:
                    self._scores[i] += 1.0
        done = self._game_loop >= self._episode_game_loops
        obs = {i: self._obs(i) for i in range(self.num_agents)}
        if self._win_rule == "battle":
            # battle scores reflect real production so reward shaping /
            # value features see a consistent signal
            for i in range(self.num_agents):
                obs[i]["battle_score"] = self._scores[i]
                obs[i]["opponent_battle_score"] = max(
                    s for j, s in enumerate(self._scores) if j != i
                ) if self.num_agents > 1 else 0.0
        rewards: Dict[int, float] = {i: 0.0 for i in range(self.num_agents)}
        info: dict = {"game_loop": self._game_loop}
        if done:
            if self._win_rule == "first":
                winner = 0
            elif self._win_rule == "battle":
                best = max(self._scores)
                leaders = [i for i, s in enumerate(self._scores) if s == best]
                winner = int(self._rng.choice(leaders))  # ties break randomly
            else:
                winner = int(self._rng.integers(0, self.num_agents))
            for i in range(self.num_agents):
                rewards[i] = 1.0 if i == winner else -1.0
            info["winner"] = winner
            info["scores"] = list(self._scores)
        return obs, rewards, done, info
