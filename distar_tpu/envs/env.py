"""Environment interface.

The contract mirrors the reference SC2Env surface (reference: distar/envs/
env.py:96-455): ``reset() -> {agent_idx: obs}``, ``step(actions) ->
(obs, rewards, done, info)`` with per-agent variable ``skip_steps`` delays
(the AlphaStar delay-action model, env.py:333-375). Observations are
*feature-level* dicts matching distar_tpu.lib.features — the real SC2
binding (protobuf -> features transform over the websocket protocol) plugs
in behind this interface; MockEnv provides the game-free implementation for
training-stack development and tests (role of the reference's
mock_sc2_env.py).
"""
from __future__ import annotations

from typing import Dict, Tuple


class BaseEnv:
    """Two-player env contract used by the actor."""

    num_agents: int = 2

    def reset(self) -> Dict[int, dict]:
        raise NotImplementedError

    def step(self, actions: Dict[int, dict]) -> Tuple[Dict[int, dict], Dict[int, float], bool, dict]:
        """``actions[idx]`` = {action_type, delay, queued, selected_units,
        target_unit, target_location} (+ skip_steps implied by delay).
        Returns (obs, winloss rewards on done, done, info)."""
        raise NotImplementedError

    def close(self) -> None:
        pass
