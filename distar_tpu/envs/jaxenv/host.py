"""Host-side BaseEnv adapter over the pure-JAX micro-battle world.

``JaxMicroBattleEnv`` makes jaxenv a drop-in for the existing actor stack
(``rl_train --env jaxenv`` without ``--anakin``): the reset/step surface,
per-agent obs dicts, and the auxiliary keys the agent's reward machinery
reads (``game_loop``, ``action_result``, ``battle_score``) all match
MockEnv. Internally it jits single-scenario reset/step/observe once and
converts at the boundary — this is the SLOW path the Anakin loop exists to
replace, kept for contract parity tests and the bench A/B.

Host-side the int64 contract leaves (``entity_num``) are restored from the
device int32 (jax runs without x64), so leaf-by-leaf parity with
``features.fake_step_data`` holds exactly (tests/test_jaxenv.py).

``episode_digest`` is the determinism witness: a sha256 over every
observation byte, reward, and done flag of a fully scripted episode —
goldens in tests/data/ catch any drift in scenario generation, dynamics,
or observation packing.
"""
from __future__ import annotations

import hashlib
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..env import BaseEnv
from .core import EnvConfig, WINNER_DRAW, reset, step
from .obs import observe
from .scenario import Scenario, ScenarioConfig, ScenarioGenerator


def _host_obs(dev_obs: dict) -> dict:
    """Device obs pytree -> host numpy with the exact contract dtypes."""
    out = jax.tree.map(np.asarray, dev_obs)
    out["entity_num"] = np.asarray(int(out["entity_num"]), np.int64)
    return out


class JaxMicroBattleEnv(BaseEnv):
    """Two-agent BaseEnv over one jaxenv scenario per episode.

    Each ``reset`` draws the next scenario from the generator chain (or
    replays a fixed ``scenario`` when one is pinned — the determinism and
    win-rate paths). Team 0 is agent 0 (home).
    """

    num_agents = 2

    def __init__(self, env_cfg: EnvConfig = EnvConfig(),
                 scenario_cfg: Optional[ScenarioConfig] = None,
                 seed: int = 0, scenario: Optional[Scenario] = None):
        self.cfg = env_cfg
        self.gen = ScenarioGenerator(
            scenario_cfg
            if scenario_cfg is not None
            else ScenarioConfig(units_per_squad=env_cfg.units_per_squad))
        self._key = jax.random.PRNGKey(seed)
        self._pinned = scenario
        self._state = None
        self._entity_num = {0: 1, 1: 1}
        self._jit_reset = jax.jit(partial(reset, env_cfg))
        self._jit_step = jax.jit(partial(step, env_cfg))
        self._jit_obs = jax.jit(partial(observe, env_cfg), static_argnums=(1,))

    # --------------------------------------------------------------- BaseEnv
    def _obs_pair(self) -> Dict[int, dict]:
        out = {}
        for team in (0, 1):
            o = _host_obs(self._jit_obs(self._state, team))
            o["game_loop"] = int(self._state.t) * self.cfg.loops_per_step
            o["action_result"] = [1]
            o["battle_score"] = float(self._state.dmg_dealt[team])
            o["opponent_battle_score"] = float(self._state.dmg_dealt[1 - team])
            # the end token of the NEXT action's pointer rows equals this
            # obs's entity_num; remembered so step() can recover sun
            self._entity_num[team] = int(o["entity_num"])
            out[team] = o
        return out

    def reset(self) -> Dict[int, dict]:
        if self._pinned is not None:
            scn = self._pinned
        else:
            self._key, k = jax.random.split(self._key)
            scn = self.gen.generate(k)
        self._state = self._jit_reset(scn)
        return self._obs_pair()

    def step(self, actions: Dict[int, dict]) -> Tuple[Dict[int, dict], Dict[int, float], bool, dict]:
        if self._state is None:
            raise RuntimeError("step() before reset()")

        def dev_action(a: dict) -> dict:
            return {k: jnp.asarray(np.asarray(a[k]))
                    for k in ("action_type", "delay", "queued", "selected_units",
                              "target_unit", "target_location")}

        def sun_of(a: dict, obs_entity_num: int) -> jnp.ndarray:
            # host actors don't ship selected_units_num; recover it as the
            # position of the end token (== entity_num) in the pointer rows
            if "selected_units_num" in a:
                return jnp.asarray(int(np.asarray(a["selected_units_num"])))
            su = np.asarray(a["selected_units"]).reshape(-1)
            hits = np.flatnonzero(su == obs_entity_num)
            n = int(hits[0]) + 1 if hits.size else su.shape[0]
            return jnp.asarray(n, jnp.int32)

        if 0 not in actions:
            raise ValueError("agent 0 action required (home team)")
        a0 = dev_action(actions[0])
        s0 = sun_of(actions[0], self._entity_num[0])
        kw = {}
        if 1 in actions:
            kw["action_away"] = dev_action(actions[1])
            kw["selected_units_num_away"] = sun_of(actions[1], self._entity_num[1])
        self._state, rew, done, winner = self._jit_step(self._state, a0, s0, **kw)
        obs = self._obs_pair()
        done = bool(done)
        rewards = {0: float(rew["winloss"][0]), 1: float(rew["winloss"][1])}
        info: dict = {"game_loop": obs[0]["game_loop"],
                      "battle_reward": {0: float(rew["battle"][0]),
                                        1: float(rew["battle"][1])}}
        if done:
            w = int(winner)
            info["winner"] = -1 if w == WINNER_DRAW else w
        return obs, rewards, done, info


def episode_digest(seed: int = 0,
                   scenario_cfg: Optional[ScenarioConfig] = None,
                   env_cfg: Optional[EnvConfig] = None,
                   max_steps: int = 64) -> dict:
    """Deterministic fingerprint of one fully scripted episode.

    Both teams play the built-in scripted controller (``action_away=None``
    drives away; home passes no_op so the home scripted path stays
    exercised via auto-acquire). Returns the per-step digest chain and the
    final sha256 — bit-identical across fresh processes for the same seed
    and configs (tests/test_jaxenv.py goldens).
    """
    from ...lib import features as F

    env_cfg = env_cfg or EnvConfig(units_per_squad=4)
    scenario_cfg = scenario_cfg or ScenarioConfig(
        units_per_squad=env_cfg.units_per_squad,
        max_units=env_cfg.units_per_squad, episode_len=max_steps)
    env = JaxMicroBattleEnv(env_cfg, scenario_cfg, seed=seed)
    obs = env.reset()
    no_op = {
        "action_type": np.asarray(0, np.int64),
        "delay": np.asarray(1, np.int64),
        "queued": np.asarray(0, np.int64),
        "selected_units": np.zeros(F.MAX_SELECTED_UNITS_NUM, np.int64),
        "target_unit": np.asarray(0, np.int64),
        "target_location": np.asarray(0, np.int64),
        "selected_units_num": np.asarray(1, np.int64),
    }
    h = hashlib.sha256()

    def eat(tree):
        for leaf in jax.tree.leaves(tree):
            h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())

    eat({k: obs[0][k] for k in ("spatial_info", "scalar_info", "entity_info",
                                "entity_num")})
    steps = 0
    winner = None
    for _ in range(max_steps):
        obs, rewards, done, info = env.step({0: no_op})
        steps += 1
        eat({k: obs[0][k] for k in ("spatial_info", "scalar_info",
                                    "entity_info", "entity_num")})
        eat(np.asarray([rewards[0], rewards[1]], np.float64))
        h.update(b"\x01" if done else b"\x00")
        if done:
            winner = info.get("winner")
            break
    return {"sha256": h.hexdigest(), "steps": steps, "winner": winner}
