"""jaxenv: the on-device world (ISSUE 17 tentpole).

A pure-JAX, vmap-able micro-battle environment speaking the real Features
observation/action contract, plus the Anakin fused rollout loop that trains
the flagship model against it with zero per-step host transfers. See
docs/envs.md for the full state/step/reward spec and the Features mapping.
"""
from .anakin import AnakinDataLoader, AnakinRunner, device_pure_report
from .core import EnvConfig, EnvState, micro_legal_mask, reset, step
from .host import JaxMicroBattleEnv, episode_digest
from .obs import observe
from .scenario import Scenario, ScenarioConfig, ScenarioGenerator
from .winrate import (
    ModelPolicy,
    ScriptedPolicy,
    attack_nearest_policy,
    head_to_head,
    idle_policy,
    model_policy,
)

__all__ = [
    "AnakinDataLoader",
    "AnakinRunner",
    "device_pure_report",
    "EnvConfig",
    "EnvState",
    "micro_legal_mask",
    "reset",
    "step",
    "observe",
    "JaxMicroBattleEnv",
    "episode_digest",
    "Scenario",
    "ScenarioConfig",
    "ScenarioGenerator",
    "ModelPolicy",
    "ScriptedPolicy",
    "attack_nearest_policy",
    "idle_policy",
    "model_policy",
    "head_to_head",
]
