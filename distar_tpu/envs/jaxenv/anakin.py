"""Anakin fused rollout: env step + policy forward + LSTM carry in one scan.

Podracer's Anakin layout (PAPERS.md) compiles the whole agent-environment
interaction into a single XLA program: ``AnakinRunner._rollout`` is one
jitted function — lane re-seeding, a ``lax.scan`` over ``unroll_len`` of
(observe -> sample_action -> env step), and the bootstrap observation —
whose carry is donated, so a training iteration performs zero per-step
host transfers (``device_pure_report`` proves it on the jaxpr; tests add a
``jax.transfer_guard`` witness). The emitted batch is already in the exact
time-major collate layout ``learner.data.fake_rl_batch`` documents, so
``RLLearner`` consumes it unchanged via ``AnakinDataLoader``.

Semantics mirror the host actor's window rules (actor/agent.py):

* a lane whose episode finishes mid-window keeps stepping a frozen env
  (core.step freezes state and zeroes rewards after done) while every mask
  and behaviour_logp is zeroed — the learner sees dead padding;
* finished lanes are re-seeded with FRESH scenarios (new fold of the carry
  key) at the next window boundary, with their LSTM carry zeroed;
* ``teacher_logit`` is the behaviour policy's own logits (self-teacher):
  the KL term of the loss is exactly zero, keeping the loss path intact
  without a second forward. A real teacher slots in via ``teacher_apply``.

**Away seat** (``opponent_seat=True``): opponent parameters become a
rollout *input* — the scan body runs a second (frozen) policy forward on
the away team's observation and feeds both action sets to ``core.step``,
so a league exploiter trains in-scan against a published main-agent
snapshot instead of the scripted opponent (ROADMAP item 2a). The emitted
batch additionally carries per-lane episode outcomes (``match_result``)
which :class:`AnakinDataLoader` strips host-side into a results buffer
for league/arena match reporting. The default single-policy path is
untouched: same jitted entry, same key-split schedule, bit-identical
batches.

The runner is a single-device building block: vmap/shard_map it across the
``parallel/`` mesh by mapping ``rollout`` over a leading key/params axis.
"""
from __future__ import annotations

import time
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...lib import actions as ACT
from ...lib import features as F
from ...obs import get_registry
from .core import EnvConfig, micro_legal_mask, reset, step
from .obs import observe
from .scenario import ScenarioConfig, ScenarioGenerator

# Per-action-type head-relevance LUTs (static numpy, baked into the jaxpr):
# actions_mask[head][t, b] = LUT[head][action_type] * step_mask, matching
# the host actor's per-step mask derivation from the ACTIONS spec flags.
_HEAD_LUT = {
    "action_type": np.ones(ACT.NUM_ACTIONS, np.float32),
    "delay": np.ones(ACT.NUM_ACTIONS, np.float32),
    "queued": ACT.QUEUED_MASK.astype(np.float32),
    "selected_units": ACT.SELECTED_UNITS_MASK.astype(np.float32),
    "target_unit": ACT.TARGET_UNIT_MASK.astype(np.float32),
    "target_location": ACT.TARGET_LOCATION_MASK.astype(np.float32),
}

# jaxpr primitives that would mean the scanned loop leaves the device
_IMPURE_PRIMITIVES = ("callback", "infeed", "outfeed", "host_local_array")


def _scan_eqns(jaxpr, found):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if any(tag in name for tag in _IMPURE_PRIMITIVES):
            found.append(name)
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if inner is not None:
                _scan_eqns(inner, found)
            if isinstance(v, (list, tuple)):
                for w in v:
                    inner = getattr(w, "jaxpr", None)
                    if inner is not None:
                        _scan_eqns(inner, found)


def device_pure_report(fn: Callable, *args) -> dict:
    """Trace ``fn(*args)`` and scan the full jaxpr (recursively through
    scan/cond/pjit bodies) for host-transfer primitives.

    Returns ``{"pure": bool, "offending": [primitive names]}`` — the
    acceptance witness that nothing inside the fused loop calls back to
    the host."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    found: list = []
    _scan_eqns(jaxpr.jaxpr, found)
    return {"pure": not found, "offending": found}


class AnakinRunner:
    """Fused rollout producer for one device.

    Parameters
    ----------
    model: the flax ``Model`` (flagship or smoke config) — ``sample_action``
        drives every head; hidden dims are read from ``model.cfg``.
    batch_size: B, the number of vmapped env lanes (>= 1024 for the
        acceptance run).
    unroll_len: T, steps per trajectory window.
    restrict_micro: confine sampling to the micro-battle action-type
        vocabulary via ``sample_action``'s legal_mask (default True — the
        environment ignores macro actions anyway, this keeps behaviour
        probability mass on executable commands).
    teacher_apply: optional ``(obs_leaves..., hidden, action, sun) ->
        logits`` for a real teacher; default self-teacher.
    opponent_seat: compile the two-policy rollout — ``rollout`` then takes
        frozen ``opponent_params`` driving the away team and the batch
        carries ``match_result`` episode outcomes. Off by default; the
        single-policy path is bit-identical to pre-league behaviour.
    """

    def __init__(self, model, batch_size: int, unroll_len: int,
                 env_cfg: EnvConfig = EnvConfig(),
                 scenario_cfg: Optional[ScenarioConfig] = None,
                 seed: int = 0, restrict_micro: bool = True,
                 teacher_apply: Optional[Callable] = None,
                 opponent_seat: bool = False):
        self.model = model
        self.B = int(batch_size)
        self.T = int(unroll_len)
        self.env_cfg = env_cfg
        self.gen = ScenarioGenerator(
            scenario_cfg
            if scenario_cfg is not None
            else ScenarioConfig(units_per_squad=env_cfg.units_per_squad))
        if self.gen.cfg.units_per_squad != env_cfg.units_per_squad:
            raise ValueError(
                "scenario_cfg.units_per_squad must match env_cfg "
                f"({self.gen.cfg.units_per_squad} != {env_cfg.units_per_squad})")
        lstm = model.cfg["encoder"]["core_lstm"]
        self._hidden_size = int(lstm["hidden_size"])
        self._hidden_layers = int(lstm["num_layers"])
        self._legal = jnp.asarray(micro_legal_mask()) if restrict_micro else None
        self._teacher_apply = teacher_apply
        self._seed = seed
        self.opponent_seat = bool(opponent_seat)
        self._rollout = jax.jit(self._rollout_impl, donate_argnums=(1,))
        if self.opponent_seat:
            # separate jitted entry: the opponent path has a different
            # carry structure (away LSTM state) and an extra params input
            self._rollout_opp = jax.jit(
                self._rollout_opp_impl, donate_argnums=(2,))

    # ---------------------------------------------------------------- carry
    def _zero_hidden(self):
        return tuple(
            (jnp.zeros((self.B, self._hidden_size), jnp.float32),
             jnp.zeros((self.B, self._hidden_size), jnp.float32))
            for _ in range(self._hidden_layers))

    def init_carry(self, key: Optional[jax.Array] = None):
        """(states, hidden, key): B env lanes + zero LSTM carries. With the
        away seat enabled: (states, hidden, opp_hidden, key)."""
        if key is None:
            key = jax.random.PRNGKey(self._seed)
        key, k_scn = jax.random.split(key)
        scn = self.gen.batch(k_scn, self.B)
        states = jax.vmap(partial(reset, self.env_cfg))(scn)
        hidden = self._zero_hidden()
        # the carry is donated to the fused rollout; aliased leaves (e.g.
        # reset's order_pos sharing pos's buffer) would be donated twice,
        # so force every leaf onto its own buffer
        states = jax.tree.map(lambda x: jnp.array(x, copy=True), states)
        if self.opponent_seat:
            return states, hidden, self._zero_hidden(), key
        return states, hidden, key

    # -------------------------------------------------------------- rollout
    def _sample(self, params, obs, hidden, key):
        return self.model.apply(
            params, obs["spatial_info"], obs["entity_info"], obs["scalar_info"],
            obs["entity_num"], hidden, key, self._legal,
            method=self.model.sample_action)

    def _emit_y(self, cfg, out, obs, hid, st, rew, done, step_mask):
        """One scan step's learner-batch slice (home perspective) — shared
        verbatim between the single-policy and away-seat bodies."""
        action = out["action_info"]
        sun = out["selected_units_num"]
        if self._teacher_apply is not None:
            teacher = self._teacher_apply(obs, hid, action, sun)
        else:
            teacher = out["logit"]
        logp = out["action_logp"]
        zero = jnp.zeros((self.B,), jnp.float32)
        return {
            "obs": obs,
            "action_info": action,
            "selected_units_num": sun,
            "behaviour_logp": {
                k: v * (step_mask[:, None] if v.ndim == 2 else step_mask)
                for k, v in logp.items()},
            "teacher_logit": teacher,
            "reward": {
                "winloss": rew["winloss"][:, 0] * step_mask,
                "battle": rew["battle"][:, 0] * step_mask,
                "build_order": zero, "built_unit": zero,
                "effect": zero, "upgrade": zero,
            },
            "step": (st.t * cfg.loops_per_step).astype(jnp.float32),
            "done": done.astype(jnp.float32),
            "mask": {
                "actions_mask": {
                    k: jnp.asarray(lut)[action["action_type"]] * step_mask
                    for k, lut in _HEAD_LUT.items()},
                "build_order_mask": zero,
                "built_unit_mask": zero,
                "effect_mask": step_mask,
                "cum_action_mask": step_mask,
                "step_mask": step_mask,
            },
        }

    def _rollout_impl(self, params, carry):
        cfg = self.env_cfg
        states, hidden, key = carry
        key, k_seed, k_scan = jax.random.split(key, 3)

        # window boundary: finished lanes get fresh scenarios + zero carry
        fresh_scn = jax.vmap(self.gen.generate)(jax.random.split(k_seed, self.B))
        fresh = jax.vmap(partial(reset, cfg))(fresh_scn)
        d = states.done

        def lane_where(old, new):
            return jnp.where(d.reshape((-1,) + (1,) * (new.ndim - 1)), new, old)

        states = jax.tree.map(lane_where, states, fresh)
        hidden = tuple((jnp.where(d[:, None], 0.0, h), jnp.where(d[:, None], 0.0, c))
                       for h, c in hidden)
        hidden0 = hidden

        observe_b = jax.vmap(partial(observe, cfg), in_axes=(0, None))
        step_b = jax.vmap(partial(step, cfg))

        def body(scan_carry, k_t):
            st, hid = scan_carry
            prev_done = st.done
            obs = observe_b(st, 0)
            out = self._sample(params, obs, hid, k_t)
            action = out["action_info"]
            sun = out["selected_units_num"]
            nst, rew, done, _winner = step_b(st, action, sun)
            step_mask = (~prev_done).astype(jnp.float32)
            y = self._emit_y(cfg, out, obs, hid, st, rew, done, step_mask)
            return (nst, out["hidden_state"]), y

        (states, hidden), ys = jax.lax.scan(
            body, (states, hidden), jax.random.split(k_scan, self.T))

        batch = self._assemble_batch(observe_b, states, hidden0, ys)
        return (states, hidden, key), batch

    def _assemble_batch(self, observe_b, states, hidden0, ys):
        boot = observe_b(states, 0)
        obs_full = jax.tree.map(
            lambda a, b: jnp.concatenate([a, b[None]], axis=0), ys["obs"], boot)
        sun = ys["selected_units_num"]
        return {
            "spatial_info": obs_full["spatial_info"],
            "entity_info": obs_full["entity_info"],
            "scalar_info": obs_full["scalar_info"],
            "entity_num": obs_full["entity_num"],
            "hidden_state": hidden0,
            "action_info": ys["action_info"],
            "selected_units_num": sun,
            "behaviour_logp": ys["behaviour_logp"],
            "teacher_logit": ys["teacher_logit"],
            "reward": ys["reward"],
            "step": ys["step"],
            "done": ys["done"],
            "mask": dict(
                ys["mask"],
                selected_units_mask=(
                    jnp.arange(F.MAX_SELECTED_UNITS_NUM)[None, None, :]
                    < sun[..., None]),
            ),
            "model_last_iter": jnp.zeros((self.B,), jnp.float32),
        }

    def _rollout_opp_impl(self, params, opp_params, carry):
        """Two-policy sibling of ``_rollout_impl``: the away team is driven
        by a frozen opponent policy (its own LSTM carry rides the donated
        carry), and per-step ``(winner, finished)`` outcomes are emitted so
        the host can report league matches. The home side's batch semantics
        are identical to the single-policy path."""
        cfg = self.env_cfg
        states, hidden, opp_hidden, key = carry
        key, k_seed, k_scan = jax.random.split(key, 3)

        fresh_scn = jax.vmap(self.gen.generate)(jax.random.split(k_seed, self.B))
        fresh = jax.vmap(partial(reset, cfg))(fresh_scn)
        d = states.done

        def lane_where(old, new):
            return jnp.where(d.reshape((-1,) + (1,) * (new.ndim - 1)), new, old)

        states = jax.tree.map(lane_where, states, fresh)
        hidden = tuple((jnp.where(d[:, None], 0.0, h), jnp.where(d[:, None], 0.0, c))
                       for h, c in hidden)
        opp_hidden = tuple(
            (jnp.where(d[:, None], 0.0, h), jnp.where(d[:, None], 0.0, c))
            for h, c in opp_hidden)
        hidden0 = hidden

        observe_b = jax.vmap(partial(observe, cfg), in_axes=(0, None))
        step_b = jax.vmap(partial(step, cfg))

        def body(scan_carry, k_t):
            st, hid, opp_hid = scan_carry
            prev_done = st.done
            # independent streams per seat (winrate.head_to_head idiom)
            ka, kb = jax.random.split(k_t)
            obs = observe_b(st, 0)
            out = self._sample(params, obs, hid, ka)
            obs_away = observe_b(st, 1)
            out_away = self._sample(opp_params, obs_away, opp_hid, kb)
            nst, rew, done, winner = step_b(
                st, out["action_info"], out["selected_units_num"],
                out_away["action_info"], out_away["selected_units_num"])
            step_mask = (~prev_done).astype(jnp.float32)
            y = self._emit_y(cfg, out, obs, hid, st, rew, done, step_mask)
            y["match_winner"] = winner
            y["match_finished"] = done & ~prev_done
            return (nst, out["hidden_state"], out_away["hidden_state"]), y

        (states, hidden, opp_hidden), ys = jax.lax.scan(
            body, (states, hidden, opp_hidden), jax.random.split(k_scan, self.T))

        winner = ys.pop("match_winner")
        finished = ys.pop("match_finished")
        batch = self._assemble_batch(observe_b, states, hidden0, ys)
        batch["match_result"] = {
            "winner": winner, "finished": finished,
            "steps": ys["step"],
        }
        return (states, hidden, opp_hidden, key), batch

    def rollout(self, params, carry, opponent_params=None):
        """One fused window: (new_carry, learner batch [T, B] on device).
        With ``opponent_seat``, ``opponent_params`` drive the away team and
        the batch gains a ``match_result`` leaf (host-stripped by the
        loader before the learner sees the batch)."""
        if self.opponent_seat:
            assert opponent_params is not None, \
                "opponent_seat runner needs opponent_params"
            return self._rollout_opp(params, opponent_params, carry)
        assert opponent_params is None, \
            "construct AnakinRunner(opponent_seat=True) to pass opponent_params"
        return self._rollout(params, carry)

    def purity_report(self, params, carry, opponent_params=None) -> dict:
        """Jaxpr audit of the full fused window (scan body included)."""
        if self.opponent_seat:
            return device_pure_report(
                self._rollout_opp_impl, params, opponent_params, carry)
        return device_pure_report(self._rollout_impl, params, carry)


class AnakinDataLoader:
    """Iterator feeding ``RLLearner.set_dataloader`` from an AnakinRunner.

    The learner's lazy ``_setup_state`` pulls one batch for shapes before it
    owns params, so the loader bootstraps its own parameter pytree (one
    ``model.init``) and switches to ``params_provider`` (the learner's live
    train state) as soon as it returns one — on-policy after the first
    window. Batches stay on device end to end: the learner's ``shard_batch``
    is ``jnp.asarray`` and passes jnp arrays through.

    With an ``opponent_seat`` runner, ``opponent_provider`` supplies the
    frozen away-team parameters each window (a league snapshot published
    by the coordinator; defaults to the bootstrap pytree — a frozen copy
    of the initial policy). The per-lane episode outcomes are stripped
    host-side into a results buffer; ``drain_results()`` hands them to the
    league learner loop for match reporting.
    """

    def __init__(self, runner: AnakinRunner,
                 params_provider: Optional[Callable] = None,
                 opponent_provider: Optional[Callable] = None):
        self.runner = runner
        self._params_provider = params_provider or (lambda: None)
        self._opponent_provider = opponent_provider or (lambda: None)
        self._bootstrap_params = None
        self._carry = None
        self._results: list = []
        reg = get_registry()
        reg.gauge("distar_rollout_plane_backend",
                  "active rollout-plane backend (1 = active)",
                  backend="anakin").set(1)
        self._g_rate = reg.gauge(
            "distar_anakin_env_steps_per_s",
            "fused-loop environment steps per wall second")
        self._c_batches = reg.counter(
            "distar_anakin_batches_total", "trajectory windows produced")
        self._c_episodes = reg.counter(
            "distar_env_episodes_total", "jaxenv episodes finished",
            backend="anakin")
        self._h_window = reg.histogram(
            "distar_anakin_window_seconds", "wall time per fused window")

    def _bootstrap(self):
        if self._bootstrap_params is None:
            r = self.runner
            carry = r.init_carry(jax.random.PRNGKey(r._seed))
            states, hidden = carry[0], carry[1]
            obs = jax.vmap(partial(observe, r.env_cfg), in_axes=(0, None))(states, 0)
            self._bootstrap_params = r.model.init(
                jax.random.PRNGKey(r._seed),
                obs["spatial_info"], obs["entity_info"], obs["scalar_info"],
                obs["entity_num"], hidden, jax.random.PRNGKey(r._seed + 1),
                method=r.model.sample_action)
        return self._bootstrap_params

    def _params(self):
        live = self._params_provider()
        if live is not None:
            return live
        return self._bootstrap()

    def _opponent_params(self):
        frozen = self._opponent_provider()
        if frozen is not None:
            return frozen
        return self._bootstrap()

    def drain_results(self) -> list:
        """Episode outcomes accumulated since the last drain (opponent-seat
        windows only): ``[{"winner": "home"|"away"|"draw", "steps": n}]``
        in finish order — the league learner's match-report feed."""
        out, self._results = self._results, []
        return out

    def __iter__(self):
        return self

    def __next__(self):
        if self._carry is None:
            self._carry = self.runner.init_carry()
        t0 = time.perf_counter()
        try:
            if self.runner.opponent_seat:
                self._carry, batch = self.runner.rollout(
                    self._params(), self._carry,
                    opponent_params=self._opponent_params())
                ended = self._collect_results(batch.pop("match_result"))
            else:
                self._carry, batch = self.runner.rollout(
                    self._params(), self._carry)
                # one deliberate host sync per window for honest wall-clock
                # metrics
                ended = int(jnp.sum(batch["done"][-1]))
        except Exception:
            # the fused call donates the carry; a failure mid-window leaves
            # the old carry pointing at deleted buffers, which would poison
            # every retry — drop it so a supervised restart re-initialises
            self._carry = None
            raise
        dt = max(time.perf_counter() - t0, 1e-9)
        self._g_rate.set(self.runner.B * self.runner.T / dt)
        self._h_window.observe(dt)
        self._c_batches.inc()
        if ended:
            self._c_episodes.inc(ended)
        return batch

    def _collect_results(self, match_result: dict) -> int:
        """Strip the device-side outcome leaves into host records (the one
        host sync the opponent-seat window pays, replacing the metrics
        sync of the default path)."""
        from .core import WINNER_AWAY, WINNER_HOME

        finished = np.asarray(match_result["finished"])  # [T, B] bool
        winner = np.asarray(match_result["winner"])      # [T, B] i32
        steps = np.asarray(match_result["steps"])        # [T, B] f32
        names = {WINNER_HOME: "home", WINNER_AWAY: "away"}
        for t, b in zip(*np.nonzero(finished)):
            self._results.append({
                "winner": names.get(int(winner[t, b]), "draw"),
                "steps": float(steps[t, b]),
            })
        return int(finished.sum())
