"""Features-contract observation builder: EnvState -> the flagship obs pytree.

``observe(cfg, state, team)`` emits exactly the schema in
``lib.features`` — SPATIAL_INFO planes (effect_* as coordinate lists),
SCALAR_INFO fields, ENTITY_INFO vectors padded to MAX_ENTITY_NUM — with the
contract dtypes, built entirely from jnp ops so it lives inside the Anakin
``lax.scan``. One documented divergence: on device, int64 contract leaves
(``entity_num``) are int32 because jax runs without x64; the host adapter
(``host.JaxMicroBattleEnv``) casts them back so host-side parity is
leaf-by-leaf exact (tests/test_jaxenv.py).

Entity packing (own alive units first, then enemies) comes from
``core.pack_perm`` — the same permutation ``core.step`` uses to decode
pointer actions, so the model's entity slots always refer to these rows.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...lib import actions as ACT
from ...lib import features as F
from .core import EnvConfig, EnvState, pack_perm, team_vector, unit_types
from .scenario import (
    CATALOG_COOLDOWN,
    CATALOG_DENSE_TYPES,
    CELL,
    MAP_H,
    MAP_W,
)

# SC2 player_relative plane codes
_PR_SELF, _PR_ENEMY = 1, 4
_RACE_ZERG = 2


def _pad_entities(vals, slot_mask, dtype):
    """[N] per-packed-slot values -> [MAX_ENTITY_NUM] contract vector."""
    vals = jnp.where(slot_mask, vals, 0)
    out = jnp.zeros(F.MAX_ENTITY_NUM, dtype)
    return out.at[: vals.shape[0]].set(vals.astype(dtype))


def observe(cfg: EnvConfig, state: EnvState, team: int = 0) -> dict:
    """One team's schema-complete observation (no batch dim, device arrays)."""
    N = cfg.num_units
    team_of = team_vector(cfg)
    own = team_of == team
    perm, entity_num = pack_perm(cfg, state, team)
    slot_mask = jnp.arange(N) < entity_num

    types = unit_types(cfg, state)
    dense = jnp.asarray(CATALOG_DENSE_TYPES)[types]
    px = jnp.clip(jnp.round(state.pos[:, 0]), 0, MAP_W - 1)
    py = jnp.clip(jnp.round(state.pos[:, 1]), 0, MAP_H - 1)

    def packed(unit_vals):
        return jnp.asarray(unit_vals)[perm]

    entity_info = {k: jnp.zeros(F.MAX_ENTITY_NUM, dt) for k, dt in F.ENTITY_INFO.items()}
    entity_info.update(
        unit_type=_pad_entities(packed(dense), slot_mask, np.int16),
        alliance=_pad_entities(
            packed(jnp.where(own, _PR_SELF, _PR_ENEMY)), slot_mask, np.uint8),
        x=_pad_entities(packed(px), slot_mask, np.uint8),
        y=_pad_entities(packed(py), slot_mask, np.uint8),
        health_ratio=_pad_entities(
            packed(state.health / jnp.maximum(state.max_health, 1e-6)),
            slot_mask, np.float16),
        build_progress=_pad_entities(
            packed(jnp.ones(N, jnp.float32)), slot_mask, np.float16),
        display_type=_pad_entities(packed(jnp.ones(N, jnp.int32)), slot_mask, np.uint8),
        weapon_cooldown=_pad_entities(
            packed(jnp.clip(jnp.ceil(state.cooldown), 0, 255)), slot_mask, np.uint8),
        is_active=_pad_entities(
            packed((state.order_kind != 0).astype(jnp.int32)), slot_mask, np.uint8),
        order_length=_pad_entities(
            packed((state.order_kind != 0).astype(jnp.int32)), slot_mask, np.uint8),
        last_selected_units=_pad_entities(
            packed(state.last_selected[team].astype(jnp.int32)), slot_mask, np.int8),
        last_targeted_unit=_pad_entities(
            packed(state.last_targeted[team].astype(jnp.int32)), slot_mask, np.int8),
    )

    # --- spatial planes
    terrain8 = jnp.repeat(jnp.repeat(state.scenario.terrain, CELL, axis=0),
                          CELL, axis=1).astype(np.uint8)
    iy = py.astype(jnp.int32)
    ix = px.astype(jnp.int32)
    pr_val = jnp.where(state.alive, jnp.where(own, _PR_SELF, _PR_ENEMY), 0)
    player_relative = jnp.zeros(F.SPATIAL_SIZE, np.uint8).at[iy, ix].max(
        pr_val.astype(np.uint8))
    spatial_info = {
        "height_map": terrain8 * np.uint8(64),
        "visibility_map": jnp.full(F.SPATIAL_SIZE, 2, np.uint8),
        "creep": jnp.zeros(F.SPATIAL_SIZE, np.uint8),
        "player_relative": player_relative,
        "alerts": jnp.zeros(F.SPATIAL_SIZE, np.uint8),
        "pathable": terrain8,
        "buildable": terrain8,
    }
    for k, dt in F.SPATIAL_INFO.items():
        if k.startswith("effect_"):
            spatial_info[k] = jnp.zeros((F.EFFECT_LENGTH,), dt)

    # --- scalar stats
    own_alive = (state.alive & own).sum()
    enemy_alive = (state.alive & ~own).sum()
    own_counts = jnp.zeros(ACT.NUM_UNIT_TYPES, jnp.int32).at[dense].add(
        (state.alive & own).astype(jnp.int32))
    enemy_counts = jnp.zeros(ACT.NUM_UNIT_TYPES, jnp.int32).at[dense].add(
        (state.alive & ~own).astype(jnp.int32))
    stats = jnp.stack([
        own_alive.astype(jnp.float32),
        (state.health * own).sum(),
        enemy_alive.astype(jnp.float32),
        state.dmg_dealt[team],
        state.dmg_dealt[1 - team],
        state.kills[team],
        state.kills[1 - team],
        state.t.astype(jnp.float32),
        (state.max_health * own).sum(),
        (state.max_health * ~own).sum(),
    ])
    scalar_info = {k: jnp.zeros(shape, dt) for k, (dt, shape) in F.SCALAR_INFO.items()}
    scalar_info.update(
        home_race=jnp.asarray(_RACE_ZERG, np.uint8),
        away_race=jnp.asarray(_RACE_ZERG, np.uint8),
        time=(state.t * cfg.loops_per_step).astype(np.float32),
        unit_counts_bow=jnp.clip(own_counts, 0, 255).astype(np.uint8),
        agent_statistics=jnp.log1p(jnp.maximum(stats, 0.0)).astype(np.float32),
        last_action_type=state.last_action[team, 0].astype(np.int16),
        last_delay=state.last_action[team, 1].astype(np.int16),
        last_queued=state.last_action[team, 2].astype(np.int16),
        unit_type_bool=(own_counts > 0).astype(np.uint8),
        enemy_unit_type_bool=(enemy_counts > 0).astype(np.uint8),
    )

    return {
        "spatial_info": spatial_info,
        "scalar_info": scalar_info,
        "entity_info": entity_info,
        # int32 on device (jax runs without x64); the host adapter casts to
        # the contract's int64
        "entity_num": jnp.maximum(entity_num, 1).astype(jnp.int32),
    }
