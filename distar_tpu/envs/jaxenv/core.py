"""Pure-JAX micro-battle dynamics: reset / step over fixed-shape unit arrays.

Two squads of units (positions, health, cooldowns) fight on the contract's
spatial rectangle. Commands arrive in the real Features action layout —
action_type indexes the 327-action vocabulary, selected_units are pointer
slots into the observation's entity list, target_unit is an entity slot,
target_location a flat spatial index — and are decoded on device through
static semantic LUTs built from the action contract. Reward is damage
differential (``battle``) plus a terminal win bonus (``winloss``).

Every function is a pure jax transform of (config, state, action): single-env
written, ``jax.vmap``-able over a batch of scenarios, and deterministic given
the scenario key (goldens in tests/test_jaxenv.py pin this bit-for-bit).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...lib import actions as ACT
from ...lib import features as F
from .scenario import (
    CATALOG_COOLDOWN,
    CATALOG_DAMAGE,
    CATALOG_HEALTH,
    CATALOG_RANGE,
    CATALOG_SPEED,
    CELL,
    MAP_H,
    MAP_W,
    Scenario,
)

# ------------------------------------------------------------ semantic LUTs
# Order kinds a unit can hold between steps.
KIND_STOP, KIND_MOVE, KIND_ATTACK_MOVE, KIND_ATTACK_UNIT = 0, 1, 2, 3

# action_type -> command semantics, derived from the action contract's
# per-head applicability flags (unit-targeted actions command an attack on
# the target, location actions move — attack-move when the action is an
# Attack variant — bare selected-units actions stop/hold).
_SEM_NONE, _SEM_MOVE, _SEM_ATTACK_MOVE, _SEM_ATTACK_UNIT, _SEM_STOP = 0, 1, 2, 3, 4


def _build_action_semantics() -> np.ndarray:
    sem = np.zeros(ACT.NUM_ACTIONS, np.int32)
    for i, a in enumerate(ACT.ACTIONS):
        if a["target_unit"]:
            sem[i] = _SEM_ATTACK_UNIT
        elif a["target_location"]:
            sem[i] = _SEM_ATTACK_MOVE if "Attack" in a["name"] else _SEM_MOVE
        elif a["selected_units"]:
            sem[i] = _SEM_STOP
    return sem


ACTION_SEMANTIC = _build_action_semantics()
_SEM_TO_KIND = np.array(
    [KIND_STOP, KIND_MOVE, KIND_ATTACK_MOVE, KIND_ATTACK_UNIT, KIND_STOP], np.int32)

# The micro-battle-meaningful action subset (optional policy legal_mask):
# no_op, Attack_pt, Attack_unit, HoldPosition, Move_pt, Move_unit, Smart_pt,
# Smart_unit, Stop — every other action decodes to one of these semantics
# anyway, but constraining sampling concentrates exploration.
MICRO_ACTION_TYPES = (0, 2, 3, 156, 197, 198, 265, 266, 267)


def micro_legal_mask() -> np.ndarray:
    mask = np.zeros(ACT.NUM_ACTIONS, bool)
    mask[list(MICRO_ACTION_TYPES)] = True
    return mask


# Winner codes (EnvState.winner)
WINNER_NONE, WINNER_HOME, WINNER_AWAY, WINNER_DRAW = -1, 0, 1, 2


@dataclass(frozen=True)
class EnvConfig:
    """Static (hashable, jit-closure-safe) dynamics knobs."""

    units_per_squad: int = 8
    loops_per_step: int = 22      # game loops one env step represents
    damage_norm: float = 200.0    # battle reward = damage diff / this
    timeout_margin: float = 0.05  # health-fraction lead needed to win a timeout
    hit_slack: float = 1.0        # px of target-radius slack on weapon range

    @property
    def num_units(self) -> int:
        return 2 * self.units_per_squad


class EnvState(NamedTuple):
    """Complete battle state, all leaves fixed-shape (N = 2 * U units; the
    first U slots are home, the rest away)."""

    scenario: Scenario
    pos: jax.Array           # f32 [N, 2] (x, y)
    health: jax.Array        # f32 [N]
    max_health: jax.Array    # f32 [N]
    cooldown: jax.Array      # f32 [N] steps until the weapon is ready
    alive: jax.Array         # bool [N]
    order_kind: jax.Array    # i32 [N] KIND_*
    order_pos: jax.Array     # f32 [N, 2]
    order_target: jax.Array  # i32 [N] unit index, -1 = none
    t: jax.Array             # i32 [] env steps taken
    done: jax.Array          # bool []
    winner: jax.Array        # i32 [] WINNER_*
    last_action: jax.Array   # i32 [2, 3] per team (action_type, delay, queued)
    last_selected: jax.Array  # bool [2, N] units in each team's last selection
    last_targeted: jax.Array  # bool [2, N] unit each team last targeted
    dmg_dealt: jax.Array     # f32 [2] cumulative damage by team
    kills: jax.Array         # f32 [2] cumulative kills by team


def team_vector(cfg: EnvConfig) -> jnp.ndarray:
    """i32 [N]: 0 for home slots, 1 for away slots."""
    U = cfg.units_per_squad
    return jnp.concatenate([jnp.zeros(U, jnp.int32), jnp.ones(U, jnp.int32)])


def reset(cfg: EnvConfig, scenario: Scenario) -> EnvState:
    U = cfg.units_per_squad
    types = jnp.concatenate([scenario.type_home, scenario.type_away])
    slot = jnp.arange(U)
    alive = jnp.concatenate([slot < scenario.n_home, slot < scenario.n_away])
    pos = jnp.concatenate([scenario.pos_home, scenario.pos_away]).astype(jnp.float32)
    health = jnp.asarray(CATALOG_HEALTH)[types] * alive
    N = cfg.num_units
    return EnvState(
        scenario=scenario,
        pos=pos,
        health=health,
        # masked like health so never-spawned pad slots contribute nothing to
        # the timeout health-fraction denominator
        max_health=jnp.asarray(CATALOG_HEALTH)[types] * alive,
        cooldown=jnp.zeros(N, jnp.float32),
        alive=alive,
        order_kind=jnp.zeros(N, jnp.int32),
        order_pos=pos,
        order_target=jnp.full(N, -1, jnp.int32),
        t=jnp.asarray(0, jnp.int32),
        done=jnp.asarray(False),
        winner=jnp.asarray(WINNER_NONE, jnp.int32),
        last_action=jnp.zeros((2, 3), jnp.int32),
        last_selected=jnp.zeros((2, N), bool),
        last_targeted=jnp.zeros((2, N), bool),
        dmg_dealt=jnp.zeros(2, jnp.float32),
        kills=jnp.zeros(2, jnp.float32),
    )


def unit_types(cfg: EnvConfig, state: EnvState) -> jnp.ndarray:
    """i32 [N] catalog row per unit slot."""
    return jnp.concatenate([state.scenario.type_home, state.scenario.type_away])


def pack_perm(cfg: EnvConfig, state: EnvState, team) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Entity packing for ``team``'s observation: a permutation placing the
    team's own alive units first, then alive enemies, then dead slots (the
    contract wants valid entities in the first entity_num rows), plus the
    alive count. ``step`` decodes pointer actions through the SAME
    permutation, so entity slots the model emits land on the right units."""
    own = team_vector(cfg) == team
    rank = jnp.where(state.alive & own, 0, jnp.where(state.alive & ~own, 1, 2))
    perm = jnp.argsort(rank, stable=True)
    entity_num = state.alive.sum().astype(jnp.int32)
    return perm, entity_num


def _decode_team_action(cfg: EnvConfig, state: EnvState, team,
                        action: dict, selected_units_num) -> EnvState:
    """Apply one team's contract-layout action to its units' orders."""
    N = cfg.num_units
    at = jnp.asarray(action["action_type"]).reshape(()).astype(jnp.int32)
    at = jnp.clip(at, 0, ACT.NUM_ACTIONS - 1)
    sem = jnp.asarray(ACTION_SEMANTIC)[at]
    has_sel = jnp.asarray(ACT.SELECTED_UNITS_MASK)[at]
    perm, entity_num = pack_perm(cfg, state, team)

    # selected-units pointer: entity slots -> unit ids, end-token lane (and
    # any post-end junk) excluded via selected_units_num
    S = F.MAX_SELECTED_UNITS_NUM
    su = jnp.asarray(action["selected_units"]).reshape(S).astype(jnp.int32)
    sun = jnp.asarray(selected_units_num).reshape(()).astype(jnp.int32)
    lane_ok = (jnp.arange(S) < (sun - 1)) & (su >= 0) & (su < entity_num) & (su < N)
    sel_unit_ids = perm[jnp.clip(su, 0, N - 1)]
    sel = jnp.zeros(N, bool).at[sel_unit_ids].max(lane_ok)
    own = team_vector(cfg) == team
    sel = sel & own & state.alive

    # target unit: an entity slot in the same packed view
    tslot = jnp.asarray(action["target_unit"]).reshape(()).astype(jnp.int32)
    t_ok = (tslot >= 0) & (tslot < entity_num) & (tslot < N)
    target_id = perm[jnp.clip(tslot, 0, N - 1)]

    # target location: flat index over the (y, x) spatial rectangle
    loc = jnp.asarray(action["target_location"]).reshape(()).astype(jnp.int32)
    loc = jnp.clip(loc, 0, MAP_H * MAP_W - 1)
    tpos = jnp.stack([(loc % MAP_W).astype(jnp.float32),
                      (loc // MAP_W).astype(jnp.float32)])

    valid = has_sel & (sem != _SEM_NONE) & jnp.where(sem == _SEM_ATTACK_UNIT, t_ok, True)
    upd = sel & valid
    new_kind = jnp.asarray(_SEM_TO_KIND)[sem]
    order_kind = jnp.where(upd, new_kind, state.order_kind)
    order_pos = jnp.where(upd[:, None], tpos[None, :], state.order_pos)
    order_target = jnp.where(
        upd,
        jnp.where(sem == _SEM_ATTACK_UNIT, target_id, -1),
        state.order_target,
    )

    last_action = state.last_action.at[team].set(jnp.stack([
        at,
        jnp.asarray(action.get("delay", 0)).reshape(()).astype(jnp.int32),
        jnp.asarray(action.get("queued", 0)).reshape(()).astype(jnp.int32),
    ]))
    targeted = jnp.zeros(N, bool).at[target_id].set(
        valid & (sem == _SEM_ATTACK_UNIT) & upd.any())
    return state._replace(
        order_kind=order_kind,
        order_pos=order_pos,
        order_target=order_target,
        last_action=last_action,
        last_selected=state.last_selected.at[team].set(sel),
        last_targeted=state.last_targeted.at[team].set(targeted),
    )


def _scripted_orders(cfg: EnvConfig, state: EnvState, team) -> EnvState:
    """Built-in opponent: every unit of ``team`` attack-moves at the nearest
    living enemy (a chase-and-shoot baseline; pure, no PRNG)."""
    team_of = team_vector(cfg)
    own = team_of == team
    enemy_alive = state.alive & ~own
    d = jnp.linalg.norm(state.pos[:, None, :] - state.pos[None, :, :], axis=-1)
    d = jnp.where(enemy_alive[None, :], d, jnp.inf)
    nearest = jnp.argmin(d, axis=1)
    has_enemy = enemy_alive.any()
    upd = own & state.alive & has_enemy
    return state._replace(
        order_kind=jnp.where(upd, KIND_ATTACK_MOVE, state.order_kind),
        order_pos=jnp.where(upd[:, None], state.pos[nearest], state.order_pos),
        order_target=jnp.where(upd, -1, state.order_target),
    )


def step(cfg: EnvConfig, state: EnvState,
         action_home: dict, selected_units_num_home,
         action_away: Optional[dict] = None, selected_units_num_away=None):
    """One simultaneous tick. ``action_away=None`` plays the scripted
    opponent. Returns ``(state, reward, done, winner)`` where ``reward`` is
    ``{"battle": f32[2], "winloss": f32[2]}`` (home, away). Once done, the
    state freezes and further steps are zero-reward no-ops (window padding
    semantics — the Anakin loop masks them out)."""
    prev = state
    prev_done = state.done
    team_of = team_vector(cfg)
    U = cfg.units_per_squad
    N = cfg.num_units
    types = unit_types(cfg, state)

    state = _decode_team_action(cfg, state, 0, action_home, selected_units_num_home)
    if action_away is None:
        state = _scripted_orders(cfg, state, 1)
    else:
        state = _decode_team_action(cfg, state, 1, action_away, selected_units_num_away)

    rng_ = jnp.asarray(CATALOG_RANGE)[types] + cfg.hit_slack
    dmg_ = jnp.asarray(CATALOG_DAMAGE)[types]
    spd_ = jnp.asarray(CATALOG_SPEED)[types]
    cd_ = jnp.asarray(CATALOG_COOLDOWN)[types]

    # --- target resolution
    d = jnp.linalg.norm(state.pos[:, None, :] - state.pos[None, :, :], axis=-1)
    enemy = team_of[:, None] != team_of[None, :]
    cand = enemy & state.alive[None, :]
    d_cand = jnp.where(cand, d, jnp.inf)
    nearest = jnp.argmin(d_cand, axis=1)
    nearest_d = jnp.min(d_cand, axis=1)
    explicit = (state.order_kind == KIND_ATTACK_UNIT)
    explicit_ok = explicit & (state.order_target >= 0) \
        & state.alive[jnp.clip(state.order_target, 0, N - 1)]
    # stop/hold and attack-move auto-acquire in range; plain move does not
    # shoot. An explicit attack whose designated target is still out of
    # range ALSO auto-acquires — chasers return fire on the way in instead
    # of marching mutely through the defending squad.
    explicit_dist = d[jnp.arange(N), jnp.clip(state.order_target, 0, N - 1)]
    explicit_near = explicit_ok & (explicit_dist <= rng_)
    auto = (state.order_kind == KIND_STOP) | (state.order_kind == KIND_ATTACK_MOVE) \
        | (explicit_ok & ~explicit_near)
    auto_ok = auto & jnp.isfinite(nearest_d)
    target = jnp.where(explicit_near, jnp.clip(state.order_target, 0, N - 1),
                       jnp.where(auto_ok, nearest, -1))
    t_idx = jnp.clip(target, 0, N - 1)
    t_dist = d[jnp.arange(N), t_idx]
    engaged = (target >= 0) & (t_dist <= rng_)
    shoot = state.alive & engaged & (state.cooldown <= 0.0)

    dmg_in = jnp.zeros(N, jnp.float32).at[t_idx].add(jnp.where(shoot, dmg_, 0.0))
    cooldown = jnp.where(shoot, cd_, jnp.maximum(state.cooldown - 1.0, 0.0))

    # --- movement (attackers in range hold; everyone else follows orders)
    chase = explicit_ok & ~engaged
    dest = jnp.where(
        chase[:, None], state.pos[t_idx],
        jnp.where(((state.order_kind == KIND_MOVE)
                   | (state.order_kind == KIND_ATTACK_MOVE))[:, None],
                  state.order_pos, state.pos))
    dvec = dest - state.pos
    dist = jnp.linalg.norm(dvec, axis=-1)
    stepv = dvec / jnp.maximum(dist, 1e-6)[:, None] \
        * jnp.minimum(spd_, dist)[:, None]
    moving = state.alive & ~engaged & (dist > 1e-3)
    newpos = state.pos + jnp.where(moving[:, None], stepv, 0.0)
    newpos = jnp.clip(newpos, 0.5,
                      jnp.array([MAP_W - 0.5, MAP_H - 0.5], jnp.float32))

    def _passable(p):
        cx = (p[:, 0] // CELL).astype(jnp.int32)
        cy = (p[:, 1] // CELL).astype(jnp.int32)
        return state.scenario.terrain[cy, cx]

    # wall slide: when the full step lands in a blocked cell, fall back to
    # the x-only then y-only component so units skirt walls instead of
    # pinning against them (no pathfinding, but unsticks straight-liners)
    slide_x = jnp.stack([newpos[:, 0], state.pos[:, 1]], axis=-1)
    slide_y = jnp.stack([state.pos[:, 0], newpos[:, 1]], axis=-1)
    cand = jnp.where(_passable(newpos)[:, None], newpos,
                     jnp.where(_passable(slide_x)[:, None], slide_x,
                               jnp.where(_passable(slide_y)[:, None], slide_y,
                                         state.pos)))
    pos = jnp.where(moving[:, None], cand, state.pos)

    # --- health / outcome
    health = jnp.maximum(state.health - dmg_in, 0.0)
    alive = state.alive & (health > 0.0)
    died = state.alive & ~alive
    dealt = jnp.where(shoot, jnp.minimum(dmg_, state.health[t_idx]), 0.0)
    dealt_home = (dealt * (team_of == 0)).sum()
    dealt_away = (dealt * (team_of == 1)).sum()
    kills_home = (died & (team_of == 1)).sum().astype(jnp.float32)
    kills_away = (died & (team_of == 0)).sum().astype(jnp.float32)

    t2 = state.t + 1
    home_alive = alive[:U].any()
    away_alive = alive[U:].any()
    timeout = t2 >= state.scenario.episode_len
    end = (~home_alive) | (~away_alive) | timeout
    hfrac = health[:U].sum() / jnp.maximum(state.max_health[:U].sum(), 1e-6)
    afrac = health[U:].sum() / jnp.maximum(state.max_health[U:].sum(), 1e-6)
    timeout_winner = jnp.where(
        hfrac > afrac + cfg.timeout_margin, WINNER_HOME,
        jnp.where(afrac > hfrac + cfg.timeout_margin, WINNER_AWAY, WINNER_DRAW))
    winner = jnp.where(
        ~end, WINNER_NONE,
        jnp.where(home_alive & ~away_alive, WINNER_HOME,
                  jnp.where(away_alive & ~home_alive, WINNER_AWAY,
                            jnp.where(~home_alive & ~away_alive, WINNER_DRAW,
                                      timeout_winner)))).astype(jnp.int32)

    battle_home = (dealt_home - dealt_away) / cfg.damage_norm
    winloss_home = jnp.where(
        end & (winner == WINNER_HOME), 1.0,
        jnp.where(end & (winner == WINNER_AWAY), -1.0, 0.0))

    new_state = state._replace(
        pos=pos, health=health, cooldown=cooldown, alive=alive,
        t=t2, done=state.done | end, winner=winner,
        dmg_dealt=state.dmg_dealt + jnp.stack([dealt_home, dealt_away]),
        kills=state.kills + jnp.stack([kills_home, kills_away]),
    )
    # freeze after done: padded steps replay the terminal state, zero reward
    new_state = jax.tree.map(
        lambda old, new: jnp.where(prev_done, old, new), prev, new_state)
    live = 1.0 - prev_done.astype(jnp.float32)
    reward = {
        "battle": jnp.stack([battle_home, -battle_home]) * live,
        "winloss": jnp.stack([winloss_home, -winloss_home]) * live,
    }
    return new_state, reward, new_state.done, new_state.winner
