"""Head-to-head win-rate evaluation over fixed PRNG-keyed scenario sets.

The SC2-blocked remainder of PR 15: ``FleetRollout.compare()``'s win-rate
leg gets real episodes here. ``head_to_head`` runs policy A (home) vs
policy B (away) across a batch of scenarios — one jitted ``lax.scan`` to
the timeout, lanes freeze at their terminal step — and reduces final
winner codes to a win-rate summary. The scenario set is a pure function of
the key set, so a student/teacher A/B is reproducible bit-for-bit and both
orderings can be averaged to cancel the home/away asymmetry.

Policies are ``(obs_batch, carry, key) -> (action_info, selected_units_num,
carry)`` with an ``init_carry(batch)`` hook; ``model_policy`` wraps the
flagship ``sample_action`` (LSTM carry threaded), and the scripted
``attack_nearest_policy``/``idle_policy`` are the mock engines the tier-1
compare() test uses.
"""
from __future__ import annotations

import time
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ...lib import features as F
from ...obs import get_registry
from .core import (
    EnvConfig,
    WINNER_AWAY,
    WINNER_HOME,
    micro_legal_mask,
    reset,
    step,
)
from .obs import observe
from .scenario import ScenarioConfig, ScenarioGenerator

ATTACK_UNIT = 3  # contract action_type: Attack_unit


class ScriptedPolicy:
    """Stateless policy from a pure fn(obs_batch, key) -> (action, sun)."""

    def __init__(self, fn: Callable):
        self._fn = fn

    def init_carry(self, batch: int):
        return None

    def __call__(self, obs, carry, key):
        action, sun = self._fn(obs, key)
        return action, sun, carry


def _attack_nearest(obs, key):
    """Select every own unit, focus-fire the enemy slot nearest the squad
    centroid (packed obs puts own alive units first, enemies after —
    core.pack_perm; entity x/y are the rounded px positions)."""
    alliance = obs["entity_info"]["alliance"]          # [B, 512]
    B = alliance.shape[0]
    S = F.MAX_SELECTED_UNITS_NUM
    entity_num = obs["entity_num"].astype(jnp.int32)   # [B]
    slot_ok = jnp.arange(F.MAX_ENTITY_NUM)[None] < entity_num[:, None]
    own = (alliance == 1) & slot_ok
    enemy = (alliance == 4) & slot_ok
    n_own = own.sum(axis=1).astype(jnp.int32)          # [B]
    lane = jnp.arange(S)[None]                          # [1, S]
    su = jnp.where(lane < n_own[:, None], lane,
                   jnp.where(lane == n_own[:, None], entity_num[:, None], 0))
    sun = jnp.minimum(n_own + 1, S)
    ex = obs["entity_info"]["x"].astype(jnp.float32)   # [B, 512]
    ey = obs["entity_info"]["y"].astype(jnp.float32)
    cx = jnp.sum(jnp.where(own, ex, 0.0), axis=1) / jnp.maximum(n_own, 1)
    cy = jnp.sum(jnp.where(own, ey, 0.0), axis=1) / jnp.maximum(n_own, 1)
    d2 = (ex - cx[:, None]) ** 2 + (ey - cy[:, None]) ** 2
    target = jnp.argmin(jnp.where(enemy, d2, jnp.inf), axis=1).astype(jnp.int32)
    # argmin over an all-inf row returns 0; fall back to the first enemy slot
    target = jnp.where(enemy.any(axis=1), target,
                       jnp.minimum(n_own, F.MAX_ENTITY_NUM - 1))
    action = {
        "action_type": jnp.full((B,), ATTACK_UNIT, jnp.int32),
        "delay": jnp.ones((B,), jnp.int32),
        "queued": jnp.zeros((B,), jnp.int32),
        "selected_units": su.astype(jnp.int32),
        "target_unit": target,
        "target_location": jnp.zeros((B,), jnp.int32),
    }
    return action, sun


def _idle(obs, key):
    B = obs["entity_num"].shape[0]
    action = {
        "action_type": jnp.zeros((B,), jnp.int32),
        "delay": jnp.ones((B,), jnp.int32),
        "queued": jnp.zeros((B,), jnp.int32),
        "selected_units": jnp.zeros((B, F.MAX_SELECTED_UNITS_NUM), jnp.int32),
        "target_unit": jnp.zeros((B,), jnp.int32),
        "target_location": jnp.zeros((B,), jnp.int32),
    }
    return action, jnp.ones((B,), jnp.int32)


def attack_nearest_policy() -> ScriptedPolicy:
    return ScriptedPolicy(_attack_nearest)


def idle_policy() -> ScriptedPolicy:
    return ScriptedPolicy(_idle)


class ModelPolicy:
    """sample_action-driven policy with its own LSTM carry."""

    def __init__(self, model, params, restrict_micro: bool = True):
        self.model = model
        self.params = params
        lstm = model.cfg["encoder"]["core_lstm"]
        self._hidden_size = int(lstm["hidden_size"])
        self._hidden_layers = int(lstm["num_layers"])
        self._legal = jnp.asarray(micro_legal_mask()) if restrict_micro else None

    def init_carry(self, batch: int):
        z = jnp.zeros((batch, self._hidden_size), jnp.float32)
        return tuple((z, z) for _ in range(self._hidden_layers))

    def __call__(self, obs, carry, key):
        out = self.model.apply(
            self.params, obs["spatial_info"], obs["entity_info"],
            obs["scalar_info"], obs["entity_num"], carry, key, self._legal,
            method=self.model.sample_action)
        return out["action_info"], out["selected_units_num"], out["hidden_state"]


def model_policy(model, params, restrict_micro: bool = True) -> ModelPolicy:
    return ModelPolicy(model, params, restrict_micro=restrict_micro)


def head_to_head(policy_a, policy_b,
                 episodes: int = 16, seed: int = 0,
                 keys: Optional[jax.Array] = None,
                 env_cfg: EnvConfig = EnvConfig(),
                 scenario_cfg: Optional[ScenarioConfig] = None) -> dict:
    """Policy A (home) vs policy B (away) over a fixed scenario set.

    Returns ``{win_rate, wins, losses, draws, episodes}`` where ``win_rate``
    counts a draw as half a win for A, plus the per-match stats a payoff
    ledger ingests: ``matches`` (one ``{winner, draw, game_steps}`` record
    per episode, in key order), ``mean_game_steps``, and ``duration_s``
    (wall-clock for the whole batch, compile included on first call).
    ``keys`` pins the exact scenario set (e.g. the league's fixed eval
    suite); otherwise ``episodes`` scenarios are derived from ``seed``.
    """
    scenario_cfg = (scenario_cfg if scenario_cfg is not None
                    else ScenarioConfig(units_per_squad=env_cfg.units_per_squad))
    if scenario_cfg.units_per_squad != env_cfg.units_per_squad:
        raise ValueError("scenario_cfg.units_per_squad must match env_cfg")
    gen = ScenarioGenerator(scenario_cfg)
    if keys is None:
        keys = jax.random.split(jax.random.PRNGKey(seed), episodes)
    B = keys.shape[0]
    T = int(scenario_cfg.episode_len)

    observe_b = jax.vmap(partial(observe, env_cfg), in_axes=(0, None))
    step_b = jax.vmap(partial(step, env_cfg))

    def run(keys):
        states = jax.vmap(partial(reset, env_cfg))(jax.vmap(gen.generate)(keys))
        ca = policy_a.init_carry(B)
        cb = policy_b.init_carry(B)

        def body(c, k):
            states, ca, cb = c
            ka, kb = jax.random.split(k)
            act_a, sun_a, ca = policy_a(observe_b(states, 0), ca, ka)
            act_b, sun_b, cb = policy_b(observe_b(states, 1), cb, kb)
            states, _, _, _ = step_b(states, act_a, sun_a, act_b, sun_b)
            return (states, ca, cb), None

        (states, _, _), _ = jax.lax.scan(
            body, (states, ca, cb),
            jax.random.split(jax.random.fold_in(keys[0], 0x5eed), T))
        # lanes freeze after done, so states.t is each lane's terminal step
        return states.winner, states.t

    t0 = time.monotonic()
    winner, game_steps = jax.jit(run)(keys)
    winner = jax.device_get(winner)
    game_steps = jax.device_get(game_steps)
    duration_s = time.monotonic() - t0
    wins = int((winner == WINNER_HOME).sum())
    losses = int((winner == WINNER_AWAY).sum())
    draws = B - wins - losses
    win_rate = (wins + 0.5 * draws) / max(B, 1)
    matches = []
    for i in range(B):
        w = int(winner[i])
        name = {WINNER_HOME: "home", WINNER_AWAY: "away"}.get(w, "draw")
        matches.append({"winner": name, "draw": name == "draw",
                        "game_steps": int(game_steps[i])})
    get_registry().gauge(
        "distar_env_head2head_win_rate",
        "home-side win rate of the last jaxenv head-to-head evaluation",
    ).set(win_rate)
    return {"win_rate": win_rate, "wins": wins, "losses": losses,
            "draws": draws, "episodes": B, "matches": matches,
            "mean_game_steps": float(game_steps.mean()) if B else 0.0,
            "duration_s": duration_s}
