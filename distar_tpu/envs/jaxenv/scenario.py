"""PRNG-keyed scenario parameter structs + the procedural generator.

A Scenario is everything that varies between micro-battle episodes — unit
composition, squad sizes, terrain mask, spawn geometry — as a pytree of
fixed-shape device arrays, so a batch of scenarios is just
``jax.vmap(generator.generate)(keys)`` and procedural curriculum is a pure
function of (key, config). The league's payoff matrix gets its scenario
lever through the key alone: same key + same config => the same battle,
bit for bit (tests/test_jaxenv.py goldens).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ...lib import actions as ACT
from ...lib import features as F

# Spatial geometry: the world IS the contract's spatial rectangle. Positions
# are float (x, y) with x in [0, 160), y in [0, 152); the terrain occupancy
# grid is one cell per CELL*CELL pixel block, sized so it upsamples exactly
# to SPATIAL_SIZE for the pathable/buildable planes.
MAP_H, MAP_W = F.SPATIAL_SIZE  # (y=152, x=160)
CELL = 8
GRID_H, GRID_W = MAP_H // CELL, MAP_W // CELL  # (19, 20)

# Unit catalog: a handful of real SC2 unit types (raw game ids that exist in
# the action contract's 260-type vocabulary) with micro-battle combat stats.
# Columns are parallel arrays so a catalog row gathers in-jit.
CATALOG_RAW_TYPES = np.array([48, 105, 110, 74], dtype=np.int64)  # marine, zergling, roach, stalker
CATALOG_DENSE_TYPES = ACT.UNIT_TYPES_REORDER_ARRAY[CATALOG_RAW_TYPES]
assert (CATALOG_DENSE_TYPES >= 0).all(), "catalog unit ids must be in the contract vocabulary"
CATALOG_HEALTH = np.array([45.0, 35.0, 145.0, 160.0], dtype=np.float32)
CATALOG_DAMAGE = np.array([6.0, 5.0, 16.0, 13.0], dtype=np.float32)
CATALOG_RANGE = np.array([12.0, 3.0, 10.0, 14.0], dtype=np.float32)  # px
CATALOG_SPEED = np.array([2.0, 3.0, 1.5, 2.0], dtype=np.float32)     # px/step
CATALOG_COOLDOWN = np.array([2.0, 1.0, 3.0, 3.0], dtype=np.float32)  # steps between shots
NUM_CATALOG_TYPES = len(CATALOG_RAW_TYPES)


class Scenario(NamedTuple):
    """One episode's parameters (a vmap-able pytree of device arrays)."""

    key: jax.Array          # the generating PRNG key (provenance + folds)
    n_home: jax.Array       # int32 [] live home units (<= units_per_squad)
    n_away: jax.Array       # int32 []
    type_home: jax.Array    # int32 [U] catalog row per slot
    type_away: jax.Array    # int32 [U]
    pos_home: jax.Array     # float32 [U, 2] spawn (x, y)
    pos_away: jax.Array     # float32 [U, 2]
    terrain: jax.Array      # bool [GRID_H, GRID_W], True = passable
    episode_len: jax.Array  # int32 [] env steps until timeout


@dataclass(frozen=True)
class ScenarioConfig:
    """Static knobs of the procedural distribution (hashable: jit-static)."""

    units_per_squad: int = 8      # U: the padded squad width
    min_units: int = 2
    max_units: int = 8            # inclusive; clamped to units_per_squad
    episode_len: int = 64
    blocked_frac: float = 0.12    # fraction of terrain cells impassable
    spawn_spread: float = 12.0    # px of per-unit jitter around the spawn center
    spawn_margin: float = 20.0    # px the spawn centers keep from map edges
    mirror_spawns: bool = True    # away spawn = point mirror of home spawn
    mirror_types: bool = False    # away squad = home's composition + size
    #   (composition-fair episodes: win-rate A/Bs measure the POLICY, not
    #   the catalog matchup lottery)

    def __post_init__(self):
        if not (1 <= self.min_units <= self.max_units <= self.units_per_squad):
            raise ValueError(
                f"need 1 <= min_units <= max_units <= units_per_squad, got "
                f"{self.min_units}/{self.max_units}/{self.units_per_squad}")


class ScenarioGenerator:
    """key -> Scenario, pure and jit/vmap-compatible.

    ``generate`` draws squad sizes, catalog compositions, a blob terrain
    mask, and mirrored spawn clusters; ``batch`` is the vmapped convenience
    used by the Anakin loop and the win-rate evaluator.
    """

    def __init__(self, cfg: ScenarioConfig = ScenarioConfig()):
        self.cfg = cfg

    def generate(self, key: jax.Array) -> Scenario:
        cfg = self.cfg
        U = cfg.units_per_squad
        k_nh, k_na, k_th, k_ta, k_terrain, k_center, k_jh, k_ja = jax.random.split(key, 8)
        n_home = jax.random.randint(k_nh, (), cfg.min_units, cfg.max_units + 1, jnp.int32)
        n_away = jax.random.randint(k_na, (), cfg.min_units, cfg.max_units + 1, jnp.int32)
        type_home = jax.random.randint(k_th, (U,), 0, NUM_CATALOG_TYPES, jnp.int32)
        if cfg.mirror_types:
            n_away = n_home
            type_away = type_home
        else:
            type_away = jax.random.randint(k_ta, (U,), 0, NUM_CATALOG_TYPES, jnp.int32)

        # spawn geometry: home center in the left band, away mirrored (or
        # independently drawn in the right band)
        m = cfg.spawn_margin
        cx = jax.random.uniform(k_center, (), minval=m, maxval=MAP_W / 3.0)
        cy = jax.random.uniform(
            jax.random.fold_in(k_center, 1), (), minval=m, maxval=MAP_H - m)
        home_center = jnp.stack([cx, cy])
        if cfg.mirror_spawns:
            away_center = jnp.stack([MAP_W - cx, MAP_H - cy])
        else:
            ax = jax.random.uniform(
                jax.random.fold_in(k_center, 2), (),
                minval=2.0 * MAP_W / 3.0, maxval=MAP_W - m)
            ay = jax.random.uniform(
                jax.random.fold_in(k_center, 3), (), minval=m, maxval=MAP_H - m)
            away_center = jnp.stack([ax, ay])
        s = cfg.spawn_spread
        pos_home = home_center[None] + jax.random.uniform(k_jh, (U, 2), minval=-s, maxval=s)
        pos_away = away_center[None] + jax.random.uniform(k_ja, (U, 2), minval=-s, maxval=s)
        bound = jnp.array([MAP_W - 1.0, MAP_H - 1.0])
        pos_home = jnp.clip(pos_home, 1.0, bound)
        pos_away = jnp.clip(pos_away, 1.0, bound)

        # blob terrain: iid blocked cells, then guaranteed-passable discs
        # around both spawn clusters so no unit starts inside a wall
        passable = jax.random.uniform(k_terrain, (GRID_H, GRID_W)) >= cfg.blocked_frac
        gy, gx = jnp.mgrid[0:GRID_H, 0:GRID_W]
        cell_center = jnp.stack(  # (x, y) of each cell center, in px
            [gx * CELL + CELL / 2.0, gy * CELL + CELL / 2.0], axis=-1)
        carve_r = cfg.spawn_spread + 1.5 * CELL
        for center in (home_center, away_center):
            d = jnp.linalg.norm(cell_center - center[None, None], axis=-1)
            passable = passable | (d <= carve_r)

        return Scenario(
            key=key,
            n_home=n_home.astype(jnp.int32),
            n_away=n_away.astype(jnp.int32),
            type_home=type_home,
            type_away=type_away,
            pos_home=pos_home.astype(jnp.float32),
            pos_away=pos_away.astype(jnp.float32),
            terrain=passable,
            episode_len=jnp.asarray(cfg.episode_len, jnp.int32),
        )

    def batch(self, key: jax.Array, n: int) -> Scenario:
        """[n] stacked scenarios from n folds of ``key``."""
        return jax.vmap(self.generate)(jax.random.split(key, n))
