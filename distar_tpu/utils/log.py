"""Logging and windowed metric meters.

Covers the roles of the reference's log_helper (TextLogger, VariableRecord,
MoveAverage/EMA meters; reference: distar/ctools/utils/log_helper.py). The
TensorBoard sink is optional — when tensorboardX is unavailable we fall back
to a JSONL scalar sink so training metrics are always recorded.
"""
from __future__ import annotations

import json
import logging
import os
import time
from collections import deque
from typing import Dict, Optional


class AverageMeter:
    """Windowed moving average over the last ``length`` values."""

    def __init__(self, length: int = 100):
        assert length > 0
        self._values: deque = deque(maxlen=length)

    def update(self, value) -> None:
        self._values.append(float(value))

    @property
    def val(self) -> float:
        return self._values[-1] if self._values else 0.0

    @property
    def avg(self) -> float:
        return sum(self._values) / len(self._values) if self._values else 0.0


class EMAMeter:
    """Exponential moving average meter with debias at startup.

    The raw EMA accumulates from zero, so after ``n`` updates it underweights
    by a factor ``1 - alpha**n``; ``avg`` divides that factor back out (Adam-
    style bias correction), making early reads unbiased estimates instead of
    zero-dragged ones."""

    def __init__(self, alpha: float = 0.99):
        assert 0.0 < alpha < 1.0
        self._alpha = alpha
        self._ema = 0.0
        self._count = 0
        self._last = 0.0

    def update(self, value) -> None:
        value = float(value)
        self._last = value
        self._count += 1
        self._ema = self._alpha * self._ema + (1.0 - self._alpha) * value

    @property
    def val(self) -> float:
        return self._last

    @property
    def count(self) -> int:
        return self._count

    @property
    def avg(self) -> float:
        if self._count == 0:
            return 0.0
        return self._ema / (1.0 - self._alpha ** self._count)


class VariableRecord:
    """A named collection of meters with tabulated text rendering.

    Mirrors the role of the reference's VariableRecord (windowed meters keyed
    by variable name, rendered into the iteration log line).
    """

    def __init__(self, length: int = 100):
        self._length = length
        self._meters: Dict[str, AverageMeter] = {}

    def register_var(self, name: str) -> None:
        self._meters.setdefault(name, AverageMeter(self._length))

    def update_var(self, info: Dict[str, float]) -> None:
        for k, v in info.items():
            self.register_var(k)
            self._meters[k].update(v)

    def get(self, name: str) -> AverageMeter:
        return self._meters[name]

    def vars(self):
        return dict(self._meters)

    def get_vars_text(self) -> str:
        rows = [
            "{:<40s} {:>12.5f} {:>12.5f}".format(k, m.val, m.avg)
            for k, m in sorted(self._meters.items())
        ]
        header = "{:<40s} {:>12s} {:>12s}".format("name", "value", "avg")
        return "\n".join([header] + rows)


class TextLogger:
    """File + console logger, one per role/rank."""

    _instances = 0

    def __init__(self, path: str, name: str = "distar_tpu", to_console: bool = True):
        os.makedirs(path, exist_ok=True)
        TextLogger._instances += 1
        self._logger = logging.getLogger(f"{name}.{TextLogger._instances}")
        self._logger.handlers.clear()
        self._logger.setLevel(logging.INFO)
        self._logger.propagate = False
        fmt = logging.Formatter("[%(asctime)s][%(levelname)s] %(message)s")
        fh = logging.FileHandler(os.path.join(path, f"{name}.log"))
        fh.setFormatter(fmt)
        self._logger.addHandler(fh)
        if to_console:
            ch = logging.StreamHandler()
            ch.setFormatter(fmt)
            self._logger.addHandler(ch)

    def info(self, msg: str) -> None:
        self._logger.info(msg)

    def error(self, msg: str) -> None:
        self._logger.error(msg)


class ScalarSink:
    """Scalar metric sink: tensorboardX when available, else JSONL.

    ``force_jsonl`` pins the JSONL backend regardless of tensorboardX —
    used by the metrics-registry exporter (obs.JsonlExporter), whose
    output feeds line-oriented ops tooling, not TB."""

    def __init__(self, path: str, force_jsonl: bool = False):
        os.makedirs(path, exist_ok=True)
        self._tb = None
        if not force_jsonl:
            try:  # pragma: no cover - depends on optional dep
                from tensorboardX import SummaryWriter

                self._tb = SummaryWriter(path)
            except Exception:
                pass
        if self._tb is None:
            self._file = open(os.path.join(path, "scalars.jsonl"), "a")

    def add_scalar(self, name: str, value: float, global_step: int) -> None:
        if self._tb is not None:  # pragma: no cover
            self._tb.add_scalar(name, value, global_step)
        else:
            self._file.write(
                json.dumps(
                    {"ts": time.time(), "step": global_step, "name": name, "value": float(value)}
                )
                + "\n"
            )
            self._file.flush()

    def add_scalars(self, info: Dict[str, float], global_step: int) -> None:
        for k, v in info.items():
            self.add_scalar(k, v, global_step)

    def close(self) -> None:
        """Flush and release the sink (idempotent). Without this the jsonl
        handle lives until interpreter exit — long-lived roles that rotate
        experiment dirs leak one fd per rotation."""
        if self._tb is not None:  # pragma: no cover - optional dep
            try:
                self._tb.close()
            except Exception:
                pass
            self._tb = None
        f, self._file = getattr(self, "_file", None), None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass


def build_logger(path: str, name: str, to_console: bool = True):
    """Return (TextLogger, ScalarSink, VariableRecord) triple for a role."""
    return TextLogger(path, name, to_console), ScalarSink(os.path.join(path, "scalars")), VariableRecord()
