"""Host-keyed persistent-compile-cache paths.

XLA:CPU AOT cache entries bake in the COMPILING machine's CPU feature set;
loading them on a host with different features logs "could lead to
execution errors such as SIGILL" — and this container demonstrably moves
between hosts with different features (observed: entries compiled with
+prefer-no-scatter/+amx-avx512-era flags loaded on a host without them,
followed by segfaults inside backend_compile_and_load). Keying the cache
directory by a hash of the host's CPU flags makes a migrated VM start a
fresh cache instead of executing foreign machine code.
"""
from __future__ import annotations

import hashlib
import os


def _host_cpu_key() -> str:
    # LLVM (and therefore XLA:CPU's machine type) picks the target CPU from
    # family/model/stepping, not the flag list alone — two hosts with
    # identical flags but different models get different machine types, so
    # the key must include the identity lines too (round-4 MULTICHIP run
    # still hit the mismatch warning with a flags-only key)
    ident: list[str] = []
    try:
        with open("/proc/cpuinfo") as f:
            seen_processor = False
            for line in f:
                key = line.split(":", 1)[0].strip()
                # one per-CPU block is enough (all cores are identical);
                # stop at the SECOND block rather than at any single key —
                # ARM lists 'CPU implementer'/'CPU part' AFTER 'Features',
                # so an early break there would drop the identity lines
                if key == "processor":
                    if seen_processor:
                        break
                    seen_processor = True
                # x86 lists 'flags'; ARM lists 'Features'
                if key in ("flags", "Features"):
                    ident.append(" ".join(sorted(line.split(":", 1)[1].split())))
                elif key in ("vendor_id", "cpu family", "model", "model name",
                             "stepping", "CPU implementer", "CPU part"):
                    ident.append(line.split(":", 1)[1].strip())
    except OSError:
        pass
    if ident:
        return hashlib.sha1("|".join(ident).encode()).hexdigest()[:8]
    import platform

    # last resort: the full uname tuple — never hash an empty string, which
    # would give distinct hosts the same key and reintroduce shared caches
    return hashlib.sha1("|".join(platform.uname()).encode()).hexdigest()[:8]


def cache_dir(base: str) -> str:
    """``/tmp/jax_cache_x`` -> ``/tmp/jax_cache_x-<cpu-flags-hash>``."""
    return f"{base}-{_host_cpu_key()}"


def configure(jax, base: str) -> None:
    """Point jax's persistent compile cache at the host-keyed directory."""
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir(base))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:
        # losing the cache means cold multi-minute compiles everywhere the
        # callers warn about — degrade, but never silently
        import logging

        logging.getLogger(__name__).warning(
            "persistent compile cache NOT configured (%r); compiles will be cold", e
        )
