"""Host-keyed persistent-compile-cache paths.

XLA:CPU AOT cache entries bake in the COMPILING machine's CPU feature set;
loading them on a host with different features logs "could lead to
execution errors such as SIGILL" — and this container demonstrably moves
between hosts with different features (observed: entries compiled with
+prefer-no-scatter/+amx-avx512-era flags loaded on a host without them,
followed by segfaults inside backend_compile_and_load). Keying the cache
directory by a hash of the host's CPU flags makes a migrated VM start a
fresh cache instead of executing foreign machine code.
"""
from __future__ import annotations

import hashlib
import os


def _host_cpu_key() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 lists 'flags'; ARM lists 'Features'
                if line.startswith(("flags", "Features")):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    return hashlib.sha1(flags.encode()).hexdigest()[:8]
    except OSError:
        pass
    import platform

    # last resort: the full uname tuple — never hash an empty string, which
    # would give distinct hosts the same key and reintroduce shared caches
    return hashlib.sha1("|".join(platform.uname()).encode()).hexdigest()[:8]


def cache_dir(base: str) -> str:
    """``/tmp/jax_cache_x`` -> ``/tmp/jax_cache_x-<cpu-flags-hash>``."""
    return f"{base}-{_host_cpu_key()}"


def configure(jax, base: str) -> None:
    """Point jax's persistent compile cache at the host-keyed directory."""
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir(base))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:
        # losing the cache means cold multi-minute compiles everywhere the
        # callers warn about — degrade, but never silently
        import logging

        logging.getLogger(__name__).warning(
            "persistent compile cache NOT configured (%r); compiles will be cold", e
        )
