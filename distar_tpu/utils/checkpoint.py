"""Checkpoint save/load + crash-safe auto-checkpoint.

Role of the reference CheckpointHelper (reference: distar/ctools/torch_utils/
checkpoint_helper.py:85-369): pytree save/restore with partial-match loading
and an ``auto_checkpoint`` wrapper that saves on any exception or POSIX
signal. Storage is orbax when available, msgpack (flax serialization)
otherwise — both produce a single self-contained directory/file per step.
"""
from __future__ import annotations

import signal
import traceback
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from . import storage

try:
    from flax import serialization
except Exception:  # pragma: no cover
    serialization = None


class CountVar:
    """A named persistent counter (reference checkpoint_helper.py:281)."""

    def __init__(self, value: int = 0):
        self._value = int(value)

    @property
    def val(self) -> int:
        return self._value

    def add(self, n: int = 1) -> None:
        self._value += n

    def update(self, value: int) -> None:
        self._value = int(value)


def _host_snapshot(state: Any):
    """Device->host COPY of a pytree: the only part of a save that must
    happen before donated buffers are reused by the next train step.

    np.array (not np.asarray): asarray aliases numpy inputs and can alias
    CPU-backend jax buffers — a snapshot that shares memory with donated
    state is silently corrupted by the next step."""
    return jax.tree.map(lambda x: np.array(x) if hasattr(x, "shape") else x, state)


def _write_checkpoint(path: str, host_state: Any, metadata: Optional[Dict]) -> str:
    payload = {"state": host_state, "metadata": metadata or {}}
    blob = serialization.msgpack_serialize(_to_serialisable(payload))
    # scheme-routed (utils/storage.py): local fs by default with atomic
    # tmp+rename and orphan reaping; mem:// / gs:// / custom for pod IO
    storage.write_bytes(path, blob)
    return path


def save_checkpoint(path: str, state: Any, metadata: Optional[Dict] = None) -> str:
    """Serialise a pytree (host-transferred) to ``path`` (msgpack)."""
    return _write_checkpoint(path, _host_snapshot(state), metadata)


class AsyncCheckpointer:
    """Overlap checkpoint serialization + disk IO with training.

    TPU-first divergence from the reference's synchronous torch.save in the
    hot loop (checkpoint_helper.py:125-140): ``save`` snapshots the pytree
    to host memory synchronously (cheap D2H; required before the next step
    reuses the donated buffers), then a single background thread does the
    msgpack serialize + atomic write. At most one save is in flight — a new
    save first joins the previous one, bounding extra host memory to one
    checkpoint copy and keeping file ordering. ``wait()`` drains (call it
    at run end and before any load of a path that may still be writing).
    """

    def __init__(self):
        self._thread = None
        self._error: Optional[BaseException] = None

    def save(self, path: str, state: Any, metadata: Optional[Dict] = None) -> str:
        import threading

        # join BEFORE snapshotting: at most one host copy exists at a time
        # (this also surfaces any previous write failure loudly)
        self.wait()
        host_state = _host_snapshot(state)

        def _write():
            try:
                _write_checkpoint(path, host_state, metadata)
            except BaseException as e:  # surfaced by the next wait()/save()
                self._error = e

        t = threading.Thread(target=_write, name="async-ckpt-writer", daemon=True)
        # start before publishing: a signal handler's sync save between the
        # two statements joins the previous (finished) thread, never an
        # unstarted one
        t.start()
        self._thread = t
        return path

    def wait(self) -> None:
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("async checkpoint write failed") from err


def load_checkpoint(path: str, target: Any = None) -> Dict:
    """Load a checkpoint; when ``target`` is given the state is restored into
    its structure (partial-match: missing leaves keep target values, extra
    leaves are dropped — the reference's partial-load semantics)."""
    payload = serialization.msgpack_restore(storage.read_bytes(path))
    state = payload["state"]
    if target is not None:
        state = _partial_restore(target, state)
    return {"state": state, "metadata": payload.get("metadata", {})}


def load_params(path: str) -> Any:
    """Inference-side load: checkpoint -> bare model params.

    Learner checkpoints carry ``{"params", "opt_state"}``; the optimizer
    state is dead weight for serving/eval, so it is dropped here. Bare
    param pytrees (e.g. converted reference checkpoints) pass through.
    One choke point for every params-only consumer (serve registry,
    play/eval loaders) instead of per-caller ``["state"].get("params")``."""
    state = load_checkpoint(path)["state"]
    if isinstance(state, dict) and "params" in state and "opt_state" in state:
        return state["params"]
    return state


def _to_serialisable(tree):
    if isinstance(tree, dict):
        return {str(k): _to_serialisable(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return {f"__seq_{i}": _to_serialisable(v) for i, v in enumerate(tree)}
    return tree


def _from_seq(d):
    if isinstance(d, dict) and d and all(k.startswith("__seq_") for k in d):
        return [d[f"__seq_{i}"] for i in range(len(d))]
    return d


def _partial_restore(target, state):
    """Overlay ``state`` onto ``target`` structure, matching by path."""
    state = _from_seq(state)
    if isinstance(target, dict):
        out = {}
        src = state if isinstance(state, dict) else {}
        for k, v in target.items():
            out[k] = _partial_restore(v, src[str(k)]) if str(k) in src else v
        return out
    if isinstance(target, (list, tuple)):
        src = state if isinstance(state, (list, dict)) else []
        if isinstance(src, dict):
            src = _from_seq(src)
        vals = [
            _partial_restore(t, src[i]) if i < len(src) else t for i, t in enumerate(target)
        ]
        if hasattr(target, "_fields"):  # NamedTuple (e.g. optax states)
            return type(target)(*vals)
        return type(target)(vals)
    return state if state is not None else target


def auto_checkpoint(save_fn: Callable[[], None]):
    """Wrap a run loop so exceptions and signals trigger ``save_fn`` before
    re-raising (reference checkpoint_helper.py:325-369)."""

    def decorator(fn):
        def wrapped(*args, **kwargs):
            handled = [signal.SIGTERM, signal.SIGINT]
            previous = {}

            def handler(sig, frame):
                save_fn()
                for s, prev in previous.items():
                    signal.signal(s, prev)
                raise SystemExit(f"signal {sig}: checkpoint saved")

            for s in handled:
                try:
                    previous[s] = signal.signal(s, handler)
                except ValueError:  # not main thread
                    pass
            try:
                return fn(*args, **kwargs)
            except SystemExit:
                raise
            except BaseException:
                traceback.print_exc()
                save_fn()
                raise
            finally:
                for s, prev in previous.items():
                    try:
                        signal.signal(s, prev)
                    except ValueError:
                        pass

        return wrapped

    return decorator
