"""Checkpoint save/load + crash-safe auto-checkpoint.

Role of the reference CheckpointHelper (reference: distar/ctools/torch_utils/
checkpoint_helper.py:85-369): pytree save/restore with partial-match loading
and an ``auto_checkpoint`` wrapper that saves on any exception or POSIX
signal. Storage is orbax when available, msgpack (flax serialization)
otherwise — both produce a single self-contained directory/file per step.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
import traceback
import zlib
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from . import storage

try:
    from flax import serialization
except Exception:  # pragma: no cover
    serialization = None


class CheckpointCorruptError(Exception):
    """The checkpoint bytes on storage don't match what was written
    (truncated write, bit rot) or don't decode. Callers holding a
    ``CheckpointManager`` fall back to the previous generation."""


class CheckpointMismatchError(ValueError):
    """The checkpoint decodes fine but does not FIT the state it is being
    restored into (leaf shapes differ — a different model config, or a
    stale ``experiments/`` dir from an unrelated run poisoning auto-resume).
    ``resume_latest`` treats it like corruption: skip the generation, fall
    back, cold-start if nothing fits — never silently train on foreign
    weights."""


def _is_sharded(path: str) -> bool:
    """Sharded checkpoint DIRECTORIES (parallel/ckpt.py) are detected by
    their manifest so every monolithic-path consumer (manager pointer,
    verify, load, learner restore) routes transparently."""
    try:
        return storage.exists(path.rstrip("/") + "/sharding.json")
    except (OSError, ValueError):
        return False


class CountVar:
    """A named persistent counter (reference checkpoint_helper.py:281)."""

    def __init__(self, value: int = 0):
        self._value = int(value)

    @property
    def val(self) -> int:
        return self._value

    def add(self, n: int = 1) -> None:
        self._value += n

    def update(self, value: int) -> None:
        self._value = int(value)


def _host_snapshot(state: Any):
    """Device->host COPY of a pytree: the only part of a save that must
    happen before donated buffers are reused by the next train step.

    np.array (not np.asarray): asarray aliases numpy inputs and can alias
    CPU-backend jax buffers — a snapshot that shares memory with donated
    state is silently corrupted by the next step."""
    return jax.tree.map(lambda x: np.array(x) if hasattr(x, "shape") else x, state)


def _manifest_path(path: str) -> str:
    return path + ".manifest"


def _write_checkpoint(path: str, host_state: Any, metadata: Optional[Dict]) -> str:
    payload = {"state": host_state, "metadata": metadata or {}}
    blob = serialization.msgpack_serialize(_to_serialisable(payload))
    # scheme-routed (utils/storage.py): local fs by default with atomic
    # tmp+fsync+rename and orphan reaping; mem:// / gs:// / custom for pod IO
    storage.write_bytes(path, blob)
    # integrity sidecar AFTER the blob: a manifest's presence implies the
    # blob it describes landed; loads verify size+CRC against it so a
    # truncated/bit-flipped checkpoint is detected instead of half-restored
    manifest = {
        "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
        "size": len(blob),
        "ts": time.time(),
        "metadata_keys": sorted((metadata or {}).keys()),
    }
    storage.write_bytes(_manifest_path(path), json.dumps(manifest).encode())
    return path


def verify_checkpoint(path: str) -> bool:
    """True when ``path`` exists and its bytes match the manifest (or, for
    legacy manifest-less checkpoints, merely exists). Never raises.
    Sharded checkpoint directories verify every shard blob's self-CRC."""
    try:
        if _is_sharded(path):
            from ..parallel import ckpt as _sharded

            _sharded.verify_sharded(path)
            return True
        blob = storage.read_bytes(path)
        _verify_blob(path, blob)
        return True
    except (CheckpointCorruptError, OSError, ValueError):
        return False


def _verify_blob(path: str, blob: bytes) -> None:
    mpath = _manifest_path(path)
    if not storage.exists(mpath):
        return  # legacy checkpoint: decode errors still surface typed below
    try:
        manifest = json.loads(storage.read_bytes(mpath))
    except (ValueError, OSError) as e:
        raise CheckpointCorruptError(f"{path}: unreadable manifest: {e!r}") from e
    if len(blob) != int(manifest.get("size", -1)):
        raise CheckpointCorruptError(
            f"{path}: size {len(blob)} != manifest {manifest.get('size')} (truncated write?)"
        )
    crc = zlib.crc32(blob) & 0xFFFFFFFF
    if crc != int(manifest.get("crc32", -1)):
        raise CheckpointCorruptError(
            f"{path}: crc32 {crc:#010x} != manifest {int(manifest.get('crc32', 0)):#010x}"
        )


def save_checkpoint(path: str, state: Any, metadata: Optional[Dict] = None) -> str:
    """Serialise a pytree (host-transferred) to ``path`` (msgpack) with a
    CRC/size manifest sidecar (``<path>.manifest``)."""
    return _write_checkpoint(path, _host_snapshot(state), metadata)


class AsyncCheckpointer:
    """Overlap checkpoint serialization + disk IO with training.

    TPU-first divergence from the reference's synchronous torch.save in the
    hot loop (checkpoint_helper.py:125-140): ``save`` snapshots the pytree
    to host memory synchronously (cheap D2H; required before the next step
    reuses the donated buffers), then a single background thread does the
    msgpack serialize + atomic write. At most one save is in flight — a new
    save first joins the previous one, bounding extra host memory to one
    checkpoint copy and keeping file ordering. ``wait()`` drains (call it
    at run end and before any load of a path that may still be writing).
    """

    def __init__(self):
        self._thread = None
        self._error: Optional[BaseException] = None

    def save(self, path: str, state: Any, metadata: Optional[Dict] = None,
             on_complete: Optional[Callable[[str], None]] = None,
             snapshot_fn: Optional[Callable[[Any], Any]] = None,
             write_fn: Optional[Callable[[str, Any, Optional[Dict]], str]] = None) -> str:
        # join BEFORE snapshotting: at most one host copy exists at a time
        # (this also surfaces any previous write failure loudly)
        self.wait()
        # snapshot/write are pluggable so sharded checkpoints
        # (parallel/ckpt.py: per-shard D2H, then per-shard blob writes)
        # reuse the same one-in-flight/durable-pointer discipline
        host_state = (snapshot_fn or _host_snapshot)(state)
        writer = write_fn or _write_checkpoint

        def _write():
            try:
                writer(path, host_state, metadata)
                if on_complete is not None:
                    # latest-pointer publication rides the writer thread: the
                    # pointer must never name a checkpoint that isn't durable
                    on_complete(path)
            except BaseException as e:  # surfaced by the next wait()/save()
                self._error = e

        t = threading.Thread(target=_write, name="async-ckpt-writer", daemon=True)
        # start before publishing: a signal handler's sync save between the
        # two statements joins the previous (finished) thread, never an
        # unstarted one
        t.start()
        self._thread = t
        return path

    def wait(self) -> None:
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("async checkpoint write failed") from err


def load_checkpoint(path: str, target: Any = None, verify: bool = True) -> Dict:
    """Load a checkpoint; when ``target`` is given the state is restored into
    its structure (partial-match: missing leaves keep target values, extra
    leaves are dropped — the reference's partial-load semantics).

    With ``verify`` (default) the blob is checked against its manifest
    sidecar, and decode failures are raised as ``CheckpointCorruptError`` —
    corrupt/truncated checkpoints are DETECTED here, so resume paths can
    fall back to the previous generation instead of restoring garbage.

    Sharded checkpoint directories (parallel/ckpt.py) route to the
    resharding restore — same return shape, plus a ``sharding_layout``
    key; callers that only read state/metadata don't notice."""
    if _is_sharded(path):
        from ..parallel import ckpt as _sharded

        return _sharded.restore_sharded(path, target=target, verify=verify)
    blob = storage.read_bytes(path)
    if verify:
        _verify_blob(path, blob)
    try:
        payload = serialization.msgpack_restore(blob)
    except Exception as e:
        raise CheckpointCorruptError(f"{path}: undecodable msgpack: {e!r}") from e
    state = payload["state"]
    if target is not None:
        state = _partial_restore(target, state)
    return {"state": state, "metadata": payload.get("metadata", {})}


def load_params(path: str) -> Any:
    """Inference-side load: checkpoint -> bare model params.

    Learner checkpoints carry ``{"params", "opt_state"}``; the optimizer
    state is dead weight for serving/eval, so it is dropped here. Bare
    param pytrees (e.g. converted reference checkpoints) pass through.
    One choke point for every params-only consumer (serve registry,
    play/eval loaders) instead of per-caller ``["state"].get("params")``."""
    state = load_checkpoint(path)["state"]
    if isinstance(state, dict) and "params" in state and "opt_state" in state:
        return state["params"]
    return state


def _to_serialisable(tree):
    if isinstance(tree, dict):
        return {str(k): _to_serialisable(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return {f"__seq_{i}": _to_serialisable(v) for i, v in enumerate(tree)}
    return tree


def _from_seq(d):
    if isinstance(d, dict) and d and all(k.startswith("__seq_") for k in d):
        return [d[f"__seq_{i}"] for i in range(len(d))]
    return d


def _partial_restore(target, state):
    """Overlay ``state`` onto ``target`` structure, matching by path."""
    state = _from_seq(state)
    if isinstance(target, dict):
        out = {}
        src = state if isinstance(state, dict) else {}
        for k, v in target.items():
            out[k] = _partial_restore(v, src[str(k)]) if str(k) in src else v
        return out
    if isinstance(target, (list, tuple)):
        src = state if isinstance(state, (list, dict)) else []
        if isinstance(src, dict):
            src = _from_seq(src)
        vals = [
            _partial_restore(t, src[i]) if i < len(src) else t for i, t in enumerate(target)
        ]
        if hasattr(target, "_fields"):  # NamedTuple (e.g. optax states)
            return type(target)(*vals)
        return type(target)(vals)
    return state if state is not None else target


class CheckpointManager:
    """Durable ``latest`` pointer over checkpoint generations.

    A crash-resuming learner needs one answer to "where do I restart from":
    ``latest.json`` in the checkpoint directory holds the newest-first list
    of recorded generations, written atomically (storage's tmp+fsync+rename)
    so a crash mid-update leaves the previous pointer intact.
    ``resolve_latest`` walks the list and returns the first generation whose
    bytes still verify — a truncated or bit-flipped newest checkpoint falls
    back to the previous one (counted in
    ``distar_resilience_ckpt_fallbacks_total`` + a flight-recorder event).

    ``role`` partitions generations within one checkpoint directory: a
    manager with ``role="student"`` records into ``latest_student.json``
    and stamps each generation with the role, and ``generations()``
    additionally filters entries by role — so a teacher's crash-resume can
    NEVER pick a distillation-student generation (or vice versa) even if
    both roles share a directory or a pointer file is hand-edited. The
    empty role is the teacher/default tier (the historical ``latest.json``,
    unchanged on disk).
    """

    POINTER = "latest.json"

    def __init__(self, directory: str, keep: int = 5, role: str = ""):
        assert keep >= 1
        self.directory = directory
        self.keep = keep
        self.role = str(role or "")
        self._lock = threading.Lock()

    @property
    def pointer_path(self) -> str:
        name = self.POINTER if not self.role else f"latest_{self.role}.json"
        return os.path.join(self.directory, name)

    # -------------------------------------------------------------- recording
    def record(self, path: str, step: int = 0) -> None:
        """Publish ``path`` as the newest generation. Call only after the
        checkpoint bytes are durable (sync save return / async on_complete)."""
        with self._lock:
            gens = [g for g in self.generations() if g.get("path") != path]
            entry = {"path": path, "step": int(step), "ts": time.time()}
            if self.role:
                entry["role"] = self.role
            gens.insert(0, entry)
            gens = gens[: self.keep]
            storage.write_bytes(
                self.pointer_path,
                json.dumps({"generations": gens}, indent=1).encode(),
            )

    def generations(self) -> List[Dict]:
        """Recorded generations, newest first ([] when no pointer yet)."""
        if not storage.exists(self.pointer_path):
            return []
        try:
            data = json.loads(storage.read_bytes(self.pointer_path))
        except (ValueError, OSError):
            return []  # torn pointer: treated as no-resume, not a crash
        gens = data.get("generations", [])
        # role filter: even a hand-merged pointer file cannot hand this
        # role another role's generation (the resume-isolation contract)
        return [g for g in gens if isinstance(g, dict) and g.get("path")
                and str(g.get("role", "") or "") == self.role]

    # -------------------------------------------------------------- resolving
    def resolve_latest(self) -> Optional[Dict]:
        """Newest generation whose checkpoint still verifies, or None.
        Invalid generations are skipped (observably), not deleted — forensics
        may want the corrupt bytes."""
        for gen in self.generations():
            if verify_checkpoint(gen["path"]):
                return gen
            self._note_fallback(gen["path"])
        return None

    @staticmethod
    def _note_fallback(path: str) -> None:
        from ..obs import get_flight_recorder, get_registry

        get_registry().counter(
            "distar_resilience_ckpt_fallbacks_total",
            "corrupt/missing checkpoint generations skipped on resume",
        ).inc()
        get_flight_recorder().record("ckpt_fallback", path=path)

    def load_latest(self, target: Any = None) -> Optional[Dict]:
        """Load the newest valid generation (manifest-verified); None when no
        generation survives. The load itself can still race a concurrent
        corruption — a ``CheckpointCorruptError`` here falls through to the
        next generation."""
        for gen in self.generations():
            try:
                out = load_checkpoint(gen["path"], target=target, verify=True)
            except (CheckpointCorruptError, OSError, ValueError):
                self._note_fallback(gen["path"])
                continue
            out["path"] = gen["path"]
            return out
        return None


def auto_checkpoint(save_fn: Callable[[], None]):
    """Wrap a run loop so exceptions and signals trigger ``save_fn`` before
    re-raising (reference checkpoint_helper.py:325-369)."""

    def decorator(fn):
        def wrapped(*args, **kwargs):
            handled = [signal.SIGTERM, signal.SIGINT]
            previous = {}

            def handler(sig, frame):
                save_fn()
                for s, prev in previous.items():
                    signal.signal(s, prev)
                raise SystemExit(f"signal {sig}: checkpoint saved")

            for s in handled:
                try:
                    previous[s] = signal.signal(s, handler)
                except ValueError:  # not main thread
                    pass
            try:
                return fn(*args, **kwargs)
            except SystemExit:
                raise
            except BaseException:
                traceback.print_exc()
                save_fn()
                raise
            finally:
                for s, prev in previous.items():
                    try:
                        signal.signal(s, prev)
                    except ValueError:
                        pass

        return wrapped

    return decorator
