"""Timing helpers.

``EasyTimer`` mirrors the reference's CUDA-event-aware timer
(distar/ctools/utils/time_helper.py) — on TPU the analogue of a device sync
is ``jax.block_until_ready`` on the step outputs, which callers invoke before
leaving the timed region (the timer itself stays device-agnostic).
"""
from __future__ import annotations

import functools
import threading
import time


class EasyTimer:
    """Context-manager wall-clock timer: ``with timer: ...; timer.value``."""

    def __init__(self):
        self.value = 0.0
        self._start = 0.0

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.value = time.perf_counter() - self._start
        return False


class StopWatch:
    """Hierarchical named profiler, role of pysc2's stopwatch.sw decorator.

    Thread-safe: actor env-worker threads and comm pull loops record into the
    same instance concurrently (one lock around the per-name lists; the
    timed regions themselves run lock-free)."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self.times = {}
        self._lock = threading.Lock()

    def __call__(self, name: str):
        return _SWContext(self, name)

    def decorate(self, name: str):
        def wrapper(fn):
            @functools.wraps(fn)
            def inner(*args, **kwargs):
                with self(name):
                    return fn(*args, **kwargs)

            return inner

        return wrapper

    def _record(self, name: str, dt: float) -> None:
        with self._lock:
            self.times.setdefault(name, []).append(dt)

    def summary(self):
        with self._lock:
            snap = {k: list(v) for k, v in self.times.items()}
        return {
            k: {"sum": sum(v), "num": len(v), "avg": sum(v) / len(v)}
            for k, v in snap.items()
            if v
        }

    def report(self, registry=None, prefix: str = "distar_stopwatch") -> dict:
        """Publish the summary into the metrics registry (histogram per
        name, fed from the raw samples) and reset the sample store; returns
        the summary that was published. The reset makes repeated reports
        incremental — samples are never double-counted."""
        from ..obs import get_registry

        reg = registry or get_registry()
        with self._lock:
            snap, self.times = self.times, {}
        summary = {}
        for name, samples in snap.items():
            if not samples:
                continue
            hist = reg.histogram(f"{prefix}_seconds", "stopwatch timed regions", region=name)
            for dt in samples:
                hist.observe(dt)
            summary[name] = {
                "sum": sum(samples),
                "num": len(samples),
                "avg": sum(samples) / len(samples),
            }
        return summary


class _SWContext:
    def __init__(self, sw: StopWatch, name: str):
        self._sw = sw
        self._name = name
        self._start = 0.0

    def __enter__(self):
        if self._sw.enabled:
            self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._sw.enabled:
            self._sw._record(self._name, time.perf_counter() - self._start)
        return False


sw = StopWatch()
