"""Timing helpers.

``EasyTimer`` mirrors the reference's CUDA-event-aware timer
(distar/ctools/utils/time_helper.py) — on TPU the analogue of a device sync
is ``jax.block_until_ready`` on the step outputs, which callers invoke before
leaving the timed region (the timer itself stays device-agnostic).
"""
from __future__ import annotations

import functools
import time


class EasyTimer:
    """Context-manager wall-clock timer: ``with timer: ...; timer.value``."""

    def __init__(self):
        self.value = 0.0
        self._start = 0.0

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.value = time.perf_counter() - self._start
        return False


class StopWatch:
    """Hierarchical named profiler, role of pysc2's stopwatch.sw decorator."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self.times = {}

    def __call__(self, name: str):
        return _SWContext(self, name)

    def decorate(self, name: str):
        def wrapper(fn):
            @functools.wraps(fn)
            def inner(*args, **kwargs):
                with self(name):
                    return fn(*args, **kwargs)

            return inner

        return wrapper

    def summary(self):
        return {
            k: {"sum": sum(v), "num": len(v), "avg": sum(v) / len(v)}
            for k, v in self.times.items()
            if v
        }


class _SWContext:
    def __init__(self, sw: StopWatch, name: str):
        self._sw = sw
        self._name = name
        self._start = 0.0

    def __enter__(self):
        if self._sw.enabled:
            self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._sw.enabled:
            self._sw.times.setdefault(self._name, []).append(time.perf_counter() - self._start)
        return False


sw = StopWatch()
