"""Pluggable byte-blob storage backends, routed by URL scheme.

Role of the reference file_helper's multi-backend payload IO (reference:
distar/ctools/utils/file_helper.py:30-32 routes read/save through
ceph/memcached/redis paths next to the local-fs default). The TPU-pod
analogue of ceph is GCS, and the memcached role (a shared in-memory blob
store for hot payloads) is covered by the in-process ``mem://`` backend —
useful in tests and single-host runs; a networked store can register its
own backend without touching any call site.

Schemes:
  * plain paths / ``file://``  -> LocalBackend (atomic tmp+rename writes)
  * ``mem://``                 -> MemBackend (process-local dict)
  * ``gs://``                  -> GcsBackend (stub: raises with guidance
                                  until google-cloud-storage is installed;
                                  nothing in this image may pip install)

``utils.checkpoint`` and ``comm.serializer.save_payload/load_payload``
route through here, so checkpoints, league snapshots and trajectory
payloads can live on any registered backend.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Iterable, Tuple


class StorageBackend:
    """Byte-blob store. Paths are backend-native (scheme stripped)."""

    def write_bytes(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def list(self, prefix: str) -> Iterable[str]:
        raise NotImplementedError


class LocalBackend(StorageBackend):
    """Local filesystem with the atomic write discipline checkpoints need:
    unique tmp + fsync + os.replace (a crash-path sync save can race an
    in-flight async writer on the same target; distinct tmps keep both
    complete), and reaping of orphaned tmps from SIGKILLed writers. The
    fsync matters for crash-resume: without it a machine death after
    os.replace can surface a zero-length "complete" file — exactly the
    torn state the checkpoint manifest check exists to catch, but the
    latest-pointer itself must never be torn."""

    def write_bytes(self, path: str, data: bytes) -> None:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            try:  # durably order the rename itself (best-effort: not all
                dirfd = os.open(parent, os.O_RDONLY)  # filesystems allow it)
                try:
                    os.fsync(dirfd)
                finally:
                    os.close(dirfd)
            except OSError:
                pass
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        import glob

        for stale in glob.glob(glob.escape(path) + ".tmp.*"):
            try:
                if time.time() - os.path.getmtime(stale) > 600:
                    os.unlink(stale)
            except OSError:
                pass

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def delete(self, path: str) -> None:
        os.unlink(path)

    def list(self, prefix: str) -> Iterable[str]:
        import glob

        return sorted(glob.glob(prefix + "*"))


class MemBackend(StorageBackend):
    """Process-local blob dict — the memcached-role backend for tests and
    single-host runs."""

    def __init__(self):
        self._blobs: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def write_bytes(self, path: str, data: bytes) -> None:
        with self._lock:
            self._blobs[path] = bytes(data)

    def read_bytes(self, path: str) -> bytes:
        with self._lock:
            if path not in self._blobs:
                raise FileNotFoundError(f"mem://{path}")
            return self._blobs[path]

    def exists(self, path: str) -> bool:
        with self._lock:
            return path in self._blobs

    def delete(self, path: str) -> None:
        with self._lock:
            if path not in self._blobs:
                raise FileNotFoundError(f"mem://{path}")
            del self._blobs[path]

    def list(self, prefix: str) -> Iterable[str]:
        with self._lock:
            return sorted(k for k in self._blobs if k.startswith(prefix))


class GcsBackend(StorageBackend):
    """GCS stub: the pod-scale analogue of the reference's ceph path. The
    client library is not in this image (and installing is out of scope);
    every call raises with the wiring a deployment needs."""

    _HINT = (
        "gs:// storage needs the google-cloud-storage client, which is not "
        "bundled. Install it in your deployment image and register a real "
        "backend: storage.register_backend('gs', YourGcsBackend())."
    )

    def _unavailable(self):
        try:
            import google.cloud.storage  # noqa: F401  (present in real pods)
        except ImportError as e:
            raise RuntimeError(self._HINT) from e
        raise RuntimeError(
            "google-cloud-storage is importable but the bundled GcsBackend "
            "is a stub; register a real backend via register_backend()."
        )

    def write_bytes(self, path, data):
        self._unavailable()

    def read_bytes(self, path):
        self._unavailable()

    def exists(self, path):
        self._unavailable()

    def delete(self, path):
        self._unavailable()

    def list(self, prefix):
        self._unavailable()


_BACKENDS: Dict[str, StorageBackend] = {
    "file": LocalBackend(),
    "mem": MemBackend(),
    "gs": GcsBackend(),
}


def register_backend(scheme: str, backend: StorageBackend) -> None:
    _BACKENDS[scheme] = backend


def resolve(path: str) -> Tuple[StorageBackend, str]:
    """``scheme://rest`` -> (backend, rest); schemeless paths are local.
    Windows drive letters ("C:/...") are not schemes: a scheme needs '://'."""
    if "://" in path:
        scheme, rest = path.split("://", 1)
        backend = _BACKENDS.get(scheme)
        if backend is None:
            raise ValueError(f"no storage backend registered for {scheme}://")
        return backend, rest
    return _BACKENDS["file"], path


def write_bytes(path: str, data: bytes) -> None:
    backend, rest = resolve(path)
    backend.write_bytes(rest, data)


def read_bytes(path: str) -> bytes:
    backend, rest = resolve(path)
    return backend.read_bytes(rest)


def exists(path: str) -> bool:
    backend, rest = resolve(path)
    return backend.exists(rest)


def delete(path: str) -> None:
    backend, rest = resolve(path)
    backend.delete(rest)
