"""Layered YAML config cascade.

The reference framework merges a per-module ``*_default_config.yaml`` with the
user config at every constructor via ``deep_merge_dicts``
(reference: distar/ctools/utils/config_helper.py). We keep the same cascade
semantics but carry configs in an attribute-accessible dict (``Config``)
instead of EasyDict.
"""
from __future__ import annotations

import copy
import os
from typing import Any, Dict, Mapping

import yaml


class Config(dict):
    """A dict with attribute access, recursively applied. YAML-friendly."""

    def __init__(self, *args, **kwargs):
        super().__init__()
        d = dict(*args, **kwargs)
        for k, v in d.items():
            self[k] = v

    @staticmethod
    def _wrap(value: Any) -> Any:
        if isinstance(value, Mapping) and not isinstance(value, Config):
            return Config(value)
        if isinstance(value, (list, tuple)):
            return type(value)(Config._wrap(v) for v in value)
        return value

    def __setitem__(self, key, value):
        super().__setitem__(key, Config._wrap(value))

    def __setattr__(self, key, value):
        self[key] = value

    def __getattr__(self, key):
        try:
            return self[key]
        except KeyError as e:
            raise AttributeError(key) from e

    def __deepcopy__(self, memo):
        return Config({k: copy.deepcopy(v, memo) for k, v in self.items()})

    def to_dict(self) -> Dict[str, Any]:
        return _unwrap(self)


def _unwrap(value: Any) -> Any:
    """Recursively convert Config/Mapping nodes back to plain dicts."""
    if isinstance(value, Mapping):
        return {k: _unwrap(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return type(value)(_unwrap(v) for v in value)
    return value


def deep_merge_dicts(base: Mapping, override: Mapping) -> Config:
    """Return a new Config = base overridden by ``override``, recursively.

    Semantics match the reference's deep_merge_dicts: nested dicts merge
    key-by-key, any non-dict value in ``override`` wins wholesale.
    """
    out = Config(copy.deepcopy(dict(base)))
    for k, v in override.items():
        if isinstance(v, Mapping) and isinstance(out.get(k), Mapping):
            out[k] = deep_merge_dicts(out[k], v)
        else:
            out[k] = copy.deepcopy(v)
    return out


def read_config(path: str) -> Config:
    """Load a YAML file into a Config. Raises FileNotFoundError when absent
    (optional layers should check existence and pass {})."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    with open(path, "r") as f:
        data = yaml.safe_load(f)
    return Config(data or {})


def save_config(cfg: Mapping, path: str) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    body = _unwrap(cfg)
    with open(path, "w") as f:
        yaml.safe_dump(body, f, default_flow_style=False, sort_keys=False)
