from .config import Config, deep_merge_dicts, read_config, save_config
from .log import TextLogger, VariableRecord, AverageMeter, EMAMeter, build_logger
from .timing import EasyTimer

__all__ = [
    "Config",
    "deep_merge_dicts",
    "read_config",
    "save_config",
    "TextLogger",
    "VariableRecord",
    "AverageMeter",
    "EMAMeter",
    "EasyTimer",
    "build_logger",
]
