"""Process-wide metrics registry: counters, gauges, bounded-reservoir histograms.

The substrate the rest of the stack publishes into (Podracer-style dataflow
telemetry, arxiv 2104.06272: per-stage timing, queue gauges, throughput
counters on every hop of the actor→learner loop). One registry per process;
every instrument is thread-safe — actor env-worker threads, comm pull loops
and the learner run loop all write concurrently.

Naming convention (docs/observability.md): ``distar_<subsystem>_<name>_<unit>``
with ``_total`` for counters. Labels are for *bounded* dimensions only
(token, race, hop — never per-trajectory ids).
"""
from __future__ import annotations

import re
import threading
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> LabelKey:
    for k in labels:
        if not _LABEL_RE.match(k):
            raise ValueError(f"invalid label name {k!r}")
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter. ``inc`` with a negative amount raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter can only go up (inc {amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value; set/inc/dec."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Distribution over a bounded reservoir (last ``reservoir`` observations)
    plus lifetime count/sum. Quantiles come from the reservoir — recent-window
    semantics, which for step-time/latency series is what operators want."""

    def __init__(self, reservoir: int = 1024):
        assert reservoir > 0
        self._lock = threading.Lock()
        self._reservoir: deque = deque(maxlen=reservoir)
        self._count = 0
        self._sum = 0.0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._reservoir.append(value)
            self._count += 1
            self._sum += value

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile over the reservoir (0.0 when empty)."""
        assert 0.0 <= q <= 1.0
        with self._lock:
            if not self._reservoir:
                return 0.0
            ordered = sorted(self._reservoir)
        idx = min(len(ordered) - 1, max(0, int(q * len(ordered))))
        return ordered[idx]

    def quantiles(self, qs: Iterable[float]) -> Dict[float, float]:
        with self._lock:
            ordered = sorted(self._reservoir)
        out = {}
        for q in qs:
            if not ordered:
                out[q] = 0.0
            else:
                out[q] = ordered[min(len(ordered) - 1, max(0, int(q * len(ordered))))]
        return out


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Thread-safe instrument store keyed by (name, labelset).

    The same (name, labels) always returns the same instrument; re-declaring a
    name with a different type raises (one name = one metric family)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._types: Dict[str, str] = {}
        self._helps: Dict[str, str] = {}
        self._metrics: Dict[str, Dict[LabelKey, object]] = {}
        self._hist_reservoir: Dict[str, int] = {}

    def _get(self, kind: str, name: str, help: str, labels: Dict[str, str], **kwargs):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        key = _label_key(labels)
        with self._lock:
            existing = self._types.get(name)
            if existing is not None and existing != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {existing}, not {kind}"
                )
            self._types[name] = kind
            if help and not self._helps.get(name):
                self._helps[name] = help
            family = self._metrics.setdefault(name, {})
            inst = family.get(key)
            if inst is None:
                inst = _TYPES[kind](**kwargs)
                family[key] = inst
            return inst

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get("counter", name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "", reservoir: int = 1024, **labels) -> Histogram:
        with self._lock:
            # all series of one family share the reservoir size (first wins)
            reservoir = self._hist_reservoir.setdefault(name, reservoir)
        return self._get("histogram", name, help, labels, reservoir=reservoir)

    # ------------------------------------------------------------- inspection
    def collect(self) -> List[dict]:
        """Stable snapshot: [{name, type, help, series: [(labels, instrument)]}]
        sorted by name then labelset (deterministic rendering)."""
        with self._lock:
            names = sorted(self._metrics)
            out = []
            for name in names:
                out.append(
                    {
                        "name": name,
                        "type": self._types[name],
                        "help": self._helps.get(name, ""),
                        "series": sorted(self._metrics[name].items()),
                    }
                )
            return out

    def snapshot(self) -> Dict[str, float]:
        """Flat scalar view ``name{k=v,...} -> value`` (histograms expand to
        _count/_sum/p50/p99) — the JSONL exporter's input."""
        flat: Dict[str, float] = {}
        for fam in self.collect():
            for key, inst in fam["series"]:
                suffix = "{" + ",".join(f"{k}={v}" for k, v in key) + "}" if key else ""
                base = fam["name"] + suffix
                if fam["type"] == "histogram":
                    flat[base + "_count"] = float(inst.count)
                    flat[base + "_sum"] = inst.sum
                    qs = inst.quantiles((0.5, 0.99))
                    flat[base + "_p50"] = qs[0.5]
                    flat[base + "_p99"] = qs[0.99]
                else:
                    flat[base] = inst.value
        return flat


_registry_lock = threading.Lock()
_registry: Optional[MetricsRegistry] = None


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (created on first use)."""
    global _registry
    reg = _registry
    if reg is not None:  # lock-free fast path: hot paths call this per event
        return reg
    with _registry_lock:
        if _registry is None:
            _registry = MetricsRegistry()
        return _registry


def set_registry(registry: Optional[MetricsRegistry]) -> MetricsRegistry:
    """Swap the process default (tests install a fresh one); returns the
    previous registry (None when unset)."""
    global _registry
    with _registry_lock:
        prev = _registry
        _registry = registry
        return prev
