"""TSDB-lite: bounded ring-buffer time-series over registry snapshots.

The consumption side of the metrics registry (the registry itself is
point-in-time: counters/gauges answer "what is the value now", never "what
was it 30 seconds ago"). A ``TimeSeriesStore`` keeps a small ring of
``(ts, value)`` points per flattened-snapshot key, keyed additionally by
*source* so one store can hold the whole fleet (the coordinator ingests
shipped snapshots from every actor/learner/serve process; see
``obs/shipper.py``). Windowed queries (last/mean/min/max/rate over the most
recent N seconds) are what the health rules engine (``obs/health.py``)
evaluates.

Memory is bounded by construction: ``points_per_series`` ring slots x
``max_series`` series — a few MB at the defaults, independent of run length.
No external deps; everything is stdlib + threads.
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from .registry import MetricsRegistry, get_registry

SeriesKey = Tuple[str, str]  # (source, name)


class TimeSeriesStore:
    """Thread-safe bounded store of (ts, value) rings keyed by (source, name)."""

    def __init__(self, points_per_series: int = 240, max_series: int = 4096):
        assert points_per_series > 0 and max_series > 0
        self._points = points_per_series
        self._max_series = max_series
        self._lock = threading.Lock()
        self._series: Dict[SeriesKey, deque] = {}
        self._source_seen: Dict[str, float] = {}
        self._dropped = 0  # series refused past the max_series cap
        self._evicted = 0  # series reclaimed when their source departed

    # ------------------------------------------------------------------ write
    def record(self, name: str, value: float, ts: Optional[float] = None,
               source: str = "local") -> bool:
        """Append one point; returns False when the series cap refused a NEW
        series (existing series always accept)."""
        ts = time.time() if ts is None else float(ts)
        key = (source, name)
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                if len(self._series) >= self._max_series:
                    self._dropped += 1
                    return False
                ring = deque(maxlen=self._points)
                self._series[key] = ring
            ring.append((ts, float(value)))
            prev = self._source_seen.get(source, 0.0)
            if ts > prev:
                self._source_seen[source] = ts
            return True

    def record_snapshot(self, snapshot: Dict[str, float], ts: Optional[float] = None,
                        source: str = "local") -> int:
        """Append one point per scalar of a flattened registry snapshot
        (``MetricsRegistry.snapshot()`` keys); returns the number recorded."""
        ts = time.time() if ts is None else float(ts)
        n = 0
        for name, value in snapshot.items():
            try:
                value = float(value)
            except (TypeError, ValueError):
                continue
            if self.record(name, value, ts=ts, source=source):
                n += 1
        return n

    def evict_source(self, source: str) -> int:
        """Reclaim every series a departed source left behind. Without this
        an elastic fleet exhausts the ``max_series`` cap permanently: each
        drained/evicted member's series sit in their rings forever and
        ``record`` refuses every NEW series from its replacement. The
        coordinator calls this (via ``TelemetryIngest.evict_endpoint``)
        whenever an endpoint's lease expires or it deregisters. Returns the
        number of series evicted (counted in
        ``distar_obs_series_evicted_total``)."""
        with self._lock:
            dead = [k for k in self._series if k[0] == source]
            for k in dead:
                del self._series[k]
            self._source_seen.pop(source, None)
            self._evicted += len(dead)
        if dead:
            get_registry().counter(
                "distar_obs_series_evicted_total",
                "TSDB series reclaimed because their source's lease expired "
                "or it deregistered",
            ).inc(len(dead))
        return len(dead)

    # ------------------------------------------------------------------- read
    def names(self, source: Optional[str] = None) -> List[str]:
        with self._lock:
            return sorted({n for (s, n) in self._series if source is None or s == source})

    def sources(self) -> Dict[str, dict]:
        """Per-source last-seen accounting: {source: {last_ts, age_s, series}}."""
        now = time.time()
        with self._lock:
            counts: Dict[str, int] = {}
            for (s, _n) in self._series:
                counts[s] = counts.get(s, 0) + 1
            return {
                s: {
                    "last_ts": last,
                    "age_s": max(0.0, now - last),
                    "series": counts.get(s, 0),
                }
                for s, last in self._source_seen.items()
            }

    def matching_names(self, metric: str, source: Optional[str] = None) -> List[str]:
        """Series keys for a metric reference: the exact flattened key, or —
        for a labelled family — every series of the family (``metric{...}``
        prefix). Lets rules name a family (``distar_coordinator_queue_depth``)
        and cover all its tokens."""
        prefix = metric + "{"
        return [n for n in self.names(source)
                if n == metric or n.startswith(prefix)]

    def query(self, name: str, window_s: float = 60.0,
              source: Optional[str] = None) -> Optional[dict]:
        """Windowed aggregate over the most recent ``window_s`` seconds of one
        series. ``source=None`` picks the single source holding the series
        when unambiguous, else the freshest. Returns None for unknown series
        or an empty window. ``rate`` is (last-first)/(t_last-t_first) — the
        counter-increase slope; 0.0 for a flat window, None with <2 points."""
        with self._lock:
            if source is None:
                candidates = [(s, n) for (s, n) in self._series if n == name]
                if not candidates:
                    return None
                key = max(candidates, key=lambda k: self._series[k][-1][0]
                          if self._series[k] else 0.0)
            else:
                key = (source, name)
                if key not in self._series:
                    return None
            pts = list(self._series[key])
        if not pts:
            return None
        cutoff = pts[-1][0] - float(window_s)
        window = [(t, v) for (t, v) in pts if t >= cutoff]
        if not window:
            return None
        values = [v for (_t, v) in window]
        finite = [v for v in values if math.isfinite(v)]
        t0, v0 = window[0]
        t1, v1 = window[-1]
        rate: Optional[float] = None
        if len(window) >= 2 and t1 > t0:
            rate = (v1 - v0) / (t1 - t0)
        elif len(window) >= 2:
            rate = 0.0
        return {
            "name": name,
            "source": key[0],
            "count": len(window),
            "last": v1,
            "mean": (sum(finite) / len(finite)) if finite else v1,
            "min": min(finite) if finite else v1,
            "max": max(finite) if finite else v1,
            "rate": rate,
            "first_ts": t0,
            "last_ts": t1,
            "age_s": max(0.0, time.time() - t1),
        }

    def points(self, name: str, window_s: float = 300.0,
               source: Optional[str] = None, limit: int = 240) -> Dict[str, list]:
        """Raw windowed points per source: {source: [[ts, value], ...]} —
        the /timeseries route's payload (opsctl query renders it)."""
        with self._lock:
            keys = [(s, n) for (s, n) in self._series
                    if n == name and (source is None or s == source)]
            snap = {k: list(self._series[k]) for k in keys}
        out: Dict[str, list] = {}
        cutoff = time.time() - float(window_s)
        for (s, _n), pts in snap.items():
            window = [[t, v] for (t, v) in pts if t >= cutoff]
            out[s] = window[-limit:]
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "series": len(self._series),
                "max_series": self._max_series,
                "points_per_series": self._points,
                "dropped_series": self._dropped,
                "evicted_series": self._evicted,
            }


class RegistrySampler:
    """Background thread snapshotting a ``MetricsRegistry`` into a store at a
    fixed cadence — the feed that turns the registry's "now" into history.
    ``sample_once()`` is exposed for deterministic tests."""

    def __init__(self, store: TimeSeriesStore, registry: Optional[MetricsRegistry] = None,
                 interval_s: float = 1.0, source: str = "local"):
        assert interval_s > 0
        self.store = store
        self.interval_s = interval_s
        self.source = source
        self._registry = registry
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample_once(self, ts: Optional[float] = None) -> int:
        reg = self._registry or get_registry()
        snap = reg.snapshot()
        n = self.store.record_snapshot(snap, ts=ts, source=self.source)
        reg.counter(
            "distar_tsdb_samples_total", "registry snapshots folded into the TSDB"
        ).inc()
        reg.gauge(
            "distar_tsdb_series", "series resident in the TSDB ring store"
        ).set(self.store.stats()["series"])
        return n

    def start(self) -> "RegistrySampler":
        if self._thread is not None:
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.sample_once()
                except Exception:
                    pass  # sampling must never kill the host process

        self._thread = threading.Thread(target=run, daemon=True, name="obs-sampler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
