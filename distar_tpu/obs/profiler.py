"""jax.profiler session wrapper + step-phase breakdown publication.

``ProfilerSession`` guards ``jax.profiler.start_trace``/``stop_trace`` behind
availability checks (profiling is best-effort telemetry: a missing/broken
profiler must never take down training) and counts sessions in the registry.
``record_step_phases`` is the single choke point the learner run loop uses to
publish its data-wait / device-step / host-callback breakdown.
"""
from __future__ import annotations

import logging
import os
from typing import Dict, Optional

from .registry import MetricsRegistry, get_registry


class ProfilerSession:
    """Start/stop wrapper for the device profiler.

    ``profiler`` is injectable (tests pass a stub); the default resolves
    ``jax.profiler`` lazily so importing obs never imports jax. Failures are
    never fatal but no longer silent either: each failed start/stop counts
    ``distar_profiler_failures_total{stage=...}``, and a successful stop
    records ``last_profile_path`` — the newest capture dir under the logdir,
    what the admin ``/profile`` route hands to the trace analyzer."""

    def __init__(self, logdir: str, profiler=None, registry: Optional[MetricsRegistry] = None):
        self.logdir = logdir
        self.active = False
        self.failures = 0
        self.last_profile_path: Optional[str] = None
        self._profiler = profiler
        self._registry = registry

    def _resolve(self):
        if self._profiler is None:
            import jax

            self._profiler = jax.profiler
        return self._profiler

    def _count_failure(self, stage: str) -> None:
        self.failures += 1
        reg = self._registry or get_registry()
        reg.counter(
            "distar_profiler_failures_total",
            "profiler start/stop failures (best-effort, training continues)",
            stage=stage,
        ).inc()

    def start(self) -> bool:
        if self.active:
            return True
        try:
            # surface an unwritable logdir HERE, typed, instead of letting
            # stop_trace throw away an entire captured session later
            os.makedirs(self.logdir, exist_ok=True)
            self._resolve().start_trace(self.logdir)
        except Exception as e:  # best-effort: never kill training over a trace
            logging.warning("profiler start_trace failed: %r", e)
            self._count_failure("start")
            return False
        self.active = True
        reg = self._registry or get_registry()
        reg.counter("distar_profiler_sessions_total", "profiler traces started").inc()
        return True

    def stop(self) -> bool:
        if not self.active:
            return False
        self.active = False
        try:
            self._resolve().stop_trace()
        except Exception as e:
            logging.warning("profiler stop_trace failed: %r", e)
            self._count_failure("stop")
            return False
        self.last_profile_path = self._newest_capture() or self.logdir
        return True

    def _newest_capture(self) -> Optional[str]:
        """Newest session dir under ``<logdir>/plugins/profile/`` (the
        layout ``jax.profiler`` writes); None when nothing landed."""
        root = os.path.join(self.logdir, "plugins", "profile")
        try:
            stamps = [os.path.join(root, d) for d in os.listdir(root)]
            stamps = [d for d in stamps if os.path.isdir(d)]
            return max(stamps, key=os.path.getmtime) if stamps else None
        except OSError:
            return None


_PHASES = ("data_wait", "device_step", "host_callback")


def record_step_phases(
    phases: Dict[str, float], registry: Optional[MetricsRegistry] = None
) -> None:
    """Publish one train iteration's phase breakdown (seconds) into
    ``distar_learner_step_phase_seconds{phase=...}`` histograms."""
    reg = registry or get_registry()
    for phase, seconds in phases.items():
        reg.histogram(
            "distar_learner_step_phase_seconds",
            "learner step time by phase",
            phase=str(phase),
        ).observe(float(seconds))
