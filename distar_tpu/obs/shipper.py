"""Telemetry shipping: fleet processes push registry snapshots to the broker.

The Prometheus ``/metrics`` route is pull-based and per-process — an operator
watching a 100-actor league would need 100 scrape targets. Following the
centralized-actor-telemetry design of SEED RL (PAPERS.md), every fleet
process instead runs a ``TelemetryShipper``: a background thread that
periodically snapshots the local ``MetricsRegistry`` and pushes the compact
flat dict to the coordinator over the existing comm serializer (the same
pickle+LZ codec the data plane speaks; ``POST /coordinator/telemetry`` with
an ``application/x-distar-serialized`` body). The coordinator's
``TelemetryIngest`` folds each message into the shared ``TimeSeriesStore``
as per-source series with last-seen/staleness tracking — one place that
sees the whole fleet, which is what the rules engine (``obs/health.py``)
evaluates.

Both ends also work in-process (``ingest=`` instead of an address) so the
all-in-one launcher and tests exercise the identical path minus the socket.
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Optional, Tuple

from .registry import MetricsRegistry, get_registry
from .timeseries import TimeSeriesStore

SERIALIZED_CONTENT_TYPE = "application/x-distar-serialized"

# every running shipper in this process, so a broker restart/failover can
# nudge them all to re-ship immediately (weak: a dropped shipper unregisters
# itself by dying)
_ACTIVE_LOCK = threading.Lock()
_ACTIVE_SHIPPERS: "weakref.WeakSet" = weakref.WeakSet()
_FAILOVER_HOOK_INSTALLED = False


def request_resync_all(reason: str) -> int:
    """Ask every active shipper in this process to re-ship its full registry
    snapshot NOW (out of cycle) — called when discovery's heartbeat learns
    the broker lost our records (``reason="heartbeat"``) and when the HA
    client fails over to a new primary (``reason="failover"``). A restarted
    or newly-promoted broker would otherwise show every source stale until
    the next natural ship interval. Returns the number of shippers nudged."""
    with _ACTIVE_LOCK:
        shippers = list(_ACTIVE_SHIPPERS)
    for s in shippers:
        s.request_resync(reason)
    return len(shippers)


def _install_failover_hook() -> None:
    """One-time: subscribe to client-side coordinator failovers so shippers
    resync the moment a new primary is adopted. Lazy + best-effort (obs must
    stay importable without comm)."""
    global _FAILOVER_HOOK_INSTALLED
    with _ACTIVE_LOCK:
        if _FAILOVER_HOOK_INSTALLED:
            return
        _FAILOVER_HOOK_INSTALLED = True
    try:
        from ..comm import ha

        ha.add_failover_listener(lambda _targets: request_resync_all("failover"))
    except Exception:  # noqa: BLE001 - telemetry must not break on comm shape
        pass


class TelemetryIngest:
    """Coordinator-side sink: fold shipped snapshots into the fleet store.

    Messages may additionally carry ``traces`` (tail-sampled span records
    from the shipper process's ``TraceBuffer``) and ``exemplars`` (its
    latency-exemplar snapshot); both fold into the shared trace machinery
    (``obs/tracestore.py``) when a ``TraceIngest`` is attached, so the
    coordinator serves ``GET /traces`` for the whole fleet and its health
    rules can name offending trace_ids in alert events."""

    def __init__(self, store: TimeSeriesStore, registry: Optional[MetricsRegistry] = None,
                 traces=None):
        self.store = store
        self._registry = registry
        self.traces = traces  # Optional[tracestore.TraceIngest]
        # source -> the service endpoint ("ip:port") the shipper declared;
        # how coordinator lease evictions map back to TSDB sources
        self._endpoints: dict = {}
        self._lock = threading.Lock()

    def ingest(self, msg: dict) -> int:
        """Fold one shipped message ``{source, ts, snapshot, interval_s?,
        endpoint?, traces?, exemplars?}`` into per-source series; returns
        the number of scalars recorded. ``endpoint`` (the shipper's
        registered service address) links the source to its coordinator
        lease, so a lease eviction can reclaim the series
        (``evict_endpoint``) — and the source's traces with them."""
        if not isinstance(msg, dict) or not isinstance(msg.get("snapshot"), dict):
            raise ValueError("telemetry message must be {source, ts, snapshot}")
        source = str(msg.get("source") or "unknown")
        ts = float(msg.get("ts") or time.time())
        endpoint = msg.get("endpoint")
        if endpoint:
            with self._lock:
                self._endpoints[source] = str(endpoint)
        n = self.store.record_snapshot(msg["snapshot"], ts=ts, source=source)
        if self.traces is not None and msg.get("traces"):
            self.traces.ingest(source, msg["traces"])
        if msg.get("exemplars"):
            from .tracestore import get_exemplar_store

            get_exemplar_store().merge(msg["exemplars"])
        reg = self._registry or get_registry()
        reg.counter(
            "distar_telemetry_ingest_total", "shipped snapshots ingested", source=source
        ).inc()
        return n

    def evict_endpoint(self, endpoint: str) -> int:
        """A registered endpoint left the broker (lease expiry or graceful
        unregister): reclaim every TSDB series its shipped sources hold, so
        membership churn frees series-cap room instead of exhausting it.
        Returns the number of series evicted."""
        with self._lock:
            sources = [s for s, e in self._endpoints.items() if e == endpoint]
            for s in sources:
                del self._endpoints[s]
        if self.traces is not None:
            for s in sources:
                self.traces.evict_source(s)
        return sum(self.store.evict_source(s) for s in sources)

    def evict_source(self, source: str) -> int:
        """Direct source eviction (callers that track membership themselves,
        e.g. the autoscaler's member probes)."""
        with self._lock:
            self._endpoints.pop(source, None)
        if self.traces is not None:
            self.traces.evict_source(source)
        return self.store.evict_source(source)

    def sources(self) -> dict:
        return self.store.sources()


class TelemetryShipper:
    """Background thread pushing registry snapshots to the coordinator.

    ``coordinator_addr=(host, port)`` ships over HTTP with the comm
    serializer as the body codec; ``ingest=TelemetryIngest`` short-circuits
    in-process. Shipping is best-effort: a dead broker counts an error and
    the loop keeps going — telemetry must never take the fleet down with it.
    """

    def __init__(self, source: str,
                 coordinator_addr: Optional[Tuple[str, int]] = None,
                 ingest: Optional[TelemetryIngest] = None,
                 interval_s: float = 5.0,
                 registry: Optional[MetricsRegistry] = None,
                 timeout_s: float = 5.0,
                 endpoint: Optional[str] = None):
        assert (coordinator_addr is None) != (ingest is None), \
            "exactly one of coordinator_addr / ingest"
        assert interval_s > 0
        self.source = str(source)
        self.interval_s = interval_s
        self._addr = coordinator_addr
        self._ingest = ingest
        self._registry = registry
        self._timeout_s = timeout_s
        #: the service endpoint ("ip:port") this process registered under a
        #: coordinator lease, if any — stamped on every message so the
        #: broker can reclaim this source's series when the lease goes
        self.endpoint = endpoint
        self._stop = threading.Event()
        self._wake = threading.Event()  # out-of-cycle ship trigger (resync)
        self._pending_lock = threading.Lock()
        self._resync_reasons: list = []
        self._thread: Optional[threading.Thread] = None

    def request_resync(self, reason: str) -> None:
        """Schedule an immediate full-snapshot ship (every ship already IS a
        full registry snapshot — a resync is simply an out-of-cycle one) and
        count it under ``distar_obs_shipper_resyncs_total{reason}`` once it
        lands."""
        with self._pending_lock:
            if reason not in self._resync_reasons:
                self._resync_reasons.append(reason)
        self._wake.set()

    # ------------------------------------------------------------------- wire
    def _message(self) -> dict:
        reg = self._registry or get_registry()
        msg = {
            "source": self.source,
            "ts": time.time(),
            "interval_s": self.interval_s,
            "snapshot": reg.snapshot(),
        }
        if self.endpoint:
            msg["endpoint"] = self.endpoint
        # tail-sampled trace records + latency exemplars ride the same
        # periodic push (best-effort like the rest of telemetry: a lost
        # POST loses the batch, never blocks the role)
        from .tracestore import get_exemplar_store, get_trace_buffer

        traces = get_trace_buffer().unshipped()
        if traces:
            msg["traces"] = traces
        exemplars = get_exemplar_store().snapshot()
        if exemplars:
            msg["exemplars"] = exemplars
        return msg

    def ship_once(self) -> int:
        """Snapshot + push one message; returns scalars shipped. Raises on
        transport failure (the loop catches; direct callers see the error)."""
        msg = self._message()
        reg = self._registry or get_registry()
        if self._ingest is not None:
            n = self._ingest.ingest(msg)
        else:
            # lazy comm import: obs must stay importable without the comm
            # package fully initialised (comm itself imports obs)
            import urllib.error
            import urllib.request

            from ..comm import serializer
            from ..resilience import CommError

            host, port = self._addr
            targets = None
            if port is None or (isinstance(host, str) and "," in host):
                # HA fleet: ship to the believed-primary of the addr set and
                # share the process-wide leadership view with every other
                # coordinator client (comm.ha failover state)
                from ..comm import ha as _ha

                addrs = _ha.parse_addrs(host if port is None else f"{host}:{port}")
                if len(addrs) > 1:
                    targets = _ha.targets_for(addrs)
                    host, port = targets.active()
                else:
                    host, port = addrs[0]
            req = urllib.request.Request(
                f"http://{host}:{port}/coordinator/telemetry",
                data=serializer.dumps(msg),
                headers={"Content-Type": SERIALIZED_CONTENT_TYPE},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=self._timeout_s) as resp:
                    reply = resp.read()
                import json

                decoded = json.loads(reply)
            except (urllib.error.URLError, ConnectionError, TimeoutError,
                    OSError, ValueError) as e:
                if targets is not None:
                    targets.rotate((host, port))
                raise CommError(
                    f"telemetry ship @ {host}:{port} failed: {e!r}",
                    op="telemetry_ship", cause=e,
                ) from e
            if decoded.get("code") == 2 and targets is not None:
                # a standby answered: adopt its leadership hint and let the
                # retry policy re-ship to the new primary (telemetry is
                # ephemeral by contract, so a lost tick costs nothing)
                targets.follow(str(decoded.get("leader") or ""), (host, port))
                raise CommError(
                    f"telemetry ship @ {host}:{port}: not_leader",
                    op="telemetry_ship")
            if decoded.get("code") != 0:
                raise RuntimeError(f"telemetry ingest rejected: {decoded!r}")
            n = int(decoded.get("info") or 0)
        reg.counter(
            "distar_telemetry_ships_total", "snapshots shipped to the coordinator"
        ).inc()
        return n

    # ---------------------------------------------------------------- control
    def start(self) -> "TelemetryShipper":
        if self._thread is not None:
            return self
        self._stop.clear()

        def run():
            from ..resilience import (
                CircuitBreaker, CircuitOpenError, CommError, RetryPolicy, retry_call,
            )

            reg = self._registry or get_registry()
            errors = reg.counter(
                "distar_telemetry_ship_errors_total", "failed telemetry pushes"
            )
            # quick in-tick retry for blips; the breaker turns a dead broker
            # into cheap fail-fast ticks (no connect timeout per interval)
            # until it answers again — shipping must never stall the role
            policy = RetryPolicy(max_attempts=2, backoff_base_s=0.2,
                                 deadline_s=self._timeout_s)
            breaker = CircuitBreaker(op="telemetry_ship",
                                     reset_after_s=4 * self.interval_s)
            prev_failed = False
            while True:
                self._wake.wait(self.interval_s)
                self._wake.clear()
                if self._stop.is_set():
                    break
                with self._pending_lock:
                    reasons, self._resync_reasons = self._resync_reasons, []
                try:
                    retry_call(self.ship_once, op="telemetry_ship",
                               policy=policy, breaker=breaker)
                    if prev_failed and "recovered" not in reasons:
                        # first successful ship after an outage is itself a
                        # resync: the broker just regained this source
                        reasons.append("recovered")
                    prev_failed = False
                    for reason in reasons:
                        reg.counter(
                            "distar_obs_shipper_resyncs_total",
                            "full-snapshot re-ships after broker restart "
                            "or failover", reason=reason,
                        ).inc()
                except (CommError, CircuitOpenError):
                    errors.inc()
                    prev_failed = True
                except Exception:
                    # anything else (rejected ingest, codec bug): counted,
                    # never propagated — telemetry must not take the fleet
                    # down with it
                    errors.inc()
                    prev_failed = True
                if prev_failed and reasons:
                    # a requested resync is still owed: re-queue it so the
                    # next successful ship counts it
                    with self._pending_lock:
                        for reason in reasons:
                            if reason not in self._resync_reasons:
                                self._resync_reasons.append(reason)

        self._thread = threading.Thread(target=run, daemon=True, name="obs-shipper")
        self._thread.start()
        with _ACTIVE_LOCK:
            _ACTIVE_SHIPPERS.add(self)
        if self._addr is not None:
            _install_failover_hook()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        with _ACTIVE_LOCK:
            _ACTIVE_SHIPPERS.discard(self)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
