"""Training-dynamics observatory: in-step diagnostics, anomaly black boxes.

The obs stack could explain latency (tracing), throughput (perf) and process
health (rules), but was blind to the training math itself: the per-head loss
info dicts died in the log buffer, gradients were uninstrumented, and the
only answer to a NaN loss was a blind restart. This module closes that gap
in three moves:

* ``dynamics_tree`` — a handful of scalar reductions *inside* the jitted
  (donated) train step: per-module gradient/param global-norms, update-to-
  weight ratios, grad-clip activation, and non-finite censuses over grads,
  pre-step params and the batch. The scalars ride the step's existing info
  dict, so the learner's ONE batched ``device_get`` per step ships them —
  never a per-leaf sync. Computed every step (a few dozen scalar reductions
  are noise next to the model matmuls — DYNAMICS_r16.json holds the paired
  on/off proof); ``every_n`` gates host-side gauge EXPORT, not compute, so
  anomaly detection never has a blind window.

* ``DynamicsMonitor`` — the host side: publishes the tree plus the routed
  loss info as bounded-cardinality ``distar_train_*`` gauges, keeps a
  grad-norm EMA for explosion detection, and on anomaly (non-finite
  loss/grads, explosion vs EMA, entropy collapse) writes a debounced,
  capped **black-box bundle**: the offending batch, pre-step aux, PRNG
  seed, step index, checkpoint pointer, config digest and the diagnostics
  tree localizing the first non-finite module. ``tools/stepreplay.py``
  re-executes a bundle deterministically offline.

* ``first_nonfinite`` — provenance: a batch-borne NaN poisons every grad
  via backprop, so the census is read batch > params > grads; the first
  family with a hit names the true origin, not the blast radius.

Module top imports stdlib + the obs registry only (the obs package must
stay importable without jax); everything jit-side imports jax in-function.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Set, Tuple

from .flightrecorder import _versions, get_flight_recorder
from .registry import MetricsRegistry, get_registry
from .tracestore import note_exemplar

BUNDLE_SCHEMA = "distar.blackbox.v1"

DYNAMICS_DEFAULTS = {
    "enabled": True,
    # host-side gauge-export period (steps); the in-jit tree is computed
    # every step so detection has no blind window, and anomaly steps
    # force-publish regardless of the gate
    "every_n": 10,
    "ema_momentum": 0.99,
    # grad-norm explosion: ||g|| > factor * EMA(||g||), after warmup steps
    "explosion_factor": 10.0,
    "explosion_warmup": 20,
    # per-head |entropy| < floor => collapse (0 disables; RL-specific signal)
    "entropy_floor": 0.0,
    "blackbox": True,          # write forensic bundles on anomaly
    "blackbox_cap": 4,         # max bundles per process (disk guard)
    "blackbox_dir": "",        # default: <save_dir>/blackbox
    "blackbox_state": True,    # include post-step train state in the bundle
    "clear_n": 3,              # clean steps before an anomaly class re-arms
}

# bundle filenames are self-describing so listings never need to deserialize
_BUNDLE_RE = re.compile(r"^blackbox_(\d+)_step(\d+)_([a-z0-9_]+)\.bb$")

ANOMALY_CLASSES = (
    "loss_nonfinite",
    "grad_nonfinite",
    "grad_explosion",
    "entropy_collapse",
)


# --------------------------------------------------------------------- spec
@dataclass(frozen=True)
class DynamicsSpec:
    """Static (hashable) closure args for the in-jit tree — what the step
    needs to know about the configured grad clip to report its activation."""

    clip_type: str = "none"
    clip_threshold: float = 1.0


def tree_spec(dynamics_cfg: Optional[dict], grad_clip_cfg: Optional[dict]
              ) -> Optional[DynamicsSpec]:
    """The spec the learner threads into ``make_*_train_step`` — or None
    when dynamics is disabled, which statically compiles the step WITHOUT
    the tree (the 'off' arm of the overhead A/B)."""
    dcfg = dict(dynamics_cfg or {})
    if not dcfg.get("enabled", True):
        return None
    gc = dict(grad_clip_cfg or {})
    return DynamicsSpec(
        clip_type=str(gc.get("type", "none") or "none"),
        clip_threshold=float(gc.get("threshold", 1.0)),
    )


# ------------------------------------------------------------- in-jit tree
def _inner(tree):
    """Top-level module map of a params-like pytree ({'params': {...}} flax
    convention or a bare dict); non-dict trees become one 'all' module."""
    if isinstance(tree, dict) and "params" in tree and isinstance(tree["params"], dict):
        tree = tree["params"]
    if not isinstance(tree, dict):
        return {"all": tree}
    return tree


def _float_leaves(tree) -> list:
    import jax
    import jax.numpy as jnp

    return [
        leaf for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating)
    ]


def _l2sq(tree):
    """Sum of squares over ALL leaves (f32 accumulate) — norms are taken
    over every numeric leaf, not just floats, to match optax.global_norm."""
    import jax
    import jax.numpy as jnp

    acc = jnp.zeros((), jnp.float32)
    for leaf in jax.tree_util.tree_leaves(tree):
        leaf = jnp.asarray(leaf).astype(jnp.float32)
        acc = acc + jnp.sum(leaf * leaf)
    return acc


def _count_nonfinite(tree):
    """Number of non-finite elements across the tree's FLOAT leaves (ints
    cannot be non-finite and jnp.isfinite rejects them)."""
    import jax.numpy as jnp

    acc = jnp.zeros((), jnp.float32)
    for leaf in _float_leaves(tree):
        acc = acc + jnp.sum(~jnp.isfinite(leaf)).astype(jnp.float32)
    return acc


def dynamics_tree(params, grads, updates=None, batch=None,
                  spec: Optional[DynamicsSpec] = None) -> Dict[str, Any]:
    """The one-pass diagnostics tree, called INSIDE the jitted train step
    after ``optimizer.update`` (so ``params`` are pre-step and ``updates``
    are the post-clip deltas) and merged into the step's info dict.

    Emits flat ``dyn/<family>/<module>`` f32 scalars:

    * ``dyn/grad_norm|param_norm|update_ratio/<module>`` + ``/total``
    * ``dyn/nonfinite_grads|nonfinite_params/<module>`` + ``/total``
    * ``dyn/nonfinite_batch/<top-level key>`` + ``/total`` (float leaves)
    * ``dyn/clip_fraction`` / ``dyn/clip_active`` (per ``spec``)

    Cardinality is bounded by the model's top-level module count and the
    batch's top-level keys — both fixed by config, not by data.
    """
    import jax.numpy as jnp

    out: Dict[str, Any] = {}
    p_in, g_in = _inner(params), _inner(grads)
    u_in = _inner(updates) if updates is not None else None

    p_tot = g_tot = u_tot = jnp.zeros((), jnp.float32)
    gbad_tot = pbad_tot = jnp.zeros((), jnp.float32)
    for mod in sorted(p_in):
        p2 = _l2sq(p_in[mod])
        g2 = _l2sq(g_in[mod]) if mod in g_in else jnp.zeros((), jnp.float32)
        pn, gn = jnp.sqrt(p2), jnp.sqrt(g2)
        out[f"dyn/param_norm/{mod}"] = pn
        out[f"dyn/grad_norm/{mod}"] = gn
        p_tot, g_tot = p_tot + p2, g_tot + g2
        if u_in is not None and mod in u_in:
            u2 = _l2sq(u_in[mod])
            un = jnp.sqrt(u2)
            out[f"dyn/update_ratio/{mod}"] = un / (pn + 1e-12)
            u_tot = u_tot + u2
        gbad = _count_nonfinite(g_in[mod]) if mod in g_in else jnp.zeros((), jnp.float32)
        pbad = _count_nonfinite(p_in[mod])
        out[f"dyn/nonfinite_grads/{mod}"] = gbad
        out[f"dyn/nonfinite_params/{mod}"] = pbad
        gbad_tot, pbad_tot = gbad_tot + gbad, pbad_tot + pbad

    grad_norm_total = jnp.sqrt(g_tot)
    out["dyn/param_norm/total"] = jnp.sqrt(p_tot)
    out["dyn/grad_norm/total"] = grad_norm_total
    if u_in is not None:
        out["dyn/update_ratio/total"] = jnp.sqrt(u_tot) / (jnp.sqrt(p_tot) + 1e-12)
    out["dyn/nonfinite_grads/total"] = gbad_tot
    out["dyn/nonfinite_params/total"] = pbad_tot

    if batch is not None and isinstance(batch, dict):
        b_tot = jnp.zeros((), jnp.float32)
        for key in sorted(batch):
            if not _float_leaves(batch[key]):
                continue  # int-only obs can't be non-finite; don't emit a row
            bad = _count_nonfinite(batch[key])
            out[f"dyn/nonfinite_batch/{key}"] = bad
            b_tot = b_tot + bad
        out["dyn/nonfinite_batch/total"] = b_tot

    if spec is not None:
        from ..parallel.grad_clip import clip_activation

        frac, active = clip_activation(
            grads, grad_norm_total, spec.clip_type, spec.clip_threshold
        )
        out["dyn/clip_fraction"] = frac
        out["dyn/clip_active"] = active
    return out


# ---------------------------------------------------------- host-side views
def _f(val, default: float = 0.0) -> float:
    try:
        return float(val)
    except (TypeError, ValueError):
        return default


def _finite(val) -> bool:
    import math

    try:
        return math.isfinite(float(val))
    except (TypeError, ValueError):
        return False


def split_tree(log: Dict[str, Any]) -> Dict[str, Dict[str, float]]:
    """Group a host log dict's ``dyn/<family>/<module>`` scalars by family
    (opsctl's digest view and the tests' hand-check both read this)."""
    out: Dict[str, Dict[str, float]] = {}
    for key, val in log.items():
        if not key.startswith("dyn/"):
            continue
        parts = key.split("/", 2)
        if len(parts) == 3:
            out.setdefault(parts[1], {})[parts[2]] = _f(val)
        else:
            out.setdefault(parts[1], {})[""] = _f(val)
    return out


def first_nonfinite(log: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Localize an anomaly's origin from the censuses. Read order matters:
    one NaN in the batch makes EVERY module's grads non-finite via backprop,
    and a poisoned param does the same one hop later — so the narrowest
    family with a hit (batch, then pre-step params, then grads) names the
    origin rather than the blast radius."""
    for origin, prefix in (
        ("batch", "dyn/nonfinite_batch/"),
        ("params", "dyn/nonfinite_params/"),
        ("grads", "dyn/nonfinite_grads/"),
    ):
        hits = sorted(
            key[len(prefix):]
            for key, val in log.items()
            if key.startswith(prefix) and key[len(prefix):] != "total"
            and _f(val) > 0
        )
        if hits:
            return {"origin": origin, "module": hits[0], "all": hits}
    return None


def config_digest(cfg: Any) -> str:
    """Stable sha256 of a config mapping (canonical JSON, default=str) —
    the replay tool refuses nothing, but surfaces digest drift loudly."""
    blob = json.dumps(cfg, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()


def _plain(obj):
    """JSON round-trip: Config/EasyDict trees become plain builtins."""
    return json.loads(json.dumps(obj, default=str))


# ------------------------------------------------------------------ bundles
def load_bundle(path: str) -> Dict[str, Any]:
    from ..comm import serializer

    with open(path, "rb") as f:
        bundle = serializer.loads(f.read())
    if bundle.get("schema") != BUNDLE_SCHEMA:
        raise ValueError(
            f"{path}: schema {bundle.get('schema')!r} != {BUNDLE_SCHEMA!r}"
        )
    return bundle


def bundle_summary(bundle: Dict[str, Any]) -> Dict[str, Any]:
    prov = bundle.get("provenance") or {}
    return {
        "schema": bundle.get("schema"),
        "step": bundle.get("step"),
        "reasons": bundle.get("reasons"),
        "learner": bundle.get("learner"),
        "origin": prov.get("origin"),
        "module": prov.get("module"),
        "config_digest": bundle.get("config_digest"),
        "ckpt": (bundle.get("checkpoint") or {}).get("path"),
        "ts": bundle.get("ts"),
    }


def list_bundles(dirpath: str) -> List[Dict[str, Any]]:
    """Cheap listing from filenames alone (no deserialization)."""
    out = []
    try:
        names = sorted(os.listdir(dirpath))
    except OSError:
        return out
    for name in names:
        m = _BUNDLE_RE.match(name)
        if m:
            out.append({
                "id": name,
                "path": os.path.join(dirpath, name),
                "seq": int(m.group(1)),
                "step": int(m.group(2)),
                "reason": m.group(3),
            })
    return sorted(out, key=lambda b: b["seq"])


# ------------------------------------------------------------------ monitor
class DynamicsMonitor:
    """Host-side consumer of the in-jit tree: gauge export, anomaly
    detection with EMA + debounce, and black-box capture.

    The learner run loop calls ``before_step`` (cheap: stashes device-array
    REFS for aux state — the fetch happens only if a bundle is written) and
    ``on_step`` with the already-fetched host log dict; this class never
    adds a device sync on the healthy path.
    """

    def __init__(self, cfg: Optional[dict] = None, name: str = "learner",
                 registry: Optional[MetricsRegistry] = None,
                 blackbox_dir: str = ""):
        merged = dict(DYNAMICS_DEFAULTS)
        merged.update(dict(cfg or {}))
        self.cfg = merged
        self.enabled = bool(merged.get("enabled", True))
        self.every_n = max(1, int(merged.get("every_n", 10)))
        self.name = name
        self._reg = registry or get_registry()
        self.blackbox_dir = merged.get("blackbox_dir") or blackbox_dir
        self.ema: Optional[float] = None
        self.steps_seen = 0
        self.bundles_written = 0
        self.last_bundle_path: Optional[str] = None
        self.last_anomaly_step: Optional[int] = None
        self._active: Set[str] = set()   # debounce: currently-firing classes
        self._clean = 0                  # consecutive anomaly-free steps
        self._aux: Optional[dict] = None

    # ------------------------------------------------------------- run hooks
    def before_step(self, learner) -> None:
        if not self.enabled:
            return
        aux_fn = getattr(learner, "_dynamics_aux", None)
        self._aux = aux_fn() if aux_fn is not None else None

    def on_step(self, learner, log: Dict[str, Any],
                batch: Any = None) -> Set[str]:
        """Detect → (maybe) publish → EMA → (maybe) capture. ``log`` is the
        host-side float dict the learner already fetched; ``batch`` is the
        step's input, captured only if a bundle is written. Returns the
        anomaly classes seen this step (tests read it)."""
        if not self.enabled:
            return set()
        step = int(learner.last_iter.val)
        anomalies, grad_norm = self.detect(log)
        sampled = self.steps_seen % self.every_n == 0
        self.steps_seen += 1
        if sampled or anomalies:
            # anomaly steps force-publish: a NaN that only ever existed
            # between sample points would otherwise never reach the TSDB
            # rules that alert on it
            self.publish(log)
        if _finite(grad_norm):
            mom = float(self.cfg.get("ema_momentum", 0.99))
            self.ema = grad_norm if self.ema is None else (
                mom * self.ema + (1.0 - mom) * grad_norm
            )
            self._reg.gauge(
                "distar_train_grad_norm_ema",
                "EMA of the global gradient norm (explosion-rule baseline)",
            ).set(self.ema)
        if anomalies:
            self._clean = 0
            for reason in sorted(anomalies):
                self._reg.counter(
                    "distar_train_anomalies_total",
                    "training anomalies detected, by class",
                    reason=reason,
                ).inc()
            self.last_anomaly_step = step
            self._reg.gauge(
                "distar_train_last_anomaly_step",
                "step index of the most recent training anomaly",
            ).set(float(step))
            fresh = anomalies - self._active
            self._active |= anomalies
            if (fresh and self.cfg.get("blackbox", True)
                    and self.bundles_written < int(self.cfg.get("blackbox_cap", 4))):
                self.capture(learner, log, batch, step, sorted(anomalies))
        else:
            self._clean += 1
            if self._clean >= int(self.cfg.get("clear_n", 3)):
                self._active.clear()
        return anomalies

    # ------------------------------------------------------------- detection
    def detect(self, log: Dict[str, Any]) -> Tuple[Set[str], Optional[float]]:
        """Pure classification of one step's log dict; returns (classes,
        global grad norm). Uses only host floats — no device access."""
        anomalies: Set[str] = set()
        loss = log.get("total_loss")
        if loss is not None and not _finite(loss):
            anomalies.add("loss_nonfinite")
        grad_norm = log.get("dyn/grad_norm/total", log.get("grad_norm"))
        if grad_norm is not None and not _finite(grad_norm):
            anomalies.add("grad_nonfinite")
        for census in ("dyn/nonfinite_grads/total", "dyn/nonfinite_params/total",
                       "dyn/nonfinite_batch/total"):
            if _f(log.get(census)) > 0:
                anomalies.add("grad_nonfinite")
        if grad_norm is not None and _finite(grad_norm):
            warmup = int(self.cfg.get("explosion_warmup", 20))
            factor = float(self.cfg.get("explosion_factor", 10.0))
            if (self.ema is not None and self.steps_seen >= warmup
                    and self.ema > 0
                    and float(grad_norm) > factor * self.ema):
                anomalies.add("grad_explosion")
        floor = float(self.cfg.get("entropy_floor", 0.0))
        if floor > 0:
            for key, val in log.items():
                if not key.startswith("entropy/") or key == "entropy/total":
                    continue
                val = _f(val)
                # masked-out heads report exactly 0.0 — absence of the head,
                # not collapse of its distribution
                if val != 0.0 and abs(val) < floor:
                    anomalies.add("entropy_collapse")
                    break
        gn = float(grad_norm) if grad_norm is not None else None
        return anomalies, gn

    # ----------------------------------------------------------- publication
    def publish(self, log: Dict[str, Any]) -> None:
        """Flush the dyn/ tree + routed loss info into bounded gauges. Every
        label value below is either a loop variable over a static vocabulary
        or a parsed module/head name bounded by the model architecture."""
        g = self._reg.gauge
        for key, raw in log.items():
            if not key.startswith("dyn/"):
                continue
            val = _f(raw, default=float("nan"))
            parts = key.split("/", 2)
            family = parts[1]
            module = parts[2] if len(parts) == 3 else ""
            if family == "grad_norm":
                g("distar_train_grad_norm",
                  "per-module gradient global-norm (module=total is global)",
                  module=module).set(val)
            elif family == "param_norm":
                g("distar_train_param_norm",
                  "per-module parameter global-norm",
                  module=module).set(val)
            elif family == "update_ratio":
                g("distar_train_update_ratio",
                  "per-module update-to-weight norm ratio",
                  module=module).set(val)
            elif family == "nonfinite_grads":
                g("distar_train_nonfinite_grads",
                  "non-finite gradient elements per module",
                  module=module).set(val)
            elif family == "nonfinite_params":
                g("distar_train_nonfinite_params",
                  "non-finite parameter elements per module (pre-step)",
                  module=module).set(val)
            elif family == "nonfinite_batch":
                g("distar_train_nonfinite_batch",
                  "non-finite elements per top-level batch leaf",
                  leaf=module).set(val)
            elif family == "clip_fraction":
                g("distar_train_grad_clip_fraction",
                  "fraction of gradient signal removed by the clip").set(val)
            elif family == "clip_active":
                g("distar_train_grad_clip_active",
                  "1 when the grad clip engaged this step").set(val)
        if self.ema is not None:
            gn = log.get("dyn/grad_norm/total", log.get("grad_norm"))
            if gn is not None and _finite(gn) and self.ema > 0:
                g("distar_train_grad_norm_explosion",
                  "grad norm over its EMA (explosion-rule input)",
                  ).set(float(gn) / self.ema)
        self.route_losses(log)

    def route_losses(self, log: Dict[str, Any]) -> None:
        """Satellite: the rl/sl/distill info dicts become ``distar_train_*``
        loss gauges. The vocabularies live next to the loss code
        (losses/__init__) so a new head/field extends the routing without
        touching obs; anything off-vocabulary stays in the log buffer."""
        from ..losses import HEADS, LOSS_TERMS, REWARD_FIELDS, SL_METRIC_KEYS

        g = self._reg.gauge
        heads, fields = set(HEADS), set(REWARD_FIELDS)
        terms = set(LOSS_TERMS)
        sl_heads = ("action_type", "delay", "queued", "selected_units",
                    "target_unit", "target_location")
        pg_by_head: Dict[str, float] = {}
        for key, raw in log.items():
            if key.startswith("dyn/"):
                continue
            val = _f(raw, default=float("nan"))
            if key == "total_loss":
                g("distar_train_loss_term",
                  "loss terms (term=total is the optimized sum)",
                  term="total").set(val)
                continue
            if key == "divergence":
                g("distar_train_loss_term",
                  "loss terms (term=total is the optimized sum)",
                  term="divergence").set(val)
                continue
            if key in SL_METRIC_KEYS:
                # label key is "metric", not "name": the registry's gauge()
                # takes the family name positionally as ``name``
                g("distar_train_sl_metric",
                  "supervised accuracy/distance metrics by metric name",
                  metric=key).set(val)
                continue
            for head in sl_heads:
                if key == f"{head}_loss" or (
                        head == "selected_units" and key == "selected_units_loss"):
                    g("distar_train_loss_head",
                      "per-head loss contribution by term",
                      term="sl", head=head).set(val)
                    break
            parts = key.split("/")
            if len(parts) == 2:
                term, leaf = parts
                if term in terms and leaf == "total":
                    g("distar_train_loss_term",
                      "loss terms (term=total is the optimized sum)",
                      term=term).set(val)
                elif term in ("td", "reward", "value") and leaf in fields:
                    field = leaf
                    g("distar_train_loss_field",
                      "per-reward-field loss/values by term",
                      term=term, field=field).set(val)
                elif leaf in heads and term in terms:
                    head = leaf
                    g("distar_train_loss_head",
                      "per-head loss contribution by term",
                      term=term, head=head).set(val)
                    if term == "entropy" and val != 0.0:
                        # masked-out heads report exactly 0.0 — publishing
                        # it would trip the collapse rule on head absence
                        g("distar_train_entropy",
                          "per-head policy entropy (collapse-rule input)",
                          head=head).set(val)
                elif key == "kl/extra_at":
                    g("distar_train_loss_head",
                      "per-head loss contribution by term",
                      term="kl", head="extra_at").set(val)
            elif len(parts) == 3 and parts[0] == "pg":
                # pg/{field}/{head}: per-head pg is the field-sum (the field
                # axis is already covered by distar_train_loss_field)
                if parts[1] in fields and parts[2] in heads:
                    pg_by_head[parts[2]] = pg_by_head.get(parts[2], 0.0) + val
        for head, val in sorted(pg_by_head.items()):
            g("distar_train_loss_head",
              "per-head loss contribution by term",
              term="pg", head=head).set(val)

    # --------------------------------------------------------------- capture
    def capture(self, learner, log: Dict[str, Any], batch: Any,
                step: int, reasons: List[str]) -> Optional[str]:
        """Write the forensic black-box bundle. The ONLY place the monitor
        touches the device — and only because we are already inside an
        anomaly, where a D2H sync is the least of the step's problems."""
        import jax
        import numpy as np

        from ..comm import serializer

        dirpath = self.blackbox_dir or os.path.join(os.getcwd(), "blackbox")
        try:
            os.makedirs(dirpath, exist_ok=True)
            host_batch = None
            if batch is not None:
                # pre-device copy when the feeder already placed the batch;
                # the step does NOT donate batch buffers, so refs are valid
                host_batch = jax.tree.map(
                    lambda a: np.asarray(jax.device_get(a))
                    if hasattr(a, "shape") else a,
                    batch,
                )
            aux = jax.device_get(self._aux) if self._aux is not None else None
            state = None
            if self.cfg.get("blackbox_state", True) and learner.state is not None:
                state = jax.device_get(learner.state)
            cfg_plain = _plain(learner.cfg)
            provenance = first_nonfinite(log)
            ckpt = None
            try:
                ckpt = learner.checkpoint_manager.resolve_latest()
            except Exception:
                pass
            bundle = {
                "schema": BUNDLE_SCHEMA,
                "ts": time.time(),
                "step": step,
                "reasons": list(reasons),
                "learner": learner.name,
                "prng_seed": int(getattr(learner, "init_prng_seed", 0)),
                "batch": host_batch,
                "aux": aux,
                # honesty: donated buffers mean the pre-step state is gone —
                # this state is one optimizer step PAST the anomaly (replay
                # restores it only to rebuild shapes; param-origin anomalies
                # replay from the batch + the already-poisoned params)
                "state": state,
                "state_note": "one optimizer step PAST the anomaly (donated buffers)",
                "diagnostics": {k: _f(v, default=float("nan"))
                                for k, v in log.items()},
                "provenance": provenance,
                "checkpoint": ckpt,
                "config": cfg_plain,
                "config_digest": config_digest(cfg_plain),
                "versions": _versions(),
            }
            fname = (f"blackbox_{self.bundles_written:03d}_step{step}_"
                     f"{reasons[0]}.bb")
            path = os.path.join(dirpath, fname)
            with open(path, "wb") as f:
                f.write(serializer.dumps(bundle, compress=True))
        except Exception as e:  # forensics must never kill the run it studies
            try:
                learner.logger.error(f"black-box capture failed: {e!r}")
            except Exception:
                pass
            return None
        self.bundles_written += 1
        self.last_bundle_path = path
        trace_id = f"blackbox:{fname}"
        gn = log.get("dyn/grad_norm/total", log.get("grad_norm"))
        # the firing alerts' exemplar slot points at the bundle, so the
        # on-call path is alert -> bundle id -> stepreplay, no grepping.
        # Keys are the metric FAMILIES the default rulebook watches: a
        # rule reference like distar_train_nonfinite_grads{module=total}
        # finds its exemplar by prefix (ExemplarStore.lookup)
        note_exemplar("distar_train_grad_norm", trace_id, _f(gn))
        note_exemplar("distar_train_nonfinite_grads", trace_id,
                      _f(log.get("dyn/nonfinite_grads/total")))
        note_exemplar("distar_train_grad_norm_explosion", trace_id, _f(gn))
        note_exemplar("distar_train_entropy", trace_id,
                      _f(log.get("entropy/total")))
        note_exemplar("distar_learner_loss", trace_id, _f(log.get("total_loss")))
        self._reg.counter(
            "distar_train_blackbox_bundles_total",
            "forensic black-box bundles written",
        ).inc()
        rec = get_flight_recorder()
        rec.record(
            "dynamics_anomaly", step=step, reasons=list(reasons),
            bundle=fname, learner=learner.name,
            provenance=bundle.get("provenance"),
        )
        try:
            rec.dump(
                artifact_dir=dirpath, reason=f"dynamics:{reasons[0]}",
                config=bundle["config"], registry=self._reg,
                extra={"blackbox": bundle_summary(bundle)},
            )
        except Exception:
            pass
        try:
            learner.logger.warning(
                f"training anomaly {reasons} at step {step}: "
                f"black box -> {path}"
            )
        except Exception:
            pass
        return path
