"""Trace-event analyzer: from a captured ``jax.profiler`` trace to ranked
per-bucket step-time shares.

``ProfilerSession`` (obs/profiler.py) writes Chrome trace-event files under
``<logdir>/plugins/profile/<stamp>/<host>.trace.json.gz``. This module is
the CONSUMPTION side: it parses those files, keeps only device-op events
(the ``X`` events XLA stamps with ``args.hlo_op``/``hlo_module`` — CPU
thunks and TPU "XLA Ops" rows both carry them), classifies each op into a
named bucket (matmul/MXU, entity-attention, scatter, LSTM-scan,
collectives, host/infeed, other) and reports per-bucket time share — the
artifact ROADMAP item 5 says must drive kernel prioritization (rank the
next levers by MEASURED share, not guesswork).

Stdlib-only on purpose: the analyzer must run on artifacts shipped off the
training host (opsctl, CI perf gate) without jax installed.
"""
from __future__ import annotations

import gzip
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

# Classification taxonomy (docs/observability.md#perf): first match wins,
# most-specific first. Patterns run over ``<hlo_op> <scope-metadata>``
# lowercased — scope metadata (args.tf_op / long_name), when the backend
# emits it, lets fusions inherit their framework module (EntityEncoder,
# core_lstm, ...); bare HLO names still classify by op kind.
BUCKET_PATTERNS: Tuple[Tuple[str, re.Pattern], ...] = (
    ("collectives", re.compile(
        r"all-reduce|all_reduce|allreduce|all-gather|all_gather|reduce-scatter|"
        r"reduce_scatter|all-to-all|collective-permute|collective_permute|"
        r"psum|ppermute|partition-id|replica-id")),
    ("host/infeed", re.compile(
        r"infeed|outfeed|copy-start|copy-done|copy_start|copy_done|"
        r"\bsend\b|\brecv\b|send-done|recv-done|host-transfer|h2d|d2h|"
        r"transferto|transferfrom")),
    ("scatter", re.compile(r"scatter|segment_sum|dynamic-update-slice|dynamic_update_slice")),
    ("entity-attention", re.compile(
        r"attention|attn|entityencoder|entity_encoder|softmax|masked_fill|"
        r"flash_attention|ring_attention")),
    ("lstm-scan", re.compile(r"lstm|\bscan\b|while|core_lstm|selected_units|pointer_decode")),
    ("matmul/MXU", re.compile(
        r"dot_general|\bdot\b|dot\.|^dot|gemm|matmul|einsum|convolution|"
        r"\bconv\b|cublas|mxu")),
)
OTHER_BUCKET = "other"
BUCKETS = tuple(name for name, _ in BUCKET_PATTERNS) + (OTHER_BUCKET,)


def classify(name: str, scope: str = "") -> str:
    """Bucket for one device op; ``scope`` is optional framework metadata."""
    text = f"{name} {scope}".lower()
    for bucket, pat in BUCKET_PATTERNS:
        if pat.search(text):
            return bucket
    return OTHER_BUCKET


def find_trace_files(path: str) -> List[str]:
    """Trace-event files under a profiler logdir (or the file itself),
    newest session first. ``ProfilerSession`` logdirs contain
    ``plugins/profile/<stamp>/*.trace.json(.gz)``."""
    if os.path.isfile(path):
        return [path]
    found = []
    for dirpath, _dirnames, filenames in os.walk(path):
        for fn in filenames:
            if fn.endswith(".trace.json.gz") or fn.endswith(".trace.json"):
                found.append(os.path.join(dirpath, fn))
    # newest capture first: session dirs are timestamped, mtime breaks ties
    found.sort(key=lambda p: (os.path.getmtime(p), p), reverse=True)
    return found


def _load_events(path: str) -> List[dict]:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8", errors="replace") as f:
        doc = json.load(f)
    if isinstance(doc, list):  # bare event-array form of the trace format
        return doc
    events = doc.get("traceEvents", [])
    return events if isinstance(events, list) else []


def device_op_events(events: Iterable[dict]) -> Tuple[List[dict], int]:
    """Filter to device-op ``X`` events; returns (ops, malformed_count).

    A device op is an event XLA stamped with ``args.hlo_op`` (CPU thunk
    executor and TPU op rows both do), or — fallback for backends that only
    stamp the module — ``args.hlo_module``. Malformed events (non-dict,
    missing/bad dur) are counted, never fatal: a truncated capture should
    still produce a report."""
    ops: List[dict] = []
    malformed = 0
    for e in events:
        if not isinstance(e, dict):
            malformed += 1
            continue
        if e.get("ph") != "X":
            continue
        args = e.get("args")
        if not isinstance(args, dict):
            continue
        if "hlo_op" not in args and "hlo_module" not in args:
            continue
        try:
            dur = float(e.get("dur", 0.0))
            name = str(e.get("name", "")) or str(args.get("hlo_op", ""))
        except (TypeError, ValueError):
            malformed += 1
            continue
        if not name or dur < 0:
            malformed += 1
            continue
        ops.append({
            "name": name,
            "dur_us": dur,
            "module": str(args.get("hlo_module", "")),
            "scope": str(args.get("tf_op", args.get("long_name", ""))),
        })
    return ops, malformed


def _infer_steps(ops: List[dict], module: str) -> int:
    """Executions of ``module``: ops inside device loops repeat per step,
    but every full execution runs each HLO op at least once — the MINIMUM
    per-op occurrence count over the module's ops is the execution count."""
    counts: Dict[str, int] = {}
    for op in ops:
        if op["module"] == module:
            counts[op["name"]] = counts.get(op["name"], 0) + 1
    return min(counts.values()) if counts else 0


def analyze_events(events: Iterable[dict], steps: Optional[int] = None,
                   top_ops: int = 5) -> dict:
    """Aggregate device-op events into the ranked bucket report.

    ``steps`` pins the per-step divisor (the admin route knows how many
    iterations it captured); otherwise it is inferred from the dominant
    module's execution count. Bucket shares partition total device time, so
    they sum to 1.0 (up to float rounding) by construction."""
    ops, malformed = device_op_events(events)
    total_us = sum(op["dur_us"] for op in ops)
    module_us: Dict[str, float] = {}
    for op in ops:
        module_us[op["module"]] = module_us.get(op["module"], 0.0) + op["dur_us"]
    dominant = max(module_us, key=module_us.get) if module_us else ""
    inferred = _infer_steps(ops, dominant) if dominant else 0
    n_steps = int(steps) if steps else (inferred or 1)

    per_bucket: Dict[str, dict] = {
        b: {"time_us": 0.0, "events": 0, "ops": {}} for b in BUCKETS
    }
    for op in ops:
        b = per_bucket[classify(op["name"], op["scope"])]
        b["time_us"] += op["dur_us"]
        b["events"] += 1
        # per-op rollup keyed by the dotless root (dot.3/dot.4 -> dot)
        root = op["name"].split(".")[0] or op["name"]
        agg = b["ops"].setdefault(root, [0.0, 0])
        agg[0] += op["dur_us"]
        agg[1] += 1

    buckets = []
    for name, b in per_bucket.items():
        if not b["events"]:
            continue
        ranked_ops = sorted(b["ops"].items(), key=lambda kv: -kv[1][0])[:top_ops]
        buckets.append({
            "bucket": name,
            "time_us": round(b["time_us"], 3),
            "share": round(b["time_us"] / total_us, 6) if total_us else 0.0,
            "events": b["events"],
            "per_step_us": round(b["time_us"] / max(n_steps, 1), 3),
            "top_ops": [
                {"op": op_name, "time_us": round(us, 3), "count": count}
                for op_name, (us, count) in ranked_ops
            ],
        })
    buckets.sort(key=lambda b: -b["time_us"])
    return {
        "total_device_us": round(total_us, 3),
        "device_op_events": len(ops),
        "malformed_events": malformed,
        "steps": n_steps,
        "steps_inferred": inferred,
        "dominant_module": dominant,
        "modules": {
            m: round(us, 3) for m, us in
            sorted(module_us.items(), key=lambda kv: -kv[1])
        },
        "step_time_device_us": round(total_us / max(n_steps, 1), 3),
        "buckets": buckets,
    }


def analyze_trace(path: str, steps: Optional[int] = None) -> dict:
    """Analyze one trace file (or the newest capture under a logdir)."""
    files = find_trace_files(path)
    if not files:
        raise FileNotFoundError(f"no *.trace.json(.gz) under {path!r}")
    report = analyze_events(_load_events(files[0]), steps=steps)
    report["trace_path"] = files[0]
    return report


def render_markdown(report: dict) -> str:
    """The ranked bucket table as markdown — the human-facing half of the
    artifact (the JSON half feeds tools/perf_gate.py)."""
    lines = [
        "| bucket | step-time share | per-step ms | total ms | events | top ops |",
        "|---|---|---|---|---|---|",
    ]
    for b in report.get("buckets", []):
        tops = ", ".join(
            f"{o['op']} ({o['time_us'] / 1e3:.2f}ms)" for o in b.get("top_ops", [])[:3]
        )
        lines.append(
            f"| {b['bucket']} | {b['share'] * 100:.1f}% "
            f"| {b['per_step_us'] / 1e3:.2f} | {b['time_us'] / 1e3:.2f} "
            f"| {b['events']} | {tops} |"
        )
    total_ms = report.get("total_device_us", 0.0) / 1e3
    lines.append(
        f"\ndevice time {total_ms:.2f} ms over {report.get('steps', 1)} step(s) "
        f"({report.get('device_op_events', 0)} device-op events, "
        f"module {report.get('dominant_module') or '?'})"
    )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(
        description="rank device-op buckets from a jax.profiler trace")
    p.add_argument("path", help="trace file or profiler logdir")
    p.add_argument("--steps", type=int, default=0,
                   help="iterations captured (default: inferred)")
    p.add_argument("--json", default="", help="also write the JSON report here")
    args = p.parse_args(argv)
    report = analyze_trace(args.path, steps=args.steps or None)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
    sys.stdout.write(render_markdown(report) + "\n")
    return 0


if __name__ == "__main__":  # pragma: no cover - CLI shim
    raise SystemExit(main())
