"""Crash flight recorder: bounded event ring + forensic dump on the way down.

Long-lived fleet processes rarely die cleanly — the question after the fact
is always "what was happening in the last minute". The recorder keeps a
bounded ring of recent structured events (alert transitions from the rules
engine, span completions, checkpoint/swap milestones — anything a subsystem
``record()``s), and a crash hook (unhandled exception + SIGTERM) dumps a
forensic bundle to the artifact dir: the event ring, a full registry
snapshot, the run config, and interpreter/library versions. The bundle is
plain JSON so it survives the process that wrote it.
"""
from __future__ import annotations

import json
import os
import platform
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

from .registry import MetricsRegistry, get_registry


def _versions() -> Dict[str, str]:
    out = {
        "python": platform.python_version(),
        "platform": platform.platform(),
    }
    for mod in ("jax", "numpy"):
        m = sys.modules.get(mod)  # never import heavyweight deps from a crash path
        v = getattr(m, "__version__", None) if m is not None else None
        if v:
            out[mod] = str(v)
    return out


class FlightRecorder:
    """Thread-safe bounded ring of structured events + crash-dump hooks."""

    def __init__(self, maxlen: int = 512):
        assert maxlen > 0
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=maxlen)
        self._seq = 0
        self._hook_installed = False
        self._prev_excepthook = None
        self._prev_sigterm = None
        self._dump_args: Dict[str, Any] = {}
        self._crash_callbacks: List[Any] = []
        self.last_dump_path: Optional[str] = None

    # ------------------------------------------------------- crash callbacks
    def add_crash_callback(self, fn) -> None:
        """Register a cleanup to run whenever a crash bundle is dumped —
        resource reclamation that must happen even on an unclean exit (the
        shm transport unlinks its live rings here so a crashed fleet
        leaves no /dev/shm litter). Idempotent per callable; every failure
        is swallowed (cleanup must never raise over the crash)."""
        with self._lock:
            if fn not in self._crash_callbacks:
                self._crash_callbacks.append(fn)

    def _run_crash_callbacks(self) -> None:
        with self._lock:
            callbacks = list(self._crash_callbacks)
        for fn in callbacks:
            try:
                fn()
            except Exception:
                pass

    # ----------------------------------------------------------------- events
    def record(self, kind: str, **fields) -> dict:
        """Append one structured event; returns it (with ts + seq stamped)."""
        with self._lock:
            self._seq += 1
            event = {"seq": self._seq, "ts": time.time(), "kind": str(kind), **fields}
            self._events.append(event)
        return event

    def events(self, limit: Optional[int] = None, kind: Optional[str] = None) -> List[dict]:
        with self._lock:
            out = list(self._events)
        if kind is not None:
            out = [e for e in out if e.get("kind") == kind]
        return out[-limit:] if limit else out

    # ------------------------------------------------------------------ dumps
    def dump(self, artifact_dir: str, reason: str, config: Optional[dict] = None,
             registry: Optional[MetricsRegistry] = None,
             extra: Optional[dict] = None) -> str:
        """Write the forensic bundle; returns its path. Every failure mode
        short of the filesystem itself is swallowed into the bundle — a crash
        dump must not raise over the crash it is documenting."""
        self._run_crash_callbacks()
        reg = registry or get_registry()
        try:
            snapshot = reg.snapshot()
        except Exception as e:
            snapshot = {"__snapshot_error__": repr(e)}
        bundle = {
            "ts": time.time(),
            "reason": str(reason),
            "pid": os.getpid(),
            "versions": _versions(),
            "config": config if config is not None else self._dump_args.get("config"),
            "events": self.events(),
            "registry_snapshot": snapshot,
        }
        if extra:
            bundle.update(extra)
        os.makedirs(artifact_dir, exist_ok=True)
        fname = f"flight_{time.strftime('%Y%m%d_%H%M%S')}_{os.getpid()}.json"
        path = os.path.join(artifact_dir, fname)
        with open(path, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
        self.last_dump_path = path
        return path

    # ------------------------------------------------------------- crash hook
    def install_crash_hook(self, artifact_dir: str, config: Optional[dict] = None,
                           registry: Optional[MetricsRegistry] = None,
                           handle_sigterm: bool = True) -> None:
        """Chain onto ``sys.excepthook`` (unhandled exception -> bundle, then
        the previous hook runs) and, from the main thread, onto SIGTERM
        (bundle, then the previous disposition). Idempotent per recorder."""
        if self._hook_installed:
            self._dump_args = {"artifact_dir": artifact_dir, "config": config,
                               "registry": registry}
            return
        self._dump_args = {"artifact_dir": artifact_dir, "config": config,
                           "registry": registry}
        self._prev_excepthook = sys.excepthook

        def _excepthook(exc_type, exc, tb):
            try:
                self.record(
                    "crash",
                    error=repr(exc),
                    traceback="".join(traceback.format_exception(exc_type, exc, tb))[-8000:],
                )
                self.dump(
                    self._dump_args["artifact_dir"],
                    reason=f"unhandled:{getattr(exc_type, '__name__', exc_type)}",
                    config=self._dump_args.get("config"),
                    registry=self._dump_args.get("registry"),
                )
            except Exception:
                pass
            prev = self._prev_excepthook or sys.__excepthook__
            prev(exc_type, exc, tb)

        sys.excepthook = _excepthook

        if handle_sigterm:
            def _on_sigterm(signum, frame):
                try:
                    self.record("signal", signum=signum)
                    self.dump(
                        self._dump_args["artifact_dir"],
                        reason=f"signal:{signum}",
                        config=self._dump_args.get("config"),
                        registry=self._dump_args.get("registry"),
                    )
                except Exception:
                    pass
                prev = self._prev_sigterm
                if callable(prev):
                    prev(signum, frame)
                elif prev == signal.SIG_DFL:
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            try:  # only the main thread may set signal handlers
                self._prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
            except ValueError:
                self._prev_sigterm = None
        self._hook_installed = True

    def uninstall_crash_hook(self) -> None:
        if not self._hook_installed:
            return
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None
        self._hook_installed = False


_recorder_lock = threading.Lock()
_recorder: Optional[FlightRecorder] = None


def get_flight_recorder() -> FlightRecorder:
    """The process-wide default recorder (created on first use)."""
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def set_flight_recorder(recorder: Optional[FlightRecorder]) -> Optional[FlightRecorder]:
    """Swap the process default (tests install a fresh one); returns the
    previous recorder."""
    global _recorder
    with _recorder_lock:
        prev = _recorder
        _recorder = recorder
        return prev
