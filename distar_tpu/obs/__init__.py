"""Unified telemetry + fleet health layer.

Instrumentation side (PR 1): one process-wide ``MetricsRegistry``
(``get_registry()``) that every layer — actor, env pool, comm
shuttle/coordinator, learner, league, serve — publishes into; Prometheus
text + JSONL exporters; explicit-context trace spans that ride payloads
actor→comm→learner; freq-gated profiler hooks.

Consumption side (this package's fleet-health subsystem): a bounded
ring-buffer ``TimeSeriesStore`` fed by a ``RegistrySampler``; a
``TelemetryShipper`` pushing compact snapshots from every fleet process to
the coordinator's ``TelemetryIngest``; a declarative ``HealthRule`` engine
with a debounced ok→warning→firing state machine (``HealthEvaluator``,
``default_rulebook``); and a ``FlightRecorder`` crash bundle. Surfaced via
``GET /healthz``, ``/alerts``, ``/timeseries`` on the coordinator and serve
HTTP frontends, and ``tools/opsctl.py``. See docs/observability.md.
"""
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    set_registry,
)
from .exporters import (
    PROMETHEUS_CONTENT_TYPE,
    JsonlExporter,
    handle_health_get,
    render_prometheus,
    write_json_response,
    write_scrape_response,
)
from .trace import (
    Span,
    annotate,
    annotate_active,
    current_trace,
    finish_trace,
    format_traceparent,
    hop_names,
    is_trace,
    is_wire_ctx,
    join_trace,
    mark_hop,
    mint_span_id,
    parse_traceparent,
    set_active_trace,
    set_tracing,
    start_trace,
    trace_record,
    tracing_enabled,
    unwrap_payload,
    wire_ctx,
    wrap_payload,
)
from .tracestore import (
    ExemplarStore,
    TraceBuffer,
    TraceIngest,
    get_exemplar_store,
    get_trace_buffer,
    note_exemplar,
    set_exemplar_store,
    set_trace_buffer,
)
from .waterfall import build_waterfall, render_listing, render_waterfall
from .profiler import ProfilerSession, record_step_phases
from .perf import (
    PerfMonitor,
    estimate_collective_bytes,
    flops_of_compiled,
    flops_of_lowered,
    memory_report,
    peak_flops,
)
from .traceview import analyze_trace, classify, render_markdown
from .timeseries import RegistrySampler, TimeSeriesStore
from .shipper import SERIALIZED_CONTENT_TYPE, TelemetryIngest, TelemetryShipper
from .flightrecorder import FlightRecorder, get_flight_recorder, set_flight_recorder
from .dynamics import (
    ANOMALY_CLASSES,
    BUNDLE_SCHEMA,
    DYNAMICS_DEFAULTS,
    DynamicsMonitor,
    DynamicsSpec,
    bundle_summary,
    config_digest,
    dynamics_tree,
    first_nonfinite,
    list_bundles,
    load_bundle,
    split_tree,
    tree_spec,
)
from .health import (
    FleetHealth,
    HealthEvaluator,
    HealthRule,
    default_rulebook,
    get_fleet_health,
    init_fleet_health,
    set_fleet_health,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "set_registry",
    "PROMETHEUS_CONTENT_TYPE",
    "JsonlExporter",
    "handle_health_get",
    "render_prometheus",
    "write_json_response",
    "write_scrape_response",
    "Span",
    "annotate",
    "annotate_active",
    "current_trace",
    "finish_trace",
    "format_traceparent",
    "hop_names",
    "is_trace",
    "is_wire_ctx",
    "join_trace",
    "mark_hop",
    "mint_span_id",
    "parse_traceparent",
    "set_active_trace",
    "set_tracing",
    "start_trace",
    "trace_record",
    "tracing_enabled",
    "unwrap_payload",
    "wire_ctx",
    "wrap_payload",
    "ExemplarStore",
    "TraceBuffer",
    "TraceIngest",
    "get_exemplar_store",
    "get_trace_buffer",
    "note_exemplar",
    "set_exemplar_store",
    "set_trace_buffer",
    "build_waterfall",
    "render_listing",
    "render_waterfall",
    "ProfilerSession",
    "record_step_phases",
    "PerfMonitor",
    "estimate_collective_bytes",
    "flops_of_compiled",
    "flops_of_lowered",
    "memory_report",
    "peak_flops",
    "analyze_trace",
    "classify",
    "render_markdown",
    "RegistrySampler",
    "TimeSeriesStore",
    "SERIALIZED_CONTENT_TYPE",
    "TelemetryIngest",
    "TelemetryShipper",
    "FlightRecorder",
    "get_flight_recorder",
    "set_flight_recorder",
    "ANOMALY_CLASSES",
    "BUNDLE_SCHEMA",
    "DYNAMICS_DEFAULTS",
    "DynamicsMonitor",
    "DynamicsSpec",
    "bundle_summary",
    "config_digest",
    "dynamics_tree",
    "first_nonfinite",
    "list_bundles",
    "load_bundle",
    "split_tree",
    "tree_spec",
    "FleetHealth",
    "HealthEvaluator",
    "HealthRule",
    "default_rulebook",
    "get_fleet_health",
    "init_fleet_health",
    "set_fleet_health",
]
