"""Unified telemetry layer: metrics registry, trace spans, profiler hooks.

One process-wide ``MetricsRegistry`` (``get_registry()``) that every layer —
actor, env pool, comm shuttle/coordinator, learner, league — publishes into;
two exporters (Prometheus text served from the coordinator's ``/metrics``
route, JSONL composing with the utils.log scalar sink); explicit-context
trace spans that ride payloads actor→comm→learner. See docs/observability.md.
"""
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    set_registry,
)
from .exporters import (
    PROMETHEUS_CONTENT_TYPE,
    JsonlExporter,
    render_prometheus,
    write_scrape_response,
)
from .trace import (
    Span,
    finish_trace,
    hop_names,
    is_trace,
    mark_hop,
    mint_span_id,
    start_trace,
    unwrap_payload,
    wrap_payload,
)
from .profiler import ProfilerSession, record_step_phases

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "set_registry",
    "PROMETHEUS_CONTENT_TYPE",
    "JsonlExporter",
    "render_prometheus",
    "write_scrape_response",
    "Span",
    "finish_trace",
    "hop_names",
    "is_trace",
    "mark_hop",
    "mint_span_id",
    "start_trace",
    "unwrap_payload",
    "wrap_payload",
    "ProfilerSession",
    "record_step_phases",
]
