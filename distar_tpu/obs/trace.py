"""Distributed trace spans with explicit context propagation.

A trace context is a plain picklable dict minted at the head of a request or
pipeline (the actor when a trajectory is born, the serve client when a
request leaves the process) that rides the payload through every hop —
adapter push, shuttle transfer, serve TCP frame, replay insert frame —
into the consumer. Each ``mark_hop`` records the hop-to-hop latency into the
registry (``distar_trace_hop_seconds{hop=...}``); ``finish_trace`` records
the end-to-end age (``distar_trace_e2e_seconds{name=...}``) AND folds the
completed span into the process ``TraceBuffer`` (``obs/tracestore.py``),
whose tail sampler decides what ships to the coordinator's trace store.

Cross-process propagation is a **compact wire field** (``wire_ctx``: just
``{trace_id, span_id}``) stamped into request frames and ``traceparent``
HTTP headers; the receiving process ``join_trace``s it — minting its own
child span under the SAME trace_id with ``parent_span_id`` set — so the
client span, router span and gateway span of one request assemble into one
waterfall (``obs/waterfall.py``) on the coordinator.

Attribution: hops say *when* a context moved; ``annotate`` accumulates
*why time passed* onto the live span under a small closed vocabulary —
``queue_s`` (waiting for a flush/slot), ``blocked_s`` (flow control: replay
rate limiter, shm ring-full), ``service_s`` (actual compute), ``retry_s``
(fleet re-route/retry) — which is what the waterfall decomposes. Blocking
primitives that cannot see the request's context (the rate limiter, the
ring writer) annotate the thread's *active* context instead
(``set_active_trace`` / ``annotate_active``).

Explicit-context (dict in the payload) rather than implicit (contextvars)
because the pipeline crosses process and host boundaries through pickled
payloads — the context must serialize with the data it describes.

``set_tracing(False)`` (or ``DISTAR_TRACE=0``) disables span *minting* at
every client/server site, for the overhead A/B and byte-identical wire runs;
retention cost is bounded by the tail sampler either way.
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import List, Optional

from .registry import MetricsRegistry, get_registry

#: annotation vocabulary the waterfall analyzer decomposes (free-form keys
#: still render, under "other")
ANNOTATION_KINDS = ("queue_s", "blocked_s", "service_s", "retry_s")

_tracing_enabled = os.environ.get("DISTAR_TRACE", "1").lower() not in (
    "0", "false", "no")


def tracing_enabled() -> bool:
    return _tracing_enabled


def set_tracing(enabled: bool) -> bool:
    """Flip span minting process-wide; returns the previous setting (tests
    and the overhead A/B restore it)."""
    global _tracing_enabled
    prev = _tracing_enabled
    _tracing_enabled = bool(enabled)
    return prev


#: PRNG for span ids: seeded from the OS once, then syscall-free — ids are
#: correlation handles, not secrets, and the urandom syscall per id was a
#: measurable share of the per-request tracing cost
_id_rand = random.Random(os.urandom(16))


def mint_span_id() -> str:
    """64-bit random hex span/trace id (w3c-traceparent-sized)."""
    return f"{_id_rand.getrandbits(64):016x}"


def _instrument(kind: str, reg: MetricsRegistry, name: str, help_: str,
                **labels):
    """Per-registry memo around instrument resolution: ``registry._get``
    takes a lock and sorts the label set on every call, which a per-request
    hot path pays thousands of times for the same instrument. The memo
    lives ON the registry so it dies with it (tests swap registries
    freely)."""
    cache = getattr(reg, "_trace_inst_cache", None)
    if cache is None:
        cache = reg._trace_inst_cache = {}
    key = (kind, name) + tuple(sorted(labels.items()))
    inst = cache.get(key)
    if inst is None:
        inst = cache[key] = getattr(reg, kind)(name, help_, **labels)
    return inst


def start_trace(name: str, registry: Optional[MetricsRegistry] = None, **attrs) -> dict:
    """Mint a new trace context. ``attrs`` are free-form, low-cardinality
    annotations (player id, token) carried for debugging, not used as labels."""
    now = time.time()
    ctx = {
        "name": str(name),
        "trace_id": mint_span_id(),
        "span_id": mint_span_id(),
        "t_start": now,
        "hops": [{"hop": "start", "ts": now}],
    }
    if attrs:
        ctx["attrs"] = {k: str(v) for k, v in attrs.items()}
    return ctx


def is_trace(ctx) -> bool:
    return (
        isinstance(ctx, dict)
        and "trace_id" in ctx
        and "span_id" in ctx
        and isinstance(ctx.get("hops"), list)
    )


# -------------------------------------------------------- wire propagation
def wire_ctx(ctx: Optional[dict]) -> Optional[dict]:
    """The compact cross-process trace-context field: rides request frames
    (``req["trace"]``) and ``traceparent`` headers. Carries only identity —
    the receiver minting a child span is what makes it cheap."""
    if not is_trace(ctx):
        return None
    return {"trace_id": ctx["trace_id"], "span_id": ctx["span_id"]}


def is_wire_ctx(w) -> bool:
    return (isinstance(w, dict)
            and isinstance(w.get("trace_id"), str)
            and isinstance(w.get("span_id"), str))


def join_trace(wire, name: str, registry: Optional[MetricsRegistry] = None,
               **attrs) -> dict:
    """Server-side join: mint a child context under the caller's trace.
    A missing/garbage wire field degrades to a fresh root trace — a legacy
    client must never break a tracing server."""
    if not is_wire_ctx(wire):
        return start_trace(name, registry=registry, **attrs)
    now = time.time()
    ctx = {
        "name": str(name),
        "trace_id": str(wire["trace_id"]),
        "parent_span_id": str(wire["span_id"]),
        "span_id": mint_span_id(),
        "t_start": now,
        "hops": [{"hop": "start", "ts": now}],
    }
    if attrs:
        ctx["attrs"] = {k: str(v) for k, v in attrs.items()}
    return ctx


_TP_VERSION = "00"


def format_traceparent(ctx_or_wire) -> Optional[str]:
    """W3C ``traceparent`` header for a context (or compact wire field).
    Our ids are 8 bytes; the 16-byte w3c trace-id is left-zero-padded."""
    w = wire_ctx(ctx_or_wire) if is_trace(ctx_or_wire) else ctx_or_wire
    if not is_wire_ctx(w):
        return None
    return f"{_TP_VERSION}-{w['trace_id'].zfill(32)}-{w['span_id']}-01"


def parse_traceparent(header: Optional[str]) -> Optional[dict]:
    """Parse a ``traceparent`` header into the compact wire field (None on
    anything malformed — a garbage header is ignored, never an error)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    _ver, tid, sid = parts[0], parts[1], parts[2]
    if len(tid) != 32 or len(sid) != 16:
        return None
    try:
        int(tid, 16), int(sid, 16)
    except ValueError:
        return None
    # our ids are the low 8 bytes; a foreign full-width id keeps its tail
    return {"trace_id": tid[-16:], "span_id": sid}


# ------------------------------------------------------------- annotations
def annotate(ctx: Optional[dict], key: str, seconds: float) -> None:
    """Accumulate wall-clock attribution onto the live span (``queue_s``,
    ``blocked_s``, ``service_s``, ``retry_s`` — the waterfall vocabulary)."""
    if not is_trace(ctx) or seconds <= 0:
        return
    annot = ctx.setdefault("annot", {})
    annot[key] = annot.get(key, 0.0) + float(seconds)


_active = threading.local()


def set_active_trace(ctx: Optional[dict]):
    """Install ``ctx`` as this THREAD's active trace and return the previous
    one (callers restore it in a finally). Blocking primitives that can't
    see the request's context — the replay rate limiter, the shm ring
    writer — attribute their waits to the active context."""
    prev = getattr(_active, "ctx", None)
    _active.ctx = ctx
    return prev


def current_trace() -> Optional[dict]:
    return getattr(_active, "ctx", None)


def annotate_active(key: str, seconds: float) -> None:
    annotate(getattr(_active, "ctx", None), key, seconds)


# -------------------------------------------------------------------- hops
def mark_hop(ctx: dict, hop: str, registry: Optional[MetricsRegistry] = None) -> float:
    """Append a hop to the context and record the latency since the previous
    hop into ``distar_trace_hop_seconds{hop=...}``. Returns that latency.

    Cross-host clock skew can make the raw delta NEGATIVE; the histogram
    clamps to 0 (a latency series must not go negative) but the clamp is
    never silent: the raw delta rides the hop record (``raw_dt``) so the
    waterfall analyzer can flag skewed traces instead of rendering lies,
    and every clamp is counted in ``distar_trace_clock_skew_total{hop}``."""
    if not is_trace(ctx):
        return 0.0
    now = time.time()
    prev_ts = ctx["hops"][-1]["ts"] if ctx["hops"] else ctx["t_start"]
    raw = now - prev_ts
    dt = max(0.0, raw)
    rec = {"hop": str(hop), "ts": now}
    reg = registry or get_registry()
    if raw < 0:
        rec["raw_dt"] = raw
        _instrument(
            "counter", reg, "distar_trace_clock_skew_total",
            "hop deltas clamped to 0 because the clock ran backwards "
            "(cross-host skew — the raw delta stays on the hop record)",
            hop=str(hop),
        ).inc()
    ctx["hops"].append(rec)
    _instrument(
        "histogram", reg, "distar_trace_hop_seconds",
        "per-hop pipeline latency", hop=str(hop),
    ).observe(dt)
    return dt


def trace_record(ctx: dict, outcome: str = "ok") -> Optional[dict]:
    """Flatten a finished context into the compact span record the
    ``TraceBuffer`` keeps and ships (plain JSON-able types only)."""
    if not is_trace(ctx):
        return None
    end_ts = ctx["hops"][-1]["ts"] if ctx["hops"] else time.time()
    rec = {
        "trace_id": ctx["trace_id"],
        "span_id": ctx["span_id"],
        "name": ctx["name"],
        "ts": ctx["t_start"],
        "dur_s": max(0.0, end_ts - ctx["t_start"]),
        "outcome": str(outcome),
        # the context is dead after finish: hop dicts are safe to share
        "hops": list(ctx["hops"]),
        "pid": os.getpid(),
    }
    if "parent_span_id" in ctx:
        rec["parent_span_id"] = ctx["parent_span_id"]
    if ctx.get("annot"):
        rec["annot"] = {k: round(float(v), 6) for k, v in ctx["annot"].items()}
    if ctx.get("attrs"):
        rec["attrs"] = dict(ctx["attrs"])
    if any("raw_dt" in h for h in ctx["hops"]):
        rec["skew"] = True
    return rec


def finish_trace(ctx: dict, hop: str = "end",
                 registry: Optional[MetricsRegistry] = None,
                 outcome: str = "ok") -> float:
    """Terminal hop: records the hop latency plus the end-to-end trace age
    (``distar_trace_e2e_seconds{name=...}``), folds the completed span into
    the process ``TraceBuffer`` (tail-sampled; error/shed outcomes are
    always kept) and notes the trace as the latency exemplar for its e2e
    series. Idempotent per context. Returns the e2e age."""
    if not is_trace(ctx) or ctx.get("_finished"):
        return 0.0
    ctx["_finished"] = True
    mark_hop(ctx, hop, registry=registry)
    age = max(0.0, ctx["hops"][-1]["ts"] - ctx["t_start"])
    reg = registry or get_registry()
    _instrument(
        "histogram", reg, "distar_trace_e2e_seconds",
        "end-to-end pipeline trace age", span=ctx["name"],
    ).observe(age)
    kept = tracestore.get_trace_buffer().offer(
        ctx["name"], age, outcome, lambda: trace_record(ctx, outcome=outcome))
    ctx["_kept"] = kept  # observers gate their exemplar notes on retention
    if kept:
        # exemplars point only at RETAINED traces (an exemplar naming a
        # sampled-out trace_id would 404 on retrieval); the slow tail is
        # always retained, so the freshest exemplar is the one that matters
        tracestore.note_exemplar(_exemplar_key(ctx["name"]), ctx["trace_id"], age)
        # KEPT span completions land in the crash flight recorder's bounded
        # ring — "what was the pipeline doing in the last minute" forensics;
        # trace_id included so a crash bundle cross-references the
        # coordinator trace store (sampled-out ok spans would wash the 512-
        # event ring out in milliseconds at serve rates)
        from .flightrecorder import get_flight_recorder

        event = {"name": ctx["name"], "trace_id": ctx["trace_id"],
                 "age_s": round(age, 4), "hops": hop_names(ctx)}
        if outcome != "ok":
            event["outcome"] = str(outcome)
        get_flight_recorder().record("span", **event)
    return age


_exemplar_keys: dict = {}


def _exemplar_key(name: str) -> str:
    key = _exemplar_keys.get(name)
    if key is None:
        key = _exemplar_keys[name] = f"distar_trace_e2e_seconds{{span={name}}}"
    return key


def hop_names(ctx: dict) -> List[str]:
    return [h["hop"] for h in ctx.get("hops", [])] if is_trace(ctx) else []


class Span:
    """In-process timed region publishing ``distar_span_seconds{name=...}``.

    ``with Span("collate"): ...`` — the lightweight sibling of the
    cross-process trace context, for regions that never leave the process.
    The exit path records the region's ``outcome`` (``ok``/``error``); a
    span that exits on an exception counts ``distar_span_errors_total`` and
    ships a ``span_error`` event (exception type + optional trace_id) to
    the flight recorder ring, so crash bundles show WHICH region died, not
    just that the process did."""

    def __init__(self, name: str, registry: Optional[MetricsRegistry] = None,
                 trace: Optional[dict] = None):
        self.name = name
        self.span_id = mint_span_id()
        self.trace_id = trace["trace_id"] if is_trace(trace) else None
        self.outcome = "ok"
        self._registry = registry
        self._start = 0.0
        self.elapsed = 0.0

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.elapsed = time.perf_counter() - self._start
        self.outcome = "ok" if exc_type is None else "error"
        reg = self._registry or get_registry()
        reg.histogram(
            "distar_span_seconds", "in-process span duration", span=self.name
        ).observe(self.elapsed)
        if exc_type is not None:
            reg.counter(
                "distar_span_errors_total",
                "in-process spans that exited on an exception", span=self.name,
            ).inc()
            from .flightrecorder import get_flight_recorder

            event = {"name": self.name,
                     "error": getattr(exc_type, "__name__", str(exc_type)),
                     "elapsed_s": round(self.elapsed, 4)}
            if self.trace_id:
                event["trace_id"] = self.trace_id
            get_flight_recorder().record("span_error", **event)
        return False


# ------------------------------------------------------- payload envelope
# The adapter wraps payloads carrying a trace in this envelope; the receive
# side unwraps transparently so non-instrumented consumers see plain data.
_ENVELOPE_KEY = "__distar_trace__"


def wrap_payload(data, ctx: Optional[dict]):
    if ctx is None:
        return data
    return {_ENVELOPE_KEY: ctx, "payload": data}


def unwrap_payload(data):
    """Returns (payload, ctx_or_None)."""
    if isinstance(data, dict) and _ENVELOPE_KEY in data:
        return data.get("payload"), data[_ENVELOPE_KEY]
    return data, None


# bottom import (cycle-safe: tracestore needs _instrument from above) so the
# per-span hot path doesn't pay a sys.modules lookup per finish
from . import tracestore  # noqa: E402
