"""Pipeline trace spans with explicit context propagation.

A trace context is a plain picklable dict minted at the head of the pipeline
(the actor, when a trajectory is born) that rides the payload through every
hop — adapter push, shuttle transfer, adapter pull, dataloader collation —
into the learner. Each ``mark_hop`` records the hop-to-hop latency into the
registry (``distar_trace_hop_seconds{hop=...}``); ``finish`` records the
end-to-end age (``distar_trace_e2e_seconds{name=...}``), which for
trajectories IS the data-plane half of staleness: wall-clock from the
actor's last env step to the learner consuming the batch.

Explicit-context (dict in the payload) rather than implicit (contextvars)
because the pipeline crosses process and host boundaries through pickled
payloads — the context must serialize with the data it describes.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from .registry import MetricsRegistry, get_registry


def mint_span_id() -> str:
    """64-bit random hex span/trace id (w3c-traceparent-sized)."""
    return os.urandom(8).hex()


def start_trace(name: str, registry: Optional[MetricsRegistry] = None, **attrs) -> dict:
    """Mint a new trace context. ``attrs`` are free-form, low-cardinality
    annotations (player id, token) carried for debugging, not used as labels."""
    now = time.time()
    ctx = {
        "name": str(name),
        "trace_id": mint_span_id(),
        "span_id": mint_span_id(),
        "t_start": now,
        "hops": [{"hop": "start", "ts": now}],
    }
    if attrs:
        ctx["attrs"] = {k: str(v) for k, v in attrs.items()}
    return ctx


def is_trace(ctx) -> bool:
    return (
        isinstance(ctx, dict)
        and "trace_id" in ctx
        and "span_id" in ctx
        and isinstance(ctx.get("hops"), list)
    )


def mark_hop(ctx: dict, hop: str, registry: Optional[MetricsRegistry] = None) -> float:
    """Append a hop to the context and record the latency since the previous
    hop into ``distar_trace_hop_seconds{hop=...}``. Returns that latency."""
    if not is_trace(ctx):
        return 0.0
    now = time.time()
    prev_ts = ctx["hops"][-1]["ts"] if ctx["hops"] else ctx["t_start"]
    dt = max(0.0, now - prev_ts)
    ctx["hops"].append({"hop": str(hop), "ts": now})
    reg = registry or get_registry()
    reg.histogram(
        "distar_trace_hop_seconds", "per-hop pipeline latency", hop=str(hop)
    ).observe(dt)
    return dt


def finish_trace(ctx: dict, hop: str = "end", registry: Optional[MetricsRegistry] = None) -> float:
    """Terminal hop: records the hop latency plus the end-to-end trace age
    (``distar_trace_e2e_seconds{name=...}``). Returns the e2e age."""
    if not is_trace(ctx):
        return 0.0
    mark_hop(ctx, hop, registry=registry)
    age = max(0.0, ctx["hops"][-1]["ts"] - ctx["t_start"])
    reg = registry or get_registry()
    reg.histogram(
        "distar_trace_e2e_seconds", "end-to-end pipeline trace age", span=ctx["name"]
    ).observe(age)
    # span completions land in the crash flight recorder's bounded ring —
    # "what was the pipeline doing in the last minute" forensics
    from .flightrecorder import get_flight_recorder

    get_flight_recorder().record(
        "span", name=ctx["name"], age_s=round(age, 4), hops=hop_names(ctx)
    )
    return age


def hop_names(ctx: dict) -> List[str]:
    return [h["hop"] for h in ctx.get("hops", [])] if is_trace(ctx) else []


class Span:
    """In-process timed region publishing ``distar_span_seconds{name=...}``.

    ``with Span("collate"): ...`` — the lightweight sibling of the
    cross-process trace context, for regions that never leave the process."""

    def __init__(self, name: str, registry: Optional[MetricsRegistry] = None):
        self.name = name
        self.span_id = mint_span_id()
        self._registry = registry
        self._start = 0.0
        self.elapsed = 0.0

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._start
        reg = self._registry or get_registry()
        reg.histogram(
            "distar_span_seconds", "in-process span duration", span=self.name
        ).observe(self.elapsed)
        return False


# ------------------------------------------------------- payload envelope
# The adapter wraps payloads carrying a trace in this envelope; the receive
# side unwraps transparently so non-instrumented consumers see plain data.
_ENVELOPE_KEY = "__distar_trace__"


def wrap_payload(data, ctx: Optional[dict]):
    if ctx is None:
        return data
    return {_ENVELOPE_KEY: ctx, "payload": data}


def unwrap_payload(data):
    """Returns (payload, ctx_or_None)."""
    if isinstance(data, dict) and _ENVELOPE_KEY in data:
        return data.get("payload"), data[_ENVELOPE_KEY]
    return data, None
