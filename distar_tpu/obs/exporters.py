"""Registry exporters: Prometheus text exposition + JSONL scalar dump.

Prometheus rendering follows the text exposition format (v0.0.4): counters
and gauges render one sample per labelset; histograms render as summaries
(quantile-labelled samples + ``_sum``/``_count``), which matches their
bounded-reservoir semantics. The JSONL exporter composes with the existing
fallback sink in ``utils/log.py`` (ScalarSink) so registry snapshots land in
the same ``scalars.jsonl`` stream training metrics already use.
"""
from __future__ import annotations

import json
import math
from typing import Optional

from .registry import MetricsRegistry, get_registry

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_QUANTILES = (0.5, 0.9, 0.99)


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    v = float(v)
    # non-finite values per the Prometheus text format: "NaN", "+Inf",
    # "-Inf" — repr() would emit "nan"/"inf", which scrapers reject
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _labels_text(key, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """Render the registry in Prometheus text exposition format."""
    registry = registry or get_registry()
    lines = []
    for fam in registry.collect():
        name, kind = fam["name"], fam["type"]
        prom_type = {"counter": "counter", "gauge": "gauge", "histogram": "summary"}[kind]
        if fam["help"]:
            lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {name} {prom_type}")
        for key, inst in fam["series"]:
            if kind == "histogram":
                qs = inst.quantiles(_QUANTILES)
                for q in _QUANTILES:
                    qlabel = 'quantile="%s"' % q
                    lines.append(f"{name}{_labels_text(key, qlabel)} {_fmt(qs[q])}")
                lines.append(f"{name}_sum{_labels_text(key)} {_fmt(inst.sum)}")
                lines.append(f"{name}_count{_labels_text(key)} {_fmt(inst.count)}")
            else:
                lines.append(f"{name}{_labels_text(key)} {_fmt(inst.value)}")
    return "\n".join(lines) + "\n" if lines else ""


def write_scrape_response(handler, refresh=None, registry: Optional[MetricsRegistry] = None) -> None:
    """Answer a ``GET /metrics`` scrape on a ``BaseHTTPRequestHandler``.

    The one scrape route every HTTP surface shares (coordinator broker,
    serve gateway): run ``refresh()`` (scrape-time gauge publication), render
    the registry, write the response. A failing refresh/render answers 500
    with the repr — a scrape must never wedge the serving process."""
    try:
        if refresh is not None:
            refresh()
        data = render_prometheus(registry).encode()
        status, ctype = 200, PROMETHEUS_CONTENT_TYPE
    except Exception as e:
        data = repr(e).encode()
        status, ctype = 500, "text/plain"
    handler.send_response(status)
    handler.send_header("Content-Type", ctype)
    handler.send_header("Content-Length", str(len(data)))
    handler.end_headers()
    handler.wfile.write(data)


def write_json_response(handler, obj, status: int = 200) -> None:
    """Answer a GET with a JSON body on a ``BaseHTTPRequestHandler``."""
    data = json.dumps(obj, default=str).encode()
    handler.send_response(status)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(data)))
    handler.end_headers()
    handler.wfile.write(data)


def handle_health_get(handler, path: str) -> bool:
    """Answer the fleet-health GET routes shared by every HTTP surface
    (coordinator broker, serve gateway, replay admin):

      GET /healthz                           overall state + per-source staleness
                                             (HTTP 503 while any rule is firing)
      GET /alerts                            per-rule states + transition history
      GET /timeseries?name=&window_s=&source=  windowed stats + raw points
      GET /traces?name=&min_ms=&outcome=&limit=  retained trace listings
                                             (shipped ingest + this process's
                                             tail-sampled buffer)
      GET /trace/<id>                        one trace's span records + the
                                             assembled waterfall report

    Returns False when ``path`` is not a health route (caller 404s). Route
    failures answer 500 — an ops probe must never wedge the serving process."""
    from urllib.parse import parse_qs, urlparse

    parsed = urlparse(path)
    route = parsed.path.rstrip("/")
    if route not in ("/healthz", "/alerts", "/timeseries", "/traces") \
            and not route.startswith("/trace/"):
        return False
    try:
        from .health import get_fleet_health

        fleet = get_fleet_health()
        if route == "/healthz":
            body = fleet.healthz()
            write_json_response(handler, body,
                                status=503 if body["status"] == "firing" else 200)
        elif route == "/alerts":
            write_json_response(handler, fleet.evaluator.alerts())
        elif route == "/traces":
            from .tracestore import _listing, get_trace_buffer

            q = parse_qs(parsed.query)
            name = (q.get("name") or [None])[0] or None
            outcome = (q.get("outcome") or [None])[0] or None
            min_ms = float((q.get("min_ms") or ["0"])[0])
            limit = int((q.get("limit") or ["50"])[0])
            rows = fleet.traces.query(name=name, min_ms=min_ms,
                                      outcome=outcome, limit=limit)
            # the process's OWN tail-sampled buffer answers too, so a lone
            # gateway/store is inspectable without a coordinator in front
            for rec in get_trace_buffer().records():
                if name and rec.get("name") != name:
                    continue
                if outcome and rec.get("outcome", "ok") != outcome:
                    continue
                if float(rec.get("dur_s", 0.0)) * 1000.0 < min_ms:
                    continue
                rows.append(_listing(rec, "local"))
            rows.sort(key=lambda r: r["dur_ms"], reverse=True)
            write_json_response(handler, {
                "traces": rows[:limit],
                "ingest": fleet.traces.stats(),
                "buffer": get_trace_buffer().stats(),
            })
        elif route.startswith("/trace/"):
            from .tracestore import get_trace_buffer
            from .waterfall import build_waterfall

            trace_id = route.rsplit("/", 1)[1]
            spans = fleet.traces.get(trace_id)
            seen = {r.get("span_id") for r in spans}
            for rec in get_trace_buffer().get(trace_id):
                if rec.get("span_id") not in seen:
                    rec = dict(rec)
                    rec["source"] = "local"
                    spans.append(rec)
            if not spans:
                write_json_response(
                    handler, {"error": f"no spans for trace {trace_id!r}"},
                    status=404)
                return True
            write_json_response(handler, {
                "trace_id": trace_id,
                "spans": spans,
                "waterfall": build_waterfall(spans),
            })
        else:
            q = parse_qs(parsed.query)
            name = (q.get("name") or [""])[0]
            if not name:
                write_json_response(
                    handler, {"error": "query parameter 'name' is required"}, status=400
                )
                return True
            window_s = float((q.get("window_s") or ["300"])[0])
            source = (q.get("source") or [None])[0]
            points = fleet.store.points(name, window_s=window_s, source=source)
            stats = {
                s: fleet.store.query(name, window_s=window_s, source=s)
                for s in points
            }
            write_json_response(handler, {
                "name": name,
                "window_s": window_s,
                "stats": stats,
                "points": points,
            })
    except Exception as e:
        write_json_response(handler, {"error": repr(e)}, status=500)
    return True


class JsonlExporter:
    """Periodic registry snapshots into the JSONL scalar stream.

    Wraps ``utils.log.ScalarSink`` (the always-on fallback sink): each
    ``export(step)`` writes one line per scalar in the flattened snapshot,
    so ops tooling that already tails ``scalars.jsonl`` sees registry series
    with zero new plumbing."""

    def __init__(self, path: str, registry: Optional[MetricsRegistry] = None):
        from ..utils.log import ScalarSink

        self._sink = ScalarSink(path, force_jsonl=True)
        self._registry = registry

    def export(self, step: int = 0) -> int:
        """Dump the current snapshot; returns the number of scalars written."""
        registry = self._registry or get_registry()
        snap = registry.snapshot()
        self._sink.add_scalars(snap, global_step=step)
        return len(snap)
