"""Registry exporters: Prometheus text exposition + JSONL scalar dump.

Prometheus rendering follows the text exposition format (v0.0.4): counters
and gauges render one sample per labelset; histograms render as summaries
(quantile-labelled samples + ``_sum``/``_count``), which matches their
bounded-reservoir semantics. The JSONL exporter composes with the existing
fallback sink in ``utils/log.py`` (ScalarSink) so registry snapshots land in
the same ``scalars.jsonl`` stream training metrics already use.
"""
from __future__ import annotations

from typing import Optional

from .registry import MetricsRegistry, get_registry

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_QUANTILES = (0.5, 0.9, 0.99)


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labels_text(key, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """Render the registry in Prometheus text exposition format."""
    registry = registry or get_registry()
    lines = []
    for fam in registry.collect():
        name, kind = fam["name"], fam["type"]
        prom_type = {"counter": "counter", "gauge": "gauge", "histogram": "summary"}[kind]
        if fam["help"]:
            lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {name} {prom_type}")
        for key, inst in fam["series"]:
            if kind == "histogram":
                qs = inst.quantiles(_QUANTILES)
                for q in _QUANTILES:
                    qlabel = 'quantile="%s"' % q
                    lines.append(f"{name}{_labels_text(key, qlabel)} {_fmt(qs[q])}")
                lines.append(f"{name}_sum{_labels_text(key)} {_fmt(inst.sum)}")
                lines.append(f"{name}_count{_labels_text(key)} {_fmt(inst.count)}")
            else:
                lines.append(f"{name}{_labels_text(key)} {_fmt(inst.value)}")
    return "\n".join(lines) + "\n" if lines else ""


def write_scrape_response(handler, refresh=None, registry: Optional[MetricsRegistry] = None) -> None:
    """Answer a ``GET /metrics`` scrape on a ``BaseHTTPRequestHandler``.

    The one scrape route every HTTP surface shares (coordinator broker,
    serve gateway): run ``refresh()`` (scrape-time gauge publication), render
    the registry, write the response. A failing refresh/render answers 500
    with the repr — a scrape must never wedge the serving process."""
    try:
        if refresh is not None:
            refresh()
        data = render_prometheus(registry).encode()
        status, ctype = 200, PROMETHEUS_CONTENT_TYPE
    except Exception as e:
        data = repr(e).encode()
        status, ctype = 500, "text/plain"
    handler.send_response(status)
    handler.send_header("Content-Type", ctype)
    handler.send_header("Content-Length", str(len(data)))
    handler.end_headers()
    handler.wfile.write(data)


class JsonlExporter:
    """Periodic registry snapshots into the JSONL scalar stream.

    Wraps ``utils.log.ScalarSink`` (the always-on fallback sink): each
    ``export(step)`` writes one line per scalar in the flattened snapshot,
    so ops tooling that already tails ``scalars.jsonl`` sees registry series
    with zero new plumbing."""

    def __init__(self, path: str, registry: Optional[MetricsRegistry] = None):
        from ..utils.log import ScalarSink

        self._sink = ScalarSink(path, force_jsonl=True)
        self._registry = registry

    def export(self, step: int = 0) -> int:
        """Dump the current snapshot; returns the number of scalars written."""
        registry = self._registry or get_registry()
        snap = registry.snapshot()
        self._sink.add_scalars(snap, global_step=step)
        return len(snap)
