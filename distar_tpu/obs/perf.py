"""Live performance attribution: MFU / HBM / collective-traffic gauges.

One code path for the three consumers of XLA's cost and memory
introspection (previously bench.py, tools/memstats.py and the learner each
did their own): ``flops_of_lowered``/``flops_of_compiled`` extract flop
counts, ``memory_report`` normalises ``memory_analysis()``, ``peak_flops``
maps a device kind to its datasheet bf16 peak — and ``PerfMonitor`` turns
them into the live ``distar_perf_*`` gauges the BaseLearner run loop
publishes every iteration, so the PR 3 telemetry pipeline (TSDB, shipper,
health rules) sees MFU and HBM fleet-wide.

jax is imported lazily (importing obs never imports jax); everything here
is best-effort — a backend without cost/memory introspection degrades to
frames/s + step-time gauges, never an exception in the train loop.
"""
from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from .registry import MetricsRegistry, get_registry

# peak bf16 matmul throughput per chip, for the MFU estimate (the table
# bench.py's headline MFU and the impossible-timing recheck both key off)
PEAK_FLOPS: Dict[str, float] = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v5": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def peak_flops(device_kind: str) -> Optional[float]:
    """Datasheet bf16 peak for a ``device.device_kind`` string (longest
    matching table entry wins), or None for unknown kinds (CPU hosts)."""
    kind = (device_kind or "").lower()
    best = None
    for name, peak in PEAK_FLOPS.items():
        if name in kind and (best is None or len(name) > best[0]):
            best = (len(name), peak)
    return best[1] if best else None


def flops_of_lowered(lowered) -> float:
    """Unoptimized-HLO flop count off a ``jax.stages.Lowered`` (0.0 when the
    backend offers no cost analysis)."""
    try:
        cost = lowered.cost_analysis()
        return float(cost.get("flops", 0.0)) if cost else 0.0
    except Exception:
        return 0.0


def flops_of_compiled(compiled) -> float:
    """Post-optimization executable-level flop count — the honest MFU
    numerator (the unoptimized count can overcount fused/DCE'd work)."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        return float(cost.get("flops", 0.0)) if cost else 0.0
    except Exception:
        return 0.0


_MEM_FIELDS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "generated_code_size_in_bytes",
)


def memory_report(compiled) -> Dict[str, float]:
    """XLA ``memory_analysis()`` as a flat ``*_mb`` dict (+``total_mb`` =
    argument+output+temp). Empty dict when the backend has no analysis —
    callers merge it with ``row.update(...)`` and lose nothing."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    if mem is None:
        return {}
    out: Dict[str, float] = {}
    for field in _MEM_FIELDS:
        v = getattr(mem, field, None)
        if v is not None:
            out[field.replace("_size_in_bytes", "_mb")] = round(v / 1e6, 1)
    total = sum(
        getattr(mem, f, 0) or 0
        for f in ("argument_size_in_bytes", "output_size_in_bytes", "temp_size_in_bytes")
    )
    out["total_mb"] = round(total / 1e6, 1)
    return out


def estimate_collective_bytes(mesh, params) -> Dict[str, float]:
    """Analytic per-step collective traffic from the mesh + param tree:
    ring all-reduce of grads over dp costs ``2*(dp-1)/dp`` x param bytes,
    ZeRO-3 fsdp adds an all-gather of params (fwd+bwd, 2x) and a
    reduce-scatter of grads at ``(fsdp-1)/fsdp`` x param bytes each. A
    lower-bound ESTIMATE from the sharding specs (tp/sp activation traffic
    is shape-dependent and not counted) — the live sanity number to hold a
    profiler trace's collective bucket against."""
    import jax

    param_bytes = float(sum(
        x.size * getattr(x.dtype, "itemsize", 4)
        for x in jax.tree.leaves(params)
        if hasattr(x, "size")
    ))
    shape = dict(mesh.shape) if mesh is not None else {}
    dp = int(shape.get("dp", 1))
    fsdp = int(shape.get("fsdp", 1))
    out = {"param_bytes": param_bytes}
    if dp > 1:
        out["grad_allreduce"] = 2.0 * (dp - 1) / dp * param_bytes
    if fsdp > 1:
        frac = (fsdp - 1) / fsdp
        out["fsdp_allgather"] = 2.0 * frac * param_bytes
        out["fsdp_reducescatter"] = frac * param_bytes
    out["total"] = sum(v for k, v in out.items() if k != "param_bytes")
    return out


class PerfMonitor:
    """Per-learner live perf gauges.

    The run loop calls ``on_step`` every iteration (frames/s, step seconds,
    implied TFLOPs, MFU when the chip's peak is known) and ``note_step_args``
    once with the jitted step + its live args; flop extraction happens on a
    background daemon thread against shape specs (never the donated
    buffers), so the loop never pays a trace. HBM gauges sample
    ``device.memory_stats()`` — live allocator truth on TPU, absent on CPU.
    """

    def __init__(self, token: str, registry: Optional[MetricsRegistry] = None,
                 aot_compile: bool = False, mem_sample_every: int = 16):
        self._registry = registry or get_registry()
        self._token = token
        self._aot_compile = aot_compile
        self._mem_sample_every = max(1, int(mem_sample_every))
        self._lock = threading.Lock()
        self._analysis_started = False
        self._steps_seen = 0
        self.flops_per_step = 0.0
        self.peak: Optional[float] = None
        self.last: Dict[str, float] = {}
        r = self._registry
        self._g_frames = r.gauge("distar_perf_frames_per_s",
                                 "learner throughput, frames per second",
                                 token=token)
        self._g_step = r.gauge("distar_perf_step_seconds",
                               "last device-step wall time", token=token)
        self._g_tflops = r.gauge("distar_perf_implied_tflops",
                                 "flops_per_step / step_time", token=token)
        self._g_mfu = r.gauge("distar_perf_mfu",
                              "implied flops share of the chip's bf16 peak",
                              token=token)
        self._g_flops = r.gauge("distar_perf_flops_per_step",
                                "train-step flop count (cost_analysis)",
                                token=token)
        self._c_fail = r.counter("distar_perf_analysis_failures_total",
                                 "background cost/memory analyses that failed",
                                 token=token)

    # ------------------------------------------------------------- AOT side
    def note_step_args(self, jitted, *args) -> None:
        """First-iteration hook: snapshot shape specs of the step args and
        extract flops (and, with ``aot_compile``, the static HBM footprint)
        in the background. Idempotent; never raises into the train loop."""
        with self._lock:
            if self._analysis_started:
                return
            self._analysis_started = True
        try:
            import jax

            specs = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
                if hasattr(x, "shape") and hasattr(x, "dtype") else x,
                args,
            )
        except Exception:
            self._c_fail.inc()
            return
        threading.Thread(
            target=self._analyze, args=(jitted, specs),
            name=f"perf-analysis-{self._token}", daemon=True,
        ).start()

    def _analyze(self, jitted, specs) -> None:
        try:
            import jax

            self.peak = peak_flops(jax.devices()[0].device_kind)
            lowered = jitted.lower(*specs)
            flops = flops_of_lowered(lowered)
            if self._aot_compile:
                # opt-in: the compile is served by the persistent cache when
                # the live step already compiled this signature
                compiled = lowered.compile()
                flops = flops_of_compiled(compiled) or flops
                for kind, mb in memory_report(compiled).items():
                    self._registry.gauge(
                        "distar_perf_step_hbm_mb",
                        "static per-step HBM footprint (memory_analysis)",
                        token=self._token, kind=kind.replace("_mb", ""),
                    ).set(mb)
            if flops:
                self.flops_per_step = flops
                self._g_flops.set(flops)
        except Exception as e:  # analysis is telemetry, never training-fatal
            logging.warning("perf analysis failed: %r", e)
            self._c_fail.inc()

    # ------------------------------------------------------------ live side
    def on_step(self, step_time_s: float, frames: float) -> None:
        step_time_s = float(step_time_s)
        if step_time_s <= 0:
            return
        vals = {"step_seconds": step_time_s}
        self._g_step.set(step_time_s)
        if frames:
            vals["frames_per_s"] = frames / step_time_s
            self._g_frames.set(vals["frames_per_s"])
        if self.flops_per_step:
            tflops = self.flops_per_step / step_time_s / 1e12
            vals["implied_tflops"] = tflops
            self._g_tflops.set(tflops)
            if self.peak:
                vals["mfu"] = self.flops_per_step / step_time_s / self.peak
                self._g_mfu.set(vals["mfu"])
        self.last = vals
        self._steps_seen += 1
        if self._steps_seen % self._mem_sample_every == 1:
            self.sample_memory()

    def sample_memory(self) -> None:
        """Per-local-device allocator stats into HBM gauges (no-op on
        backends without ``memory_stats``, e.g. CPU)."""
        try:
            import jax

            for d in jax.local_devices():
                stats = d.memory_stats()
                if not stats:
                    continue
                label = f"{d.platform}:{d.id}"
                in_use = stats.get("bytes_in_use")
                if in_use is not None:
                    self._registry.gauge(
                        "distar_perf_hbm_bytes_in_use",
                        "allocator bytes currently in use", device=label,
                    ).set(float(in_use))
                peak = stats.get("peak_bytes_in_use")
                if peak is not None:
                    self._registry.gauge(
                        "distar_perf_hbm_peak_bytes",
                        "allocator high-water mark", device=label,
                    ).set(float(peak))
        except Exception:
            self._c_fail.inc()

    def set_collectives(self, mesh, params) -> None:
        """Publish the analytic per-step collective estimate for this
        learner's mesh + params (docs/observability.md#perf)."""
        try:
            est = estimate_collective_bytes(mesh, params)
        except Exception:
            self._c_fail.inc()
            return
        for kind, v in est.items():
            if kind in ("total", "param_bytes"):
                continue
            self._registry.gauge(
                "distar_perf_collective_bytes_per_step",
                "estimated per-step collective traffic from sharding specs",
                token=self._token, kind=kind,
            ).set(v)

    def snapshot(self) -> Dict[str, float]:
        """Last-step view for the admin ``status`` route / opsctl digest."""
        out = dict(self.last)
        if self.flops_per_step:
            out["flops_per_step"] = self.flops_per_step
        if self.peak:
            out["peak_flops"] = self.peak
        return out
