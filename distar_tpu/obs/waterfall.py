"""Waterfall / critical-path analyzer over one trace's span records.

The consumption side of distributed tracing (the ``obs/traceview.py``
conventions: a JSON report plus ranked markdown). Input is the span-record
list ``TraceIngest.get(trace_id)`` returns — the client span, router span,
gateway/store span of ONE request, possibly from several processes. Output:

  * a **waterfall**: every span with its offset from the root, duration,
    and a per-span time decomposition — ``queue`` (micro-batcher residency),
    ``blocked`` (replay rate-limiter / shm ring-full waits), ``retry``
    (fleet re-route), ``service`` (compute), ``child`` (time covered by a
    child span) and ``network/other`` (the unexplained remainder, which for
    a parent whose child ran in another process is mostly the wire);
  * the **critical path**: root -> longest child chain, with its segments
    ranked by seconds — the "what do I fix first" list;
  * a **skew flag**: cross-host clocks are not synchronized, so a child
    starting "before" its parent or a clamped-negative hop delta marks the
    whole waterfall suspect instead of rendering lies (the raw deltas stay
    on the hop records).

Stdlib-only and pure: callers (opsctl, tests, the /trace route) feed
records in, JSON comes out.
"""
from __future__ import annotations

from typing import Dict, List, Optional

#: decomposition vocabulary, render order
SEGMENT_KINDS = ("queue", "blocked", "retry", "service", "network/other")

_ANNOT_TO_KIND = {"queue_s": "queue", "blocked_s": "blocked",
                  "retry_s": "retry", "service_s": "service"}

_SKEW_EPS_S = 0.001


def _decompose(rec: dict, child_s: float) -> Dict[str, float]:
    """Per-span seconds by kind. Annotated seconds are authoritative;
    ``service`` falls back to the un-annotated self-time remainder when the
    span never annotated compute; whatever is left after annotations, child
    coverage and service is ``network/other`` (wire + untracked)."""
    dur = max(0.0, float(rec.get("dur_s", 0.0)))
    annot = rec.get("annot") or {}
    out = {k: 0.0 for k in SEGMENT_KINDS}
    explained = 0.0
    for key, kind in _ANNOT_TO_KIND.items():
        v = max(0.0, float(annot.get(key, 0.0)))
        out[kind] = v
        explained += v
    child_s = min(child_s, max(0.0, dur - min(explained, dur)))
    remainder = max(0.0, dur - explained - child_s)
    if out["service"] == 0.0 and child_s == 0.0:
        # a leaf that never annotated compute: its self-time IS service
        out["service"] = remainder
    else:
        out["network/other"] = remainder
    return out


def build_waterfall(records: List[dict]) -> dict:
    """Assemble one trace's records into the waterfall report dict."""
    spans = [dict(r) for r in records
             if isinstance(r, dict) and r.get("span_id")]
    if not spans:
        return {"trace_id": None, "spans": [], "critical_path": [],
                "segments": [], "skewed": False, "total_s": 0.0}
    spans.sort(key=lambda r: float(r.get("ts", 0.0)))
    by_id = {r["span_id"]: r for r in spans}
    children: Dict[str, List[dict]] = {}
    roots: List[dict] = []
    for r in spans:
        parent = r.get("parent_span_id")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(r)
        else:
            roots.append(r)
    root = roots[0] if roots else spans[0]
    t0 = float(root.get("ts", 0.0))
    total = max(float(root.get("dur_s", 0.0)),
                max(float(r.get("ts", 0.0)) + float(r.get("dur_s", 0.0))
                    for r in spans) - t0)

    skewed = any(r.get("skew") for r in spans)
    rows: List[dict] = []

    def _emit(rec: dict, depth: int) -> None:
        nonlocal skewed
        kids = sorted(children.get(rec["span_id"], ()),
                      key=lambda r: float(r.get("ts", 0.0)))
        child_s = sum(float(k.get("dur_s", 0.0)) for k in kids)
        start = float(rec.get("ts", 0.0)) - t0
        dur = float(rec.get("dur_s", 0.0))
        parent = by_id.get(rec.get("parent_span_id") or "")
        if parent is not None:
            p_start = float(parent.get("ts", 0.0)) - t0
            p_end = p_start + float(parent.get("dur_s", 0.0))
            if start < p_start - _SKEW_EPS_S or start + dur > p_end + _SKEW_EPS_S:
                skewed = True
        rows.append({
            "span_id": rec["span_id"],
            "parent_span_id": rec.get("parent_span_id"),
            "name": rec.get("name", "?"),
            "source": rec.get("source", f"pid:{rec.get('pid', '?')}"),
            "depth": depth,
            "start_ms": round(start * 1000.0, 3),
            "dur_ms": round(dur * 1000.0, 3),
            "outcome": rec.get("outcome", "ok"),
            "segments_ms": {k: round(v * 1000.0, 3)
                            for k, v in _decompose(rec, child_s).items() if v},
            "hops": [h.get("hop") for h in rec.get("hops", ())],
        })
        for k in kids:
            _emit(k, depth + 1)

    _emit(root, 0)
    # orphans (parent span never collected — e.g. a process whose buffer
    # sampled it out): rendered flat after the root tree, never dropped
    emitted = {r["span_id"] for r in rows}
    for r in spans:
        if r["span_id"] not in emitted:
            _emit(r, 0)

    # critical path: root -> longest child at each level
    path: List[dict] = []
    cur: Optional[dict] = root
    while cur is not None:
        path.append(cur)
        kids = children.get(cur["span_id"], ())
        cur = max(kids, key=lambda r: float(r.get("dur_s", 0.0)), default=None)

    # ranked segments along the critical path: (span name/source, kind, s)
    segments: List[dict] = []
    for rec in path:
        kids = children.get(rec["span_id"], ())
        child_s = sum(float(k.get("dur_s", 0.0)) for k in kids)
        for kind, v in _decompose(rec, child_s).items():
            if v > 0.0:
                segments.append({
                    "name": rec.get("name", "?"),
                    "source": rec.get("source", f"pid:{rec.get('pid', '?')}"),
                    "kind": kind,
                    "seconds": round(v, 6),
                    "share": round(v / total, 4) if total > 0 else 0.0,
                })
    segments.sort(key=lambda s: s["seconds"], reverse=True)

    return {
        "trace_id": root.get("trace_id"),
        "name": root.get("name"),
        "outcome": root.get("outcome", "ok"),
        "total_s": round(total, 6),
        "skewed": bool(skewed),
        "spans": rows,
        "critical_path": [r["span_id"] for r in path],
        "segments": segments,
    }


def render_waterfall(report: dict, width: int = 32) -> str:
    """Markdown waterfall + ranked critical-path segments for one trace."""
    lines: List[str] = []
    tid = report.get("trace_id") or "?"
    total_ms = float(report.get("total_s", 0.0)) * 1000.0
    lines.append(f"# trace {tid} — {report.get('name', '?')} "
                 f"({total_ms:.2f} ms, outcome={report.get('outcome', 'ok')})")
    if report.get("skewed"):
        lines.append("")
        lines.append("> **CLOCK SKEW**: spans from different hosts disagree "
                     "on ordering — durations are per-host truth, offsets "
                     "and the network/other split are suspect.")
    lines.append("")
    lines.append("| span | source | start ms | dur ms | bar | breakdown |")
    lines.append("|---|---|---:|---:|---|---|")
    total = max(report.get("total_s", 0.0), 1e-9)
    critical = set(report.get("critical_path", ()))
    for row in report.get("spans", ()):
        indent = "&nbsp;" * 2 * row.get("depth", 0)
        off = int(width * (row["start_ms"] / 1000.0) / total)
        bar_len = max(1, int(width * (row["dur_ms"] / 1000.0) / total))
        bar = "·" * min(off, width - 1) + "█" * min(bar_len, width - min(off, width - 1))
        seg = " ".join(f"{k}={v:.2f}" for k, v in
                       sorted(row.get("segments_ms", {}).items(),
                              key=lambda kv: -kv[1]))
        mark = "**" if row["span_id"] in critical else ""
        outcome = "" if row.get("outcome", "ok") == "ok" \
            else f" [{row['outcome']}]"
        lines.append(
            f"| {indent}{mark}{row['name']}{mark}{outcome} | {row['source']} "
            f"| {row['start_ms']:.2f} | {row['dur_ms']:.2f} | `{bar}` | {seg} |")
    lines.append("")
    lines.append("## critical path (ranked)")
    lines.append("")
    lines.append("| rank | segment | kind | ms | share |")
    lines.append("|---:|---|---|---:|---:|")
    for i, seg in enumerate(report.get("segments", ())[:12], 1):
        lines.append(
            f"| {i} | {seg['name']} @ {seg['source']} | {seg['kind']} "
            f"| {seg['seconds'] * 1000.0:.2f} | {seg['share'] * 100.0:.1f}% |")
    return "\n".join(lines) + "\n"


def render_listing(rows: List[dict]) -> str:
    """One-line-per-trace listing for ``opsctl trace`` (GET /traces rows)."""
    if not rows:
        return "no traces retained (is tracing on? is anything shipping?)\n"
    lines = ["| trace_id | name | dur ms | outcome | keep | source |",
             "|---|---|---:|---|---|---|"]
    for r in rows:
        lines.append(
            f"| {r.get('trace_id')} | {r.get('name')} | "
            f"{r.get('dur_ms', 0.0):.2f} | {r.get('outcome', 'ok')}"
            f"{' SKEW' if r.get('skew') else ''} | {r.get('keep', '')} | "
            f"{r.get('source', '')} |")
    return "\n".join(lines) + "\n"
