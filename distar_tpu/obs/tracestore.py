"""Trace collection: tail-sampled per-process buffer, coordinator ingest,
and latency exemplars.

The consumption side of ``obs/trace.py``'s spans, the way the TSDB is the
consumption side of the registry: spans used to die into histograms at
``finish_trace`` — aggregates could say "p99 is slow" but nobody could
answer "show me THIS slow request". Now every finished span becomes a
compact record offered to the process ``TraceBuffer``, whose **tail-based
sampler** (decide AFTER the outcome is known — the Dapper/modern-collector
recipe) keeps:

  * every non-``ok`` outcome (shed / error / fallback) — failures are the
    traces you always want;
  * the rolling slowest tail per span name (duration >= the p90 of a small
    per-name reservoir) — the latency investigations;
  * 1-in-N of everything else — the baseline corpus.

Everything else is dropped and counted (``distar_tracebuf_dropped_total``).
The buffer is a bounded ring; the ``TelemetryShipper`` drains records past
a ship cursor into its periodic snapshot message, and the coordinator's
``TelemetryIngest`` folds them into the ``TraceIngest`` here — bounded per
source, evicted when the member departs (exactly the TSDB series-eviction
contract), served at ``GET /traces`` and ``GET /trace/<id>``.

**Exemplars** close the alert loop: key latency histograms ``note_exemplar``
the last trace_id at observe time; a firing health rule whose metric matches
an exemplar key names a retrievable offending trace in the alert event (and
therefore in the crash bundle). Exemplar storage is a bounded last-wins map,
shipped with telemetry so coordinator-side rules see fleet exemplars.

No span data is ever unbounded: buffer, ingest and exemplar store are all
capped with counted drops.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .registry import MetricsRegistry, get_registry
from .trace import _instrument

#: drop-reason vocabulary for ``distar_tracebuf_dropped_total``
DROP_SAMPLED = "sampled_out"     # tail sampler decided against keeping
DROP_EVICTED = "evicted"         # bounded ring evicted the oldest kept record
DROP_INGEST = "ingest_cap"       # coordinator refused a new source past cap
DROP_EXEMPLAR = "exemplar_cap"   # exemplar map full for a new metric key


def _count_drop(reason: str, n: int = 1,
                registry: Optional[MetricsRegistry] = None) -> None:
    _instrument(
        "counter", registry or get_registry(), "distar_tracebuf_dropped_total",
        "trace records/exemplars dropped by the bounded collection path",
        reason=reason,
    ).inc(n)


class TraceBuffer:
    """Bounded per-process span-record buffer with tail-based sampling.

    Records retained here serve the local ``GET /traces`` surface AND feed
    the shipper (``unshipped()`` advances a cursor without removing — the
    ring bound is the only eviction)."""

    def __init__(self, maxlen: int = 512, random_one_in: int = 16,
                 slow_quantile: float = 0.98, duration_reservoir: int = 128,
                 registry: Optional[MetricsRegistry] = None):
        assert maxlen > 0 and random_one_in >= 1
        self.maxlen = int(maxlen)
        self.random_one_in = int(random_one_in)
        self.slow_quantile = float(slow_quantile)
        self._registry = registry
        self._lock = threading.Lock()
        self._records: deque = deque()
        self._durations: Dict[str, deque] = {}
        #: per-name cached slow threshold [threshold, adds_since_recompute]
        #: — recomputing the reservoir quantile on EVERY add was a
        #: measurable share of the per-request cost; staleness of up to
        #: _thresh_every adds only blurs the p90 boundary, never loses an
        #: error/shed trace
        self._thresh: Dict[str, list] = {}
        self._thresh_every = 16
        self._duration_reservoir = int(duration_reservoir)
        self._seq = 0
        self._n = 0
        self._shipped_seq = 0
        #: counter handles cached per registry epoch (offer runs per span)
        self._cc_reg = None
        self._cc: Dict[str, object] = {}

    # ------------------------------------------------------------- sampling
    def _keep_reason(self, name: str, dur: float, outcome: str) -> Optional[str]:
        """Caller holds the lock. Updates the per-name duration reservoir
        either way (the slow threshold must see the whole population)."""
        res = self._durations.get(name)
        if res is None:
            res = self._durations[name] = deque(maxlen=self._duration_reservoir)
        res.append(dur)
        if outcome != "ok":
            return "outcome"
        if len(res) >= 8:
            info = self._thresh.get(name)
            if info is None or info[1] >= self._thresh_every:
                ordered = sorted(res)
                idx = min(len(ordered) - 1,
                          int(self.slow_quantile * len(ordered)))
                info = self._thresh[name] = [ordered[idx], 0]
            else:
                info[1] += 1
            # STRICTLY above the threshold: a tightly-clustered latency
            # population ties at its own p90, and >= would retain nearly
            # every span (cost and volume) instead of the genuine tail
            if dur > info[0] > 0.0:
                return "slow"
        self._n += 1
        if self._n % self.random_one_in == 0:
            return "random"
        return None

    def _counter(self, reason: str, kept: bool):
        """Counter handle cached on the buffer per registry epoch — offer()
        runs once per finished span and must not pay the registry's
        lock+label-sort, nor even the instrument-memo tuple build."""
        reg = self._registry or get_registry()
        if self._cc_reg is not reg:
            self._cc_reg = reg
            self._cc = {}
        key = f"{'k' if kept else 'd'}:{reason}"
        c = self._cc.get(key)
        if c is None:
            if kept:
                c = reg.counter("distar_tracebuf_kept_total",
                                "trace records the tail sampler kept",
                                reason=reason)
            else:
                c = reg.counter(
                    "distar_tracebuf_dropped_total",
                    "trace records/exemplars dropped by the bounded "
                    "collection path", reason=reason)
            self._cc[key] = c
        return c

    def add(self, rec: Optional[dict]) -> bool:
        """Offer one finished span record; returns True when kept."""
        if not isinstance(rec, dict):
            return False
        return self.offer(rec.get("name", "?"), float(rec.get("dur_s", 0.0)),
                          rec.get("outcome", "ok"), lambda: rec) is not None

    def offer(self, name: str, dur_s: float, outcome: str, build) -> Optional[str]:
        """Tail-sampling front door: decide keep/drop from (name, duration,
        outcome) alone, and only call ``build()`` — the record construction,
        which is the expensive half — for the kept minority. Returns the
        keep reason or None. The per-request cost of a dropped span is one
        lock, one reservoir append and one counter increment."""
        evicted = False
        with self._lock:
            reason = self._keep_reason(name, dur_s, outcome)
            if reason is not None:
                rec = build()
                if not isinstance(rec, dict):
                    reason = None
                else:
                    rec = dict(rec)
                    rec["keep"] = reason
                    self._seq += 1
                    rec["seq"] = self._seq
                    if len(self._records) >= self.maxlen:
                        self._records.popleft()
                        evicted = True
                    self._records.append(rec)
        if reason is None:
            self._counter(DROP_SAMPLED, kept=False).inc()
            return None
        if evicted:
            self._counter(DROP_EVICTED, kept=False).inc()
        self._counter(reason, kept=True).inc()
        return reason

    # --------------------------------------------------------------- reads
    def records(self, limit: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._records)
        return out[-limit:] if limit else out

    def unshipped(self, max_records: int = 128) -> List[dict]:
        """Records kept since the last ship, advancing the cursor (shipping
        is best-effort: a lost POST loses this batch, like any telemetry)."""
        with self._lock:
            fresh = [r for r in self._records if r["seq"] > self._shipped_seq]
            fresh = fresh[-max_records:]
            if fresh:
                self._shipped_seq = fresh[-1]["seq"]
        return fresh

    def get(self, trace_id: str) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._records
                    if r.get("trace_id") == trace_id]

    def stats(self) -> dict:
        with self._lock:
            return {"resident": len(self._records), "maxlen": self.maxlen,
                    "offered": self._n, "kept_seq": self._seq,
                    "shipped_seq": self._shipped_seq}

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._durations.clear()


def _listing(rec: dict, source: str) -> dict:
    """Compact ``GET /traces`` row for one span record."""
    return {
        "trace_id": rec.get("trace_id"),
        "name": rec.get("name"),
        "ts": rec.get("ts"),
        "dur_ms": round(float(rec.get("dur_s", 0.0)) * 1000.0, 3),
        "outcome": rec.get("outcome", "ok"),
        "keep": rec.get("keep"),
        "source": source,
        **({"skew": True} if rec.get("skew") else {}),
    }


class TraceIngest:
    """Coordinator-side trace store: shipped span records, bounded per
    source, evicted on member departure (the TSDB series contract)."""

    def __init__(self, max_per_source: int = 512, max_sources: int = 64,
                 registry: Optional[MetricsRegistry] = None):
        assert max_per_source > 0 and max_sources > 0
        self.max_per_source = int(max_per_source)
        self.max_sources = int(max_sources)
        self._registry = registry
        self._lock = threading.Lock()
        self._by_source: Dict[str, deque] = {}

    def ingest(self, source: str, records) -> int:
        if not isinstance(records, (list, tuple)):
            return 0
        source = str(source or "unknown")
        accepted = 0
        evicted = 0
        with self._lock:
            ring = self._by_source.get(source)
            if ring is None:
                if len(self._by_source) >= self.max_sources:
                    _count_drop(DROP_INGEST, n=len(records),
                                registry=self._registry)
                    return 0
                ring = self._by_source[source] = deque()
            for rec in records:
                if not isinstance(rec, dict) or "trace_id" not in rec:
                    continue
                if len(ring) >= self.max_per_source:
                    ring.popleft()
                    evicted += 1
                ring.append(rec)
                accepted += 1
        if evicted:
            _count_drop(DROP_EVICTED, n=evicted, registry=self._registry)
        if accepted:
            (self._registry or get_registry()).counter(
                "distar_trace_ingest_records_total",
                "shipped span records folded into the coordinator trace store",
            ).inc(accepted)
        return accepted

    def evict_source(self, source: str) -> int:
        """A member departed (lease expiry / graceful unregister): reclaim
        its traces like its TSDB series. Returns records reclaimed."""
        with self._lock:
            ring = self._by_source.pop(source, None)
            return len(ring) if ring else 0

    # --------------------------------------------------------------- reads
    def query(self, name: Optional[str] = None, min_ms: float = 0.0,
              outcome: Optional[str] = None, limit: int = 50) -> List[dict]:
        """Compact listings, slowest first, across every source."""
        with self._lock:
            snap = {s: list(ring) for s, ring in self._by_source.items()}
        rows = []
        for source, recs in snap.items():
            for rec in recs:
                if name and rec.get("name") != name:
                    continue
                if outcome and rec.get("outcome", "ok") != outcome:
                    continue
                if float(rec.get("dur_s", 0.0)) * 1000.0 < float(min_ms):
                    continue
                rows.append(_listing(rec, source))
        rows.sort(key=lambda r: r["dur_ms"], reverse=True)
        return rows[: max(1, int(limit))]

    def get(self, trace_id: str) -> List[dict]:
        """Every span record of one trace, across sources (the waterfall
        input — a trace's spans come from several processes)."""
        with self._lock:
            snap = {s: list(ring) for s, ring in self._by_source.items()}
        out = []
        for source, recs in snap.items():
            for rec in recs:
                if rec.get("trace_id") == trace_id:
                    rec = dict(rec)
                    rec["source"] = source
                    out.append(rec)
        out.sort(key=lambda r: r.get("ts", 0.0))
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "sources": len(self._by_source),
                "records": sum(len(r) for r in self._by_source.values()),
                "max_per_source": self.max_per_source,
                "max_sources": self.max_sources,
            }


class ExemplarStore:
    """Bounded last-wins map: metric key -> the most recent trace that fed
    that latency series. Keys use the flattened-snapshot family spelling
    (``distar_trace_e2e_seconds{span=trajectory}``) so a health rule's
    metric reference (``..._p99``) finds its exemplar by prefix."""

    def __init__(self, max_entries: int = 128,
                 registry: Optional[MetricsRegistry] = None):
        assert max_entries > 0
        self.max_entries = int(max_entries)
        self._registry = registry
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = {}

    def note(self, metric: str, trace_id: str, value: float,
             ts: Optional[float] = None) -> bool:
        entry = {"trace_id": str(trace_id), "value": float(value),
                 "ts": time.time() if ts is None else float(ts)}
        with self._lock:
            if metric not in self._entries and len(self._entries) >= self.max_entries:
                capped = True
            else:
                capped = False
                self._entries[str(metric)] = entry
        if capped:
            _count_drop(DROP_EXEMPLAR, registry=self._registry)
        return not capped

    def lookup(self, metric_ref: str) -> Optional[dict]:
        """Exemplar for a rule's metric reference: exact key, else the
        freshest key the reference extends (``family{...}_p99`` matches
        ``family{...}``)."""
        with self._lock:
            entry = self._entries.get(metric_ref)
            if entry is not None:
                return dict(entry)
            best = None
            for key, e in self._entries.items():
                if metric_ref.startswith(key) and (
                        best is None or e["ts"] > best["ts"]):
                    best = e
            return dict(best) if best else None

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    def merge(self, entries) -> int:
        """Fold a shipped exemplar snapshot in (freshest ts wins per key) —
        how the coordinator's rules see fleet-process exemplars."""
        if not isinstance(entries, dict):
            return 0
        merged = 0
        for key, e in entries.items():
            if not isinstance(e, dict) or "trace_id" not in e:
                continue
            with self._lock:
                cur = self._entries.get(key)
                if cur is None and len(self._entries) >= self.max_entries:
                    capped = True
                else:
                    capped = False
                    if cur is None or float(e.get("ts", 0.0)) >= cur["ts"]:
                        self._entries[str(key)] = {
                            "trace_id": str(e["trace_id"]),
                            "value": float(e.get("value", 0.0)),
                            "ts": float(e.get("ts", 0.0)),
                        }
                        merged += 1
            if capped:
                _count_drop(DROP_EXEMPLAR, registry=self._registry)
        return merged


# ------------------------------------------------------- process defaults
_buffer_lock = threading.Lock()
_buffer: Optional[TraceBuffer] = None
_exemplars_lock = threading.Lock()
_exemplars: Optional[ExemplarStore] = None


def get_trace_buffer() -> TraceBuffer:
    """The process-wide trace buffer (created on first use)."""
    global _buffer
    with _buffer_lock:
        if _buffer is None:
            _buffer = TraceBuffer()
        return _buffer


def set_trace_buffer(buffer: Optional[TraceBuffer]) -> Optional[TraceBuffer]:
    """Swap the process default (tests install a fresh one)."""
    global _buffer
    with _buffer_lock:
        prev = _buffer
        _buffer = buffer
        return prev


def get_exemplar_store() -> ExemplarStore:
    """The process-wide exemplar store (created on first use)."""
    global _exemplars
    with _exemplars_lock:
        if _exemplars is None:
            _exemplars = ExemplarStore()
        return _exemplars


def set_exemplar_store(store: Optional[ExemplarStore]) -> Optional[ExemplarStore]:
    global _exemplars
    with _exemplars_lock:
        prev = _exemplars
        _exemplars = store
        return prev


def note_exemplar(metric: str, trace_id: Optional[str], value: float) -> None:
    """Record ``trace_id`` as the latest witness of ``metric`` (no-op
    without an id — untraced observes cost one None check)."""
    if trace_id:
        get_exemplar_store().note(metric, trace_id, value)
