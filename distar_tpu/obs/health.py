"""Health rules engine + watchdog: turn TSDB windows into alerts.

The failure mode of a long league run is rarely a clean crash — it is a
silent stall (actor starvation, NaN loss, queue saturation) that burns
hours of TPU time before a human notices. A ``HealthRule`` is a declarative
check over the ``TimeSeriesStore`` (metric reference, window, aggregate,
predicate); the ``HealthEvaluator`` runs the rulebook on a timer and drives
a debounced ok -> warning -> firing state machine per rule, emitting exactly
one structured alert event per transition (into the flight recorder and the
bounded alert history the ``/alerts`` route serves).

Debounce semantics: a breach moves ok -> warning immediately; only
``for_count`` consecutive breached evaluations escalate to firing; recovery
back to ok needs ``clear_count`` consecutive clean evaluations. One
injected NaN loss therefore produces exactly one firing alert, not one per
evaluation tick.

``FleetHealth`` bundles the whole subsystem — store, sampler, ingest,
evaluator, flight recorder — behind one process-global handle the HTTP
surfaces (coordinator broker, serve gateway) answer from.
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

from .flightrecorder import FlightRecorder, get_flight_recorder
from .registry import MetricsRegistry, get_registry
from .shipper import TelemetryIngest
from .timeseries import RegistrySampler, TimeSeriesStore

OK, WARNING, FIRING = "ok", "warning", "firing"
_STATE_LEVEL = {OK: 0, WARNING: 1, FIRING: 2}

AGGS = ("last", "mean", "min", "max", "rate")
OPS = (">", ">=", "<", "<=", "nonfinite", "stalled", "trending_up")


@dataclass
class HealthRule:
    """One declarative check over the TSDB.

    ``metric`` names a flattened snapshot key (exact) or a labelled family
    (every ``metric{...}`` series); a rule breaches when ANY matching series
    breaches. ``op='nonfinite'`` fires on NaN/Inf values; ``op='stalled'``
    fires when a series with >=2 in-window points stopped moving (rate==0) —
    the counter-watchdog primitive (no data at all is NOT a breach: a role
    that never started is absence, not a stall; staleness is tracked
    per-source instead). ``op='trending_up'`` is the gauge-drift primitive:
    it breaches when the window's slope exceeds ``threshold`` (units/s) AND
    the last value sits at or above the window mean — a persistent rise,
    not one noisy endpoint; the ``for_count`` debounce then demands the
    trend survive consecutive evaluations before firing."""

    name: str
    metric: str
    agg: str = "last"
    op: str = ">"
    threshold: float = 0.0
    window_s: float = 60.0
    for_count: int = 2
    clear_count: int = 2
    severity: str = "critical"
    source: Optional[str] = None
    summary: str = ""

    def __post_init__(self):
        assert self.agg in AGGS, f"agg {self.agg!r} not in {AGGS}"
        assert self.op in OPS, f"op {self.op!r} not in {OPS}"
        assert self.for_count >= 1 and self.clear_count >= 1

    def breached(self, q: dict) -> Optional[float]:
        """Evaluate one series window; returns the offending value on breach,
        None when healthy (or unanswerable: rate on a 1-point window)."""
        if self.op == "nonfinite":
            v = q[self.agg]
            if v is None:
                return None
            return v if not math.isfinite(v) else None
        if self.op == "stalled":
            rate = q["rate"]
            if rate is None:  # <2 points: not enough history to call a stall
                return None
            return rate if rate == 0.0 else None
        if self.op == "trending_up":
            rate, last, mean = q["rate"], q["last"], q["mean"]
            if rate is None or last is None or not math.isfinite(last):
                return None
            rising = rate > self.threshold and (mean is None or last >= mean)
            return rate if rising else None
        v = q["rate"] if self.agg == "rate" else q[self.agg]
        if v is None or not math.isfinite(v):
            return None
        hit = {
            ">": v > self.threshold,
            ">=": v >= self.threshold,
            "<": v < self.threshold,
            "<=": v <= self.threshold,
        }[self.op]
        return v if hit else None


@dataclass
class _RuleState:
    state: str = OK
    breach_streak: int = 0
    clear_streak: int = 0
    since_ts: float = field(default_factory=time.time)
    last_value: Optional[float] = None
    last_series: Optional[str] = None
    fired_count: int = 0
    no_data: bool = True


class HealthEvaluator:
    """Evaluates a rulebook against the store on a timer; owns the per-rule
    state machines and the bounded alert history."""

    def __init__(self, store: TimeSeriesStore, rules: Sequence[HealthRule],
                 recorder: Optional[FlightRecorder] = None,
                 registry: Optional[MetricsRegistry] = None,
                 interval_s: float = 1.0, history: int = 256):
        names = [r.name for r in rules]
        assert len(names) == len(set(names)), "duplicate rule names"
        self.store = store
        self.rules: List[HealthRule] = list(rules)
        self.interval_s = interval_s
        self.recorder = recorder
        self._registry = registry
        self._lock = threading.Lock()
        self._states: Dict[str, _RuleState] = {r.name: _RuleState() for r in self.rules}
        self._history: deque = deque(maxlen=history)
        self._callbacks: List = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_transition_callback(self, fn) -> None:
        """Subscribe to alert transitions: ``fn(event_dict)`` is invoked for
        every ok/warning/firing edge, after the evaluation pass, outside the
        evaluator lock (callbacks may query ``alerts()``). This is the
        remediation hook the resilience layer's ``AlertRemediator`` attaches
        to. Exceptions are swallowed: a broken remediator must not kill the
        watchdog."""
        with self._lock:
            self._callbacks.append(fn)

    # -------------------------------------------------------------- evaluate
    def _emit(self, rule: HealthRule, st: _RuleState, transition: str,
              now: float) -> dict:
        event = {
            "ts": now,
            "type": "alert",
            "rule": rule.name,
            "state": transition,
            "severity": rule.severity,
            "value": st.last_value,
            "series": st.last_series,
            "summary": rule.summary or rule.name,
        }
        # exemplar: when the rule's metric matches a latency family that
        # records trace exemplars, the alert names a retrievable offending
        # trace_id (GET /trace/<id>, opsctl trace --id) — the event rides
        # into the flight recorder, so crash bundles carry it too
        from .tracestore import get_exemplar_store

        exemplar = get_exemplar_store().lookup(rule.metric)
        if exemplar is not None:
            event["exemplar_trace_id"] = exemplar["trace_id"]
        self._history.append(event)
        recorder = self.recorder or get_flight_recorder()
        recorder.record("alert", **{k: v for k, v in event.items() if k != "type"})
        return event

    def evaluate_once(self, now: Optional[float] = None) -> List[dict]:
        """One pass over the rulebook; returns the transition events emitted."""
        now = time.time() if now is None else now
        reg = self._registry or get_registry()
        events: List[dict] = []
        with self._lock:
            for rule in self.rules:
                st = self._states[rule.name]
                names = self.store.matching_names(rule.metric, source=rule.source)
                st.no_data = not names
                worst: Optional[float] = None
                worst_series: Optional[str] = None
                for name in names:
                    if rule.source is not None:
                        sources = [rule.source]
                    else:
                        # EVERY source holding the series, not just the
                        # freshest: "a rule breaches when ANY matching
                        # series breaches" — with one series name shipped
                        # by N fleet members (N gateways' p99), querying
                        # only the last shipper masked a breaching member
                        # behind a healthy one that shipped a beat later
                        sources = list(self.store.points(
                            name, window_s=rule.window_s)) or [None]
                    for src in sources:
                        q = self.store.query(name, window_s=rule.window_s,
                                             source=src)
                        if q is None:
                            continue
                        v = rule.breached(q)
                        if v is not None and (worst is None or not math.isfinite(v)
                                              or (math.isfinite(worst) and v > worst)):
                            worst, worst_series = v, f"{q['source']}:{name}"
                if worst is not None:
                    st.last_value, st.last_series = worst, worst_series
                    st.breach_streak += 1
                    st.clear_streak = 0
                    if st.state == OK:
                        st.state, st.since_ts = WARNING, now
                        events.append(self._emit(rule, st, WARNING, now))
                    if st.state == WARNING and st.breach_streak >= rule.for_count:
                        st.state, st.since_ts = FIRING, now
                        st.fired_count += 1
                        reg.counter(
                            "distar_health_alerts_total", "rule firings",
                            rule=rule.name,
                        ).inc()
                        events.append(self._emit(rule, st, FIRING, now))
                else:
                    st.breach_streak = 0
                    st.clear_streak += 1
                    if st.state != OK and st.clear_streak >= rule.clear_count:
                        st.state, st.since_ts = OK, now
                        events.append(self._emit(rule, st, OK, now))
                reg.gauge(
                    "distar_health_rule_state",
                    "0 ok / 1 warning / 2 firing", rule=rule.name,
                ).set(_STATE_LEVEL[st.state])
            reg.counter(
                "distar_health_evaluations_total", "rulebook evaluation passes"
            ).inc()
            callbacks = list(self._callbacks)
        for event in events:  # dispatched OUTSIDE the lock (see add_…)
            for cb in callbacks:
                try:
                    cb(event)
                except Exception:
                    pass
        return events

    # --------------------------------------------------------------- surface
    def alerts(self) -> dict:
        """The ``GET /alerts`` payload: per-rule state + recent transitions."""
        with self._lock:
            rules = {
                r.name: {
                    "state": st.state,
                    "severity": r.severity,
                    "since_ts": st.since_ts,
                    "value": st.last_value,
                    "series": st.last_series,
                    "fired_count": st.fired_count,
                    "no_data": st.no_data,
                    "summary": r.summary or r.name,
                }
                for r in self.rules
                for st in (self._states[r.name],)
            }
            history = list(self._history)
        return {
            "ts": time.time(),
            "firing": sorted(n for n, r in rules.items() if r["state"] == FIRING),
            "warning": sorted(n for n, r in rules.items() if r["state"] == WARNING),
            "rules": rules,
            "history": history,
        }

    def overall_state(self) -> str:
        with self._lock:
            level = max(
                (_STATE_LEVEL[st.state] for st in self._states.values()), default=0
            )
        return {v: k for k, v in _STATE_LEVEL.items()}[level]

    # --------------------------------------------------------------- control
    def start(self) -> "HealthEvaluator":
        if self._thread is not None:
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.evaluate_once()
                except Exception:
                    pass  # the watchdog must never kill the watched

        self._thread = threading.Thread(target=run, daemon=True, name="obs-health")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# ------------------------------------------------------------ default rules
def default_rulebook(roles: Iterable[str] = ("learner", "actor", "coordinator",
                                             "trace", "serve", "replay",
                                             "distill", "arena"),
                     slo_e2e_s: float = 30.0,
                     queue_saturation: float = 384.0,
                     shed_rate_per_s: float = 5.0,
                     stall_window_s: float = 60.0,
                     slo_serve_latency_s: float = 5.0) -> List[HealthRule]:
    """The stock fleet rulebook, filtered by which roles this process hosts
    (or, on the coordinator, observes via shipped telemetry — pass all)."""
    roles = set(roles)
    book: List[HealthRule] = []
    if "learner" in roles:
        book.append(HealthRule(
            name="learner_loss_nonfinite",
            metric="distar_learner_loss", agg="last", op="nonfinite",
            window_s=stall_window_s, for_count=2,
            summary="training loss went NaN/Inf",
        ))
        book.append(HealthRule(
            name="learner_step_stall",
            metric="distar_learner_iterations_total", op="stalled",
            window_s=stall_window_s, for_count=3,
            summary="learner stopped completing optimisation steps",
        ))
        book.append(HealthRule(
            name="learner_mfu_collapse",
            # labelled family: one series per learner token; only published
            # on backends with a known peak (TPU), so CPU runs see no data
            # and no-data is not a breach
            metric="distar_perf_mfu", agg="last", op="<", threshold=0.02,
            window_s=stall_window_s, for_count=3, severity="warning",
            summary="measured MFU collapsed below 2% of the chip's peak — "
                    "the step is input/host-bound or a kernel regressed "
                    "(capture a trace: opsctl profile)",
        ))
        book.append(HealthRule(
            name="learner_grad_nonfinite",
            # fed by the dynamics tree (obs/dynamics.py): the per-module
            # census totals localize the origin; the firing alert carries a
            # blackbox:<bundle> exemplar — replay it with tools/stepreplay.py
            metric="distar_train_nonfinite_grads{module=total}",
            agg="last", op=">", threshold=0.0,
            window_s=stall_window_s, for_count=1,
            summary="non-finite gradient elements detected — the dynamics "
                    "census names the first bad module and the alert's "
                    "exemplar points at the black-box bundle "
                    "(opsctl dynamics; tools/stepreplay.py --bundle <id>)",
        ))
        book.append(HealthRule(
            name="learner_grad_explosion",
            # ratio gauge published by DynamicsMonitor: ||g|| / EMA(||g||)
            metric="distar_train_grad_norm_explosion", agg="last", op=">",
            threshold=10.0, window_s=stall_window_s, for_count=2,
            severity="warning",
            summary="gradient norm exploded past 10x its EMA — check "
                    "distar_train_grad_norm{module=...} for the culprit "
                    "module and distar_train_grad_clip_fraction for "
                    "whether the clip is saturating",
        ))
        book.append(HealthRule(
            name="learner_entropy_collapse",
            # per-head family; masked-out heads publish nothing (the
            # monitor skips exact-0.0 values), so no-data is not a breach
            metric="distar_train_entropy{head=action_type}", agg="last",
            op="<", threshold=1e-4, window_s=stall_window_s, for_count=3,
            severity="warning",
            summary="action_type policy entropy collapsed toward zero — "
                    "the policy went deterministic (premature convergence "
                    "or a broken entropy bonus); inspect "
                    "distar_train_entropy per head",
        ))
    if "distill" in roles:
        book.append(HealthRule(
            name="distill_divergence_runaway",
            # gauge drift, not level: a healthy student's KL falls toward a
            # floor; a KL RISING over the window means the student has
            # fallen behind a fast-moving teacher (stale student rollouts
            # serve increasingly off-policy actions) — warn while the
            # canary-compare gate still protects promotion
            metric="distar_distill_kl", op="trending_up", threshold=0.0,
            window_s=stall_window_s, for_count=3, severity="warning",
            summary="student-vs-teacher KL divergence is trending up over "
                    "the window — the student has fallen behind a "
                    "fast-moving teacher (check distill learner throughput "
                    "and the teacher's publish cadence)",
        ))
    if "actor" in roles:
        book.append(HealthRule(
            name="actor_env_starvation",
            metric="distar_env_steps_total", op="stalled",
            window_s=stall_window_s, for_count=3,
            summary="actors stopped stepping environments",
        ))
    if "coordinator" in roles:
        book.append(HealthRule(
            name="coordinator_queue_saturation",
            metric="distar_coordinator_queue_depth", agg="last", op=">=",
            threshold=queue_saturation, window_s=stall_window_s, for_count=3,
            severity="warning",
            summary="broker backlog near the per-token cap — consumers behind",
        ))
    if "trace" in roles:
        book.append(HealthRule(
            name="trace_e2e_slo",
            metric="distar_trace_e2e_seconds{span=trajectory}_p99",
            agg="last", op=">", threshold=slo_e2e_s,
            window_s=stall_window_s, for_count=3, severity="warning",
            summary="actor->learner e2e p99 breached the staleness SLO",
        ))
    if "serve" in roles:
        book.append(HealthRule(
            name="serve_shed_rate",
            metric="distar_serve_shed_total", agg="rate", op=">",
            threshold=shed_rate_per_s, window_s=30.0, for_count=3,
            severity="warning",
            summary="gateway shedding load faster than the tolerated rate",
        ))
        book.append(HealthRule(
            name="serve_latency_slo",
            metric="distar_serve_request_latency_seconds_p99",
            agg="last", op=">", threshold=slo_serve_latency_s,
            window_s=30.0, for_count=2, severity="warning",
            summary="gateway p99 request latency breached the serving SLO "
                    "(the alert carries an exemplar trace_id — retrieve the "
                    "waterfall: opsctl trace --id <id>)",
        ))
    if "replay" in roles:
        book.append(HealthRule(
            name="replay_table_saturation",
            metric="distar_replay_table_occupancy", agg="last", op=">=",
            threshold=0.95, window_s=stall_window_s, for_count=3,
            severity="warning",
            summary="replay table near max_size — eviction is eating "
                    "unsampled trajectories",
        ))
        book.append(HealthRule(
            name="replay_sample_stall",
            metric="distar_replay_samples_total", op="stalled",
            window_s=stall_window_s, for_count=3,
            summary="replay store stopped serving samples (learner gone or "
                    "rate limiter starved of inserts)",
        ))
    if "arena" in roles:
        book.append(HealthRule(
            name="arena_rating_regression",
            # the store publishes the NEGATED main-lineage ELO, so a rising
            # trend here means the newest generation is shedding rating
            metric="distar_arena_main_rating_inverted", op="trending_up",
            threshold=0.0, window_s=300.0, for_count=3, severity="warning",
            summary="main-lineage arena rating is trending DOWN — the newest "
                    "generation is losing skill vs the ladder (check "
                    "opsctl arena for the payoff matrix)",
        ))
        book.append(HealthRule(
            name="arena_match_stall",
            metric="distar_arena_matches_applied", op="stalled",
            window_s=stall_window_s, for_count=3, severity="warning",
            summary="arena stopped applying matches — evaluator dead or "
                    "wedged (matches gauge flat with evaluators registered)",
        ))
    return book


# ------------------------------------------------------------- fleet bundle
class FleetHealth:
    """The assembled subsystem: TSDB store + registry sampler + telemetry
    ingest + rules evaluator + flight recorder, one handle per process."""

    def __init__(self, rules: Optional[Sequence[HealthRule]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 sample_interval_s: float = 1.0,
                 eval_interval_s: float = 2.0,
                 source: str = "local",
                 recorder: Optional[FlightRecorder] = None,
                 stale_after_s: float = 30.0,
                 store: Optional[TimeSeriesStore] = None):
        self.store = store or TimeSeriesStore()
        self.recorder = recorder or get_flight_recorder()
        self.stale_after_s = stale_after_s
        self.sampler = RegistrySampler(
            self.store, registry=registry, interval_s=sample_interval_s, source=source
        )
        # fleet trace store: shipped span records land here (bounded per
        # source, evicted with the source's TSDB series); GET /traces and
        # GET /trace/<id> answer from it
        from .tracestore import TraceIngest

        self.traces = TraceIngest(registry=registry)
        self.ingest = TelemetryIngest(self.store, registry=registry,
                                      traces=self.traces)
        self.evaluator = HealthEvaluator(
            self.store, rules if rules is not None else default_rulebook(),
            recorder=self.recorder, registry=registry, interval_s=eval_interval_s,
        )
        self._started = False

    def start(self) -> "FleetHealth":
        self.sampler.start()
        self.evaluator.start()
        self._started = True
        return self

    def stop(self) -> None:
        self.evaluator.stop()
        self.sampler.stop()
        self._started = False

    def healthz(self) -> dict:
        """The ``GET /healthz`` payload: overall state, per-rule summary,
        per-source staleness."""
        alerts = self.evaluator.alerts()
        sources = {}
        for name, info in self.store.sources().items():
            info = dict(info)
            info["stale"] = info["age_s"] > self.stale_after_s
            sources[name] = info
        return {
            "ts": time.time(),
            "status": self.evaluator.overall_state(),
            "started": self._started,
            "firing": alerts["firing"],
            "warning": alerts["warning"],
            "rules": {n: r["state"] for n, r in alerts["rules"].items()},
            "sources": sources,
            "tsdb": self.store.stats(),
        }


_fleet_lock = threading.Lock()
_fleet: Optional[FleetHealth] = None


def get_fleet_health() -> FleetHealth:
    """The process-wide fleet-health handle; lazily created (NOT started —
    the HTTP surfaces always have something to answer from, but evaluation
    threads only run where an entrypoint called ``init_fleet_health``)."""
    global _fleet
    with _fleet_lock:
        if _fleet is None:
            _fleet = FleetHealth()
        return _fleet


def init_fleet_health(rules: Optional[Sequence[HealthRule]] = None,
                      start: bool = True, **kwargs) -> FleetHealth:
    """Install (and by default start) a fresh process fleet-health bundle;
    stops any previous one's threads first."""
    global _fleet
    with _fleet_lock:
        if _fleet is not None:
            _fleet.stop()
        _fleet = FleetHealth(rules=rules, **kwargs)
        fleet = _fleet
    return fleet.start() if start else fleet


def set_fleet_health(fleet: Optional[FleetHealth]) -> Optional[FleetHealth]:
    """Swap the process handle (tests install a fresh one); returns the
    previous handle (caller owns stopping it)."""
    global _fleet
    with _fleet_lock:
        prev = _fleet
        _fleet = fleet
        return prev
