// High-throughput framed socket shuttle for the actor<->learner data plane.
//
// Native-code role: the reference's data plane rides Python sockets +
// C-extension pickling (distar/ctools/worker/coordinator/adapter.py); here
// the hot path — serving and fetching multi-MB length-prefixed payloads —
// runs in C++ threads with no Python involvement (the GIL is released for
// the duration of every call), so trajectory shipping never stalls the
// actor's inference loop or the learner's host thread.
//
// Wire format: 8-byte big-endian length + payload (matches
// distar_tpu/comm/serializer.py frame()).
//
// Exposed C ABI (ctypes):
//   int  shuttle_serve(const uint8_t* data, uint64_t len, int accept_count,
//                      int timeout_ms)      -> listening port (<0 on error);
//                      detaches a thread that serves the payload to up to
//                      accept_count connections, then closes.
//   int  shuttle_fetch(const char* host, int port, int timeout_ms,
//                      uint8_t** out, uint64_t* out_len) -> 0 on success;
//                      caller frees with shuttle_free.
//   void shuttle_free(uint8_t* p)
#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

bool send_all(int fd, const uint8_t* buf, uint64_t len) {
  uint64_t sent = 0;
  while (sent < len) {
    ssize_t n = ::send(fd, buf + sent, len - sent, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR)) continue;
      return false;
    }
    sent += static_cast<uint64_t>(n);
  }
  return true;
}

bool recv_all(int fd, uint8_t* buf, uint64_t len, int timeout_ms) {
  uint64_t got = 0;
  while (got < len) {
    pollfd p{fd, POLLIN, 0};
    int pr = ::poll(&p, 1, timeout_ms);
    if (pr <= 0) return false;
    ssize_t n = ::recv(fd, buf + got, len - got, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    got += static_cast<uint64_t>(n);
  }
  return true;
}

void write_be64(uint8_t* out, uint64_t v) {
  for (int i = 7; i >= 0; --i) {
    out[7 - i] = static_cast<uint8_t>((v >> (8 * i)) & 0xff);
  }
}

uint64_t read_be64(const uint8_t* in) {
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | in[i];
  return v;
}

}  // namespace

extern "C" {

int shuttle_serve(const uint8_t* data, uint64_t len, int accept_count, int timeout_ms) {
  int listener = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listener < 0) return -1;
  int one = 1;
  ::setsockopt(listener, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = 0;  // ephemeral
  if (::bind(listener, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(listener, 16) < 0) {
    ::close(listener);
    return -2;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(listener, reinterpret_cast<sockaddr*>(&addr), &alen);
  int port = ntohs(addr.sin_port);

  // own the payload: the Python buffer is only valid during this call
  std::vector<uint8_t>* payload = new std::vector<uint8_t>(len + 8);
  write_be64(payload->data(), len);
  std::memcpy(payload->data() + 8, data, len);

  std::thread([listener, payload, accept_count, timeout_ms]() {
    for (int i = 0; i < accept_count; ++i) {
      pollfd p{listener, POLLIN, 0};
      if (::poll(&p, 1, timeout_ms) <= 0) break;  // nobody came: expire
      int conn = ::accept(listener, nullptr, nullptr);
      if (conn < 0) break;
      int one = 1;
      ::setsockopt(conn, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      send_all(conn, payload->data(), payload->size());
      ::shutdown(conn, SHUT_WR);
      ::close(conn);
    }
    ::close(listener);
    delete payload;
  }).detach();

  return port;
}

int shuttle_fetch(const char* host, int port, int timeout_ms, uint8_t** out, uint64_t* out_len) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -2;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    ::close(fd);
    return -3;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  uint8_t hdr[8];
  if (!recv_all(fd, hdr, 8, timeout_ms)) {
    ::close(fd);
    return -4;
  }
  uint64_t len = read_be64(hdr);
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(len));
  if (buf == nullptr) {
    ::close(fd);
    return -5;
  }
  if (!recv_all(fd, buf, len, timeout_ms)) {
    std::free(buf);
    ::close(fd);
    return -6;
  }
  ::close(fd);
  *out = buf;
  *out_len = len;
  return 0;
}

void shuttle_free(uint8_t* p) { std::free(p); }

}  // extern "C"
