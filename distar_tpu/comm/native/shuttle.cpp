// High-throughput framed socket shuttle for the actor<->learner data plane.
//
// Native-code role: the reference's data plane rides Python sockets +
// C-extension pickling (distar/ctools/worker/coordinator/adapter.py); here
// the hot path — serving and fetching multi-MB length-prefixed payloads —
// runs in C++ threads with no Python involvement (the GIL is released for
// the duration of every call), so trajectory shipping never stalls the
// actor's inference loop or the learner's host thread.
//
// Wire format: 8-byte big-endian length + payload (matches
// distar_tpu/comm/serializer.py frame()).
//
// Exposed C ABI (ctypes):
//   int  shuttle_serve(const uint8_t* data, uint64_t len, int accept_count,
//                      int timeout_ms)      -> listening port (<0 on error);
//                      detaches a thread that serves the payload to up to
//                      accept_count connections, then closes.
//   int  shuttle_fetch(const char* host, int port, int timeout_ms,
//                      uint8_t** out, uint64_t* out_len) -> 0 on success;
//                      caller frees with shuttle_free.
//   void shuttle_free(uint8_t* p)
#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

bool send_all(int fd, const uint8_t* buf, uint64_t len) {
  uint64_t sent = 0;
  while (sent < len) {
    ssize_t n = ::send(fd, buf + sent, len - sent, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR)) continue;
      return false;
    }
    sent += static_cast<uint64_t>(n);
  }
  return true;
}

bool recv_all(int fd, uint8_t* buf, uint64_t len, int timeout_ms) {
  uint64_t got = 0;
  while (got < len) {
    pollfd p{fd, POLLIN, 0};
    int pr = ::poll(&p, 1, timeout_ms);
    if (pr <= 0) return false;
    ssize_t n = ::recv(fd, buf + got, len - got, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    got += static_cast<uint64_t>(n);
  }
  return true;
}

void write_be64(uint8_t* out, uint64_t v) {
  for (int i = 7; i >= 0; --i) {
    out[7 - i] = static_cast<uint8_t>((v >> (8 * i)) & 0xff);
  }
}

uint64_t read_be64(const uint8_t* in) {
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | in[i];
  return v;
}

}  // namespace

extern "C" {

int shuttle_serve(const uint8_t* data, uint64_t len, int accept_count, int timeout_ms) {
  int listener = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listener < 0) return -1;
  int one = 1;
  ::setsockopt(listener, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = 0;  // ephemeral
  if (::bind(listener, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(listener, 16) < 0) {
    ::close(listener);
    return -2;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(listener, reinterpret_cast<sockaddr*>(&addr), &alen);
  int port = ntohs(addr.sin_port);

  // own the payload: the Python buffer is only valid during this call
  std::vector<uint8_t>* payload = new std::vector<uint8_t>(len + 8);
  write_be64(payload->data(), len);
  std::memcpy(payload->data() + 8, data, len);

  std::thread([listener, payload, accept_count, timeout_ms]() {
    for (int i = 0; i < accept_count; ++i) {
      pollfd p{listener, POLLIN, 0};
      if (::poll(&p, 1, timeout_ms) <= 0) break;  // nobody came: expire
      int conn = ::accept(listener, nullptr, nullptr);
      if (conn < 0) break;
      int one = 1;
      ::setsockopt(conn, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      send_all(conn, payload->data(), payload->size());
      ::shutdown(conn, SHUT_WR);
      ::close(conn);
    }
    ::close(listener);
    delete payload;
  }).detach();

  return port;
}

int shuttle_fetch(const char* host, int port, int timeout_ms, uint8_t** out, uint64_t* out_len) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -2;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    ::close(fd);
    return -3;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  uint8_t hdr[8];
  if (!recv_all(fd, hdr, 8, timeout_ms)) {
    ::close(fd);
    return -4;
  }
  uint64_t len = read_be64(hdr);
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(len));
  if (buf == nullptr) {
    ::close(fd);
    return -5;
  }
  if (!recv_all(fd, buf, len, timeout_ms)) {
    std::free(buf);
    ::close(fd);
    return -6;
  }
  ::close(fd);
  *out = buf;
  *out_len = len;
  return 0;
}

void shuttle_free(uint8_t* p) { std::free(p); }

// --------------------------------------------------------------------------
// LZ4-block-format codec (public format: lz4 block spec) for the data plane.
//
// Native-code role: the reference compresses every trajectory/model payload
// with lz4 (distar/ctools/utils/file_helper.py:21). This image has no lz4
// python package and zlib-1 compresses our ~7 MB trajectory windows at only
// ~10 MB/s (measured, tools/bench_dataplane.py) — slower than just sending
// raw bytes over loopback/DCN. This is a from-scratch hash-chain LZ77
// encoder emitting the standard LZ4 block stream (token nibbles, 255-run
// length extensions, little-endian 16-bit offsets, >=4-byte matches, tail
// literals), giving lz4-class compress speed with zero dependencies.
//
//   int64_t shuttlez_compress(src, len, dst, cap)   -> compressed size
//   int64_t shuttlez_decompress(src, len, dst, cap) -> decompressed size
//   uint64_t shuttlez_bound(len)                    -> worst-case dst size
// Both return <0 on error (cap too small / malformed stream).

namespace {

inline uint32_t read_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

constexpr int kHashBits = 16;
constexpr int kHashSize = 1 << kHashBits;

inline uint32_t hash4(uint32_t v) {
  // Fibonacci hashing of the 4-byte window
  return (v * 2654435761u) >> (32 - kHashBits);
}

constexpr int kMinMatch = 4;
constexpr int kLastLiterals = 5;       // spec: last 5 bytes are literals
constexpr int kMFLimit = 12;           // spec: last match starts >=12 bytes from end
constexpr int kMaxOffset = 65535;

inline uint8_t* put_length(uint8_t* op, uint64_t len) {
  while (len >= 255) {
    *op++ = 255;
    len -= 255;
  }
  *op++ = static_cast<uint8_t>(len);
  return op;
}

}  // namespace

uint64_t shuttlez_bound(uint64_t len) { return len + len / 255 + 16; }

int64_t shuttlez_compress(const uint8_t* src, uint64_t len, uint8_t* dst, uint64_t cap) {
  if (cap < shuttlez_bound(len)) return -1;
  uint8_t* op = dst;
  if (len < kMFLimit + 1) {
    // too small to match: one literal-only sequence
    uint8_t token = len < 15 ? static_cast<uint8_t>(len) << 4 : 0xF0;
    *op++ = token;
    if (len >= 15) op = put_length(op, len - 15);
    std::memcpy(op, src, len);
    return (op + len) - dst;
  }
  std::vector<uint32_t> table(kHashSize, 0);  // position + 1 (0 = empty)
  const uint64_t matchlimit = len - kLastLiterals;  // matches may extend to here
  const uint64_t mflimit = len - kMFLimit;          // matches must START before here
  uint64_t anchor = 0;
  uint64_t ip = 0;
  uint64_t search_nb = 1 << 6;  // lz4-style skip acceleration: the longer a
                                // stretch stays matchless (incompressible
                                // float noise), the bigger the stride
  while (ip < mflimit) {
    uint32_t h = hash4(read_u32(src + ip));
    uint64_t cand = table[h] ? table[h] - 1 : UINT64_MAX;
    table[h] = static_cast<uint32_t>(ip + 1);
    if (cand == UINT64_MAX || ip - cand > kMaxOffset ||
        read_u32(src + cand) != read_u32(src + ip)) {
      ip += (search_nb++ >> 6);
      continue;
    }
    search_nb = 1 << 6;
    // extend the match forward
    uint64_t mlen = kMinMatch;
    while (ip + mlen < matchlimit && src[cand + mlen] == src[ip + mlen]) ++mlen;
    // emit sequence: literals [anchor, ip) + match (offset, mlen)
    uint64_t lit = ip - anchor;
    uint8_t* token = op++;
    if (lit >= 15) {
      *token = 0xF0;
      op = put_length(op, lit - 15);
    } else {
      *token = static_cast<uint8_t>(lit) << 4;
    }
    std::memcpy(op, src + anchor, lit);
    op += lit;
    uint16_t offset = static_cast<uint16_t>(ip - cand);
    *op++ = static_cast<uint8_t>(offset & 0xff);
    *op++ = static_cast<uint8_t>(offset >> 8);
    uint64_t mextra = mlen - kMinMatch;
    if (mextra >= 15) {
      *token |= 0x0F;
      op = put_length(op, mextra - 15);
    } else {
      *token |= static_cast<uint8_t>(mextra);
    }
    // index a couple of positions inside the match to help the next search
    uint64_t step_end = ip + mlen;
    for (uint64_t p = ip + 1; p + kMinMatch <= step_end && p + kMinMatch <= mflimit;
         p += (mlen > 64 ? 16 : 4)) {
      table[hash4(read_u32(src + p))] = static_cast<uint32_t>(p + 1);
    }
    ip += mlen;
    anchor = ip;
  }
  // tail literals
  uint64_t lit = len - anchor;
  uint8_t* token = op++;
  if (lit >= 15) {
    *token = 0xF0;
    op = put_length(op, lit - 15);
  } else {
    *token = static_cast<uint8_t>(lit) << 4;
  }
  std::memcpy(op, src + anchor, lit);
  op += lit;
  return op - dst;
}

int64_t shuttlez_decompress(const uint8_t* src, uint64_t len, uint8_t* dst, uint64_t cap) {
  const uint8_t* ip = src;
  const uint8_t* iend = src + len;
  uint8_t* op = dst;
  uint8_t* oend = dst + cap;
  while (ip < iend) {
    uint8_t token = *ip++;
    // literals
    uint64_t lit = token >> 4;
    if (lit == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        lit += b;
      } while (b == 255);
    }
    if (ip + lit > iend || op + lit > oend) return -2;
    std::memcpy(op, ip, lit);
    ip += lit;
    op += lit;
    if (ip >= iend) break;  // last sequence has no match
    // match
    if (ip + 2 > iend) return -3;
    uint16_t offset = static_cast<uint16_t>(ip[0] | (ip[1] << 8));
    ip += 2;
    if (offset == 0 || static_cast<uint64_t>(op - dst) < offset) return -4;
    uint64_t mlen = (token & 0x0F);
    if (mlen == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -5;
        b = *ip++;
        mlen += b;
      } while (b == 255);
    }
    mlen += kMinMatch;
    if (op + mlen > oend) return -6;
    const uint8_t* match = op - offset;
    // overlapping copy must be byte-wise
    for (uint64_t i = 0; i < mlen; ++i) op[i] = match[i];
    op += mlen;
  }
  return op - dst;
}

// ------------------------------------------------------------------- crc32
// Slice-by-8 IEEE CRC-32 (the zlib/PNG polynomial, reflected 0xEDB88320):
// bit-identical to Python's zlib.crc32, so a native-enabled endpoint and a
// pure-Python fallback endpoint always agree on frame checksums — but ~4x
// faster than the unvectorized zlib in this image, which matters because
// the shm ring transport CRCs every payload byte twice (write + verify).

static uint32_t g_crc_tab[8][256];
static bool g_crc_init = false;

static void crc32_init_tables() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    g_crc_tab[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = g_crc_tab[0][i];
    for (int t = 1; t < 8; ++t) {
      c = g_crc_tab[0][c & 0xFF] ^ (c >> 8);
      g_crc_tab[t][i] = c;
    }
  }
  g_crc_init = true;
}

uint32_t shuttlez_crc32(const uint8_t* data, uint64_t len, uint32_t crc) {
  if (!g_crc_init) crc32_init_tables();
  crc = ~crc;
  // align-free 8-byte slices
  while (len >= 8) {
    uint64_t word;
    std::memcpy(&word, data, 8);
    crc ^= static_cast<uint32_t>(word);
    uint32_t hi = static_cast<uint32_t>(word >> 32);
    crc = g_crc_tab[7][crc & 0xFF] ^ g_crc_tab[6][(crc >> 8) & 0xFF] ^
          g_crc_tab[5][(crc >> 16) & 0xFF] ^ g_crc_tab[4][crc >> 24] ^
          g_crc_tab[3][hi & 0xFF] ^ g_crc_tab[2][(hi >> 8) & 0xFF] ^
          g_crc_tab[1][(hi >> 16) & 0xFF] ^ g_crc_tab[0][hi >> 24];
    data += 8;
    len -= 8;
  }
  while (len--) crc = g_crc_tab[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

}  // extern "C"
