"""Payload (de)serialization for the data plane.

Role of the reference's dumps/loads multi-codec (reference: distar/ctools/
utils/file_helper.py:21-120 — pickle/nppickle/pyarrow + lz4). lz4 isn't in
this image, so the compressed codec is zlib-1 (fast setting); pickle
protocol 5 with out-of-band buffers keeps large numpy arrays zero-copy on
the serialise side.
"""
from __future__ import annotations

import pickle
import struct
import zlib
from typing import Any, Tuple

MAGIC_RAW = b"DTR0"
MAGIC_ZLIB = b"DTZ0"


def dumps(obj: Any, compress: bool = True) -> bytes:
    payload = pickle.dumps(obj, protocol=5)
    if compress:
        return MAGIC_ZLIB + zlib.compress(payload, level=1)
    return MAGIC_RAW + payload


def loads(blob: bytes) -> Any:
    magic, body = blob[:4], blob[4:]
    if magic == MAGIC_ZLIB:
        return pickle.loads(zlib.decompress(body))
    if magic == MAGIC_RAW:
        return pickle.loads(body)
    raise ValueError(f"unknown payload magic {magic!r}")


def frame(blob: bytes) -> bytes:
    """Length-prefix a payload (8-byte big-endian), the adapter wire format
    (role of the reference's length-prefixed frames, adapter.py:140-151)."""
    return struct.pack(">Q", len(blob)) + blob


def read_frame(recv_exact) -> bytes:
    """Read one frame via a ``recv_exact(n) -> bytes`` callable."""
    (n,) = struct.unpack(">Q", recv_exact(8))
    return recv_exact(n)
