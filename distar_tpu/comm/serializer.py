"""Payload (de)serialization for the data plane.

Role of the reference's dumps/loads multi-codec (reference: distar/ctools/
utils/file_helper.py:21-120 — pickle/nppickle/pyarrow + lz4). The lz4 python
package isn't in this image, so the fast codec is our own C++ LZ4-block
implementation (comm/native/shuttle.cpp shuttlez_*; measured lz4-class
throughput vs zlib-1's ~10 MB/s on trajectory payloads — see
tools/bench_dataplane.py). Fallback order on compress: native lz -> zlib-1;
loads handles every magic regardless of what this host can produce (the
lz magic carries the decompressed size, and a pure-Python decoder exists
for .so-less hosts). Pickle protocol 5 keeps large numpy arrays zero-copy
on the serialise side.

Named codecs (``supported_codecs``): ``lz4`` (the default pair above),
``zlib`` (forced zlib-1), and ``zstd`` — gated on a ``zstandard`` binding
being importable; the replay data plane's per-connection ``hello``
negotiation picks one by preference intersection (``negotiate_codec``), so
mixed-capability fleets interoperate and a host without the binding is
simply never offered zstd frames.
"""
from __future__ import annotations

import pickle
import struct
import zlib
from typing import Any, Optional, Sequence, Tuple

from . import shuttle

MAGIC_RAW = b"DTR0"
MAGIC_ZLIB = b"DTZ0"
MAGIC_LZ = b"DTL0"  # + u64 LE decompressed size + lz4-block stream
MAGIC_ZSTD = b"DTS0"  # + u64 LE decompressed size + zstd stream

try:  # optional: the image may not ship a zstd binding — everything gates
    import zstandard as _zstd  # type: ignore[import-not-found]
except ImportError:
    _zstd = None

#: every codec NAME the protocol defines, available here or not — a hello
#: preference list containing none of these is garbage (a hostile or
#: desynced peer) and servers NACK it typed instead of silently degrading
KNOWN_CODECS = ("lz4", "zlib", "zstd")

#: negotiable wire codec names, preference-ordered for this host. "lz4" is
#: the legacy default (native LZ4-block with a zlib-1 fallback encoder —
#: one name, because a receiver handles both magics regardless); "zstd"
#: trades CPU for a better ratio on cold links and only appears when the
#: host can actually decode it.
def supported_codecs() -> Tuple[str, ...]:
    return ("lz4", "zlib") + (("zstd",) if _zstd is not None else ())


def zstd_available() -> bool:
    return _zstd is not None


def negotiate_codec(client_prefs: Optional[Sequence[str]],
                    server_codecs: Optional[Sequence[str]] = None) -> str:
    """The wire codec a connection commits to: the client's first
    preference the server also speaks, else the legacy ``"lz4"`` (which is
    what a client that never sent a preference list gets)."""
    server = tuple(server_codecs) if server_codecs is not None else supported_codecs()
    for pref in client_prefs or ():
        if pref in server and pref in supported_codecs():
            return str(pref)
    return "lz4"


def _zstd_compress(payload: bytes) -> bytes:
    return _zstd.ZstdCompressor(level=3).compress(payload)


def _zstd_decompress(body: bytes, n: int) -> bytes:
    return _zstd.ZstdDecompressor().decompress(body, max_output_size=n)


def dumps_sized(obj: Any, compress: bool = True,
                codec: str = "lz4") -> "tuple[bytes, int]":
    """``(blob, raw_len)`` where ``raw_len`` is the pickled-payload size
    before compression — the number wire-bytes telemetry compares the
    on-the-wire frame against (``distar_replay_*_bytes_{raw,wire}``).
    ``codec`` picks the compressor (a negotiated name from
    ``supported_codecs``); decode side is codec-agnostic — ``loads``
    dispatches on the magic."""
    payload = pickle.dumps(obj, protocol=5)
    raw_len = len(payload)
    if not compress:
        return MAGIC_RAW + payload, raw_len
    if codec == "zstd":
        if _zstd is None:
            raise ValueError("zstd codec requested but no zstd binding on this host")
        return MAGIC_ZSTD + struct.pack("<Q", raw_len) + _zstd_compress(payload), raw_len
    if codec == "zlib":
        return MAGIC_ZLIB + zlib.compress(payload, level=1), raw_len
    if codec != "lz4":
        raise ValueError(f"unknown wire codec {codec!r} (know {supported_codecs()})")
    lz = shuttle.lz_compress(payload)
    if lz is not None:
        return MAGIC_LZ + struct.pack("<Q", raw_len) + lz, raw_len
    return MAGIC_ZLIB + zlib.compress(payload, level=1), raw_len


def dumps(obj: Any, compress: bool = True, codec: str = "lz4") -> bytes:
    return dumps_sized(obj, compress=compress, codec=codec)[0]


def dump_stream(obj: Any, fileobj) -> None:
    """Serialize ``obj`` uncompressed straight into a writable file-like —
    the shm-ring zero-intermediate-copy path. Pickle protocol 5 streams
    each large numpy buffer into ``fileobj.write`` as its own chunk, so a
    ring-backed file receives the array bytes directly into the mapped
    memory with no intermediate ``bytes`` object. The output is a valid
    ``loads`` payload (``MAGIC_RAW`` framing); compression is deliberately
    absent — both ends share RAM, the codec pass would only add copies."""
    fileobj.write(MAGIC_RAW)
    pickle.Pickler(fileobj, protocol=5).dump(obj)


def loads_sized(blob: bytes) -> "tuple[Any, int]":
    """``(obj, raw_len)`` — the decode twin of ``dumps_sized`` (``raw_len``
    is the decompressed pickle-payload size, whatever the codec)."""
    magic, body = blob[:4], blob[4:]
    if magic == MAGIC_ZSTD:
        if len(body) < 8:
            raise ValueError("truncated zstd payload header")
        (n,) = struct.unpack("<Q", body[:8])
        # same hostile-header cap as lz: zstd tops out well under 255x on
        # real payloads; anything above is corruption/desync, not data
        if n > max(1024, (len(body) - 8) * 255):
            raise ValueError(
                f"implausible decompressed size {n} for {len(body) - 8}-byte stream")
        if _zstd is None:
            raise ValueError(
                "zstd-compressed payload but no zstd binding on this host "
                "(negotiation should have prevented this)")
        return pickle.loads(_zstd_decompress(body[8:], n)), n
    if magic == MAGIC_LZ:
        if len(body) < 8:
            raise ValueError("truncated lz payload header")
        (n,) = struct.unpack("<Q", body[:8])
        # sanity-cap the peer-supplied size before allocating: LZ4 block
        # format cannot exceed ~255x expansion, so anything above that is a
        # corrupt/hostile header, not a legitimate payload
        if n > max(1024, (len(body) - 8) * 255):
            raise ValueError(f"implausible decompressed size {n} for {len(body) - 8}-byte stream")
        return pickle.loads(shuttle.lz_decompress(body[8:], n)), n
    if magic == MAGIC_ZLIB:
        payload = zlib.decompress(body)
        return pickle.loads(payload), len(payload)
    if magic == MAGIC_RAW:
        return pickle.loads(body), len(body)
    raise ValueError(f"unknown payload magic {magic!r}")


def loads(blob: bytes) -> Any:
    return loads_sized(blob)[0]


class Opaque:
    """A fully-encoded payload (a complete ``dumps()`` blob, magic included)
    embedded as a value inside a larger message. Senders that would compress
    the enclosing frame can skip the pass when its bulk is Opaque — the
    bytes are already through the codec (the replay store uses this to
    re-serve spill-recovered trajectories without recompressing them).
    Receivers call ``decode()`` to get the original object back."""

    __slots__ = ("blob",)

    def __init__(self, blob: bytes):
        self.blob = blob

    def decode(self) -> Any:
        return loads(self.blob)

    @classmethod
    def encode(cls, obj: Any, compress: bool = True) -> "Opaque":
        return cls(dumps(obj, compress=compress))

    def __reduce__(self):
        return (Opaque, (self.blob,))


def maybe_decode(obj: Any) -> Any:
    """Transparently unwrap ``Opaque`` payloads; everything else passes
    through untouched (every sample-consumption path calls this, so whether
    an item survived a store restart is invisible to the learner)."""
    return obj.decode() if isinstance(obj, Opaque) else obj


def save_payload(path: str, obj: Any, compress: bool = True) -> str:
    """Serialise + store a payload on any registered storage backend
    (utils/storage.py scheme routing — the role of the reference
    file_helper.save_file's ceph/memcached/normal dispatch, :71-120)."""
    from ..utils import storage

    storage.write_bytes(path, dumps(obj, compress=compress))
    return path


def load_payload(path: str) -> Any:
    from ..utils import storage

    return loads(storage.read_bytes(path))


# a frame header larger than this is garbage (a peer speaking another
# protocol, or stream desync), not a legitimate payload: fail typed instead
# of attempting a multi-GiB allocation
DEFAULT_MAX_FRAME = 1 << 32  # 4 GiB


def frame(blob: bytes) -> bytes:
    """Length-prefix a payload (8-byte big-endian), the adapter wire format
    (role of the reference's length-prefixed frames, adapter.py:140-151)."""
    return struct.pack(">Q", len(blob)) + blob


def read_frame(recv_exact, max_frame_bytes: int = DEFAULT_MAX_FRAME) -> bytes:
    """Read one frame via a ``recv_exact(n) -> bytes`` callable. Raises
    ``ValueError`` on an implausible header (see DEFAULT_MAX_FRAME)."""
    (n,) = struct.unpack(">Q", recv_exact(8))
    if n > max_frame_bytes:
        raise ValueError(f"implausible frame length {n} (max {max_frame_bytes})")
    return recv_exact(n)


# ----------------------------------------------------- socket framing helpers
# The serve-plane TCP frontend and any actor-grade caller share these, so
# both ends agree on one framing + codec stack (frame/read_frame + dumps/
# loads) instead of growing per-surface wire formats.
def sock_recv_exact(sock, n: int) -> bytes:
    """Read exactly ``n`` bytes from a connected socket; ``ConnectionError``
    on EOF mid-frame (the truncated-frame error path)."""
    chunks = []
    while n > 0:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("connection closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def send_msg(sock, obj: Any, compress: bool = True, codec: str = "lz4") -> None:
    """Serialize + frame + send one message on a connected socket."""
    sock.sendall(frame(dumps(obj, compress=compress, codec=codec)))


def recv_msg(sock, max_frame_bytes: int = DEFAULT_MAX_FRAME) -> Any:
    """Receive + deserialize one framed message from a connected socket."""
    return loads(read_frame(lambda n: sock_recv_exact(sock, n), max_frame_bytes))
