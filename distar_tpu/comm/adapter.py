"""Adapter: the peer-to-peer bulk data plane.

Role parity with the reference Adapter (reference: distar/ctools/worker/
coordinator/adapter.py:66-246): push = serialise, serve the payload on an
ephemeral socket (C++ shuttle), register the endpoint with the coordinator
under a token; pull = ask the coordinator for an endpoint, connect, receive.
Failed fetches strike the dead endpoint. A background pull loop feeds a
bounded deque (backpressure = the reference's maxlen cache, adapter.py:31).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from . import shuttle
from .coordinator import Coordinator, coordinator_request
from .serializer import dumps, loads
from ..obs import finish_trace, mark_hop, unwrap_payload, wrap_payload
from ..resilience import RetryPolicy, retry_call

# one extra attempt before a fetch failure strikes the endpoint: a listen
# backlog burst / transient RST shouldn't count toward producer death
_FETCH_POLICY = RetryPolicy(max_attempts=2, backoff_base_s=0.05, backoff_max_s=0.2)
# a serve window is local resource allocation (bind/listen): brief ephemeral
# port exhaustion is transient, anything else fails fast
_SERVE_POLICY = RetryPolicy(max_attempts=3, backoff_base_s=0.1, backoff_max_s=1.0)


class Adapter:
    def __init__(
        self,
        coordinator: Optional[Coordinator] = None,
        coordinator_addr: Optional[tuple] = None,
        my_ip: str = "127.0.0.1",
        compress: bool = True,
        lease_s: Optional[float] = None,
        request_policy: Optional[RetryPolicy] = None,
    ):
        """Either a local Coordinator object (in-process wiring) or
        (host, port) of a CoordinatorServer. ``lease_s`` attaches a lease
        TTL to every registration (heartbeat to keep alive); a None
        ``request_policy`` uses the resilience default (broker RPCs retry
        through a restart)."""
        assert (coordinator is None) != (coordinator_addr is None)
        self._co = coordinator
        self._co_addr = coordinator_addr
        self._my_ip = my_ip
        self._compress = compress
        self._lease_s = lease_s
        self._policy = request_policy
        self._caches: dict = {}
        self._pull_threads: dict = {}
        self._stop = threading.Event()

    # -------------------------------------------------------------- plumbing
    def _register(self, token: str, port: int) -> None:
        if self._co is not None:
            self._co.register(token, self._my_ip, port, lease_s=self._lease_s)
        else:
            body = {"token": token, "ip": self._my_ip, "port": port}
            if self._lease_s is not None:
                body["lease_s"] = self._lease_s
            coordinator_request(*self._co_addr, "register", body, policy=self._policy)

    def _ask(self, token: str) -> Optional[dict]:
        if self._co is not None:
            return self._co.ask(token)
        return coordinator_request(
            *self._co_addr, "ask", {"token": token}, policy=self._policy
        )["info"]

    def _strike(self, ip: str, port: int) -> None:
        if self._co is not None:
            self._co.strike(ip, port)
        else:
            coordinator_request(
                *self._co_addr, "strike", {"ip": ip, "port": port}, policy=self._policy
            )

    def heartbeat(self, port: int) -> bool:
        """Refresh this endpoint's lease on the broker; False means the
        broker no longer knows us (restart/eviction) — re-register."""
        if self._co is not None:
            return self._co.heartbeat(self._my_ip, port, lease_s=self._lease_s)
        body = {"ip": self._my_ip, "port": port}
        if self._lease_s is not None:
            body["lease_s"] = self._lease_s
        return bool(
            coordinator_request(
                *self._co_addr, "heartbeat", body, policy=self._policy
            )["info"]
        )

    # ------------------------------------------------------------------- api
    def push(
        self,
        token: str,
        data: Any,
        accept_count: int = 1,
        timeout_ms: int = 60_000,
        trace: Optional[dict] = None,
    ) -> int:
        """Serve ``data`` to ``accept_count`` consumers; returns the port.

        A ``trace`` context (obs.start_trace) rides the payload in a
        transparent envelope: the pull side unwraps it, records the
        comm-hop latency, and hands consumers the bare payload."""
        if trace is not None:
            mark_hop(trace, "adapter_push")
        blob = dumps(wrap_payload(data, trace), compress=self._compress)
        port = retry_call(
            shuttle.serve, blob, accept_count=accept_count, timeout_ms=timeout_ms,
            op="shuttle_serve", policy=_SERVE_POLICY,
        )
        self._register(token, port)
        return port

    def pull(
        self,
        token: str,
        block: bool = True,
        timeout: float = 60.0,
        poll_s: float = 0.05,
        with_trace: bool = False,
    ):
        """Fetch one payload for ``token``; None when non-blocking and empty.
        ``with_trace=True`` returns ``(payload, trace_ctx_or_None)`` so
        consumers (dataloader) can carry the span onward; otherwise the
        envelope is stripped and the comm hop recorded here."""
        deadline = time.time() + timeout
        while True:
            rec = self._ask(token)
            if rec is not None:
                try:
                    blob = retry_call(
                        shuttle.fetch, rec["ip"], rec["port"],
                        timeout_ms=int(timeout * 1000),
                        op="shuttle_fetch", policy=_FETCH_POLICY,
                    )
                except (OSError, ConnectionError):
                    self._strike(rec["ip"], rec["port"])
                    continue
                payload, trace = unwrap_payload(loads(blob))
                if trace is not None:
                    mark_hop(trace, "adapter_pull")
                if with_trace:
                    return (payload, trace)
                if trace is not None:
                    # no downstream carrier: this hop terminates the span
                    finish_trace(trace, hop="consumed")
                return payload
            if not block:
                return (None, None) if with_trace else None
            if time.time() > deadline:
                raise TimeoutError(f"pull({token}) timed out")
            time.sleep(poll_s)

    def start_pull_loop(self, token: str, maxlen: int = 8, keep_trace: bool = False,
                        condition: Optional[threading.Condition] = None) -> deque:
        """Background loop keeping a bounded cache of payloads for ``token``.
        Backpressure: when the cache is full the loop pauses (payload stays
        with the producer until its serve window expires). With
        ``keep_trace`` the cache holds ``(payload, trace_ctx)`` tuples so the
        consumer can continue the span (dataloader -> learner). A
        ``condition`` is notified on every append, so consumers can block in
        ``condition.wait`` instead of busy-polling the deque."""
        from ..obs import get_registry

        cache: deque = deque(maxlen=maxlen)
        self._caches[token] = cache
        depth_gauge = get_registry().gauge(
            "distar_adapter_cache_depth", "pull-loop cache occupancy", token=token
        )

        def append(entry) -> None:
            if condition is not None:
                with condition:
                    cache.append(entry)
                    condition.notify_all()
            else:
                cache.append(entry)

        def run():
            while not self._stop.is_set():
                depth_gauge.set(len(cache))
                if len(cache) >= maxlen:
                    time.sleep(0.02)
                    continue
                try:
                    data, trace = self.pull(token, block=False, with_trace=True)
                except (TimeoutError, OSError):
                    data, trace = None, None
                if data is None:
                    time.sleep(0.02)
                else:
                    if keep_trace:
                        append((data, trace))
                    else:
                        if trace is not None:
                            finish_trace(trace, hop="consumed")
                        append(data)
                    depth_gauge.set(len(cache))

        t = threading.Thread(target=run, daemon=True)
        t.start()
        self._pull_threads[token] = t
        return cache

    def stop(self) -> None:
        self._stop.set()
