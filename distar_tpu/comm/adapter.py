"""Adapter: the peer-to-peer bulk data plane.

Role parity with the reference Adapter (reference: distar/ctools/worker/
coordinator/adapter.py:66-246): push = serialise, serve the payload on an
ephemeral socket (C++ shuttle), register the endpoint with the coordinator
under a token; pull = ask the coordinator for an endpoint, connect, receive.
Failed fetches strike the dead endpoint. A background pull loop feeds a
bounded deque (backpressure = the reference's maxlen cache, adapter.py:31).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from . import shuttle
from .coordinator import Coordinator, coordinator_request
from .serializer import dumps, loads


class Adapter:
    def __init__(
        self,
        coordinator: Optional[Coordinator] = None,
        coordinator_addr: Optional[tuple] = None,
        my_ip: str = "127.0.0.1",
        compress: bool = True,
    ):
        """Either a local Coordinator object (in-process wiring) or
        (host, port) of a CoordinatorServer."""
        assert (coordinator is None) != (coordinator_addr is None)
        self._co = coordinator
        self._co_addr = coordinator_addr
        self._my_ip = my_ip
        self._compress = compress
        self._caches: dict = {}
        self._pull_threads: dict = {}
        self._stop = threading.Event()

    # -------------------------------------------------------------- plumbing
    def _register(self, token: str, port: int) -> None:
        if self._co is not None:
            self._co.register(token, self._my_ip, port)
        else:
            coordinator_request(
                *self._co_addr, "register", {"token": token, "ip": self._my_ip, "port": port}
            )

    def _ask(self, token: str) -> Optional[dict]:
        if self._co is not None:
            return self._co.ask(token)
        return coordinator_request(*self._co_addr, "ask", {"token": token})["info"]

    def _strike(self, ip: str, port: int) -> None:
        if self._co is not None:
            self._co.strike(ip, port)
        else:
            coordinator_request(*self._co_addr, "strike", {"ip": ip, "port": port})

    # ------------------------------------------------------------------- api
    def push(self, token: str, data: Any, accept_count: int = 1, timeout_ms: int = 60_000) -> int:
        """Serve ``data`` to ``accept_count`` consumers; returns the port."""
        blob = dumps(data, compress=self._compress)
        port = shuttle.serve(blob, accept_count=accept_count, timeout_ms=timeout_ms)
        self._register(token, port)
        return port

    def pull(self, token: str, block: bool = True, timeout: float = 60.0, poll_s: float = 0.05):
        """Fetch one payload for ``token``; None when non-blocking and empty."""
        deadline = time.time() + timeout
        while True:
            rec = self._ask(token)
            if rec is not None:
                try:
                    blob = shuttle.fetch(rec["ip"], rec["port"], timeout_ms=int(timeout * 1000))
                    return loads(blob)
                except (OSError, ConnectionError):
                    self._strike(rec["ip"], rec["port"])
                    continue
            if not block:
                return None
            if time.time() > deadline:
                raise TimeoutError(f"pull({token}) timed out")
            time.sleep(poll_s)

    def start_pull_loop(self, token: str, maxlen: int = 8) -> deque:
        """Background loop keeping a bounded cache of payloads for ``token``.
        Backpressure: when the cache is full the loop pauses (payload stays
        with the producer until its serve window expires)."""
        cache: deque = deque(maxlen=maxlen)
        self._caches[token] = cache

        def run():
            while not self._stop.is_set():
                if len(cache) >= maxlen:
                    time.sleep(0.02)
                    continue
                try:
                    data = self.pull(token, block=False)
                except (TimeoutError, OSError):
                    data = None
                if data is None:
                    time.sleep(0.02)
                else:
                    cache.append(data)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        self._pull_threads[token] = t
        return cache

    def stop(self) -> None:
        self._stop.set()
