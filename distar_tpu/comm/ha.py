"""Coordinator high availability: write-ahead journal + warm standby + fencing.

The coordinator is the last stateful tier that dies with its process: leases,
job-queue records, shipped telemetry, canary config and the arena ledger all
live in plain memory (every other tier got durability in PRs 4-18). This
module makes the broker crash-safe and failover-able — the DD-PPO
preemption-tolerance lesson applied to the control plane (PAPERS.md):
workers must ride through control-plane loss without losing accounting.

Three legs, one contract:

* **Write-ahead journal** (:class:`Journal`): every mutating coordinator
  route is appended as a CRC-framed record (the ``utils/storage`` atomic
  idiom for snapshots, ``u32 len | u32 crc32 | pickle`` frames for the WAL)
  *before* the reply is sent; durable routes fsync first, heartbeat records
  ride flush-only (losing one costs a re-register, never accounting).
  Periodic snapshots bound replay; a restarted coordinator reconstructs
  registrations (leases re-aged from record timestamps), queue contents,
  strikes, canary config (it is ordinary ``register`` state) and the
  ArenaStore exactly.

* **Warm standby** (:class:`HAState` in ``standby`` role): a second
  coordinator process tails the primary's journal over a framed-TCP
  follower stream (``comm.serializer`` conventions), applies each record to
  its own replica AND its own journal, and acks the sequence number back —
  the primary's durable-route dispatch waits for that ack (semi-synchronous
  replication) so an *acked* item is on the standby before the client sees
  the ack. Leadership is lease-based: the follower stream carries
  heartbeats; ``takeover_grace_s`` without contact promotes the standby.

* **Epoch fencing**: a single epoch counter, bumped on every leadership
  acquisition and journaled as a ``__lead__`` record, is stamped on every
  reply. Clients remember the highest epoch they have seen and discard
  lower-epoch answers typed (:class:`StaleEpochError`) — a deposed primary
  cannot split-brain the fleet. A revived old primary probes its peers at
  boot, finds the higher epoch, and rejoins as a follower.

Client-side failover lives in ``coordinator_request`` (comm/coordinator.py):
a comma list of coordinator addrs, ``not_leader`` redirects and stale-epoch
rejection all ride the PR 4 retry fabric. Ambiguous acks (primary killed
between send and reply) retry only **idempotent** routes on the standby;
non-idempotent routes (``ask`` — a queue pop) surface the typed
:class:`AmbiguousAckError` instead of double-applying.

Route classification is the contract ``tools/lint_ha_routes.py`` enforces:
every route in ``CoordinatorServer.routes`` must appear in
``JOURNALED_ROUTES`` or the shrink-only ``EPHEMERAL_ROUTES`` allowlist, so
a future route (the league's matchmaker) cannot silently become volatile.
"""
from __future__ import annotations

import glob
import os
import pickle
import queue
import socket
import struct
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..resilience import CommError, FatalError, RetryableError

# --------------------------------------------------------------------- routes
#: mutating routes that are journaled on the primary, replayed on restart
#: and streamed to standbys. "push" = register, "pull" = ask in this broker.
JOURNALED_ROUTES = frozenset({
    "register",      # producer "payload ready" records + discovery + canary
    "unregister",    # graceful drain departures
    "strike",        # dead-producer accounting (5 strikes purge)
    "heartbeat",     # lease refreshes (flush-only: loss => one re-register)
    "ask",           # queue POP — consuming a record must survive a restart
    "arena_report",  # arena ledger mutations (idempotent keys dedup replays)
    # league matchmaker (league/runtime/service.py): every mutating route
    # is a pure function of (state, seeded RNG, body, record ts), so the
    # replica replays to the exact roster/assignment/lineage/RNG cursor
    "league_register",    # learner roster + lease refresh (idempotent)
    "league_ask",         # matchmaking draw — advances RNG + assignment map
    "league_report",      # job completion + arena forward (key-dedup'd)
    "league_train_info",  # step accounting + snapshot minting (seq watermark)
})

#: explicitly-ephemeral allowlist (SHRINK-ONLY — lint_ha_routes.py): routes
#: that are read-only or whose state is lossy by design. Every entry needs a
#: reason; removing one is always safe, adding one is a reviewed decision.
EPHEMERAL_ROUTES = frozenset({
    "peers",       # read-only discovery listing
    "stats",       # read-only accounting
    "depth",       # read-only accounting
    "telemetry",   # TSDB ingest is best-effort by contract: shippers re-ship
                   # full snapshots every interval (and resync on failover)
    "arena_next",  # pure function of *reported* arena state — no state here
    "league_status",  # read-only matchmaking digest (explicitly non-mutating:
                      # even assignment expiry is deferred to journaled routes)
})

#: journaled routes whose ack additionally requires fsync + standby
#: replication (when a follower is attached) before the reply goes out
DURABLE_ROUTES = frozenset({
    "register", "unregister", "strike", "ask", "arena_report",
    # league mutations are all accounting: losing an acked one would orphan
    # an assignment, double-mint a snapshot or fork the RNG cursor
    "league_register", "league_ask", "league_report", "league_train_info",
})

#: routes safe to retry across a failover after an AMBIGUOUS ack (the reply
#: was lost; the primary may or may not have applied the request). register/
#: heartbeat/unregister/strike are naturally idempotent; arena_report dedups
#: on idempotent match keys. ``ask`` is a pop — retrying a possibly-applied
#: pop would consume a second record, so it is deliberately absent.
IDEMPOTENT_ROUTES = frozenset({
    "register", "unregister", "strike", "heartbeat", "arena_report",
    "peers", "stats", "depth", "telemetry", "arena_next",
    # league_register dedups on learner_id, league_report on match keys +
    # assignment pop, league_train_info on its per-player seq watermark.
    # ``league_ask`` is deliberately absent: like ``ask`` it is a draw —
    # retrying a possibly-applied ask would mint a second assignment.
    "league_register", "league_report", "league_train_info", "league_status",
})

LEAD_ROUTE = "__lead__"  # journal-internal leadership records


# --------------------------------------------------------------------- errors
class NotLeaderError(RetryableError):
    """The addressed coordinator is a standby; follow ``leader`` and retry."""

    def __init__(self, addr: str, leader: str = "", epoch: int = -1):
        super().__init__(f"{addr} is not the leader"
                         + (f" (leader hint: {leader})" if leader else ""))
        self.addr = addr
        self.leader = leader
        self.epoch = epoch


class StaleEpochError(RetryableError):
    """A reply carried an epoch older than one already seen — a deposed
    primary's answer, discarded typed (the no-split-brain guarantee)."""

    def __init__(self, addr: str, epoch: int, max_epoch: int):
        super().__init__(
            f"stale epoch {epoch} from {addr} (fleet is at {max_epoch})")
        self.addr = addr
        self.epoch = epoch
        self.max_epoch = max_epoch


class AmbiguousAckError(FatalError):
    """A non-idempotent request may or may not have been applied (the
    connection died between send and reply). Retrying could double-apply, so
    the ambiguity surfaces typed for the caller to resolve."""

    def __init__(self, route: str, addr: str, cause: Optional[BaseException] = None):
        super().__init__(
            f"coordinator:{route} @ {addr} died between send and reply; "
            "the request may have been applied — not retrying a "
            "non-idempotent route")
        self.route = route
        self.addr = addr
        self.cause = cause


class NotLeader(Exception):
    """Server-side control flow: raised by :meth:`HAState.dispatch` on a
    standby so the HTTP layer answers the typed ``not_leader`` envelope."""

    def __init__(self, leader: str = "", epoch: int = 0):
        super().__init__("not_leader")
        self.leader = leader
        self.epoch = epoch


class JournalCorruptError(RuntimeError):
    """A snapshot failed its CRC — the journal directory is damaged beyond
    the torn-tail case replay tolerates by construction."""


def _metrics():
    from ..obs import get_registry

    return get_registry()


# -------------------------------------------------------------------- journal
_FRAME = struct.Struct("<II")  # payload length, crc32(payload)


class Journal:
    """CRC-framed write-ahead log with periodic snapshots.

    Directory layout: ``wal.<seq16>.log`` append segments (a fresh segment
    per process start and per snapshot — torn tails are always the last
    record of a segment and are discarded as never-acked) and
    ``snap.<seq16>.bin`` full-state snapshots written via the storage
    layer's atomic tmp+fsync+rename idiom. Recovery = newest CRC-valid
    snapshot + replay of every later record; compaction keeps the newest
    two snapshots and only segments newer than the older one.
    """

    def __init__(self, root: str, snapshot_every: int = 512):
        self.root = root
        self.snapshot_every = max(1, int(snapshot_every))
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self._fh = None
        self._seq = 0
        self._epoch = 0
        self._since_snapshot = 0
        self._subs: List["queue.Queue"] = []

    # ------------------------------------------------------------------ frames
    @staticmethod
    def _encode(record: dict) -> bytes:
        payload = pickle.dumps(record, protocol=5)
        return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload

    @staticmethod
    def _scan(path: str) -> List[dict]:
        """Every complete CRC-valid record in a segment; scanning stops at
        the first torn/corrupt frame (an unacked tail, never acked data)."""
        out: List[dict] = []
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return out
        off = 0
        while off + _FRAME.size <= len(data):
            n, crc = _FRAME.unpack_from(data, off)
            start, end = off + _FRAME.size, off + _FRAME.size + n
            if end > len(data):
                break  # torn tail
            payload = data[start:end]
            if zlib.crc32(payload) != crc:
                break  # bit rot / torn overwrite: stop, do not guess
            try:
                out.append(pickle.loads(payload))
            except Exception:  # undecodable record: same contract as bad CRC
                break
            off = end
        return out

    # ---------------------------------------------------------------- recovery
    def recover(self) -> Tuple[Optional[dict], List[dict]]:
        """``(snapshot_state_or_None, records_after_snapshot)``. Leaves the
        journal positioned to continue appending after the last record."""
        snaps = sorted(glob.glob(os.path.join(glob.escape(self.root), "snap.*.bin")))
        base: Optional[dict] = None
        base_seq = 0
        for path in reversed(snaps):
            try:
                raw = open(path, "rb").read()
                if len(raw) < 4:
                    continue
                (crc,) = struct.unpack("<I", raw[:4])
                if zlib.crc32(raw[4:]) != crc:
                    continue
                blob = pickle.loads(raw[4:])
                base, base_seq = blob, int(blob.get("seq", 0))
                break
            except Exception:
                continue
        if snaps and base is None:
            raise JournalCorruptError(
                f"no snapshot under {self.root} passed its CRC")
        records: List[dict] = []
        last = base_seq
        for seg in sorted(glob.glob(os.path.join(glob.escape(self.root), "wal.*.log"))):
            for rec in self._scan(seg):
                seq = int(rec.get("seq", 0))
                if seq <= last:
                    continue  # covered by the snapshot / duplicate
                records.append(rec)
                last = seq
        with self._lock:
            self._seq = last
            if base is not None:
                self._epoch = int(base.get("epoch", 0))
            for rec in records:
                if rec.get("route") == LEAD_ROUTE:
                    self._epoch = int(rec["body"].get("epoch", self._epoch))
        return base, records

    def _open_segment_locked(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
        path = os.path.join(self.root, f"wal.{self._seq + 1:016d}.log")
        self._fh = open(path, "ab")

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def set_epoch(self, epoch: int) -> None:
        with self._lock:
            self._epoch = int(epoch)

    # ------------------------------------------------------------------ append
    def append(self, route: str, body: dict, ts: Optional[float] = None,
               durable: bool = True, epoch: Optional[int] = None) -> int:
        """Append one record; returns its sequence number. ``durable``
        fsyncs before returning (the record survives power loss before the
        caller acks); non-durable records are flushed to the OS only."""
        with self._lock:
            if self._fh is None:
                self._open_segment_locked()
            self._seq += 1
            if epoch is not None:
                self._epoch = int(epoch)
            rec = {"seq": self._seq, "ts": time.time() if ts is None else ts,
                   "route": route, "body": body}
            self._fh.write(self._encode(rec))
            self._fh.flush()
            if durable:
                os.fsync(self._fh.fileno())
            self._since_snapshot += 1
            # deliver under the lock: subscriber queues must observe records
            # in seq order even if appenders race
            for q in self._subs:
                try:
                    q.put_nowait(("rec", rec))
                except queue.Full:
                    # slow follower: mark the stream broken; it reconnects
                    # and receives a fresh snapshot instead of a silent gap
                    setattr(q, "overflowed", True)
        _metrics().counter(
            "distar_coordinator_ha_journal_records_total",
            "WAL records appended (primary writes + standby tail)",
        ).inc()
        return rec["seq"]

    def want_snapshot(self) -> bool:
        with self._lock:
            return self._since_snapshot >= self.snapshot_every

    # --------------------------------------------------------------- snapshots
    def snapshot(self, state: dict) -> str:
        """Write ``state`` (plus seq/epoch) atomically, rotate the append
        segment, and compact old segments/snapshots."""
        from ..utils import storage

        with self._lock:
            blob = dict(state)
            blob["seq"], blob["epoch"] = self._seq, self._epoch
            payload = pickle.dumps(blob, protocol=5)
            path = os.path.join(self.root, f"snap.{self._seq:016d}.bin")
            storage.write_bytes(
                path, struct.pack("<I", zlib.crc32(payload)) + payload)
            self._open_segment_locked()
            self._since_snapshot = 0
        self._compact()
        _metrics().counter(
            "distar_coordinator_ha_snapshots_total",
            "journal snapshots written (replay horizon resets)",
        ).inc()
        return path

    def _compact(self) -> None:
        snaps = sorted(glob.glob(os.path.join(glob.escape(self.root), "snap.*.bin")))
        for stale in snaps[:-2]:
            try:
                os.unlink(stale)
            except OSError:
                pass
        if len(snaps) < 2:
            return
        horizon = snaps[-2]  # keep segments newer than the older kept snap
        hseq = int(os.path.basename(horizon).split(".")[1])
        for seg in sorted(glob.glob(os.path.join(glob.escape(self.root), "wal.*.log"))):
            sseq = int(os.path.basename(seg).split(".")[1])
            # a segment starting at or before the horizon only holds records
            # the snapshot already covers IF a later segment exists (the
            # newest segment is always live — never reap the open file)
            if sseq <= hseq and seg != self._current_segment():
                try:
                    os.unlink(seg)
                except OSError:
                    pass

    def _current_segment(self) -> Optional[str]:
        with self._lock:
            return self._fh.name if self._fh is not None else None

    def reset(self, state: dict, seq: int, epoch: int) -> None:
        """Adopt a leader's snapshot wholesale (a follower joining): the
        local history is superseded — snapshot the received state and start
        a fresh segment after it. Divergent local tails (possible only past
        a fencing event) are deliberately discarded."""
        with self._lock:
            self._seq = int(seq)
            self._epoch = int(epoch)
            self._since_snapshot = 0
        self.snapshot(state)

    # ------------------------------------------------------------ subscriptions
    def subscribe(self, maxsize: int = 8192) -> "queue.Queue":
        q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        q.overflowed = False  # type: ignore[attr-defined]
        with self._lock:
            self._subs.append(q)
        return q

    def unsubscribe(self, q: "queue.Queue") -> None:
        with self._lock:
            if q in self._subs:
                self._subs.remove(q)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# ------------------------------------------------------------------ ha status
def probe_ha_status(addr: str, timeout: float = 2.0) -> Optional[dict]:
    """``GET /coordinator/ha`` from ``addr`` ("host:port"); None when the
    peer is unreachable or does not speak HA."""
    import json
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(
                f"http://{addr}/coordinator/ha", timeout=timeout) as resp:
            return json.loads(resp.read())
    except (urllib.error.URLError, ConnectionError, TimeoutError, OSError,
            ValueError):
        return None


def apply_record(coordinator, rec: dict, arena_store=None,
                 league_service=None) -> None:
    """Apply one journaled record to a coordinator replica (restart replay
    and the standby tail share this one code path). Leases are re-aged from
    the record's wall timestamp, so an endpoint that stopped heartbeating
    long before the crash is evicted on the first sweep instead of getting
    a fresh TTL. League records replay through the hosted LeagueService
    with the record's clock, so lease/expiry decisions match the primary's."""
    route, body, ts = rec["route"], rec.get("body") or {}, float(rec.get("ts", 0.0))
    if route == LEAD_ROUTE:
        return
    if route.startswith("league_"):
        if league_service is None:
            from ..league.runtime import get_league_service

            league_service = get_league_service()
        method = {"league_register": "register_learner", "league_ask": "ask_job",
                  "league_report": "report",
                  "league_train_info": "train_info"}.get(route)
        if league_service is not None and method is not None:
            getattr(league_service, method)(body, now=ts)
        else:
            _metrics().counter(
                "distar_coordinator_ha_apply_skips_total",
                "journal records skipped on apply (no hosting store / "
                "unknown route)", route=route).inc()
        return
    if route == "register":
        coordinator.apply_register(
            body["token"], body["ip"], body["port"], body.get("meta"),
            lease_s=body.get("lease_s"), record_ts=ts)
    elif route == "heartbeat":
        coordinator.apply_heartbeat(
            body["ip"], body["port"], lease_s=body.get("lease_s"), record_ts=ts)
    elif route == "unregister":
        coordinator.unregister(body["ip"], body["port"])
    elif route == "strike":
        coordinator.strike(body["ip"], body["port"])
    elif route == "ask":
        coordinator.ask(body["token"])  # the pop re-executes; result discarded
    elif route == "arena_report":
        if arena_store is None:
            from ..arena import get_arena_store

            arena_store = get_arena_store()
        if arena_store is not None:
            arena_store.report_batch(body.get("matches", []))
        else:
            _metrics().counter(
                "distar_coordinator_ha_apply_skips_total",
                "journal records skipped on apply (no hosting store / "
                "unknown route)", route=route).inc()
    else:
        _metrics().counter(
            "distar_coordinator_ha_apply_skips_total",
            "journal records skipped on apply (no hosting store / "
            "unknown route)", route=route).inc()


class HAState:
    """Leadership + journaling + replication for one coordinator process.

    ``role="auto"`` probes ``peers`` at boot: a live primary with an epoch
    at least ours means we join as its standby; otherwise we lead (bumping
    the epoch past everything the journal has seen). The primary serves the
    follower feed (framed TCP) and journals every mutating route through
    :meth:`dispatch`; a standby answers every POST route with the typed
    ``not_leader`` envelope and promotes itself when the feed goes quiet
    for ``takeover_grace_s``.
    """

    def __init__(self, coordinator, journal_dir: str,
                 advertise: str = "",
                 feed_host: str = "127.0.0.1", feed_port: int = 0,
                 peers: Sequence[str] = (),
                 role: str = "auto",
                 takeover_grace_s: float = 3.0,
                 sync_timeout_s: float = 2.0,
                 snapshot_every: int = 512,
                 arena_store_fn: Optional[Callable] = None,
                 league_service_fn: Optional[Callable] = None):
        assert role in ("auto", "primary", "standby"), role
        self.coordinator = coordinator
        self.journal = Journal(journal_dir, snapshot_every=snapshot_every)
        self.advertise = advertise  # this process's HTTP addr, for hints
        self.peers = [p for p in peers if p]
        self.takeover_grace_s = float(takeover_grace_s)
        self.sync_timeout_s = float(sync_timeout_s)
        self._arena_store_fn = arena_store_fn
        self._league_service_fn = league_service_fn
        self.role = "booting"
        self.leader_hint = ""
        self._mutate_lock = threading.Lock()
        self._repl_cond = threading.Condition()
        self._follower_acked = 0
        self._followers = 0
        self._applied_seq = 0       # standby: last record applied
        self._applied_ts = 0.0      # standby: wall ts of that record
        self._leader_seq = 0        # standby: leader's latest seq (from hb)
        self._last_contact = time.monotonic()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._feed_listener: Optional[socket.socket] = None
        self.feed_host, self._feed_port_req = feed_host, feed_port
        self.feed_port = 0
        self._requested_role = role

    # ---------------------------------------------------------------- helpers
    def _arena_store(self):
        if self._arena_store_fn is not None:
            return self._arena_store_fn()
        from ..arena import get_arena_store

        return get_arena_store()

    def _league_service(self):
        if self._league_service_fn is not None:
            return self._league_service_fn()
        from ..league.runtime import get_league_service

        return get_league_service()

    @property
    def epoch(self) -> int:
        return self.journal.epoch

    def _state_blob(self) -> dict:
        store = self._arena_store()
        service = self._league_service()
        return {
            "coordinator": self.coordinator.state_snapshot(),
            "arena": store.state_blob() if store is not None else None,
            "league": service.state_blob() if service is not None else None,
        }

    def _restore_blob(self, blob: dict) -> None:
        self.coordinator.restore_state(blob.get("coordinator") or {})
        arena = blob.get("arena")
        store = self._arena_store()
        if arena is not None and store is not None:
            store.load_state(arena)
        league = blob.get("league")
        service = self._league_service()
        if league is not None and service is not None:
            service.load_state(league)

    # ------------------------------------------------------------------- boot
    def boot(self) -> "HAState":
        """Recover the local journal, pick a role, start threads."""
        base, records = self.journal.recover()
        if base is not None:
            self._restore_blob(base)
        for rec in records:
            apply_record(self.coordinator, rec, self._arena_store(),
                         self._league_service())
        self._start_feed_server()
        role = self._requested_role
        leader = ""
        if role == "auto":
            best_epoch, leader = -1, ""
            for peer in self.peers:
                st = probe_ha_status(peer)
                if st and st.get("role") == "primary" \
                        and int(st.get("epoch", -1)) >= self.journal.epoch \
                        and int(st.get("epoch", -1)) > best_epoch:
                    best_epoch, leader = int(st["epoch"]), peer
            role = "standby" if leader else "primary"
        elif role == "standby":
            leader = self.peers[0] if self.peers else ""
        if role == "primary":
            self._become_primary()
        else:
            self._become_standby(leader)
        t = threading.Thread(target=self._housekeeping, daemon=True,
                             name="coordinator-ha")
        t.start()
        self._threads.append(t)
        return self

    # -------------------------------------------------------------- leadership
    def _become_primary(self) -> None:
        epoch = self.journal.epoch + 1
        self.journal.append(LEAD_ROUTE, {"epoch": epoch, "addr": self.advertise},
                            durable=True, epoch=epoch)
        self.role = "primary"
        self.leader_hint = self.advertise
        _metrics().counter(
            "distar_coordinator_ha_leaderships_total",
            "leadership acquisitions (boot elections + standby promotions)",
        ).inc()
        self._publish_gauges()

    def _become_standby(self, leader: str) -> None:
        self.role = "standby"
        self.leader_hint = leader
        self._last_contact = time.monotonic()
        t = threading.Thread(target=self._tail_loop, daemon=True,
                             name="coordinator-ha-tail")
        t.start()
        self._threads.append(t)
        self._publish_gauges()

    def _publish_gauges(self) -> None:
        reg = _metrics()
        reg.gauge("distar_coordinator_ha_epoch",
                  "current leadership epoch of this coordinator").set(self.epoch)
        reg.gauge("distar_coordinator_ha_role",
                  "1 primary / 0 standby").set(1 if self.role == "primary" else 0)
        if self.role == "standby":
            lag = max(0, self._leader_seq - self._applied_seq)
            reg.gauge("distar_coordinator_ha_journal_lag_records",
                      "standby: records behind the primary's journal").set(lag)
            if self._applied_ts:
                reg.gauge(
                    "distar_coordinator_ha_journal_lag_seconds",
                    "standby: age of the newest applied journal record",
                ).set(max(0.0, time.time() - self._applied_ts))

    # ------------------------------------------------------------ HTTP dispatch
    def dispatch(self, name: str, body: dict, handler: Callable) -> object:
        """Route one POST through the HA contract: standbys answer
        ``not_leader`` typed; ephemeral routes pass straight through;
        journaled routes append (durable ones fsync + wait for standby
        replication) before the result is returned."""
        if self.role != "primary":
            raise NotLeader(leader=self.leader_hint, epoch=self.epoch)
        if name in EPHEMERAL_ROUTES or name not in JOURNALED_ROUTES:
            return handler(body)
        durable = name in DURABLE_ROUTES
        with self._mutate_lock:
            if name == "ask":
                # journal pops only when something was actually popped —
                # Adapter polls this route constantly on empty queues
                result = handler(body)
                seq = self.journal.append(name, body, durable=True) \
                    if result is not None else 0
            else:
                seq = self.journal.append(name, body, durable=durable)
                result = handler(body)
            if self.journal.want_snapshot():
                self.journal.snapshot(self._state_blob())
        if durable and seq:
            self._wait_replicated(seq)
        return result

    def _wait_replicated(self, seq: int) -> None:
        """Semi-synchronous replication: with a follower attached, a durable
        ack waits (bounded) until the standby confirmed the record — an
        acked item is on the standby before the client sees the ack. A slow
        or dying follower times out (counted) rather than stalling the
        fleet: availability wins, the journal still has the record."""
        with self._repl_cond:
            if self._followers == 0:
                return
            deadline = time.monotonic() + self.sync_timeout_s
            while self._follower_acked < seq:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    _metrics().counter(
                        "distar_coordinator_ha_sync_timeouts_total",
                        "durable acks that stopped waiting for a slow standby",
                    ).inc()
                    return
                self._repl_cond.wait(remaining)

    # ------------------------------------------------------------- feed server
    def _start_feed_server(self) -> None:
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self.feed_host, self._feed_port_req))
        ls.listen(8)
        self._feed_listener = ls
        self.feed_port = ls.getsockname()[1]

        def accept_loop():
            while not self._stop.is_set():
                try:
                    conn, _ = ls.accept()
                except OSError:
                    return  # listener closed
                t = threading.Thread(target=self._serve_follower,
                                     args=(conn,), daemon=True,
                                     name="coordinator-ha-feed")
                t.start()
                self._threads.append(t)

        t = threading.Thread(target=accept_loop, daemon=True,
                             name="coordinator-ha-accept")
        t.start()
        self._threads.append(t)

    def _serve_follower(self, conn: socket.socket) -> None:
        from . import serializer

        conn.settimeout(10.0)
        sub = None
        try:
            hello = serializer.recv_msg(conn)
            if not isinstance(hello, dict) or hello.get("op") != "tail":
                return
            # subscribe BEFORE snapshotting under the mutate lock: no record
            # can land between the snapshot and the stream's first item
            with self._mutate_lock:
                sub = self.journal.subscribe()
                blob = self._state_blob()
                seq, epoch = self.journal.seq, self.journal.epoch
            serializer.send_msg(conn, {"op": "snapshot", "seq": seq,
                                       "epoch": epoch, "state": blob})
            with self._repl_cond:
                self._followers += 1
                self._follower_acked = max(self._follower_acked, 0)
            send_lock = threading.Lock()
            stop_reader = threading.Event()

            def read_acks():
                while not stop_reader.is_set():
                    try:
                        msg = serializer.recv_msg(conn)
                    except socket.timeout:
                        continue  # idle follower: acks only flow with records
                    except (ConnectionError, OSError, ValueError):
                        return
                    if isinstance(msg, dict) and msg.get("op") == "ack":
                        with self._repl_cond:
                            self._follower_acked = max(
                                self._follower_acked, int(msg.get("seq", 0)))
                            self._repl_cond.notify_all()

            rt = threading.Thread(target=read_acks, daemon=True,
                                  name="coordinator-ha-acks")
            rt.start()
            try:
                while not self._stop.is_set():
                    if getattr(sub, "overflowed", False):
                        return  # follower too slow: force a resnapshot
                    try:
                        kind, rec = sub.get(timeout=0.5)
                    except queue.Empty:
                        with send_lock:
                            serializer.send_msg(
                                conn, {"op": "hb", "epoch": self.journal.epoch,
                                       "seq": self.journal.seq},
                                compress=False)
                        continue
                    with send_lock:
                        serializer.send_msg(conn, {"op": kind, "rec": rec},
                                            compress=False)
            finally:
                stop_reader.set()
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            if sub is not None:
                self.journal.unsubscribe(sub)
                with self._repl_cond:
                    self._followers = max(0, self._followers - 1)
                    self._repl_cond.notify_all()
            try:
                conn.close()
            except OSError:
                pass

    # ------------------------------------------------------------ standby tail
    def _leader_feed_addr(self) -> Optional[Tuple[str, int]]:
        st = probe_ha_status(self.leader_hint) if self.leader_hint else None
        if st and st.get("feed"):
            host, _, port = str(st["feed"]).rpartition(":")
            self.leader_hint = str(st.get("leader") or self.leader_hint)
            try:
                return host or "127.0.0.1", int(port)
            except ValueError:
                return None
        return None

    def _tail_loop(self) -> None:
        from . import serializer

        while not self._stop.is_set() and self.role == "standby":
            feed = self._leader_feed_addr()
            if feed is None:
                if self._grace_expired():
                    self._promote()
                    return
                self._stop.wait(0.25)
                continue
            try:
                conn = socket.create_connection(feed, timeout=3.0)
            except OSError:
                if self._grace_expired():
                    self._promote()
                    return
                self._stop.wait(0.25)
                continue
            conn.settimeout(3.0)
            try:
                serializer.send_msg(conn, {"op": "tail",
                                           "from_seq": self.journal.seq},
                                    compress=False)
                while not self._stop.is_set():
                    try:
                        msg = serializer.recv_msg(conn)
                    except socket.timeout:
                        if self._grace_expired():
                            self._promote()
                            return
                        continue
                    self._last_contact = time.monotonic()
                    op = msg.get("op") if isinstance(msg, dict) else None
                    if op == "snapshot":
                        with self._mutate_lock:
                            self._restore_blob(msg.get("state") or {})
                            self.journal.reset(msg.get("state") or {},
                                               int(msg.get("seq", 0)),
                                               int(msg.get("epoch", 0)))
                            self._applied_seq = int(msg.get("seq", 0))
                            self._leader_seq = self._applied_seq
                    elif op == "rec":
                        rec = msg.get("rec") or {}
                        with self._mutate_lock:
                            self.journal.append(
                                rec.get("route", "?"), rec.get("body") or {},
                                ts=rec.get("ts"),
                                durable=rec.get("route") in DURABLE_ROUTES)
                            apply_record(self.coordinator, rec,
                                         self._arena_store(),
                                         self._league_service())
                            self._applied_seq = int(rec.get("seq", 0))
                            self._applied_ts = float(rec.get("ts", 0.0))
                            self._leader_seq = max(self._leader_seq,
                                                   self._applied_seq)
                        serializer.send_msg(
                            conn, {"op": "ack", "seq": self._applied_seq},
                            compress=False)
                    elif op == "hb":
                        self._leader_seq = int(msg.get("seq", self._leader_seq))
                        self.journal.set_epoch(
                            max(self.journal.epoch, int(msg.get("epoch", 0))))
                    self._publish_gauges()
            except (ConnectionError, OSError, ValueError):
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
            # stream died: the grace clock (last contact) decides takeover
            if self._grace_expired():
                self._promote()
                return
            self._stop.wait(0.25)

    def _grace_expired(self) -> bool:
        return time.monotonic() - self._last_contact > self.takeover_grace_s

    def _promote(self) -> None:
        if self._stop.is_set() or self.role == "primary":
            return
        # one last check: another peer may already lead at a higher epoch
        for peer in self.peers:
            st = probe_ha_status(peer, timeout=1.0)
            if st and st.get("role") == "primary" \
                    and int(st.get("epoch", -1)) > self.journal.epoch:
                self.leader_hint = peer
                self._last_contact = time.monotonic()
                t = threading.Thread(target=self._tail_loop, daemon=True,
                                     name="coordinator-ha-tail")
                t.start()
                self._threads.append(t)
                return
        _metrics().counter(
            "distar_coordinator_ha_takeovers_total",
            "standby promotions after the leadership lease went quiet",
        ).inc()
        self._become_primary()

    # ------------------------------------------------------------ housekeeping
    def _housekeeping(self) -> None:
        interval = max(0.5, self.takeover_grace_s / 2.0)
        while not self._stop.wait(interval):
            self._publish_gauges()
            if self.role != "primary":
                continue
            for peer in self.peers:
                st = probe_ha_status(peer, timeout=1.0)
                if st and st.get("role") == "primary" \
                        and int(st.get("epoch", -1)) > self.journal.epoch:
                    # deposed: a newer leadership exists — rejoin as its
                    # follower instead of split-braining (clients already
                    # fence our stale-epoch answers)
                    _metrics().counter(
                        "distar_coordinator_ha_demotions_total",
                        "primaries that found a newer epoch and demoted",
                    ).inc()
                    self._become_standby(peer)
                    break

    # ----------------------------------------------------------------- status
    def status(self) -> dict:
        self._publish_gauges()
        return {
            "role": self.role,
            "epoch": self.epoch,
            "seq": self.journal.seq,
            "feed": f"{self.feed_host}:{self.feed_port}",
            "leader": self.leader_hint,
            "advertise": self.advertise,
            "peers": list(self.peers),
            "journal_lag_records": (max(0, self._leader_seq - self._applied_seq)
                                    if self.role == "standby" else 0),
            "journal_lag_seconds": (max(0.0, time.time() - self._applied_ts)
                                    if self.role == "standby" and self._applied_ts
                                    else 0.0),
            "followers": self._followers,
        }

    def final_snapshot(self) -> None:
        """Journal a parting snapshot (clean shutdown path)."""
        if self.role == "primary":
            self.journal.snapshot(self._state_blob())

    def stop(self) -> None:
        self._stop.set()
        if self._feed_listener is not None:
            try:
                self._feed_listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._feed_listener.close()
            except OSError:
                pass
            self._feed_listener = None
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        self.journal.close()


# ---------------------------------------------------------- client-side state
def parse_addrs(spec) -> Tuple[Tuple[str, int], ...]:
    """``"h1:p1,h2:p2"`` (or a list of such, or (host, port) tuples) ->
    canonical ((host, port), ...). A single coordinator is the 1-tuple."""
    if isinstance(spec, (tuple, list)) and len(spec) == 2 \
            and isinstance(spec[1], int):
        return ((str(spec[0]) or "127.0.0.1", int(spec[1])),)
    items: List[str] = []
    if isinstance(spec, str):
        items = [s.strip() for s in spec.split(",") if s.strip()]
    else:
        for entry in spec or ():
            if isinstance(entry, (tuple, list)):
                items.append(f"{entry[0]}:{entry[1]}")
            else:
                items.append(str(entry))
    out: List[Tuple[str, int]] = []
    for item in items:
        host, _, port = item.rpartition(":")
        out.append((host or "127.0.0.1", int(port)))
    if not out:
        raise ValueError(f"no coordinator addrs in {spec!r}")
    return tuple(out)


def format_addrs(addrs: Sequence[Tuple[str, int]]) -> str:
    return ",".join(f"{h}:{p}" for h, p in addrs)


class FailoverTargets:
    """Shared per-address-set client state: which coordinator is believed
    primary, and the highest epoch ever seen from the set (the fence)."""

    def __init__(self, addrs: Tuple[Tuple[str, int], ...]):
        self.addrs = addrs
        self._lock = threading.Lock()
        self._active = 0
        self.max_epoch = -1

    def active(self) -> Tuple[str, int]:
        with self._lock:
            return self.addrs[self._active]

    def note_epoch(self, epoch: int) -> None:
        with self._lock:
            self.max_epoch = max(self.max_epoch, int(epoch))

    def is_stale(self, epoch: int) -> bool:
        with self._lock:
            return int(epoch) < self.max_epoch

    def rotate(self, failed: Tuple[str, int]) -> Tuple[str, int]:
        """Advance past ``failed`` (no-op if another thread already did)."""
        moved = False
        with self._lock:
            if len(self.addrs) > 1 and self.addrs[self._active] == failed:
                self._active = (self._active + 1) % len(self.addrs)
                moved = True
            current = self.addrs[self._active]
        if moved:
            _metrics().counter(
                "distar_coordinator_ha_client_failovers_total",
                "client-side coordinator target rotations",
            ).inc()
            _notify_failover(self)
        return current

    def follow(self, leader: str, current: Tuple[str, int]) -> None:
        """Adopt a ``not_leader`` redirect's hint when it names a configured
        addr; otherwise just rotate off the standby we asked."""
        target = None
        if leader:
            try:
                target = parse_addrs(leader)[0]
            except (ValueError, IndexError):
                target = None
        with self._lock:
            if target in self.addrs:
                if self.addrs[self._active] != target:
                    self._active = self.addrs.index(target)
                    moved = True
                else:
                    moved = False
            else:
                moved = False
        if moved:
            _notify_failover(self)
        elif target is None or target not in self.addrs:
            self.rotate(current)


_TARGETS: Dict[Tuple[Tuple[str, int], ...], FailoverTargets] = {}
_TARGETS_LOCK = threading.Lock()
_FAILOVER_LISTENERS: List[Callable] = []


def targets_for(addrs: Tuple[Tuple[str, int], ...]) -> FailoverTargets:
    with _TARGETS_LOCK:
        st = _TARGETS.get(addrs)
        if st is None:
            st = _TARGETS[addrs] = FailoverTargets(addrs)
        return st


def reset_targets() -> None:
    """Forget all client failover state (tests)."""
    with _TARGETS_LOCK:
        _TARGETS.clear()


def add_failover_listener(fn: Callable) -> None:
    """``fn(targets)`` runs after any client-side target rotation — how the
    telemetry shipper learns to resync its full snapshot to a new primary
    immediately instead of a ship interval later."""
    with _TARGETS_LOCK:
        _FAILOVER_LISTENERS.append(fn)


def remove_failover_listener(fn: Callable) -> None:
    with _TARGETS_LOCK:
        if fn in _FAILOVER_LISTENERS:
            _FAILOVER_LISTENERS.remove(fn)


def _notify_failover(targets: FailoverTargets) -> None:
    with _TARGETS_LOCK:
        listeners = list(_FAILOVER_LISTENERS)
    for fn in listeners:
        try:
            fn(targets)
        except Exception:  # noqa: BLE001 - observers must not break RPCs
            pass


def is_ambiguous(err: BaseException) -> bool:
    """Could the request have been applied even though the call failed?
    A refused/unresolvable connection never carried the request; anything
    else (timeout, reset, truncated reply) may have."""
    seen = set()
    stack = [err]
    while stack:
        e = stack.pop()
        if id(e) in seen or e is None:
            continue
        seen.add(id(e))
        if isinstance(e, (ConnectionRefusedError, socket.gaierror)):
            return False
        for attr in ("cause", "reason", "__cause__", "__context__"):
            nxt = getattr(e, attr, None)
            if isinstance(nxt, BaseException):
                stack.append(nxt)
    return True
