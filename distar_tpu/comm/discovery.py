"""Coordinator-backed service discovery: register + lease keep-alive + read.

The client-side idiom every scale-out fleet speaks (PR 4 gave the broker
lease/heartbeat eviction; PR 9 added the non-popping ``peers`` read): a
service process registers its endpoint under a token with a lease, keeps it
alive from a daemon thread — re-registering when the broker answers a
heartbeat with False, i.e. it lost our records across a restart — and
consumers read the live fleet back non-destructively via ``peers`` (an
``ask`` would pop the records and unregister the fleet it discovered).
Lease-expired endpoints are evicted broker-side, so a fresh read never
contains a process that stopped heartbeating.

``replay.sharding.register_shard`` (token ``replay_shard``) and
``serve.fleet.discovery.register_gateway`` (token ``serve_gateway``) are
thin wrappers over this module.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Tuple


def _norm_addr(coordinator_addr) -> Tuple[str, Optional[int]]:
    """Accept the classic ``(host, port)`` tuple, a ``"host:port"`` string,
    or an HA comma list (``"h1:p1,h2:p2"`` or ``("h1:p1,h2:p2", None)``) and
    return the pair ``coordinator_request`` expects — ``port=None`` marks an
    HA spec the request layer resolves with leadership failover."""
    if isinstance(coordinator_addr, str):
        if "," in coordinator_addr:
            return coordinator_addr, None
        host, _, port = coordinator_addr.rpartition(":")
        return host or "127.0.0.1", int(port)
    host, port = coordinator_addr
    if port is None or (isinstance(host, str) and "," in host):
        return str(host), None
    return host, int(port)


def register_endpoint(coordinator_addr: Tuple[str, int], token: str, host: str,
                      port: int, meta: Optional[dict] = None,
                      lease_s: Optional[float] = None,
                      heartbeat_interval_s: Optional[float] = None,
                      stop_event: Optional[threading.Event] = None) -> threading.Thread:
    """Register ``host:port`` under ``token`` and keep its lease alive from a
    daemon thread. The first register happens synchronously (a failure raises
    to the caller — a fleet member that can't reach its broker should fail
    loudly at startup, not silently serve undiscovered); later heartbeats
    never raise. Returns the started thread; set ``thread.stop_event`` (or
    pass your own) to end the keep-alive."""
    from .coordinator import coordinator_request

    chost, cport = _norm_addr(coordinator_addr)
    body = {"token": token, "ip": host, "port": port, "meta": meta or {}}
    if lease_s:
        body["lease_s"] = lease_s
    coordinator_request(chost, cport, "register", body)
    interval = heartbeat_interval_s or (max(1.0, lease_s / 3.0) if lease_s else 10.0)
    stop = stop_event or threading.Event()

    def beat():
        while not stop.wait(interval):
            try:
                hb = {"ip": host, "port": port}
                if lease_s:
                    hb["lease_s"] = lease_s
                alive = coordinator_request(chost, cport, "heartbeat", hb)
                if not (alive or {}).get("info", False):
                    # broker lost our records (restart / failover to a
                    # standby that missed us): re-register, and nudge any
                    # telemetry shippers in this process to re-ship their
                    # full snapshot — the restarted broker would otherwise
                    # show this source stale until the next natural ship
                    coordinator_request(chost, cport, "register", body)
                    from ..obs.shipper import request_resync_all

                    request_resync_all("heartbeat")
            except Exception:  # noqa: BLE001 - keep-alive must never crash a role
                continue

    t = threading.Thread(target=beat, name=f"{token}-heartbeat", daemon=True)
    t.stop_event = stop  # type: ignore[attr-defined]
    t.start()
    return t


def unregister_endpoint(coordinator_addr: Tuple[str, int], host: str,
                        port: int) -> int:
    """Graceful departure: drop this endpoint from the broker NOW instead of
    waiting for its lease to lapse — the first step of every drain (a member
    must leave discovery *before* it starts shedding, or routers keep
    pinning new work to it for a whole lease TTL). Returns the number of
    records removed. Raises ``CommError`` on an unreachable broker; drain
    paths treat that as best-effort (the lease still lapses)."""
    from .coordinator import coordinator_request

    chost, cport = _norm_addr(coordinator_addr)
    reply = coordinator_request(chost, cport, "unregister",
                                {"ip": host, "port": port})
    return int(reply.get("info") or 0)


def start_refresh(coordinator_addr: Tuple[str, int], token: str,
                  apply_fn, interval_s: float = 5.0,
                  stop_event: Optional[threading.Event] = None) -> threading.Thread:
    """Live membership, client side: periodically re-read the fleet under
    ``token`` and hand the records to ``apply_fn(records)`` — joins and
    drains become visible to a long-lived client without a restart (the
    standalone router's refresh-loop idiom, shared). A failed read (broker
    blip) keeps the previous view; ``apply_fn`` exceptions are swallowed
    too — a refresher must never take its client down. Returns the daemon
    thread; set ``thread.stop_event`` to end it."""
    stop = stop_event or threading.Event()

    def loop():
        while not stop.wait(interval_s):
            try:
                apply_fn(discover_endpoints(coordinator_addr, token))
            except Exception:  # noqa: BLE001 - keep serving on a stale view
                continue

    t = threading.Thread(target=loop, name=f"{token}-refresh", daemon=True)
    t.stop_event = stop  # type: ignore[attr-defined]
    t.start()
    return t


def discover_endpoints(coordinator_addr: Tuple[str, int], token: str) -> List[dict]:
    """The live fleet registered under ``token``: a non-destructive read of
    the coordinator's ``peers`` route. Returns the raw records
    (``{"ip", "port", "meta", "ts"}``), possibly empty — callers decide
    whether an empty fleet is an error."""
    from .coordinator import coordinator_request

    host, port = _norm_addr(coordinator_addr)
    reply = coordinator_request(host, port, "peers", {"token": token})
    return list(reply.get("info") or [])
