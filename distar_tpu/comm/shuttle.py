"""ctypes binding for the C++ socket shuttle, with a pure-Python fallback.

Builds ``native/shuttle.cpp`` on first use (g++ -O2 -shared -fPIC); when the
toolchain or build is unavailable the Python implementation (threads +
stdlib sockets — IO releases the GIL anyway, but framing runs in Python)
keeps everything working.
"""
from __future__ import annotations

import ctypes
import os
import socket
import struct
import subprocess
import threading
import time
from typing import Optional, Tuple

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "native", "shuttle.cpp")
_SO = os.path.join(_DIR, "native", "libshuttle.so")

_lib = None
_lib_lock = threading.Lock()


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            try:
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", _SO, _SRC, "-lpthread"],
                    check=True,
                    capture_output=True,
                )
            except (OSError, subprocess.CalledProcessError):
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.shuttle_serve.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
        ]
        lib.shuttle_serve.restype = ctypes.c_int
        lib.shuttle_fetch.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.shuttle_fetch.restype = ctypes.c_int
        lib.shuttle_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.shuttlez_bound.argtypes = [ctypes.c_uint64]
        lib.shuttlez_bound.restype = ctypes.c_uint64
        lib.shuttlez_compress.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
        ]
        lib.shuttlez_compress.restype = ctypes.c_int64
        lib.shuttlez_decompress.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
        ]
        lib.shuttlez_decompress.restype = ctypes.c_int64
        lib.shuttlez_crc32.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32,
        ]
        lib.shuttlez_crc32.restype = ctypes.c_uint32
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


# ----------------------------------------------------------------- checksum
def crc32(data, crc: int = 0) -> int:
    """IEEE CRC-32, bit-identical to ``zlib.crc32`` but faster via the
    native slice-by-8 kernel (this image's zlib is unvectorized, and the
    shm ring transport checksums every payload byte twice — write +
    verify). Accepts bytes or buffer views; degrades to ``zlib.crc32``
    for tiny inputs (call overhead) and .so-less hosts — the value is
    identical either way. Views go through numpy's zero-copy data pointer
    rather than ctypes ``from_buffer``: the latter forms a reference
    cycle (_objects -> memoryview) that pins the underlying mmap until a
    GC pass, which made SharedMemory teardown raise BufferError."""
    import zlib

    lib = _load()
    n = len(data)
    if lib is None or n < 1024:
        return zlib.crc32(data, crc)
    if isinstance(data, bytes):
        # c_char_p conversion borrows the bytes' internal buffer — no copy
        return lib.shuttlez_crc32(data, n, crc)
    try:
        import numpy as np

        arr = np.frombuffer(data, dtype=np.uint8)  # zero-copy, refcounted
        return lib.shuttlez_crc32(
            ctypes.cast(arr.ctypes.data, ctypes.c_char_p), arr.nbytes, crc)
    except (TypeError, ValueError, BufferError, ImportError):
        return zlib.crc32(data, crc)


# ------------------------------------------------------- lz4-block codec
def lz_compress(data: bytes) -> Optional[bytes]:
    """LZ4-block compress via the native codec; None when the native lib is
    unavailable (callers fall back to zlib)."""
    lib = _load()
    if lib is None:
        return None
    cap = lib.shuttlez_bound(len(data))
    out = (ctypes.c_uint8 * cap)()
    n = lib.shuttlez_compress(data, len(data), out, cap)
    if n < 0:
        raise OSError(f"shuttlez_compress failed: {n}")
    return bytes(bytearray(out)[:n])


def lz_decompress(blob: bytes, decompressed_len: int) -> bytes:
    """LZ4-block decompress; uses the native codec when available, else a
    pure-Python decoder (the format is trivially decodable)."""
    lib = _load()
    if lib is not None:
        out = (ctypes.c_uint8 * decompressed_len)()
        n = lib.shuttlez_decompress(blob, len(blob), out, decompressed_len)
        if n < 0:
            raise ValueError(f"shuttlez_decompress failed: {n}")
        if n != decompressed_len:
            raise ValueError(f"decompressed {n} != expected {decompressed_len}")
        return bytes(out)
    return _py_lz_decompress(blob, decompressed_len)


def _py_lz_decompress(blob: bytes, decompressed_len: int) -> bytes:
    """Pure-Python LZ4-block decoder (fallback when g++/the .so is absent).

    Raises ValueError (never IndexError) on truncated/malformed streams so
    callers see the same error contract as the native decoder.
    """
    src = memoryview(blob)
    out = bytearray()
    i, end = 0, len(blob)

    def read_byte(pos: int) -> int:
        if pos >= end:
            raise ValueError("malformed lz stream (truncated)")
        return src[pos]

    while i < end:
        token = src[i]
        i += 1
        lit = token >> 4
        if lit == 15:
            while True:
                b = read_byte(i)
                i += 1
                lit += b
                if b != 255:
                    break
        if i + lit > end:
            raise ValueError("malformed lz stream (truncated literals)")
        out += src[i : i + lit]
        i += lit
        if i >= end:
            break
        if i + 2 > end:
            raise ValueError("malformed lz stream (truncated offset)")
        offset = src[i] | (src[i + 1] << 8)
        i += 2
        if offset == 0 or offset > len(out):
            raise ValueError("malformed lz stream (bad offset)")
        mlen = token & 0x0F
        if mlen == 15:
            while True:
                b = read_byte(i)
                i += 1
                mlen += b
                if b != 255:
                    break
        mlen += 4
        start = len(out) - offset
        if offset >= mlen:
            out += out[start : start + mlen]
        else:
            for k in range(mlen):  # overlapping copy must be sequential
                out.append(out[start + k])
    if len(out) != decompressed_len:
        raise ValueError(f"decompressed {len(out)} != expected {decompressed_len}")
    return bytes(out)


def _metrics():
    from ..obs import get_registry

    return get_registry()


def serve(payload: bytes, accept_count: int = 1, timeout_ms: int = 30_000) -> int:
    """Serve ``payload`` (framed) on an ephemeral port to up to
    ``accept_count`` connections; returns the port."""
    reg = _metrics()
    reg.counter("distar_shuttle_serves_total", "serve windows opened").inc()
    reg.counter("distar_shuttle_tx_bytes_total", "payload bytes offered").inc(len(payload))
    lib = _load()
    if lib is not None:
        buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        port = lib.shuttle_serve(buf, len(payload), accept_count, timeout_ms)
        if port > 0:
            return port
        raise OSError(f"shuttle_serve failed: {port}")
    return _py_serve(payload, accept_count, timeout_ms)


def fetch(host: str, port: int, timeout_ms: int = 30_000) -> bytes:
    """Fetch one framed payload from host:port."""
    reg = _metrics()
    lib = _load()
    try:
        if lib is not None:
            out = ctypes.POINTER(ctypes.c_uint8)()
            out_len = ctypes.c_uint64()
            rc = lib.shuttle_fetch(
                host.encode(), port, timeout_ms, ctypes.byref(out), ctypes.byref(out_len)
            )
            if rc != 0:
                raise OSError(f"shuttle_fetch failed: {rc}")
            try:
                blob = ctypes.string_at(out, out_len.value)
            finally:
                lib.shuttle_free(out)
        else:
            blob = _py_fetch(host, port, timeout_ms)
    except (OSError, ConnectionError):
        reg.counter("distar_shuttle_fetch_errors_total", "failed fetches").inc()
        raise
    reg.counter("distar_shuttle_fetches_total", "payloads fetched").inc()
    reg.counter("distar_shuttle_rx_bytes_total", "payload bytes received").inc(len(blob))
    return blob


# ------------------------------------------------------------ python fallback
def _py_serve(payload: bytes, accept_count: int, timeout_ms: int) -> int:
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    listener.bind(("0.0.0.0", 0))
    listener.listen(16)
    listener.settimeout(timeout_ms / 1000.0)
    port = listener.getsockname()[1]
    framed = struct.pack(">Q", len(payload)) + payload
    reg = _metrics()
    reg.gauge("distar_shuttle_active_serves", "serve windows currently open").inc()

    def run():
        served = 0
        try:
            for _ in range(accept_count):
                try:
                    conn, _ = listener.accept()
                except socket.timeout:
                    break
                # accepted sockets do NOT inherit the listener timeout
                # (always-blocking since py3.4): without this, one consumer
                # that connects and never reads parks sendall forever and
                # the serve window never expires
                conn.settimeout(timeout_ms / 1000.0)
                try:
                    with conn:
                        conn.sendall(framed)
                    served += 1
                except OSError:
                    continue  # hung/reset consumer: window stays open for others
        finally:
            listener.close()
            reg.gauge("distar_shuttle_active_serves").dec()
            if served < accept_count:
                # expired serve window: the payload copies nobody fetched
                # are drops, the loss side of broker-depth accounting
                reg.counter(
                    "distar_shuttle_drops_total", "serve-window expiries (unfetched payloads)"
                ).inc(accept_count - served)

    threading.Thread(target=run, daemon=True).start()
    return port


def _py_fetch(host: str, port: int, timeout_ms: int) -> bytes:
    # timeout_ms is a DEADLINE over the whole fetch (connect + every recv),
    # not a per-recv idle timeout: a peer trickling one byte per timeout
    # window used to hold the fetch open indefinitely
    deadline = time.monotonic() + timeout_ms / 1000.0
    with socket.create_connection((host, port), timeout=timeout_ms / 1000.0) as s:

        def recv_exact(n: int) -> bytes:
            chunks = []
            while n > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise socket.timeout(f"fetch deadline ({timeout_ms}ms) exceeded")
                s.settimeout(remaining)
                chunk = s.recv(min(n, 1 << 20))
                if not chunk:
                    raise ConnectionError("short read")
                chunks.append(chunk)
                n -= len(chunk)
            return b"".join(chunks)

        (length,) = struct.unpack(">Q", recv_exact(8))
        return recv_exact(length)
