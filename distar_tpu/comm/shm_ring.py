"""Shared-memory ring transport: the zero-copy data plane for colocated hops.

PR 9's ``--replay-fast-path`` measured ~19x over TCP loopback for the
in-process case; this module generalizes the win to colocated *processes*
(Podracer/Sebulba: actors and inference on the same host should never
touch a socket). Every framed-TCP connection in the repo — replay
insert/sample, serve act/act_many — can negotiate a pair of single-writer/
single-reader byte rings over ``multiprocessing.shared_memory`` in its
``hello`` frame; the TCP socket stays open as the control channel and the
cross-host (or post-fault) fallback leg.

Ring layout (one shm segment per direction)::

    [ 128-byte header | capacity bytes of frame data ]

    header words (8-byte aligned, little-endian):
      magic, capacity, write_pos, read_pos,
      writer_gen, reader_gen, writer_closed, reader_closed,
      writer_heartbeat, reader_heartbeat

``write_pos``/``read_pos`` are *monotonic* byte counters (offset = pos %
capacity), so free space and frame availability are plain subtractions and
wraparound needs no special frames. A frame is ``u32 length | u32 crc32 |
payload`` where the payload is a ``comm.serializer`` blob; payloads
serialize **straight into the ring** (``serializer.dump_stream`` — pickle
protocol 5 streams each numpy buffer into the mapped memory with no
intermediate bytes object) and deserialize **straight out of it** (a
non-wrapping frame hands ``loads`` a memoryview of the ring itself).

Doorbell: a futex is not reachable from portable Python and an fd
socketpair cannot cross the TCP hello, so each endpoint owns a loopback
UDP socket and rings the peer's with a 1-byte datagram after every
publish/consume — the blocked side sleeps in the *kernel* (recvfrom),
waking in tens of microseconds instead of burning a spin. The datagram is
only a wake hint: the ring header stays the single source of truth (the
woken side re-checks its condition, and the wait slices every 250 ms to
re-verify, so a lost ding costs a latency blip, never correctness). Peer
death is detected from the header, not the doorbell: each endpoint's
background beat thread refreshes its heartbeat word every ``window/4``
seconds and a clean close sets the closed flag, so a blocked reader (or
a writer blocked on a full ring) raises a *typed* ``ShmPeerDeadError``
within one heartbeat window of a SIGKILL — the client then falls back to
the TCP leg (``distar_shm_fallbacks_total``).

Negotiation (server side: ``hello_nack`` + ``negotiate_server``; client
side: ``offer_transports`` + ``maybe_attach``): the client's hello
advertises ``transports: [shm, tcp]`` plus its host identity (hostname +
boot id — a spoofed hostname alone never matches, and a forged full token
still dies at attach time because the segment names don't exist on the
impostor's host). When both sides agree they share a host, the server
mints the ring pair, returns the segment names in the hello reply, and
the connection's data frames move over the rings.

Lifecycle: the server owns the segments; they are unlinked on connection
teardown, at interpreter exit (atexit), and from the resilience crash hook
(``FlightRecorder.add_crash_callback``) so a crashed fleet does not leak
``/dev/shm`` entries. A SIGKILL'd process cannot run any of those — its
peer detects the death typed, and the *owner* side's restart mints fresh
segments (stale ones die at reboot; document, don't pretend).
"""
from __future__ import annotations

import atexit
import os
import pickle
import select
import socket
import struct
import threading
import time
import zlib  # noqa: F401 - kept for callers monkeypatching the fallback
from typing import Any, Callable, Dict, Optional, Tuple

from ..resilience import CommError
from . import serializer
from .shuttle import crc32 as _crc32  # native slice-by-8, zlib-identical

#: transport names a hello may legitimately ask for; anything else is a
#: hostile/garbage preference and the server NACKs it typed (bad_hello)
KNOWN_TRANSPORTS = ("shm", "tcp")

MAGIC = b"DSHMRG1\x00"
HEADER_SIZE = 128
_OFF_MAGIC = 0
_OFF_CAPACITY = 8
_OFF_WRITE_POS = 16
_OFF_READ_POS = 24
_OFF_WRITER_GEN = 32
_OFF_READER_GEN = 40
_OFF_WRITER_CLOSED = 48
_OFF_READER_CLOSED = 56
_OFF_WRITER_HB = 64
_OFF_READER_HB = 72

#: per-direction ring capacity (bytes). One request is in flight per
#: connection, so the ring only ever holds ~one frame; 4 MiB covers real
#: trajectory payloads with room for the occasional big weight blob to
#: stream through in chunks (a frame LARGER than the ring is rejected
#: typed — the TCP leg carries it instead).
DEFAULT_RING_BYTES = int(os.environ.get("DISTAR_SHM_RING_BYTES", 4 << 20))

#: a peer whose heartbeat word is older than this is dead (SIGKILL'd /
#: hung); its beat thread refreshes every window/4 while alive
DEFAULT_HEARTBEAT_WINDOW_S = 2.0

_FRAME_HDR = struct.Struct("<II")  # length, crc32
_U64 = struct.Struct("<Q")
_F64 = struct.Struct("<d")

# ----------------------------------------------------------------- errors


class ShmError(CommError):
    """Typed shm-transport failure. Subclasses ``CommError`` (retryable +
    ``ConnectionError``) so every legacy transport-fault handler catches
    it; ``reason`` labels the fallback counter. ``code``/``to_wire`` follow
    the serve/replay wire-error contract — both planes register
    ``shm_error`` (their ``RingServiceError``) so a ring-pump reply
    rehydrates typed on every peer."""

    reason = "shm_error"
    code = "shm_error"

    def __init__(self, message: str, op: str = "", reason: str = ""):
        super().__init__(message, op=op)
        if reason:
            self.reason = reason

    def to_wire(self) -> dict:
        return {"code": self.code, "error": str(self)}


class ShmPeerDeadError(ShmError):
    """The ring peer died (stale heartbeat / generation change / closed
    flag) while this side was blocked on it."""

    reason = "peer_dead"


class ShmFrameTooLargeError(ShmError):
    """The frame being written can never fit the ring — rejected typed at
    send so the caller can route it over the TCP leg instead of blocking
    forever on space that cannot appear."""

    reason = "frame_too_large"


class ShmCorruptError(ShmError):
    """A frame failed its CRC (or the header desynced): the ring contents
    are no longer trustworthy."""

    reason = "corrupt"


class ShmTimeout(ShmError, TimeoutError):
    """The peer is alive but did not produce/consume within the timeout —
    the shm analogue of ``socket.timeout``."""

    reason = "timeout"


class ShmUnavailableError(ShmError):
    """This host cannot speak shm (no ``multiprocessing.shared_memory``)."""

    reason = "unavailable"


# ------------------------------------------------------- host environment

# injectable module handle: tests patch this to None to simulate a host
# without multiprocessing.shared_memory (the fallback-negotiation case)
try:
    from multiprocessing import shared_memory as _sm  # noqa: N813
except ImportError:  # pragma: no cover - every CPython >= 3.8 has it
    _sm = None


def shm_available() -> bool:
    return _sm is not None


def host_identity() -> str:
    """Same-host rendezvous token: hostname plus the kernel boot id, so a
    spoofed hostname alone never matches (and even a forged full token
    fails at segment-attach time — the names don't exist cross-host)."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip()
    except OSError:  # non-Linux: hostname-only (attach still self-verifies)
        boot = ""
    return f"{socket.gethostname()}|{boot}"


def offer_transports(prefer: str = "auto") -> list:
    """The ``transports`` preference list a client hello should carry.
    ``tcp`` means "never shm" (no list sent at all keeps the legacy wire
    byte-identical, so callers skip the key when this returns ['tcp'])."""
    if prefer not in ("auto", "shm", "tcp"):
        raise ValueError(f"transport must be auto|shm|tcp, got {prefer!r}")
    if prefer == "tcp" or not shm_available():
        return ["tcp"]
    return ["shm", "tcp"]


def hello_nack(req: dict) -> Optional[str]:
    """Reason string when a hello's preference lists contain no recognized
    name at all (garbage/hostile hello — NACK typed instead of silently
    degrading); None when the hello is answerable. A preference that is
    recognized but unavailable on this host still degrades gracefully."""
    codecs = req.get("codecs")
    if codecs and not any(c in serializer.KNOWN_CODECS for c in codecs):
        return (f"no recognized codec in {list(codecs)!r} "
                f"(know {list(serializer.KNOWN_CODECS)})")
    transports = req.get("transports")
    if transports and not any(t in KNOWN_TRANSPORTS for t in transports):
        return (f"no recognized transport in {list(transports)!r} "
                f"(know {list(KNOWN_TRANSPORTS)})")
    return None


# ------------------------------------------------------------ observability


def _metrics():
    from ..obs import get_registry

    return get_registry()


def note_fallback(reason: str) -> None:
    """Count one shm->tcp fallback (peer death, attach failure, oversized
    frame, corruption) under its reason label."""
    _metrics().counter(
        "distar_shm_fallbacks_total",
        "shm-transport operations that fell back to the TCP leg",
        reason=reason,
    ).inc()


# ------------------------------------------------------------- ring segment

_live_lock = threading.Lock()
_live_rings: Dict[str, "ShmRing"] = {}
_cleanup_hooked = False


def _register_owned(ring: "ShmRing") -> None:
    global _cleanup_hooked
    with _live_lock:
        _live_rings[ring.name] = ring
        if not _cleanup_hooked:
            _cleanup_hooked = True
            atexit.register(unlink_all)
    try:
        # (re-)attach to the CURRENT flight recorder every time — tests and
        # role restarts swap recorders, and add_crash_callback dedupes
        from ..obs import get_flight_recorder

        get_flight_recorder().add_crash_callback(unlink_all)
    except Exception:  # crash hook is best-effort plumbing
        pass


def _deregister_owned(ring: "ShmRing") -> None:
    with _live_lock:
        _live_rings.pop(ring.name, None)


def unlink_all() -> int:
    """Unlink every ring this process still owns (atexit + the resilience
    crash hook call this so a crashed fleet leaves no /dev/shm litter)."""
    with _live_lock:
        rings = list(_live_rings.values())
    for ring in rings:
        ring.unlink()
    return len(rings)


def _untrack(shm) -> None:
    """Detach an ATTACHED (non-owning) segment from this process's
    resource tracker: on 3.8-3.12 ``SharedMemory(name=...)`` registers the
    segment as ours, and the tracker would unlink the server's ring (with
    a leak warning) when the client exits."""
    try:  # stdlib-private, so fail soft on future layout changes
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


class ShmRing:
    """One shared-memory ring segment (header + data region). Create on
    the owning side, attach by name on the peer; ``RingWriter``/
    ``RingReader`` are the single-writer/single-reader endpoints."""

    def __init__(self, shm, owner: bool):
        self._shm = shm
        self.owner = owner
        self.name = shm.name
        self.buf = shm.buf
        self._closed = False
        self._unlinked = False
        if owner:
            self.buf[_OFF_MAGIC:_OFF_MAGIC + 8] = MAGIC
            capacity = shm.size - HEADER_SIZE
            _U64.pack_into(self.buf, _OFF_CAPACITY, capacity)
            for off in (_OFF_WRITE_POS, _OFF_READ_POS, _OFF_WRITER_GEN,
                        _OFF_READER_GEN, _OFF_WRITER_CLOSED, _OFF_READER_CLOSED):
                _U64.pack_into(self.buf, off, 0)
            _F64.pack_into(self.buf, _OFF_WRITER_HB, 0.0)
            _F64.pack_into(self.buf, _OFF_READER_HB, 0.0)
            _register_owned(self)
        else:
            if bytes(self.buf[_OFF_MAGIC:_OFF_MAGIC + 8]) != MAGIC:
                shm.close()
                raise ShmCorruptError(
                    f"segment {self.name!r} is not a distar shm ring")
        self.capacity = _U64.unpack_from(self.buf, _OFF_CAPACITY)[0]

    # ------------------------------------------------------------ factories
    @classmethod
    def create(cls, capacity: int = DEFAULT_RING_BYTES) -> "ShmRing":
        if _sm is None:
            raise ShmUnavailableError("no multiprocessing.shared_memory on this host")
        if capacity < 4096:
            raise ValueError(f"ring capacity {capacity} is below the 4 KiB floor")
        shm = _sm.SharedMemory(create=True, size=HEADER_SIZE + int(capacity))
        return cls(shm, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        if _sm is None:
            raise ShmUnavailableError("no multiprocessing.shared_memory on this host")
        try:
            shm = _sm.SharedMemory(name=name)
        except (FileNotFoundError, OSError, ValueError) as e:
            raise ShmError(f"cannot attach ring {name!r}: {e!r}",
                           reason="attach_failed") from e
        _untrack(shm)
        return cls(shm, owner=False)

    # -------------------------------------------------------- header access
    # every accessor guards against a locally-closed ring (buf = None):
    # another thread tearing the connection down mid-wait must surface as
    # a TYPED ShmError to the pump/caller, not a raw TypeError
    def _hdr(self):
        buf = self.buf
        if buf is None:
            raise ShmError(f"ring {self.name} closed locally", reason="closed")
        return buf

    def _u64(self, off: int) -> int:
        return _U64.unpack_from(self._hdr(), off)[0]

    def _set_u64(self, off: int, value: int) -> None:
        _U64.pack_into(self._hdr(), off, value)

    def _f64(self, off: int) -> float:
        return _F64.unpack_from(self._hdr(), off)[0]

    def _set_f64(self, off: int, value: float) -> None:
        _F64.pack_into(self._hdr(), off, value)

    # ------------------------------------------------------------- lifecycle
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            # release our memoryview before closing the mapping (CPython
            # refuses to close an shm with exported buffers)
            self.buf = None
            self._shm.close()
        except (OSError, BufferError):
            pass

    def unlink(self) -> None:
        if not self.owner or self._unlinked:
            return
        self._unlinked = True
        _deregister_owned(self)
        self.close()
        try:
            # re-balance the resource tracker before unlink: a same-process
            # attach (in-process servers, tests) already _untrack'd the
            # name, and SharedMemory.unlink's own unregister would then
            # KeyError-spam the tracker daemon. register is set-idempotent.
            from multiprocessing import resource_tracker

            resource_tracker.register(self._shm._name, "shared_memory")
        except Exception:
            pass
        try:
            self._shm.unlink()
        except (FileNotFoundError, OSError):
            pass


def _copy_into(buf, capacity: int, pos: int, data) -> None:
    """Copy ``data`` into the ring data region at absolute position
    ``pos`` (mod capacity), splitting across the wrap point as needed."""
    off = pos % capacity
    n = len(data)
    first = min(n, capacity - off)
    buf[HEADER_SIZE + off:HEADER_SIZE + off + first] = data[:first]
    if n > first:
        buf[HEADER_SIZE:HEADER_SIZE + (n - first)] = data[first:]


def _view_out(buf, capacity: int, pos: int, n: int):
    """Payload at absolute ``pos``: a zero-copy memoryview when the frame
    is contiguous, an assembled bytes object when it wraps."""
    off = pos % capacity
    if off + n <= capacity:
        return buf[HEADER_SIZE + off:HEADER_SIZE + off + n]
    first = capacity - off
    return (bytes(buf[HEADER_SIZE + off:HEADER_SIZE + capacity])
            + bytes(buf[HEADER_SIZE:HEADER_SIZE + (n - first)]))


class Doorbell:
    """One endpoint's wake channel: a loopback UDP socket the PEER rings
    with a 1-byte datagram whenever it publishes a frame or frees ring
    space. Purely a latency device — the ring header remains the truth,
    so lost/spurious dings are harmless. The remote address is either set
    from the hello fields (client side) or learned from the source
    address of the first ding (server side), so no extra handshake frame
    is needed."""

    #: wait-slice: an upper bound on wake latency when a ding is lost AND
    #: the cadence of peer-death re-checks while blocked
    SLICE_S = 0.25

    def __init__(self):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.setblocking(False)  # waits go through select (GIL-free)
        self.port = self._sock.getsockname()[1]
        self._remote: Optional[Tuple[str, int]] = None
        self._closed = False

    def set_remote(self, port: int) -> None:
        self._remote = ("127.0.0.1", int(port))

    def ring(self) -> None:
        remote = self._remote
        if remote is None or self._closed:
            return
        try:
            self._sock.sendto(b"\x01", remote)
        except OSError:
            pass

    def _drain(self) -> None:
        """Consume pending dings without blocking (learning the remote
        address from the first sender when unknown)."""
        try:
            while True:
                _, addr = self._sock.recvfrom(16)
                if self._remote is None:
                    self._remote = addr
        except (BlockingIOError, OSError, ValueError):
            pass

    def wait(self, cond: Callable[[], bool], timeout_s: float,
             check: Callable[[], None], op: str) -> None:
        """Block until ``cond()`` holds: kernel-sleep on the doorbell in
        slices (``select``, so the GIL is released), re-checking the
        header (``check`` raises typed on peer death) each wake. Raises
        ``ShmTimeout`` past the deadline."""
        if cond():
            if self._remote is None:
                self._drain()  # learn the remote from any queued ding
            return
        deadline = time.monotonic() + timeout_s
        while True:
            check()
            if cond():
                self._drain()
                return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise ShmTimeout(
                    f"{op} timed out after {timeout_s:.1f}s on shm ring", op=op)
            try:
                select.select([self._sock], [], [],
                              min(self.SLICE_S, remaining))
            except (OSError, ValueError):
                pass
            self._drain()

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class _RingFile:
    """Writable file-like over the staged (unpublished) region of a ring:
    what ``serializer.dump_stream`` pickles into. Blocks for space when
    the ring is full (counted into the ring-full-wait histogram), raises
    typed when the frame can never fit, and CRCs incrementally so the
    frame header needs no second pass over the payload."""

    def __init__(self, writer: "RingWriter", frame_start: int, timeout_s: float):
        self._w = writer
        self._frame_start = frame_start
        self.pos = frame_start + _FRAME_HDR.size  # payload starts past the header
        self.crc = 0
        self._timeout_s = timeout_s
        self._read_cache = writer.read_pos()

    def write(self, data) -> int:
        w = self._w
        ring = w.ring
        if not isinstance(data, bytes):
            data = memoryview(data).cast("B")
        n = len(data)
        if self.pos + n - self._frame_start > ring.capacity:
            raise ShmFrameTooLargeError(
                f"frame exceeds ring capacity {ring.capacity} "
                f"(>= {self.pos + n - self._frame_start} bytes)", op=w.op)
        # common case: the whole chunk fits the cached free-space estimate
        # (read_pos only moves forward, so a stale cache under-estimates)
        if n <= ring.capacity - (self.pos - self._read_cache):
            _copy_into(ring.buf, ring.capacity, self.pos, data)
            self.crc = _crc32(data, self.crc)
            self.pos += n
            return n
        taken = 0
        while taken < n:
            self._read_cache = w.read_pos()
            free = ring.capacity - (self.pos - self._read_cache)
            if free <= 0:
                w.wait_for_space(self.pos, self._timeout_s)
                continue
            chunk = data[taken:taken + min(free, n - taken)]
            _copy_into(ring.buf, ring.capacity, self.pos, chunk)
            self.crc = _crc32(chunk, self.crc)
            self.pos += len(chunk)
            taken += len(chunk)
        return n


class RingWriter:
    """The single writing endpoint of one ring."""

    def __init__(self, ring: ShmRing, op: str = "shm",
                 bell: Optional[Doorbell] = None):
        self.ring = ring
        self.op = op
        self.bell = bell if bell is not None else Doorbell()
        self._gen = (int.from_bytes(os.urandom(7), "big") | 1)
        ring._set_u64(_OFF_WRITER_GEN, self._gen)
        self.beat()
        self._pos = ring._u64(_OFF_WRITE_POS)
        self._peer_gen = 0
        reg = _metrics()
        self._c_frames = reg.counter(
            "distar_shm_tx_frames_total", "frames written to shm rings")
        self._c_bytes = reg.counter(
            "distar_shm_tx_bytes_total", "bytes written to shm rings")
        self._h_full_wait = reg.histogram(
            "distar_shm_ring_full_wait_seconds",
            "writer wall-clock blocked on a full ring waiting for the reader")

    # ------------------------------------------------------------- liveness
    def beat(self) -> None:
        self.ring._set_f64(_OFF_WRITER_HB, time.time())

    def read_pos(self) -> int:
        return self.ring._u64(_OFF_READ_POS)

    def _check_reader_alive(self) -> None:
        ring = self.ring
        if ring._u64(_OFF_READER_CLOSED):
            raise ShmPeerDeadError("shm reader closed the ring", op=self.op)
        gen = ring._u64(_OFF_READER_GEN)
        if gen:
            if self._peer_gen == 0:
                self._peer_gen = gen
            elif gen != self._peer_gen:
                raise ShmPeerDeadError(
                    "shm reader generation changed (peer restarted)", op=self.op)
            hb = ring._f64(_OFF_READER_HB)
            if hb and time.time() - hb > DEFAULT_HEARTBEAT_WINDOW_S:
                raise ShmPeerDeadError(
                    f"shm reader heartbeat stale ({time.time() - hb:.2f}s)",
                    op=self.op)

    def wait_for_space(self, staged_end: int, timeout_s: float) -> None:
        t0 = time.monotonic()
        try:
            self.bell.wait(
                lambda: self.ring.capacity - (staged_end - self.read_pos()) > 0,
                timeout_s, self._check_reader_alive, f"{self.op}:send")
        finally:
            waited = time.monotonic() - t0
            self._h_full_wait.observe(waited)
            if waited > 0.0005:
                # backpressure attribution: the blocked producer charges its
                # ring-full wait to the request span riding this thread
                # (clients install theirs via obs.set_active_trace)
                from ..obs.trace import annotate_active

                annotate_active("blocked_s", waited)

    # ------------------------------------------------------------------ api
    def send(self, obj: Any, timeout_s: float = 30.0) -> int:
        """Serialize ``obj`` straight into the ring and publish it as one
        CRC'd frame; returns the frame's payload length."""
        ring = self.ring
        start = self._pos
        f = _RingFile(self, start, timeout_s)
        serializer.dump_stream(obj, f)
        length = f.pos - start - _FRAME_HDR.size
        _copy_into(ring.buf, ring.capacity, start,
                   _FRAME_HDR.pack(length, f.crc))
        self.beat()
        self._pos = f.pos
        ring._set_u64(_OFF_WRITE_POS, self._pos)  # the publish
        self.bell.ring()  # wake a reader blocked on an empty ring
        self._c_frames.inc()
        self._c_bytes.inc(length + _FRAME_HDR.size)
        return length

    def close(self) -> None:
        try:
            if self.ring.buf is not None:
                self.ring._set_u64(_OFF_WRITER_CLOSED, 1)
        except (ShmError, TypeError, ValueError):
            pass


class RingReader:
    """The single reading endpoint of one ring."""

    def __init__(self, ring: ShmRing, op: str = "shm",
                 bell: Optional[Doorbell] = None):
        self.ring = ring
        self.op = op
        self.bell = bell if bell is not None else Doorbell()
        self._gen = (int.from_bytes(os.urandom(7), "big") | 1)
        ring._set_u64(_OFF_READER_GEN, self._gen)
        self.beat()
        self._pos = ring._u64(_OFF_READ_POS)
        self._peer_gen = 0
        reg = _metrics()
        self._c_frames = reg.counter(
            "distar_shm_rx_frames_total", "frames read from shm rings")
        self._c_bytes = reg.counter(
            "distar_shm_rx_bytes_total", "bytes read from shm rings")

    # ------------------------------------------------------------- liveness
    def beat(self) -> None:
        self.ring._set_f64(_OFF_READER_HB, time.time())

    def write_pos(self) -> int:
        return self.ring._u64(_OFF_WRITE_POS)

    def _check_writer_alive(self) -> None:
        ring = self.ring
        if self.write_pos() > self._pos:
            return  # data is ready: serve it even if the peer died after
        if ring._u64(_OFF_WRITER_CLOSED):
            raise ShmPeerDeadError("shm writer closed the ring", op=self.op)
        gen = ring._u64(_OFF_WRITER_GEN)
        if gen:
            if self._peer_gen == 0:
                self._peer_gen = gen
            elif gen != self._peer_gen:
                raise ShmPeerDeadError(
                    "shm writer generation changed (peer restarted)", op=self.op)
            hb = ring._f64(_OFF_WRITER_HB)
            if hb and time.time() - hb > DEFAULT_HEARTBEAT_WINDOW_S:
                raise ShmPeerDeadError(
                    f"shm writer heartbeat stale ({time.time() - hb:.2f}s)",
                    op=self.op)

    # ------------------------------------------------------------------ api
    def recv(self, timeout_s: float = 30.0) -> Any:
        """Block for the next frame (typed ``ShmTimeout`` /
        ``ShmPeerDeadError``), CRC-check it, and deserialize — zero-copy
        when the frame did not wrap the ring edge."""
        ring = self.ring
        self.bell.wait(lambda: self.write_pos() > self._pos, timeout_s,
                       self._check_writer_alive, f"{self.op}:recv")
        off = self._pos % ring.capacity
        if off + _FRAME_HDR.size <= ring.capacity:  # contiguous header
            length, crc = _FRAME_HDR.unpack_from(ring.buf, HEADER_SIZE + off)
        else:
            length, crc = _FRAME_HDR.unpack(bytes(_view_out(
                ring.buf, ring.capacity, self._pos, _FRAME_HDR.size)))
        if length > ring.capacity - _FRAME_HDR.size \
                or self._pos + _FRAME_HDR.size + length > self.write_pos():
            raise ShmCorruptError(
                f"implausible frame length {length} at pos {self._pos} "
                f"(capacity {ring.capacity})", op=self.op)
        payload = _view_out(ring.buf, ring.capacity,
                            self._pos + _FRAME_HDR.size, length)
        try:
            if _crc32(payload) != crc:
                raise ShmCorruptError(
                    f"frame CRC mismatch at pos {self._pos} (length {length})",
                    op=self.op)
            try:
                obj = serializer.loads(payload)
            except (pickle.UnpicklingError, ValueError, EOFError) as e:
                raise ShmCorruptError(f"undecodable shm frame: {e!r}",
                                      op=self.op) from e
        finally:
            # release on EVERY path: a leaked export keeps the mapping
            # pinned and SharedMemory.close() raises BufferError at GC
            if isinstance(payload, memoryview):
                payload.release()
        # consume AFTER decode: a zero-copy view must not be overwritten
        # by the writer while loads is still reading it
        self._pos += _FRAME_HDR.size + length
        ring._set_u64(_OFF_READ_POS, self._pos)
        self.bell.ring()  # wake a writer blocked on a full ring
        self.beat()
        self._c_frames.inc()
        self._c_bytes.inc(length + _FRAME_HDR.size)
        return obj

    def close(self) -> None:
        try:
            if self.ring.buf is not None:
                self.ring._set_u64(_OFF_READER_CLOSED, 1)
        except (ShmError, TypeError, ValueError):
            pass


# ------------------------------------------------------------- connections


class ShmPeer:
    """One side of a negotiated ring pair: a writer on the outbound ring,
    a reader on the inbound one, and a beat thread keeping both heartbeat
    words fresh while this side is alive (so idleness is never mistaken
    for death). The server side ``owner=True`` unlinks the segments on
    close; the client side only detaches."""

    def __init__(self, tx: ShmRing, rx: ShmRing, owner: bool, op: str = "shm"):
        self._tx_ring = tx
        self._rx_ring = rx
        self.owner = owner
        self.op = op
        #: ONE doorbell socket per endpoint: the peer rings it on publish
        #: (data ready) and on consume (space freed); both this side's
        #: writer and reader sleep on it and re-check their own condition
        self.bell = Doorbell()
        self.writer = RingWriter(tx, op=op, bell=self.bell)
        self.reader = RingReader(rx, op=op, bell=self.bell)
        self._closed = threading.Event()
        self._beat_thread = threading.Thread(
            target=self._beat_loop, name=f"shm-beat-{op}", daemon=True)
        self._beat_thread.start()

    def _beat_loop(self) -> None:
        interval = DEFAULT_HEARTBEAT_WINDOW_S / 4.0
        while not self._closed.wait(interval):
            try:
                self.writer.beat()
                self.reader.beat()
            except (ShmError, TypeError, ValueError):  # released under us
                return

    # ------------------------------------------------------------------ api
    def send(self, obj: Any, timeout_s: float = 30.0) -> int:
        return self.writer.send(obj, timeout_s=timeout_s)

    def recv(self, timeout_s: float = 30.0) -> Any:
        return self.reader.recv(timeout_s=timeout_s)

    def request(self, req: Any, timeout_s: float = 30.0) -> Any:
        """One RPC over the rings: send the request frame, block for the
        response frame (the client-side data-plane hot path)."""
        self.send(req, timeout_s=timeout_s)
        return self.recv(timeout_s=timeout_s)

    @property
    def names(self) -> Tuple[str, str]:
        return (self._tx_ring.name, self._rx_ring.name)

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        # join BEFORE tearing the rings down: a beat mid-flight would race
        # the unlink below (tolerated by its except, but joining removes
        # the window entirely); the loop re-checks _closed every wait tick
        if self._beat_thread is not threading.current_thread():
            self._beat_thread.join(timeout=DEFAULT_HEARTBEAT_WINDOW_S)
        self.writer.close()
        self.reader.close()
        self.bell.ring()  # nudge a blocked peer so it re-checks the flags
        self.bell.close()
        for ring in (self._tx_ring, self._rx_ring):
            if self.owner:
                ring.unlink()
            else:
                ring.close()


def mint_ring_pair(ring_bytes: int = DEFAULT_RING_BYTES,
                   op: str = "shm") -> Tuple[ShmPeer, dict]:
    """Server side: create both direction rings and return (server peer,
    the hello-reply fields the client needs to attach)."""
    c2s = ShmRing.create(ring_bytes)
    try:
        s2c = ShmRing.create(ring_bytes)
    except Exception:
        c2s.unlink()
        raise
    peer = ShmPeer(tx=s2c, rx=c2s, owner=True, op=op)
    fields = {"transport": "shm", "shm_c2s": c2s.name, "shm_s2c": s2c.name,
              "ring_bytes": int(ring_bytes), "doorbell_port": peer.bell.port}
    return peer, fields


def attach_ring_pair(reply: dict, op: str = "shm") -> ShmPeer:
    """Client side: attach the rings a hello reply named (client writes
    c2s, reads s2c)."""
    c2s = ShmRing.attach(reply["shm_c2s"])
    try:
        s2c = ShmRing.attach(reply["shm_s2c"])
    except Exception:
        c2s.close()
        raise
    peer = ShmPeer(tx=c2s, rx=s2c, owner=False, op=op)
    port = reply.get("doorbell_port")
    if port:
        peer.bell.set_remote(int(port))
        peer.bell.ring()  # announce our doorbell address to the server
    return peer


def maybe_attach(reply: dict, op: str = "shm") -> Optional[ShmPeer]:
    """Attach when the server's hello reply negotiated shm; None (counted)
    when it didn't or the attach fails — the caller stays on TCP."""
    if not isinstance(reply, dict) or reply.get("transport") != "shm":
        return None
    try:
        return attach_ring_pair(reply, op=op)
    except Exception:
        note_fallback("attach_failed")
        return None


def negotiate_server(req: dict, transport: str = "auto",
                     ring_bytes: int = DEFAULT_RING_BYTES,
                     op: str = "shm") -> Tuple[dict, Optional[ShmPeer]]:
    """Server side of the hello: decide the connection's transport.

    Returns ``(reply_fields, peer)`` — ``peer`` is the live server ring
    endpoint when shm was agreed (caller starts a ``RingService`` on it
    and must close it on connection teardown), else None. shm is agreed
    only when the client offered it, this server allows it, both report
    the same host identity, and the segments actually mint."""
    prefs = req.get("transports")
    if prefs is None:
        return {}, None  # legacy client: no negotiation, no reply fields
    want_shm = ("shm" in prefs and transport in ("auto", "shm")
                and shm_available()
                and str(req.get("host", "")) == host_identity())
    if want_shm:
        try:
            peer, fields = mint_ring_pair(ring_bytes, op=op)
            return fields, peer
        except Exception:
            note_fallback("mint_failed")
    return {"transport": "tcp"}, None


class RingService:
    """Server-side pump for one negotiated connection: a daemon thread
    that answers ring frames with ``dispatch(req)`` until the connection
    tears down or the client dies (detected typed). Owns the peer's
    lifecycle — ``stop()`` closes and unlinks the rings."""

    POLL_S = 0.25

    def __init__(self, peer: ShmPeer, dispatch: Callable[[Any], Any],
                 name: str = "shm-ring-service"):
        self._peer = peer
        self._dispatch = dispatch
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)

    def start(self) -> "RingService":
        self._thread.start()
        return self

    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    req = self._peer.recv(timeout_s=self.POLL_S)
                except ShmTimeout:
                    continue
                except ShmError:
                    return  # peer dead/corrupt: the TCP leg owns recovery
                try:
                    resp = self._dispatch(req)
                except Exception as e:  # dispatch bug must not kill the pump
                    resp = ShmError(repr(e), op=self._thread.name).to_wire()
                try:
                    self._peer.send(resp, timeout_s=30.0)
                except ShmError:
                    return
        finally:
            self._peer.close()

    def stop(self, join_s: float = 2.0) -> None:
        self._stop.set()
        self._thread.join(join_s)
        self._peer.close()  # idempotent; covers a wedged pump thread
