from .adapter import Adapter
from .coordinator import Coordinator, CoordinatorServer, coordinator_request
from .serializer import dumps, loads
from . import shuttle

__all__ = [
    "Adapter",
    "Coordinator",
    "CoordinatorServer",
    "coordinator_request",
    "dumps",
    "loads",
    "shuttle",
]
