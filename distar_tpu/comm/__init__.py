from .adapter import Adapter
from .coordinator import Coordinator, CoordinatorServer, coordinator_request
from .serializer import dumps, loads
from . import shuttle
from .shm_ring import (
    ShmError,
    ShmPeer,
    ShmPeerDeadError,
    ShmRing,
    shm_available,
)
from ..resilience import CommError  # typed transport error raised by this package

__all__ = [
    "Adapter",
    "CommError",
    "Coordinator",
    "CoordinatorServer",
    "coordinator_request",
    "dumps",
    "loads",
    "shuttle",
    "ShmError",
    "ShmPeer",
    "ShmPeerDeadError",
    "ShmRing",
    "shm_available",
]
