"""Coordinator: token-keyed metadata broker for the peer-to-peer data plane.

Role parity with the reference Coordinator (reference: distar/ctools/worker/
coordinator/coordinator.py:62-232): producers register "payload ready at
ip:port" records under a token; consumers pop a record and connect directly —
the broker never touches tensor payloads. Dead producers accumulate strikes
on failed fetches and are dropped after 5 (coordinator.py:114-128).

Transport here is the same stdlib HTTP/JSON server as the league API.
"""
from __future__ import annotations

import json
import threading
import time
from collections import defaultdict, deque
from typing import Dict, Optional

from ..utils import Config


class Coordinator:
    def __init__(self, maxlen_per_token: int = 512, max_age_s: Optional[float] = None,
                 default_lease_s: Optional[float] = None):
        """``max_age_s``: default serve-window age filter applied by BOTH
        ``depth()`` and ``stats()`` (records older than the producers' serve
        window are loss, not backlog). None = no filtering.
        ``default_lease_s``: lease TTL applied to every registration that
        doesn't pass its own ``lease_s`` — endpoints that stop heartbeating
        are evicted wholesale (the liveness complement to per-fetch strikes).
        None = registrations never expire by lease."""
        self._maxlen = maxlen_per_token
        self._max_age_s = max_age_s
        self._default_lease_s = default_lease_s
        self._records: Dict[str, deque] = defaultdict(lambda: deque(maxlen=self._maxlen))
        self._strikes: Dict[str, int] = defaultdict(int)
        # "ip:port" -> monotonic expiry: lease bookkeeping rides
        # time.monotonic() so an NTP step can neither mass-evict a healthy
        # fleet nor immortalize dead endpoints (record *ages* in depth()/
        # stats() stay wall-clock — they describe data, not liveness)
        self._leases: Dict[str, float] = {}
        self._last_sweep = 0.0  # monotonic
        self._evict_callbacks: list = []
        self._lock = threading.RLock()

    def add_evict_callback(self, fn) -> None:
        """Subscribe to endpoint departures: ``fn("ip:port")`` runs whenever
        an endpoint's records leave the broker through the lease sweep or an
        explicit ``unregister`` (NOT per-fetch strikes — a struck endpoint
        may still be alive). Callbacks run under the broker lock and must be
        quick and never call back into the coordinator; exceptions are
        swallowed. This is how ``TelemetryIngest`` learns to evict a dead
        member's TSDB series instead of hoarding them against the cap."""
        with self._lock:
            self._evict_callbacks.append(fn)

    def _notify_evicted(self, key: str) -> None:
        for fn in self._evict_callbacks:
            try:
                fn(key)
            except Exception:  # noqa: BLE001 - observers must not break the broker
                pass

    def register(self, token: str, ip: str, port: int, meta: Optional[dict] = None,
                 lease_s: Optional[float] = None) -> bool:
        return self.apply_register(token, ip, port, meta, lease_s=lease_s)

    def apply_register(self, token: str, ip: str, port: int,
                       meta: Optional[dict] = None, lease_s: Optional[float] = None,
                       record_ts: Optional[float] = None) -> bool:
        """``register`` plus the journal-replay re-aging hook: ``record_ts``
        (the original wall time from the WAL record) anchors both the record
        timestamp and the lease, so a replayed registration whose lease
        already lapsed during the outage expires on the first sweep instead
        of getting a fresh TTL."""
        lease_s = self._default_lease_s if lease_s is None else lease_s
        now = time.time()
        ts = now if record_ts is None else record_ts
        with self._lock:
            self._records[token].append(
                {"ip": ip, "port": port, "meta": meta or {}, "ts": ts}
            )
            if lease_s is not None:
                self._leases[f"{ip}:{port}"] = \
                    time.monotonic() + lease_s - (now - ts)
            return True

    def heartbeat(self, ip: str, port: int, lease_s: Optional[float] = None) -> bool:
        """Refresh an endpoint's lease. Returns True when the broker still
        holds records for that endpoint — False tells a producer its state
        is gone (broker restarted or evicted) and it must re-register."""
        return self.apply_heartbeat(ip, port, lease_s=lease_s)

    def apply_heartbeat(self, ip: str, port: int, lease_s: Optional[float] = None,
                        record_ts: Optional[float] = None) -> bool:
        lease_s = self._default_lease_s if lease_s is None else lease_s
        age = 0.0 if record_ts is None else max(0.0, time.time() - record_ts)
        key = f"{ip}:{port}"
        with self._lock:
            self._sweep_leases()
            if lease_s is not None:
                self._leases[key] = time.monotonic() + lease_s - age
            from ..obs import get_registry

            get_registry().counter(
                "distar_coordinator_heartbeats_total", "endpoint lease refreshes"
            ).inc()
            return any(
                f"{r['ip']}:{r['port']}" == key for q in self._records.values() for r in q
            )

    def _purge_endpoint(self, key: str) -> int:
        """Drop every record registered by ``key`` ("ip:port"); the shared
        removal path behind strikes AND lease eviction. Caller holds lock."""
        removed = 0
        for q in self._records.values():
            dead = [r for r in q if f"{r['ip']}:{r['port']}" == key]
            for r in dead:
                q.remove(r)
            removed += len(dead)
        self._strikes.pop(key, None)
        self._leases.pop(key, None)
        return removed

    def _sweep_leases(self, min_interval_s: float = 1.0) -> None:
        """Evict endpoints whose lease expired (at most once per
        ``min_interval_s`` — called from the hot read paths). Caller holds
        lock."""
        now = time.monotonic()
        if now - self._last_sweep < min_interval_s:
            return
        self._last_sweep = now
        expired = [k for k, exp in self._leases.items() if exp < now]
        if not expired:
            return
        from ..obs import get_registry

        evictions = get_registry().counter(
            "distar_coordinator_evictions_total",
            "endpoints evicted on lease expiry",
        )
        for key in expired:
            self._purge_endpoint(key)
            evictions.inc()
            self._notify_evicted(key)

    def unregister(self, ip: str, port: int) -> int:
        """Graceful departure: drop every record for ``ip:port`` NOW (a
        draining member must leave discovery before it starts shedding, not
        ``lease_s`` later when the lease lapses). Returns the number of
        records removed; fires the same eviction observers as the lease
        sweep so downstream state (TSDB series) is reclaimed either way."""
        key = f"{ip}:{port}"
        with self._lock:
            removed = self._purge_endpoint(key)
            from ..obs import get_registry

            get_registry().counter(
                "distar_coordinator_unregisters_total",
                "endpoints that deregistered gracefully (drain path)",
            ).inc()
            self._notify_evicted(key)
            return removed

    def ask(self, token: str) -> Optional[dict]:
        """Pop the oldest ready record for a token (None when empty)."""
        with self._lock:
            self._sweep_leases()
            q = self._records.get(token)
            if not q:
                return None
            return q.popleft()

    def peers(self, token: str) -> list:
        """Non-destructive listing of a token's live records — the service-
        discovery read (``ask`` is a work-queue pop; a shard map built by
        popping would unregister the fleet it discovered). Lease sweeping
        applies first, so evicted endpoints never appear in a fresh map."""
        with self._lock:
            self._sweep_leases()
            return [dict(r) for r in self._records.get(token, ())]

    _UNSET = object()  # sentinel: "use the instance default max_age_s"

    @staticmethod
    def _filtered_len(q, max_age_s: Optional[float]) -> int:
        if max_age_s is None:
            return len(q)
        cutoff = time.time() - max_age_s
        return sum(1 for r in q if r.get("ts", 0) >= cutoff)

    def depth(self, token: str, max_age_s=_UNSET) -> int:
        """Registered-but-unconsumed records for a token — the broker-side
        backlog (payloads wait in producer serve windows until fetched), the
        queue hop that client-cache occupancy can't see. ``max_age_s``
        excludes records older than the producers' serve window: those
        payloads expired and will never be consumed, so they are loss, not
        backlog. Defaults to the instance-wide ``max_age_s`` so depth(),
        stats() and the /metrics gauges all agree on one filter."""
        if max_age_s is Coordinator._UNSET:
            max_age_s = self._max_age_s
        with self._lock:
            self._sweep_leases()
            q = self._records.get(token)
            if not q:
                return 0
            return self._filtered_len(q, max_age_s)

    def strike(self, ip: str, port: int) -> None:
        """Report a dead producer endpoint; 5 strikes purges its records."""
        key = f"{ip}:{port}"
        with self._lock:
            self._strikes[key] += 1
            if self._strikes[key] >= 5:
                self._purge_endpoint(key)

    def stats(self, max_age_s=_UNSET) -> dict:
        """Per-token depth with the SAME age filter as ``depth()`` (they used
        to disagree: stats counted raw lengths, so /metrics and ask-side
        accounting drifted whenever serve windows expired). Pass
        ``max_age_s=None`` explicitly for raw unfiltered lengths."""
        if max_age_s is Coordinator._UNSET:
            max_age_s = self._max_age_s
        with self._lock:
            self._sweep_leases()
            return {
                token: self._filtered_len(q, max_age_s)
                for token, q in self._records.items()
            }

    def state_snapshot(self) -> dict:
        """Full broker state in wire/journal-safe form (HA snapshots and the
        follower feed). Lease expiries cross the process boundary as
        *remaining seconds* — monotonic readings are meaningless in another
        process, wall timestamps would re-import the NTP hazard."""
        with self._lock:
            mono = time.monotonic()
            return {
                "records": {t: [dict(r) for r in q]
                            for t, q in self._records.items() if q},
                "strikes": dict(self._strikes),
                "lease_remaining": {k: exp - mono
                                    for k, exp in self._leases.items()},
            }

    def restore_state(self, state: dict) -> None:
        """Adopt a ``state_snapshot()`` wholesale (journal recovery or a
        standby receiving the leader's snapshot)."""
        with self._lock:
            self._records = defaultdict(lambda: deque(maxlen=self._maxlen))
            for token, recs in (state.get("records") or {}).items():
                self._records[token].extend(dict(r) for r in recs)
            self._strikes = defaultdict(int)
            self._strikes.update(state.get("strikes") or {})
            mono = time.monotonic()
            self._leases = {
                k: mono + float(rem)
                for k, rem in (state.get("lease_remaining") or {}).items()
            }

    def publish_metrics(self, registry=None) -> None:
        """Refresh ``distar_coordinator_queue_depth{token=...}`` gauges (and
        the strike gauge) — called by the /metrics route at scrape time."""
        from ..obs import get_registry

        reg = registry or get_registry()
        for token, depth in self.stats().items():
            reg.gauge(
                "distar_coordinator_queue_depth",
                "broker backlog per token (age-filtered)",
                token=token,
            ).set(depth)
        with self._lock:
            strikes = sum(self._strikes.values())
        reg.gauge(
            "distar_coordinator_endpoint_strikes", "outstanding dead-endpoint strikes"
        ).set(strikes)


class CoordinatorServer:
    """HTTP wrapper: POST /coordinator/<register|ask|strike|stats|telemetry>,
    GET /metrics (Prometheus scrape) + the fleet-health routes
    /healthz, /alerts, /timeseries (obs.handle_health_get)."""

    def __init__(self, coordinator: Optional[Coordinator] = None, host="127.0.0.1", port=0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.coordinator = coordinator or Coordinator()
        co = self.coordinator
        # HA is attached after construction (attach_ha) because HAState
        # needs this server's bound port for its advertise addr; the box
        # lets the request handlers see the attachment without a rebuild
        ha_box: dict = {"ha": None}
        self._ha_box = ha_box

        def _ingest_telemetry(msg: dict) -> int:
            # fold shipped snapshots into the process fleet store: the broker
            # is the one place that sees every actor/learner/serve source
            from ..obs import get_fleet_health

            return get_fleet_health().ingest.ingest(msg)

        def _evict_telemetry(key: str) -> None:
            # an endpoint left (lease lapsed or graceful unregister): free
            # its TSDB series so a churning fleet can't exhaust the series
            # cap permanently (the ingest maps endpoint -> shipped sources)
            from ..obs import get_fleet_health

            get_fleet_health().ingest.evict_endpoint(key)

        co.add_evict_callback(_evict_telemetry)

        routes = {
            # explicit-arg extraction (not **b): a wire body must not be
            # able to reach internal kwargs like apply_register's record_ts
            "register": lambda b: co.register(
                b["token"], b["ip"], b["port"],
                meta=b.get("meta"), lease_s=b.get("lease_s")),
            "ask": lambda b: co.ask(b["token"]),
            "peers": lambda b: co.peers(b["token"]),
            "strike": lambda b: co.strike(b["ip"], b["port"]),
            "heartbeat": lambda b: co.heartbeat(
                b["ip"], b["port"], lease_s=b.get("lease_s")),
            "unregister": lambda b: co.unregister(b["ip"], b["port"]),
            # absent max_age_s -> the coordinator's own default filter, so
            # HTTP callers and in-process callers see identical accounting
            "stats": lambda b: (
                co.stats(b["max_age_s"]) if "max_age_s" in b else co.stats()
            ),
            "depth": lambda b: (
                co.depth(b["token"], b["max_age_s"])
                if "max_age_s" in b
                else co.depth(b["token"])
            ),
            "telemetry": _ingest_telemetry,
            # arena wire plane (served when this coordinator hosts the
            # ArenaStore; the store's idempotent keys make arena_report
            # exactly-once even when the retry fabric replays a POST)
            "arena_next": lambda b: _arena_call(
                "next_match", b.get("players", []),
                episodes=int(b.get("episodes", 8))),
            "arena_report": lambda b: _arena_call(
                "report_batch", b.get("matches", [])),
            # league wire plane (served when this coordinator hosts the
            # LeagueService): the matchmaker's mutating routes are journaled
            # like the arena ledger's, so broker failover loses no roster,
            # assignment or snapshot-lineage state (the body is passed
            # whole — the service does its own explicit field extraction)
            "league_register": lambda b: _league_call("register_learner", b),
            "league_ask": lambda b: _league_call("ask_job", b),
            "league_report": lambda b: _league_call("report", b),
            "league_train_info": lambda b: _league_call("train_info", b),
            "league_status": lambda b: _league_call("status", b),
        }

        def _arena_call(method: str, *args, **kwargs):
            from ..arena import get_arena_store

            store = get_arena_store()
            if store is None:
                raise RuntimeError("no arena store hosted on this coordinator")
            return getattr(store, method)(*args, **kwargs)

        def _league_call(method: str, body: dict):
            from ..league.runtime import get_league_service

            service = get_league_service()
            if service is None:
                raise RuntimeError("no league service hosted on this coordinator")
            return getattr(service, method)(body)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                """GET /metrics: Prometheus text exposition of the process
                registry (queue-depth gauges refreshed at scrape time);
                GET /healthz, /alerts, /timeseries: fleet-health JSON."""
                from ..obs import handle_health_get, write_scrape_response

                if self.path.rstrip("/") == "/metrics":
                    write_scrape_response(self, refresh=co.publish_metrics)
                    return
                if self.path.rstrip("/") == "/coordinator/ha":
                    # leadership digest (standby probes, client boot-strapping,
                    # opsctl status): role/epoch/journal seq/feed addr/lag —
                    # 404 when this coordinator runs without HA
                    from ..obs import write_json_response

                    ha_state = ha_box["ha"]
                    if ha_state is None:
                        self.send_response(404)
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    write_json_response(self, ha_state.status())
                    return
                if self.path.rstrip("/") == "/autoscaler":
                    # elastic-control-plane digest (opsctl status reads it):
                    # answered from the process-global autoscaler when one
                    # runs in this coordinator, 404 otherwise
                    from ..fleet import get_autoscaler
                    from ..obs import write_json_response

                    scaler = get_autoscaler()
                    if scaler is None:
                        self.send_response(404)
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    write_json_response(self, scaler.status())
                    return
                if self.path.rstrip("/") == "/league/status":
                    # matchmaking digest (opsctl league reads it): answered
                    # from the process-global LeagueService when this
                    # coordinator hosts one, 404 otherwise
                    from ..league.runtime import get_league_service
                    from ..obs import write_json_response

                    service = get_league_service()
                    if service is None:
                        self.send_response(404)
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    write_json_response(self, service.status())
                    return
                if self.path.rstrip("/") in ("/arena/ratings", "/arena/payoff"):
                    # skill-ledger snapshots (opsctl arena / perf_gate skill
                    # read these): answered from the process-global ArenaStore
                    # when this coordinator hosts one, 404 otherwise
                    from ..arena import get_arena_store
                    from ..obs import write_json_response

                    store = get_arena_store()
                    if store is None:
                        self.send_response(404)
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    snap = (store.ratings_snapshot()
                            if self.path.rstrip("/").endswith("ratings")
                            else store.payoff_snapshot())
                    write_json_response(self, snap)
                    return
                if handle_health_get(self, self.path):
                    return
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_POST(self):
                from ..obs import finish_trace, format_traceparent, join_trace, parse_traceparent

                name = self.path.strip("/").split("/")[-1]
                length = int(self.headers.get("Content-Length", 0))
                # w3c traceparent propagation (the broker is an HTTP hop in
                # discovery/league flows too): a caller-supplied header
                # joins this route's span under the caller's trace_id
                wire = parse_traceparent(self.headers.get("traceparent"))
                ctx = join_trace(wire, f"coordinator_{name}") \
                    if wire is not None else None
                outcome = "ok"
                try:
                    raw = self.rfile.read(length)
                    ctype = self.headers.get("Content-Type", "")
                    if name == "telemetry" and ctype.startswith(
                        "application/x-distar"
                    ):
                        # shipped snapshots ride the comm serializer codec
                        # (pickle+LZ), not JSON — same stack as the data plane
                        from .serializer import loads as _loads

                        body = _loads(raw)
                    else:
                        body = json.loads(raw or b"{}")
                    fn = routes.get(name)
                    ha_state = ha_box["ha"]
                    if fn is None:
                        payload = {"code": 404, "info": f"no route {name}"}
                    elif ha_state is not None:
                        from .ha import NotLeader

                        try:
                            payload = {"code": 0,
                                       "info": ha_state.dispatch(name, body, fn)}
                        except NotLeader as e:
                            # typed redirect: clients follow the hint under
                            # the retry fabric instead of seeing a 500
                            payload = {"code": 2, "info": "not_leader",
                                       "leader": e.leader}
                            outcome = "not_leader"
                    else:
                        payload = {"code": 0, "info": fn(body)}
                except Exception as e:
                    payload = {"code": 1, "info": repr(e)}
                    outcome = "error"
                ha_state = ha_box["ha"]
                if ha_state is not None:
                    # the fencing stamp: every reply carries the epoch so a
                    # deposed primary's answers are detectably stale
                    payload.setdefault("epoch", ha_state.epoch)
                    payload.setdefault("role", ha_state.role)
                data = json.dumps(payload, default=str).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                if ctx is not None:
                    self.send_header("traceparent", format_traceparent(ctx))
                    finish_trace(ctx, "coordinator_done", outcome=outcome)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def attach_ha(self, ha_state) -> None:
        """Wire a booted :class:`distar_tpu.comm.ha.HAState` into request
        dispatch: POSTs route through its journal/leadership contract and
        every reply is epoch-stamped. Attach before ``start()``."""
        self._ha_box["ha"] = ha_state

    @property
    def ha(self):
        return self._ha_box["ha"]

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        # reap the serve loop before closing its socket under it
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._server.server_close()


def _coordinator_request_once(host: str, port: int, route: str,
                              body: Optional[dict], timeout: float) -> dict:
    """One transport attempt; raises a typed ``CommError`` instead of
    leaking ``URLError``/timeout/JSON-decode exceptions to call sites."""
    import urllib.error
    import urllib.request

    from ..resilience import CommError

    op = f"coordinator:{route}"
    req = urllib.request.Request(
        f"http://{host}:{port}/coordinator/{route}",
        data=json.dumps(body or {}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())
    except (urllib.error.URLError, ConnectionError, TimeoutError, OSError,
            ValueError) as e:
        # ValueError covers a truncated/garbage JSON body from a peer dying
        # mid-response — as transient as the connection reset it really is
        raise CommError(f"{op} @ {host}:{port} failed: {e!r}", op=op, cause=e) from e


def _failover_request_once(targets, route: str, body: Optional[dict],
                           timeout: float) -> dict:
    """One HA-aware attempt against the believed-primary of an addr set:
    transport failures rotate the target (ambiguous acks on non-idempotent
    routes surface typed instead), ``not_leader`` replies follow the
    leadership hint, and replies whose epoch is below the highest ever seen
    are discarded — all raised as typed retryables so the PR 4 fabric
    drives the redirect loop."""
    from ..resilience import CommError
    from . import ha as _ha

    host, port = targets.active()
    addr = f"{host}:{port}"
    try:
        reply = _coordinator_request_once(host, port, route, body, timeout)
    except CommError as e:
        targets.rotate((host, port))
        if route not in _ha.IDEMPOTENT_ROUTES and _ha.is_ambiguous(e):
            # the primary died between send and reply: an `ask` may have
            # popped a record whose reply we never saw — retrying on the
            # standby would consume a SECOND record, so refuse typed
            raise _ha.AmbiguousAckError(route, addr, cause=e) from e
        raise
    epoch = reply.get("epoch")
    if epoch is not None:
        epoch = int(epoch)
        if targets.is_stale(epoch):
            # a deposed primary still answering: fence it out
            from ..obs import get_registry

            get_registry().counter(
                "distar_coordinator_ha_stale_replies_total",
                "replies discarded for carrying a deposed primary's epoch",
            ).inc()
            targets.rotate((host, port))
            raise _ha.StaleEpochError(addr, epoch, targets.max_epoch)
        targets.note_epoch(epoch)
    if reply.get("code") == 2 and reply.get("info") == "not_leader":
        targets.follow(str(reply.get("leader") or ""), (host, port))
        raise _ha.NotLeaderError(addr, str(reply.get("leader") or ""),
                                 int(epoch if epoch is not None else -1))
    return reply


def coordinator_request(host: str, port: Optional[int], route: str,
                        body: Optional[dict] = None, timeout=10.0, policy=None):
    """Broker RPC under the resilience retry fabric.

    Default policy rides through a several-second broker restart
    (``resilience.DEFAULT_COMM_POLICY``); pass ``resilience.NO_RETRY`` for a
    single attempt. Raises ``resilience.CommError`` (a ``ConnectionError``
    subclass, so legacy ``except OSError`` sites still catch it) once the
    policy is exhausted.

    HA fleets pass a comma list of coordinators — ``("h1:p1,h2:p2", None)``
    or ``"h1:p1,h2:p2"`` as ``host`` with ``port=None`` — and the call
    follows leadership across failovers (``not_leader`` redirects, epoch
    fencing, ambiguous-ack typing for non-idempotent routes). A single
    ``(host, port)`` keeps the exact pre-HA behavior."""
    from ..resilience import DEFAULT_COMM_POLICY, retry_call

    op = f"coordinator:{route}"
    if port is None or (isinstance(host, str) and "," in host):
        from . import ha as _ha

        spec = host if port is None else f"{host}:{port}"
        addrs = _ha.parse_addrs(spec)
        if len(addrs) > 1:
            targets = _ha.targets_for(addrs)
            return retry_call(
                _failover_request_once, targets, route, body, timeout,
                op=op, policy=policy or DEFAULT_COMM_POLICY,
            )
        host, port = addrs[0]
    return retry_call(
        _coordinator_request_once, host, port, route, body, timeout,
        op=op, policy=policy or DEFAULT_COMM_POLICY,
    )
