"""Coordinator: token-keyed metadata broker for the peer-to-peer data plane.

Role parity with the reference Coordinator (reference: distar/ctools/worker/
coordinator/coordinator.py:62-232): producers register "payload ready at
ip:port" records under a token; consumers pop a record and connect directly —
the broker never touches tensor payloads. Dead producers accumulate strikes
on failed fetches and are dropped after 5 (coordinator.py:114-128).

Transport here is the same stdlib HTTP/JSON server as the league API.
"""
from __future__ import annotations

import json
import threading
import time
from collections import defaultdict, deque
from typing import Dict, Optional

from ..utils import Config


class Coordinator:
    def __init__(self, maxlen_per_token: int = 512, max_age_s: Optional[float] = None):
        """``max_age_s``: default serve-window age filter applied by BOTH
        ``depth()`` and ``stats()`` (records older than the producers' serve
        window are loss, not backlog). None = no filtering."""
        self._maxlen = maxlen_per_token
        self._max_age_s = max_age_s
        self._records: Dict[str, deque] = defaultdict(lambda: deque(maxlen=self._maxlen))
        self._strikes: Dict[str, int] = defaultdict(int)
        self._lock = threading.RLock()

    def register(self, token: str, ip: str, port: int, meta: Optional[dict] = None) -> bool:
        with self._lock:
            self._records[token].append(
                {"ip": ip, "port": port, "meta": meta or {}, "ts": time.time()}
            )
            return True

    def ask(self, token: str) -> Optional[dict]:
        """Pop the oldest ready record for a token (None when empty)."""
        with self._lock:
            q = self._records.get(token)
            if not q:
                return None
            return q.popleft()

    _UNSET = object()  # sentinel: "use the instance default max_age_s"

    @staticmethod
    def _filtered_len(q, max_age_s: Optional[float]) -> int:
        if max_age_s is None:
            return len(q)
        cutoff = time.time() - max_age_s
        return sum(1 for r in q if r.get("ts", 0) >= cutoff)

    def depth(self, token: str, max_age_s=_UNSET) -> int:
        """Registered-but-unconsumed records for a token — the broker-side
        backlog (payloads wait in producer serve windows until fetched), the
        queue hop that client-cache occupancy can't see. ``max_age_s``
        excludes records older than the producers' serve window: those
        payloads expired and will never be consumed, so they are loss, not
        backlog. Defaults to the instance-wide ``max_age_s`` so depth(),
        stats() and the /metrics gauges all agree on one filter."""
        if max_age_s is Coordinator._UNSET:
            max_age_s = self._max_age_s
        with self._lock:
            q = self._records.get(token)
            if not q:
                return 0
            return self._filtered_len(q, max_age_s)

    def strike(self, ip: str, port: int) -> None:
        """Report a dead producer endpoint; 5 strikes purges its records."""
        key = f"{ip}:{port}"
        with self._lock:
            self._strikes[key] += 1
            if self._strikes[key] >= 5:
                for q in self._records.values():
                    dead = [r for r in q if f"{r['ip']}:{r['port']}" == key]
                    for r in dead:
                        q.remove(r)
                self._strikes.pop(key)

    def stats(self, max_age_s=_UNSET) -> dict:
        """Per-token depth with the SAME age filter as ``depth()`` (they used
        to disagree: stats counted raw lengths, so /metrics and ask-side
        accounting drifted whenever serve windows expired). Pass
        ``max_age_s=None`` explicitly for raw unfiltered lengths."""
        if max_age_s is Coordinator._UNSET:
            max_age_s = self._max_age_s
        with self._lock:
            return {
                token: self._filtered_len(q, max_age_s)
                for token, q in self._records.items()
            }

    def publish_metrics(self, registry=None) -> None:
        """Refresh ``distar_coordinator_queue_depth{token=...}`` gauges (and
        the strike gauge) — called by the /metrics route at scrape time."""
        from ..obs import get_registry

        reg = registry or get_registry()
        for token, depth in self.stats().items():
            reg.gauge(
                "distar_coordinator_queue_depth",
                "broker backlog per token (age-filtered)",
                token=token,
            ).set(depth)
        with self._lock:
            strikes = sum(self._strikes.values())
        reg.gauge(
            "distar_coordinator_endpoint_strikes", "outstanding dead-endpoint strikes"
        ).set(strikes)


class CoordinatorServer:
    """HTTP wrapper: POST /coordinator/<register|ask|strike|stats|telemetry>,
    GET /metrics (Prometheus scrape) + the fleet-health routes
    /healthz, /alerts, /timeseries (obs.handle_health_get)."""

    def __init__(self, coordinator: Optional[Coordinator] = None, host="127.0.0.1", port=0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.coordinator = coordinator or Coordinator()
        co = self.coordinator

        def _ingest_telemetry(msg: dict) -> int:
            # fold shipped snapshots into the process fleet store: the broker
            # is the one place that sees every actor/learner/serve source
            from ..obs import get_fleet_health

            return get_fleet_health().ingest.ingest(msg)

        routes = {
            "register": lambda b: co.register(**b),
            "ask": lambda b: co.ask(b["token"]),
            "strike": lambda b: co.strike(b["ip"], b["port"]),
            # absent max_age_s -> the coordinator's own default filter, so
            # HTTP callers and in-process callers see identical accounting
            "stats": lambda b: (
                co.stats(b["max_age_s"]) if "max_age_s" in b else co.stats()
            ),
            "depth": lambda b: (
                co.depth(b["token"], b["max_age_s"])
                if "max_age_s" in b
                else co.depth(b["token"])
            ),
            "telemetry": _ingest_telemetry,
        }

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                """GET /metrics: Prometheus text exposition of the process
                registry (queue-depth gauges refreshed at scrape time);
                GET /healthz, /alerts, /timeseries: fleet-health JSON."""
                from ..obs import handle_health_get, write_scrape_response

                if self.path.rstrip("/") == "/metrics":
                    write_scrape_response(self, refresh=co.publish_metrics)
                    return
                if handle_health_get(self, self.path):
                    return
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_POST(self):
                name = self.path.strip("/").split("/")[-1]
                length = int(self.headers.get("Content-Length", 0))
                try:
                    raw = self.rfile.read(length)
                    ctype = self.headers.get("Content-Type", "")
                    if name == "telemetry" and ctype.startswith(
                        "application/x-distar"
                    ):
                        # shipped snapshots ride the comm serializer codec
                        # (pickle+LZ), not JSON — same stack as the data plane
                        from .serializer import loads as _loads

                        body = _loads(raw)
                    else:
                        body = json.loads(raw or b"{}")
                    fn = routes.get(name)
                    payload = (
                        {"code": 404, "info": f"no route {name}"}
                        if fn is None
                        else {"code": 0, "info": fn(body)}
                    )
                except Exception as e:
                    payload = {"code": 1, "info": repr(e)}
                data = json.dumps(payload, default=str).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


def coordinator_request(host: str, port: int, route: str, body: Optional[dict] = None, timeout=10.0):
    import urllib.request

    req = urllib.request.Request(
        f"http://{host}:{port}/coordinator/{route}",
        data=json.dumps(body or {}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())
