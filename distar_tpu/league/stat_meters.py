"""Per-race strategy/distance/unit-count EMA meters for league TB logging.

Role parity with the reference's league stat trio (reference: distar/ctools/
worker/league/cum_stat.py, dist_stat.py, unit_num_stat.py — per-race EMA
grids updated from each game result and dumped to TensorBoard):

* DistStat     — pseudo-reward distances (bo/cum distance, battle totals)
* CumStat      — cumulative-stat slot frequencies (what the agent built)
* UnitNumStat  — built-unit-count averages

All keyed race -> metric; fed from the per-side result dicts the actor sends
(league.actor_send_result), rendered via get_text()/stat_info_dict.
"""
from __future__ import annotations

from collections import defaultdict
from functools import partial
from typing import Dict

from ..obs import get_registry
from .stats import EmaMeter


def _meter_dict(decay: float, warm_up_size: int):
    return defaultdict(partial(EmaMeter, decay, warm_up_size))


class RaceMeterGrid:
    """race -> metric-name -> EmaMeter.

    Every update is mirrored into the process metrics registry
    (``distar_league_stat{grid=,race=,metric=}`` gauges), so the race grids
    are scrapeable from /metrics instead of living only in a private dict.
    ``grid`` is the subclass name; metric keys come from a bounded vocabulary
    (stat slot/unit names), keeping label cardinality finite."""

    def __init__(self, decay: float = 0.995, warm_up_size: int = 1000,
                 publish: bool = True):
        self._decay = decay
        self._warm_up = warm_up_size
        self._publish = publish
        self._grid: Dict[str, Dict[str, EmaMeter]] = defaultdict(
            partial(_meter_dict, decay, warm_up_size)
        )
        self.game_count: Dict[str, int] = defaultdict(int)

    def update(self, race: str, info: Dict[str, float]) -> None:
        self.game_count[race] += 1
        # getattr: resume pickles from before the registry mirror lack _publish
        reg = get_registry() if getattr(self, "_publish", True) else None
        grid_label = type(self).__name__.lower()
        if reg is not None:
            reg.counter(
                "distar_league_games_total", "game results folded into race grids",
                grid=grid_label, race=race,
            ).inc()
        for k, v in info.items():
            try:
                meter = self._grid[race][k]
                meter.update(float(v))
            except (TypeError, ValueError):
                continue
            if reg is not None:
                reg.gauge(
                    "distar_league_stat", "per-race EMA stat grids",
                    grid=grid_label, race=race, metric=k,
                ).set(meter.val)

    @property
    def stat_info_dict(self) -> Dict[str, Dict[str, float]]:
        return {
            race: {k: m.val for k, m in metrics.items()}
            for race, metrics in self._grid.items()
        }

    def get_text(self) -> str:
        rows = []
        for race, metrics in sorted(self._grid.items()):
            for k, m in sorted(metrics.items()):
                rows.append(f"{race:<10s} {k:<40s} {m.val:>10.4f} ({m.count})")
        return "\n".join(rows) if rows else "(empty)"


class DistStat(RaceMeterGrid):
    """Consumes keys: bo_distance, cum_distance, battle_reward_total,
    bo_reward_total, cum_reward_total (when present in the result info)."""

    KEYS = ("bo_distance", "cum_distance", "battle_reward_total",
            "bo_reward_total", "cum_reward_total", "game_steps")

    def update_from_result(self, race: str, side_info: Dict) -> None:
        self.update(race, {k: side_info[k] for k in self.KEYS if k in side_info})


class CumStat(RaceMeterGrid):
    """Cumulative-stat slot frequencies, keyed by slot name (lib.stat.CUM_DICT)."""

    def update_from_result(self, race: str, side_info: Dict) -> None:
        cum = side_info.get("cumulative_stat")
        if cum is None:
            return
        from ..lib.stat import CUM_DICT

        info = {}
        for slot, active in enumerate(cum):
            if active and slot < len(CUM_DICT):
                info[str(CUM_DICT[slot])] = 1.0
        self.update(race, info)


class UnitNumStat(RaceMeterGrid):
    """Built-unit-count averages, keyed by unit name."""

    def update_from_result(self, race: str, side_info: Dict) -> None:
        units = side_info.get("unit_num")
        if units:
            self.update(race, {f"unit_num/{k}": v for k, v in units.items()})
