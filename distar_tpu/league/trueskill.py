"""TrueSkill ladder rating (1v1), the ELO alternative.

Role parity with the reference's TrueSkill ladder (reference: distar/ctools/
worker/ladder/trueskill_algo.py). Standard Herbrich et al. (2006) two-player
update with a draw margin: mu/sigma per player, Gaussian truncation
corrections v/w, and a conservative exposed rating mu - 3*sigma.
"""
from __future__ import annotations

import math
from collections import defaultdict
from functools import partial
from typing import Dict, Tuple

SQRT2 = math.sqrt(2.0)


def _phi(x: float) -> float:  # standard normal pdf
    return math.exp(-0.5 * x * x) / math.sqrt(2.0 * math.pi)


def _cdf(x: float) -> float:
    return 0.5 * (1.0 + math.erf(x / SQRT2))


def _v_win(t: float, eps: float) -> float:
    denom = _cdf(t - eps)
    return _phi(t - eps) / max(denom, 1e-12)


def _w_win(t: float, eps: float) -> float:
    v = _v_win(t, eps)
    return v * (v + t - eps)


def _v_draw(t: float, eps: float) -> float:
    a, b = eps - t, -eps - t
    denom = _cdf(a) - _cdf(b)
    return (_phi(b) - _phi(a)) / max(denom, 1e-12)


def _w_draw(t: float, eps: float) -> float:
    a, b = eps - t, -eps - t
    denom = _cdf(a) - _cdf(b)
    v = _v_draw(t, eps)
    return v * v + (a * _phi(a) - b * _phi(b)) / max(denom, 1e-12)


class TrueSkill:
    def __init__(
        self,
        mu: float = 25.0,
        sigma: float = 25.0 / 3.0,
        beta: float = 25.0 / 6.0,
        tau: float = 25.0 / 300.0,
        draw_probability: float = 0.1,
    ):
        self.mu0, self.sigma0 = mu, sigma
        self.beta, self.tau = beta, tau
        self.draw_probability = draw_probability
        self.ratings: Dict[str, Tuple[float, float]] = defaultdict(
            partial(tuple, (mu, sigma))
        )
        self.game_count = 0

    def _get(self, pid: str) -> Tuple[float, float]:
        r = self.ratings[pid]
        return (r[0], r[1]) if isinstance(r, tuple) and len(r) == 2 else (self.mu0, self.sigma0)

    def update(self, winner: str, loser: str, draw: bool = False) -> None:
        mu_w, sig_w = self._get(winner)
        mu_l, sig_l = self._get(loser)
        sig_w = math.sqrt(sig_w ** 2 + self.tau ** 2)
        sig_l = math.sqrt(sig_l ** 2 + self.tau ** 2)
        c2 = 2 * self.beta ** 2 + sig_w ** 2 + sig_l ** 2
        c = math.sqrt(c2)
        t = (mu_w - mu_l) / c
        eps = _draw_margin(self.draw_probability, self.beta) / c
        if draw:
            v, w = _v_draw(t, eps), _w_draw(t, eps)
        else:
            v, w = _v_win(t, eps), _w_win(t, eps)
        self.ratings[winner] = (
            mu_w + (sig_w ** 2 / c) * v,
            sig_w * math.sqrt(max(1.0 - (sig_w ** 2 / c2) * w, 1e-6)),
        )
        self.ratings[loser] = (
            mu_l - (sig_l ** 2 / c) * v,
            sig_l * math.sqrt(max(1.0 - (sig_l ** 2 / c2) * w, 1e-6)),
        )
        self.game_count += 1

    def exposed(self, pid: str) -> float:
        mu, sigma = self._get(pid)
        return mu - 3.0 * sigma

    def leaderboard(self) -> Dict[str, float]:
        return dict(
            sorted(
                ((pid, self.exposed(pid)) for pid in self.ratings),
                key=lambda kv: -kv[1],
            )
        )

    def get_text(self) -> str:
        return "\n".join(
            f"{pid:<40s} mu={self._get(pid)[0]:6.2f} sigma={self._get(pid)[1]:5.2f} "
            f"exposed={score:6.2f}"
            for pid, score in self.leaderboard().items()
        )


def _draw_margin(draw_probability: float, beta: float, n_players: int = 2) -> float:
    """Inverse-CDF draw margin for the given draw probability."""
    # eps = Phi^-1((p_draw + 1) / 2) * sqrt(n) * beta
    target = (draw_probability + 1.0) / 2.0
    lo, hi = 0.0, 10.0
    for _ in range(60):
        mid = (lo + hi) / 2
        if _cdf(mid) < target:
            lo = mid
        else:
            hi = mid
    return lo * math.sqrt(n_players) * beta
