from .algorithms import pfsp
from .api import LeagueAPIServer, league_request
from .elo import ELORating
from .league import LEAGUE_DEFAULTS, League
from .payoff import Payoff
from .player import (
    ActivePlayer,
    AdaptiveEvolutionaryExploiterPlayer,
    ExpertExploiterPlayer,
    ExpertPlayer,
    ExploiterPlayer,
    HistoricalPlayer,
    MainExploiterPlayer,
    MainPlayer,
    Player,
    active_player_type,
)
from .stats import EmaMeter, WindowedMeter

__all__ = [
    "pfsp",
    "LeagueAPIServer",
    "league_request",
    "ELORating",
    "LEAGUE_DEFAULTS",
    "League",
    "Payoff",
    "ActivePlayer",
    "AdaptiveEvolutionaryExploiterPlayer",
    "ExpertExploiterPlayer",
    "ExpertPlayer",
    "ExploiterPlayer",
    "HistoricalPlayer",
    "MainExploiterPlayer",
    "MainPlayer",
    "Player",
    "active_player_type",
    "EmaMeter",
    "WindowedMeter",
]
