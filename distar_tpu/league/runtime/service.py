"""Coordinator-hosted league service: the matchmaking control plane.

The seed :class:`~distar_tpu.league.league.League` is transport-agnostic
and deterministic given its RNG — but it draws from the *module-level*
``random`` (and ``np.random`` inside ``ExploiterPlayer.is_reset``), which
makes its decisions impossible to replay from a journal. This service is
the journal-safe wrapper the HA coordinator hosts (comm/ha.py anticipated
it by name: "a future route (the league's matchmaker)"): every mutating
entry point is a pure function of (state, seeded RNG, request body, record
timestamp), so replaying the coordinator's WAL reconstructs the league —
roster, snapshot lineage, assignment map, RNG cursor — exactly.

What it owns, and what it deliberately does not:

* **Roster** — learners register under a league player id (MP*/EP*/ME*…),
  and a player whose learners all stopped heartbeating is *frozen*:
  derived from journaled ``last_seen`` timestamps, never stored, so a
  SIGKILL'd learner's players stay in the league (matchable as opponents)
  without a tombstone route. A supervised restart re-registers and thaws.
* **Matchmaking** — ``ask_job`` draws the branch (sp/pfsp/vs_main/eval)
  from the player class's configured probabilities with the service RNG,
  then picks the opponent. PFSP weights are NOT re-grown from league win
  counters: they come from the arena's live payoff matrix
  (:meth:`~distar_tpu.arena.store.ArenaStore.pfsp_preview`, the Wilson-CI
  ledger PR 18 built) so matchmaking sharpens as real results arrive.
* **Assignments** — every job carries a ``job_id``; outstanding
  assignments expire after ``job_ttl_s`` (pruned lazily *inside journaled
  routes* using the record timestamp, so replay prunes identically). A
  learner killed mid-job therefore leaves no orphaned assignment, and its
  acked reports are already in the arena ledger (idempotent keys).
* **Snapshot minting** — historical players are minted from
  ``CheckpointManager`` generations: a learner reports the generation
  path it just recorded and the service snapshots its player to exactly
  that file. Minting is idempotent on (player_id, generation_path):
  duplicate triggers (retries, ambiguous acks) return the existing
  snapshot. Reset decisions (exploiter re-spawns) use the service RNG.
* **Not owned**: win/loss accounting (the arena store's job — one ledger,
  one dedup) and the match transport (learners report through
  ``league_report`` which forwards to the co-hosted store in-process).
"""
from __future__ import annotations

import pickle
import random
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..league import League
from ..player import (
    ActivePlayer,
    AdaptiveEvolutionaryExploiterPlayer,
    ExploiterPlayer,
    MainExploiterPlayer,
    MainPlayer,
)

#: the four dispatch branches the runtime distinguishes (metrics label set)
BRANCHES = ("sp", "pfsp", "vs_main", "eval")


def _metrics():
    from ...obs import get_registry

    return get_registry()


class LeagueService:
    """Journal-replayable league control plane (hosted by the coordinator).

    Every mutating method takes the wire ``body`` plus an optional ``now``:
    live dispatch leaves ``now`` unset (wall clock), journal replay passes
    the record's timestamp — the only clock the service ever reads, so a
    cold replay reconstructs lease ages and assignment expiry decisions.
    """

    def __init__(self, cfg: Optional[dict] = None, seed: int = 0,
                 lease_s: float = 30.0, job_ttl_s: float = 180.0,
                 league: Optional[League] = None):
        self.league = league if league is not None else League(cfg)
        self.lease_s = float(lease_s)
        self.job_ttl_s = float(job_ttl_s)
        self._seed = int(seed)
        self._rng = random.Random(self._seed)
        self._lock = threading.RLock()
        # learner_id -> {player_id, ip, port, registered_ts, last_seen}
        self.learners: Dict[str, dict] = {}
        # job_id -> {player_ids, branch, learner_id, actor, issued_ts}
        self.assignments: Dict[str, dict] = {}
        # "{player_id}|{generation_path}" -> minted snapshot id
        self._minted: "OrderedDict[str, str]" = OrderedDict()
        # per-player last-applied train_info seq (idempotency watermark)
        self._train_seq: Dict[str, int] = {}
        # match keys already folded into league payoffs (mirrors the arena
        # dedup so a replayed report can't double-count the league view)
        self._seen: "OrderedDict[str, None]" = OrderedDict()
        self._seen_cap = 100_000
        self._job_seq = 0
        self.jobs_by_branch: Dict[str, int] = {b: 0 for b in BRANCHES}
        self.orphans_total = 0
        self.reassignments_total = 0
        # let League.save_resume/load_resume carry the runtime state too
        # (satellite: a cold coordinator replay reconstructs the league)
        self.league.attach_runtime(self._runtime_state, self._load_runtime_state)

    # ------------------------------------------------------------------ clock
    @staticmethod
    def _now(now: Optional[float]) -> float:
        return time.time() if now is None else float(now)

    # ---------------------------------------------------------------- learner
    def register_learner(self, body: dict, now: Optional[float] = None) -> dict:
        """Register (or heartbeat — re-registering refreshes the lease) one
        learner process under its league player. Idempotent by learner_id."""
        ts = self._now(now)
        learner_id = str(body.get("learner_id") or body.get("player_id") or "")
        player_id = str(body.get("player_id") or "")
        with self._lock:
            player = self.league.active_players.get(player_id)
            if player is None:
                return {"registered": False, "error": f"unknown player {player_id}"}
            entry = self.learners.get(learner_id)
            if entry is None:
                entry = self.learners[learner_id] = {
                    "player_id": player_id,
                    "ip": str(body.get("ip", "")),
                    "port": int(body.get("port", 0)),
                    "registered_ts": ts,
                }
                self.league.register_learner(
                    player_id, ip=entry["ip"], port=entry["port"],
                    rank=int(body.get("rank", 0)),
                    world_size=int(body.get("world_size", 1)))
            entry["player_id"] = player_id
            entry["last_seen"] = ts
            reply = {
                "registered": True,
                "checkpoint_path": player.checkpoint_path,
                "teacher_checkpoint_path": player.teacher_checkpoint_path,
                "lease_s": self.lease_s,
                # last-applied train_info watermark: a restarted learner
                # resumes its seq numbering past it instead of replaying
                # into the duplicate filter
                "train_seq": self._train_seq.get(player_id, -1),
            }
        self._publish_metrics(ts)
        return reply

    def _frozen_players_locked(self, ts: float) -> List[str]:
        """Players whose every registered learner stopped heartbeating —
        derived, never stored: freezing survives replay for free and a
        supervised restart thaws by re-registering."""
        by_player: Dict[str, List[float]] = {}
        for entry in self.learners.values():
            by_player.setdefault(entry["player_id"], []).append(entry["last_seen"])
        return sorted(
            pid for pid, seen in by_player.items()
            if all(ts - s > self.lease_s for s in seen)
        )

    # ------------------------------------------------------------ matchmaking
    def pfsp_weights(self, home: str, candidates: List[str]) -> List[float]:
        """Opponent weights for ``home`` over ``candidates`` — the arena
        store's variance-PFSP row, bit-identical to
        ``ArenaStore._pfsp_preview_locked([home]+candidates)[home]``
        (the agreement the determinism tests pin). Uniform fallback when no
        arena store is hosted or the row degenerates."""
        from ...arena import get_arena_store

        if not candidates:
            return []
        store = get_arena_store()
        if store is not None:
            row = store.pfsp_preview([home] + list(candidates)).get(home, {})
            weights = [float(row.get(c, 0.0)) for c in candidates]
            if sum(weights) > 0:
                return weights
        return [1.0 / len(candidates)] * len(candidates)

    def _pick_pfsp(self, home_id: str, candidates: List[str]):
        keys = sorted(c for c in candidates if c != home_id)
        if not keys:
            return None
        weights = self.pfsp_weights(home_id, keys)
        return self.league.historical_players[
            self._rng.choices(keys, weights=weights, k=1)[0]]

    def _main_id_for(self, player_id: str) -> Optional[str]:
        """ME<suffix> pairs with MP<suffix>; fall back to the first main."""
        actives = self.league.active_players
        candidate = f"MP{player_id[2:]}"
        if candidate in actives:
            return candidate
        mains = sorted(pid for pid in actives if pid.startswith("MP"))
        return mains[0] if mains else None

    def _choose_opponent(self, player: ActivePlayer, branch: str):
        """(effective_branch, opponent Player) — deterministic given the
        service RNG and the current roster/ledger. Falls back down the
        branch ladder (vs_main -> pfsp -> sp mirror) instead of raising so
        a journaled ask can always be replayed."""
        league = self.league
        hist = league.historical_players
        pid = player.player_id
        if branch == "vs_main" and isinstance(
                player, (MainExploiterPlayer, AdaptiveEvolutionaryExploiterPlayer)):
            main_id = self._main_id_for(pid)
            if main_id is not None:
                return "vs_main", league.active_players[main_id]
            branch = "pfsp"
        if branch == "eval":
            keys = sorted(hist.keys())
            if keys:
                return "eval", hist[self._rng.choice(keys)]
            branch = "pfsp"
        if branch == "sp" and isinstance(player, MainPlayer):
            mains = sorted(
                mid for mid, p in league.active_players.items()
                if isinstance(p, MainPlayer))
            opp_id = self._rng.choice(mains) if mains else pid
            return "sp", league.active_players.get(opp_id, player)
        # pfsp (and every fallback): class-appropriate historical pool
        if isinstance(player, (MainExploiterPlayer,
                               AdaptiveEvolutionaryExploiterPlayer)):
            main_id = self._main_id_for(pid)
            pool = [hid for hid, p in hist.items() if p.parent_id == main_id]
            opp = self._pick_pfsp(pid, pool or list(hist.keys()))
        else:
            pool = [hid for hid, p in hist.items() if p.pipeline != "bot"]
            opp = self._pick_pfsp(pid, pool or list(hist.keys()))
        if opp is not None:
            return "pfsp", opp
        return "sp", player  # empty league: mirror-match bootstrap

    def ask_job(self, body: dict, now: Optional[float] = None) -> Optional[dict]:
        """PFSP matchmaking for one actor/learner ask. Returns the job dict
        (league ``_job_template`` layout + ``job_id``) or None for an
        unknown player — never raises, so the journaled record is always
        replayable."""
        ts = self._now(now)
        player_id = str(body.get("player_id") or "")
        with self._lock:
            self._prune_assignments_locked(ts)
            player = self.league.active_players.get(player_id)
            if player is None:
                return None
            probs = dict(self.league.cfg.branch_probs.get(
                type(player).__name__, {"pfsp": 1.0}))
            drawn = self._rng.choices(
                list(probs.keys()), weights=list(probs.values()), k=1)[0]
            branch, opponent = self._choose_opponent(player, drawn)
            job = self.league._job_template([player, opponent], branch)
            if branch == "vs_main":
                # the main is a frozen opponent: no teacher, no data
                for idx, p in enumerate((player, opponent)):
                    if isinstance(p, MainPlayer):
                        job["teacher_player_ids"][idx] = "none"
                        job["teacher_checkpoint_paths"][idx] = "none"
                job["send_data_players"] = [player_id]
            elif branch == "eval":
                job["teacher_player_ids"] = ["none", "none"]
                job["teacher_checkpoint_paths"] = ["none", "none"]
                job["send_data_players"] = []
            job["env_info"]["map_name"] = self._rng.choices(
                list(self.league.cfg.map_names),
                weights=list(self.league.cfg.map_id_weights), k=1)[0]
            self._job_seq += 1
            job_id = f"J{self._job_seq}"
            job["job_id"] = job_id
            self.assignments[job_id] = {
                "player_ids": list(job["player_ids"]),
                "branch": branch,
                "learner_id": str(body.get("learner_id", "")),
                "actor": str(body.get("actor", "")),
                "issued_ts": ts,
            }
            self.jobs_by_branch[branch] = self.jobs_by_branch.get(branch, 0) + 1
        _metrics().counter(
            "distar_league_jobs_dispatched_total",
            "league jobs handed to actors, by matchmaking branch",
            branch=branch).inc()
        self._publish_metrics(ts)
        return job

    def _prune_assignments_locked(self, ts: float) -> None:
        """Expire assignments older than ``job_ttl_s``. Runs only inside
        journaled routes with the record clock, so live and replay expire
        the same set — the no-orphaned-jobs invariant the drill checks."""
        dead = [jid for jid, a in self.assignments.items()
                if ts - a["issued_ts"] > self.job_ttl_s]
        for jid in dead:
            del self.assignments[jid]
        if dead:
            self.orphans_total += len(dead)
            _metrics().counter(
                "distar_league_orphaned_jobs_total",
                "job assignments expired without a report (dead actor)",
            ).inc(len(dead))

    # -------------------------------------------------------------- reporting
    def report(self, body: dict, now: Optional[float] = None) -> dict:
        """Complete one assignment and ingest its match records.

        The records are arena-format (idempotent ``key`` per episode) and
        are forwarded to the co-hosted ArenaStore in-process — one ledger,
        one dedup, and because the forward happens inside this journaled
        route, WAL replay re-ingests through the same path (the store's
        keys turn replays into exact dedups)."""
        from ...arena import get_arena_store

        ts = self._now(now)
        matches = list(body.get("matches") or [])
        job_id = str(body.get("job_id", ""))
        store = get_arena_store()
        arena = store.report_batch(matches) if store is not None \
            else {"applied": 0, "duplicates": 0}
        with self._lock:
            self._prune_assignments_locked(ts)
            completed = self.assignments.pop(job_id, None) is not None
            learner_id = str(body.get("learner_id", ""))
            if learner_id in self.learners:
                self.learners[learner_id]["last_seen"] = ts
            for rec in matches:
                key = str(rec.get("key", ""))
                if not key or key in self._seen:
                    continue
                self._seen[key] = None
                while len(self._seen) > self._seen_cap:
                    self._seen.popitem(last=False)
                self._ingest_league_payoff_locked(rec)
        self._publish_metrics(ts)
        return {"completed": completed, **arena}

    def _ingest_league_payoff_locked(self, rec: dict) -> None:
        """Mirror one match into the league-side payoff records (the
        is_trained_enough/vs_main-threshold inputs) — dedup'd by the same
        idempotent keys the arena uses."""
        home, away = str(rec.get("home", "")), str(rec.get("away", ""))
        winner = str(rec.get("winner", "draw"))
        stats = {"game_steps": float(rec.get("game_steps", 0.0)),
                 "game_iters": 0, "game_duration": float(rec.get("duration_s", 0.0))}
        wr_home = {"home": 1.0, "away": 0.0}.get(winner, 0.5)
        players = self.league.all_players
        if home in players and home != away:
            players[home].payoff.update(away, {"winrate": wr_home, **stats})
            players[home].total_game_count += 1
        if away in players and home != away:
            players[away].payoff.update(home, {"winrate": 1.0 - wr_home, **stats})
            players[away].total_game_count += 1

    # ---------------------------------------------------------------- minting
    def train_info(self, body: dict, now: Optional[float] = None) -> dict:
        """Learner progress ingest + snapshot minting + reset decision.

        Idempotent two ways: a per-player ``seq`` watermark makes the step
        accounting replay-safe under ambiguous-ack retries, and minting
        dedups on (player_id, generation_path) — the same checkpoint
        generation can never become two historical players."""
        ts = self._now(now)
        player_id = str(body.get("player_id") or "")
        with self._lock:
            self._prune_assignments_locked(ts)
            player = self.league.active_players.get(player_id)
            if player is None:
                return {"ok": False, "error": f"unknown player {player_id}"}
            seq = body.get("seq")
            if seq is not None:
                seq = int(seq)
                if seq <= self._train_seq.get(player_id, -1):
                    return {"ok": True, "duplicate": True}
                self._train_seq[player_id] = seq
            player.total_agent_step += int(body.get("train_steps", 0))
            if body.get("checkpoint_path"):
                player.checkpoint_path = str(body["checkpoint_path"])
            learner_id = str(body.get("learner_id", ""))
            if learner_id in self.learners:
                self.learners[learner_id]["last_seen"] = ts
            reply: dict = {"ok": True, "minted": False}
            gen = str(body.get("generation_path") or "")
            if gen:
                mint_key = f"{player_id}|{gen}"
                snap_id = self._minted.get(mint_key)
                if snap_id is not None:
                    reply["snapshot_id"] = snap_id
                else:
                    snap = player.snapshot()
                    snap.checkpoint_path = gen  # mint from the recorded
                    # CheckpointManager generation, not the name heuristic
                    self.league.historical_players[snap.player_id] = snap
                    self._minted[mint_key] = snap.player_id
                    reply.update(minted=True, snapshot_id=snap.player_id)
                    _metrics().counter(
                        "distar_league_snapshot_mints_total",
                        "historical players minted from checkpoint generations",
                    ).inc()
                    if self._should_reset(player):
                        reset_path = player.teacher_checkpoint_path
                        if reset_path and reset_path != "none":
                            player.reset_payoff()
                            player.checkpoint_path = reset_path
                            reply["reset_checkpoint_path"] = reset_path
        self._publish_metrics(ts)
        return reply

    def _should_reset(self, player: ActivePlayer) -> bool:
        """Deterministic re-spawn policy (the player classes' own is_reset
        draws from np.random/module random — unusable under WAL replay):
        main exploiters always restart after a snapshot, exploiters with
        the configured probability from the service RNG, mains never."""
        if isinstance(player, (MainExploiterPlayer,
                               AdaptiveEvolutionaryExploiterPlayer)):
            return True
        if isinstance(player, ExploiterPlayer):
            return self._rng.random() < ExploiterPlayer.reset_prob
        return False

    # ------------------------------------------------------------ reassignment
    def note_reassignment(self, n: int = 1) -> None:
        with self._lock:
            self.reassignments_total += int(n)
        _metrics().counter(
            "distar_league_reassignments_total",
            "elastic actor moves between learners (payoff-driven)",
        ).inc(int(n))

    # ----------------------------------------------------------------- status
    def status(self, body: Optional[dict] = None, now: Optional[float] = None) -> dict:
        """Read-only digest (``GET /league/status`` / ``opsctl league``).
        Ephemeral route: must not mutate — expiry here would diverge the
        replica from the journal."""
        ts = self._now(now)
        with self._lock:
            frozen = self._frozen_players_locked(ts)
            learners = {
                lid: {**e, "age_s": max(0.0, ts - e["last_seen"]),
                      "fresh": ts - e["last_seen"] <= self.lease_s}
                for lid, e in self.learners.items()
            }
            active = sum(1 for e in learners.values() if e["fresh"])
            snap = {
                "active_learners": active,
                "registered_learners": len(self.learners),
                "frozen_players": frozen,
                "learners": learners,
                "active_players": sorted(self.league.active_players),
                "historical_players": sorted(self.league.historical_players),
                "assignments_pending": len(self.assignments),
                "assignments": {
                    jid: dict(a) for jid, a in self.assignments.items()},
                "jobs_by_branch": dict(self.jobs_by_branch),
                "snapshot_mints": len(self._minted),
                "minted": dict(self._minted),
                "orphaned_jobs": self.orphans_total,
                "reassignments": self.reassignments_total,
                "lease_s": self.lease_s,
                "job_ttl_s": self.job_ttl_s,
            }
        self._publish_metrics(ts)
        return snap

    def _publish_metrics(self, ts: float) -> None:
        reg = _metrics()
        with self._lock:
            fresh = sum(1 for e in self.learners.values()
                        if ts - e["last_seen"] <= self.lease_s)
            frozen = len(self._frozen_players_locked(ts))
            pending = len(self.assignments)
        reg.gauge("distar_league_active_learners",
                  "learners with a fresh lease (registered and heartbeating)",
                  ).set(fresh)
        reg.gauge("distar_league_frozen_players",
                  "league players whose every learner lease lapsed",
                  ).set(frozen)
        reg.gauge("distar_league_assignments_pending",
                  "dispatched jobs awaiting a result report").set(pending)

    # ------------------------------------------------------------- durability
    def _runtime_state(self) -> dict:
        """The runtime leg (roster, assignment map, mint lineage, RNG
        cursor) — embedded in both ``state_blob`` and, via the attached
        hooks, ``League.save_resume``."""
        return {
            "seed": self._seed,
            "rng": self._rng.getstate(),
            "learners": {k: dict(v) for k, v in self.learners.items()},
            "assignments": {k: dict(v) for k, v in self.assignments.items()},
            "minted": list(self._minted.items()),
            "train_seq": dict(self._train_seq),
            "seen": list(self._seen.keys()),
            "job_seq": self._job_seq,
            "jobs_by_branch": dict(self.jobs_by_branch),
            "orphans_total": self.orphans_total,
            "reassignments_total": self.reassignments_total,
        }

    def _load_runtime_state(self, data: dict) -> None:
        self._seed = int(data.get("seed", self._seed))
        self._rng.setstate(data["rng"])
        self.learners = {k: dict(v) for k, v in data["learners"].items()}
        self.assignments = {k: dict(v) for k, v in data["assignments"].items()}
        self._minted = OrderedDict(data["minted"])
        self._train_seq = dict(data["train_seq"])
        self._seen = OrderedDict((k, None) for k in data.get("seen", []))
        self._job_seq = int(data["job_seq"])
        self.jobs_by_branch = dict(data["jobs_by_branch"])
        self.orphans_total = int(data["orphans_total"])
        self.reassignments_total = int(data["reassignments_total"])

    def state_blob(self) -> dict:
        """Detached full state — the HA snapshot payload (third leg next to
        the coordinator and arena blobs). Pickle round-trip detaches live
        player objects so later matches can't mutate a handed-out snapshot."""
        with self._lock:
            blob = {
                "league": {
                    "active_players": self.league.active_players,
                    "historical_players": self.league.historical_players,
                    "elo": self.league.elo,
                    "trueskill": self.league.trueskill,
                    "learners": {k: list(v)
                                 for k, v in self.league._learners.items()},
                },
                "runtime": self._runtime_state(),
            }
            return pickle.loads(pickle.dumps(blob))

    def load_state(self, data: dict) -> None:
        with self._lock:
            lg = data["league"]
            self.league.active_players = lg["active_players"]
            self.league.historical_players = lg["historical_players"]
            self.league.elo = lg["elo"]
            self.league.trueskill = lg["trueskill"]
            self.league._learners = {k: list(v)
                                     for k, v in lg.get("learners", {}).items()}
            self._load_runtime_state(data["runtime"])

    def state_digest(self) -> dict:
        """Timestamp-free structural digest for replica comparison (the
        chaos drill's equality check): wall-clock skew between a live
        dispatch and its journal record is real but meaningless; roster,
        lineage, assignments, counters and the RNG cursor must be exact."""
        with self._lock:
            return {
                "active_players": {
                    pid: {"ckpt": p.checkpoint_path,
                          "step": p.total_agent_step,
                          "snapshots": p.snapshot_times}
                    for pid, p in sorted(self.league.active_players.items())},
                "historical_players": {
                    pid: {"ckpt": p.checkpoint_path, "parent": p.parent_id}
                    for pid, p in sorted(self.league.historical_players.items())},
                "learners": sorted(
                    (lid, e["player_id"]) for lid, e in self.learners.items()),
                "assignments": sorted(
                    (jid, a["branch"], tuple(a["player_ids"]))
                    for jid, a in self.assignments.items()),
                "minted": sorted(self._minted.items()),
                "train_seq": dict(sorted(self._train_seq.items())),
                "job_seq": self._job_seq,
                "jobs_by_branch": dict(sorted(self.jobs_by_branch.items())),
                "orphans_total": self.orphans_total,
                "rng": hash(self._rng.getstate()),
            }


# --------------------------------------------------------------- process-global
_SERVICE: Optional[LeagueService] = None
_SERVICE_LOCK = threading.Lock()


def set_league_service(service: Optional[LeagueService]) -> None:
    global _SERVICE
    with _SERVICE_LOCK:
        _SERVICE = service


def get_league_service() -> Optional[LeagueService]:
    with _SERVICE_LOCK:
        return _SERVICE
