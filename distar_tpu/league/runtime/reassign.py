"""Payoff-driven elastic actor reassignment between league learners.

DD-PPO's lesson (PAPERS.md) applied to the league: actor capacity is one
elastic pool, not N static allotments. The matchmaking value of an episode
is highest where the payoff matrix is most uncertain — a pair at winrate
0.5 teaches PFSP the most, a solved pair (0 or 1) teaches nothing — so the
reassigner periodically re-divides the actor budget in proportion to each
learner's summed outcome variance ``w(1-w)`` over its arena pairs, then
drives the PR 12 fleet machinery (``FleetSupervisor.scale_up`` /
``scale_down`` — graceful LIFO drain, ``min_members`` floor) to match.

Everything is read from public surfaces: the payoff cells come from
``ArenaStore.payoff_snapshot()`` (or an injected probe for tests), the
moves go through the supervisor, and the move count is reported to the
hosted :class:`~.service.LeagueService` so ``opsctl league`` and the
``distar_league_reassignments_total`` counter see every rebalance.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

#: outcome variance of an unplayed pair (w = 0.5): the exploration prior
UNPLAYED_VARIANCE = 0.25


def _largest_remainder(weights: Dict[str, float], total: int,
                       floor: int) -> Dict[str, int]:
    """Split ``total`` seats proportionally to ``weights`` with a per-key
    ``floor``, exact by largest-remainder rounding (deterministic ties by
    key). Floors are granted first; the remainder follows the weights."""
    keys = sorted(weights)
    n = len(keys)
    if n == 0:
        return {}
    floor = max(0, int(floor))
    spare = max(0, int(total) - floor * n)
    wsum = sum(max(0.0, weights[k]) for k in keys)
    if wsum <= 0:
        shares = {k: spare / n for k in keys}
    else:
        shares = {k: spare * max(0.0, weights[k]) / wsum for k in keys}
    out = {k: floor + int(shares[k]) for k in keys}
    leftover = floor * n + spare - sum(out.values())
    by_frac = sorted(keys, key=lambda k: (-(shares[k] - int(shares[k])), k))
    for k in by_frac[:leftover]:
        out[k] += 1
    return out


class PayoffReassigner:
    """Rebalance actor fleets across learners from the live payoff matrix.

    ``fleet_players`` maps fleet name (as registered on the supervisor) to
    the league player id that learner trains. ``payoff_fn`` defaults to the
    process-global arena store's ``payoff_snapshot``; tests inject a
    fixture. ``step()`` computes quotas, applies the delta (downscales
    first so the budget is never exceeded mid-move) and returns the per-
    fleet deltas actually applied.
    """

    def __init__(self, supervisor, fleet_players: Dict[str, str],
                 total_actors: int, min_actors: int = 1,
                 payoff_fn: Optional[Callable[[], dict]] = None,
                 service=None):
        self.supervisor = supervisor
        self.fleet_players = dict(fleet_players)
        self.total_actors = int(total_actors)
        self.min_actors = int(min_actors)
        self._payoff_fn = payoff_fn
        self._service = service

    def _payoff_cells(self) -> List[dict]:
        if self._payoff_fn is not None:
            snap = self._payoff_fn()
        else:
            from ...arena import get_arena_store

            store = get_arena_store()
            if store is None:
                return []
            snap = store.payoff_snapshot()
        return list(snap.get("cells") or [])

    def learning_weights(self) -> Dict[str, float]:
        """Per-fleet summed outcome variance of its player's arena pairs.
        A learner with no recorded pairs gets the unplayed prior, so fresh
        exploiters are seeded with capacity instead of starved."""
        cells = self._payoff_cells()
        weights: Dict[str, float] = {}
        for fleet, player in self.fleet_players.items():
            var, pairs = 0.0, 0
            for cell in cells:
                if player not in (cell.get("a"), cell.get("b")):
                    continue
                wr = float(cell.get("win_rate", 0.5))
                var += wr * (1.0 - wr)
                pairs += 1
            weights[fleet] = var if pairs else UNPLAYED_VARIANCE
        return weights

    def desired(self) -> Dict[str, int]:
        return _largest_remainder(
            self.learning_weights(), self.total_actors, self.min_actors)

    def step(self) -> Dict[str, int]:
        """One rebalance pass. Returns {fleet: applied_delta}; reports the
        moved-actor count to the league service (if attached)."""
        want = self.desired()
        have = {name: self.supervisor.actual(name) for name in want}
        deltas = {name: want[name] - have[name] for name in want}
        # drain first: freed slots fund the grows, keeping the pool bounded
        for name in sorted(want):
            if deltas[name] < 0:
                self.supervisor.scale_down(name, -deltas[name])
        for name in sorted(want):
            if deltas[name] > 0:
                self.supervisor.scale_up(name, deltas[name])
        moved = sum(d for d in deltas.values() if d > 0)
        if moved and self._service is not None:
            self._service.note_reassignment(moved)
        return deltas
