"""League runtime launchers: N concurrent learners under one matchmaker.

Two halves, matching the two processes of a league deployment:

* :class:`LeagueLearnerLoop` — the per-process training loop a
  ``rl_train --type league-learner`` hosts. One league player, one
  independent learner (own ``parallel/`` mesh, own replay/data routing,
  own ``CheckpointManager`` role-key lineage), one fused Anakin rollout
  with the **away seat** carrying the frozen opponent the matchmaker
  picked. Per round: ask a job, resolve opponent params from the job's
  checkpoint path, train, report the finished episodes under idempotent
  match keys, record a checkpoint generation, and stream train-info (which
  is where historical snapshots get minted server-side).
* :class:`LeagueRunner` — the ``rl_train --type league-run`` parent: hosts
  the coordinator (LeagueService + ArenaStore + optional HA journal) in
  process, spawns one learner subprocess per active player, optionally
  runs the payoff-driven actor reassigner against a PR 12 fleet, and
  summarises the economy (payoff matrix, mints, jobs-by-branch) on exit.

Model publication rides the existing serving surface: a
:class:`LeaguePublisher` pushes every new checkpoint generation into the
per-player gateway behind a ``GatewayMux`` — the wire ``player`` field the
mux already routes by is exactly the league player id, so actors pinned to
a player always sample against that player's latest generation.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

#: default league roster for a small self-play economy: one main agent and
#: two exploiter classes (the three-learner quickstart in docs/league.md)
DEFAULT_PLAYERS = ("MP0", "EP0", "ME0")


def league_cfg(player_ids: Sequence[str],
               teacher_path: str = "none") -> dict:
    """A League config whose roster is exactly ``player_ids``, cold-started
    (empty checkpoint paths — learners publish real generations as they
    train). The default historical seed players are disabled: history grows
    only from minted snapshots, so the payoff matrix is all real matches."""
    ids = list(player_ids)
    n = len(ids)
    return {"league": {
        "use_historical_players": False,
        "save_initial_snapshot": True,
        "active_players": {
            "player_id": ids,
            "checkpoint_path": [""] * n,
            "pipeline": ["default"] * n,
            "frac_id": [1] * n,
            "z_path": ["3map.json"] * n,
            "z_prob": [0.0] * n,
            "teacher_id": ["none"] * n,
            "teacher_path": [teacher_path] * n,
            "one_phase_step": [1e9] * n,
            "chosen_weight": [1.0] * n,
        },
    }}


class LeaguePublisher:
    """Per-player model publication through the ``GatewayMux`` player field.

    Every published generation loads into the named player's own
    ``ModelRegistry`` and activates (the gateway's zero-downtime hot swap);
    an unknown player is a no-op — a league can mint players faster than
    the serving fleet reconfigures, and publication must never stall the
    training loop for it."""

    def __init__(self, mux) -> None:
        self.mux = mux
        self.published: Dict[str, str] = {}  # player_id -> last version

    def publish(self, player_id: str, version: str,
                checkpoint_path: str) -> bool:
        from ...serve.errors import UnknownPlayerError

        try:
            gateway = self.mux.resolve(player_id)
        except (KeyError, UnknownPlayerError):
            return False
        gateway.registry.load(version, source=checkpoint_path, activate=True)
        self.published[player_id] = version
        return True


class LeagueLearnerLoop:
    """One league learner: matchmade self-play rounds over a fused rollout.

    ``remote`` is a :class:`~..remote.RemoteLeagueService`;  ``learner`` a
    constructed RLLearner whose dataloader is ``loader`` (an
    ``AnakinDataLoader`` over an ``opponent_seat=True`` runner, its
    ``opponent_provider`` wired to :meth:`opponent_params`). The loop owns
    the opponent slot: each job re-resolves it from the job's away-seat
    checkpoint path (live own params for true self-play, ``load_params``
    for a frozen snapshot/main, bootstrap-init for unpublished players).
    """

    def __init__(self, player_id: str, remote, learner, loader,
                 rounds: int = 2, iters_per_round: int = 1,
                 eval_windows: int = 3, publisher=None,
                 learner_id: str = ""):
        self.player_id = player_id
        self.remote = remote
        self.learner = learner
        self.loader = loader
        self.rounds = int(rounds)
        self.iters_per_round = int(iters_per_round)
        self.eval_windows = int(eval_windows)
        self.publisher = publisher
        self.learner_id = learner_id or f"{player_id}@{os.getpid()}"
        self._opp_params = None
        self._opp_lock = threading.Lock()
        self.jobs_done = 0
        self.mints = 0

    # ---------------------------------------------------------- opponent slot
    def opponent_params(self):
        """The away seat's params — the loader's ``opponent_provider``.
        None (before the first job / for never-published opponents) lets
        the loader fall back to its deterministic bootstrap init. A
        callable slot (live self-play) is re-resolved every window."""
        with self._opp_lock:
            params = self._opp_params
        return params() if callable(params) else params

    def _live_params(self):
        state = getattr(self.learner, "_state", None)
        return state["params"] if state else None

    def _resolve_opponent(self, job: dict) -> str:
        from ...utils.checkpoint import load_params

        away = str(job["player_ids"][1])
        path = str(job["checkpoint_paths"][1] or "")
        params = None
        if away == self.player_id:
            # live self-play: the train step donates the learner state
            # each iteration, so a stashed params reference is deleted
            # after one optimizer step — hand the loader a resolver that
            # re-reads the current state at every rollout window instead
            params = self._live_params
        elif path and os.path.exists(path):
            params = load_params(path)
        with self._opp_lock:
            self._opp_params = params
        return away

    # ---------------------------------------------------------------- matches
    def _matches_for(self, job: dict, away: str) -> List[dict]:
        results = self.loader.drain_results()
        return [{
            "key": f"{job['job_id']}e{i}",
            "home": self.player_id,
            "away": away,
            "round": 0,
            "winner": r["winner"],
            "game_steps": float(r["steps"]),
            "duration_s": 0.0,
        } for i, r in enumerate(results)]

    # ------------------------------------------------------------------- run
    def run_round(self, seq: int) -> dict:
        """One matchmade round: ask -> train (or eval-rollout) -> report ->
        checkpoint generation -> train-info. Returns a round summary."""
        job = self.remote.ask_job(self.player_id, learner_id=self.learner_id)
        if not job:
            return {"job": None}
        away = self._resolve_opponent(job)
        branch = job.get("branch", "")
        if branch == "eval":
            # evaluation matches: rollout windows only, no optimizer steps
            # (the job's send_data_players is empty by construction)
            for _ in range(self.eval_windows):
                next(self.loader)
        else:
            target = self.learner.last_iter.val + self.iters_per_round
            self.learner.run(max_iterations=target)
        matches = self._matches_for(job, away)
        # short rounds can end mid-episode: roll a few extra (cheap,
        # already-compiled) windows so the round reports real outcomes and
        # the payoff matrix fills from actual matches
        for _ in range(self.eval_windows):
            if matches:
                break
            next(self.loader)
            matches = self._matches_for(job, away)
        self.remote.report(job["job_id"], matches, learner_id=self.learner_id)
        self.jobs_done += 1

        path = os.path.join(
            self.learner.save_dir, "checkpoints",
            f"{self.player_id}_iteration_{self.learner.last_iter.val}.ckpt")
        self.learner.save(path, sync=True)
        gens = self.learner.checkpoint_manager.generations()
        gen_path = gens[0]["path"] if gens else path
        reply = self.remote.train_info(
            self.player_id, seq=seq,
            train_steps=self.iters_per_round if branch != "eval" else 0,
            checkpoint_path=gen_path, generation_path=gen_path,
            learner_id=self.learner_id)
        if reply.get("minted"):
            self.mints += 1
        if self.publisher is not None:
            self.publisher.publish(self.player_id, f"gen{seq}", gen_path)
        reset = str(reply.get("reset_checkpoint_path") or "")
        if reset and os.path.exists(reset):
            # exploiter re-spawn: the service snapshotted us and rolled the
            # lineage back to the teacher checkpoint
            self.learner.restore(reset)
        return {"job": job["job_id"], "branch": branch, "away": away,
                "matches": len(matches), "minted": bool(reply.get("minted"))}

    def run(self) -> dict:
        reply = self.remote.register_learner(
            self.player_id, learner_id=self.learner_id)
        if not reply.get("registered"):
            raise RuntimeError(f"league rejected {self.player_id}: {reply}")
        ckpt = str(reply.get("checkpoint_path") or "")
        if ckpt and os.path.exists(ckpt):
            self.learner.restore(ckpt)
        # continue the train-info numbering past the service's watermark so
        # a supervised restart doesn't replay into the duplicate filter
        base = int(reply.get("train_seq", -1)) + 1
        summaries = []
        for i in range(1, self.rounds + 1):
            out = self.run_round(base + i - 1)
            summaries.append(out)
            # analysis: allow(no-print) — per-round progress on the league-learner subprocess's stdout, read by the league-run parent and operators tailing the child
            print(f"league-learner {self.player_id}: round {i}/{self.rounds}"
                  f" {out}", flush=True)
        return {"player_id": self.player_id, "rounds": summaries,
                "jobs": self.jobs_done, "mints": self.mints,
                "iters": self.learner.last_iter.val}


# --------------------------------------------------------------------- fleet
def league_actor_cmd(player_id: str, coordinator: str = ""):
    """Member command for a league actor-slot fleet (``kind="actor"``).

    The smoke/capacity member: prints the standard ready line and holds
    a seat until drained (stdin close / terminate). A real distributed
    deployment swaps this build_cmd for ``rl_train --type actor`` with the
    player's plane address — the PR 12 drain semantics are identical."""
    code = (
        "import sys\n"
        "print('LEAGUE-ACTOR 127.0.0.1 0 player=%s', flush=True)\n"
        "sys.stdin.read()\n" % player_id
    )

    def build(index: int) -> List[str]:
        return [sys.executable, "-u", "-c", code]

    return build


def build_actor_fleets(player_ids: Sequence[str], actors_per_player: int = 1,
                       coordinator: str = "", min_actors: int = 1):
    """A started ``FleetSupervisor`` with one actor-slot fleet per player
    (fleet name ``actors-<player>``), plus the fleet->player map the
    :class:`~.reassign.PayoffReassigner` takes."""
    from ...fleet.supervisor import FleetSupervisor, SubprocessFleet

    supervisor = FleetSupervisor()
    fleet_players = {}
    for pid in player_ids:
        name = f"actors-{pid}"
        fleet = SubprocessFleet(
            name, "actor", league_actor_cmd(pid, coordinator),
            drain_timeout_s=1.0, min_members=min_actors)
        supervisor.add_fleet(fleet)
        fleet_players[name] = pid
        supervisor.scale_up(name, actors_per_player)
    supervisor.start()
    return supervisor, fleet_players


# -------------------------------------------------------------------- runner
class LeagueRunner:
    """The league-run parent process: coordinator + matchmaker + N learners.

    Hosts the :class:`~.service.LeagueService` (and an ``ArenaStore``)
    inside a ``CoordinatorServer`` — with ``journal_dir`` the whole control
    plane rides the PR 19 HA journal, so killing and restarting this
    process replays the league exactly. Learner subprocesses are spawned
    through ``rl_train --type league-learner`` (one per active player,
    each its own JAX process / mesh) and awaited; ``run()`` returns the
    final digest and a process return code.
    """

    def __init__(self, player_ids: Sequence[str] = DEFAULT_PLAYERS,
                 save_path: str = "", journal_dir: str = "",
                 arena_store_path: str = "", seed: int = 0,
                 lease_s: float = 30.0, job_ttl_s: float = 180.0,
                 learner_argv_extra: Optional[List[str]] = None,
                 rounds: int = 2, iters_per_round: int = 1,
                 actors_per_player: int = 0, reassign: bool = False,
                 env: Optional[dict] = None):
        self.player_ids = list(player_ids)
        self.save_path = save_path
        self.journal_dir = journal_dir
        self.arena_store_path = arena_store_path
        self.seed = int(seed)
        self.lease_s = float(lease_s)
        self.job_ttl_s = float(job_ttl_s)
        self.learner_argv_extra = list(learner_argv_extra or [])
        self.rounds = int(rounds)
        self.iters_per_round = int(iters_per_round)
        self.actors_per_player = int(actors_per_player)
        self.reassign = bool(reassign)
        self.env = dict(env) if env else None
        self.server = None
        self.ha_state = None
        self.store = None
        self.service = None
        self.supervisor = None
        self.procs: Dict[str, subprocess.Popen] = {}

    # ----------------------------------------------------------- control plane
    def start_control_plane(self, port: int = 0) -> str:
        """Coordinator + ArenaStore + LeagueService (+ HA journal). Returns
        the address learners connect to."""
        from ...arena import ArenaStore, set_arena_store
        from ...comm import Coordinator, CoordinatorServer
        from .service import LeagueService, set_league_service

        self.store = ArenaStore(path=self.arena_store_path or None)
        if self.arena_store_path:
            self.store.maybe_load()
        set_arena_store(self.store)
        self.service = LeagueService(
            league_cfg(self.player_ids), seed=self.seed,
            lease_s=self.lease_s, job_ttl_s=self.job_ttl_s)
        set_league_service(self.service)
        co = Coordinator()
        self.server = CoordinatorServer(coordinator=co, port=port)
        if self.journal_dir:
            from ...comm.ha import HAState

            self.ha_state = HAState(
                co, self.journal_dir,
                arena_store_fn=lambda: self.store,
                league_service_fn=lambda: self.service)
            self.ha_state.boot()
            self.server.attach_ha(self.ha_state)
        self.server.start()
        addr = f"127.0.0.1:{self.server.port}"
        # analysis: allow(no-print) — launcher stdout: the address line operators (and the drill) read to reach the control plane
        print(f"league-run control plane on {addr} "
              f"(journal={'on' if self.journal_dir else 'off'})", flush=True)
        return addr

    # --------------------------------------------------------------- learners
    def _learner_cmd(self, player_id: str, addr: str) -> List[str]:
        return [
            sys.executable, "-u", "-m", "distar_tpu.bin.rl_train",
            "--type", "league-learner",
            "--player-id", player_id,
            "--coordinator-addr", addr,
            "--league-rounds", str(self.rounds),
            "--league-iters-per-round", str(self.iters_per_round),
            *(["--save-path", self.save_path] if self.save_path else []),
            *self.learner_argv_extra,
        ]

    def spawn_learners(self, addr: str) -> None:
        for pid in self.player_ids:
            self.procs[pid] = subprocess.Popen(
                self._learner_cmd(pid, addr), env=self.env)
            # analysis: allow(no-print) — launcher stdout: pid lines the drill and operators use to target kills
            print(f"league-run: spawned learner {pid} "
                  f"(pid {self.procs[pid].pid})", flush=True)

    def wait_learners(self, timeout_s: float = 1800.0) -> Dict[str, int]:
        deadline = time.monotonic() + timeout_s
        codes: Dict[str, int] = {}
        for pid, proc in self.procs.items():
            remaining = max(1.0, deadline - time.monotonic())
            try:
                codes[pid] = proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                proc.kill()
                codes[pid] = -9
        return codes

    # ------------------------------------------------------------ reassigner
    def _reassign_step(self):
        from .reassign import PayoffReassigner

        if self.supervisor is None:
            return {}
        total = sum(self.supervisor.actual(n)
                    for n in self.supervisor.fleets())
        fleet_players = {n: n.split("actors-", 1)[1]
                         for n in self.supervisor.fleets()}
        reassigner = PayoffReassigner(
            self.supervisor, fleet_players, total_actors=total,
            payoff_fn=self.store.payoff_snapshot, service=self.service)
        return reassigner.step()

    # -------------------------------------------------------------------- run
    def run(self, port: int = 0, timeout_s: float = 1800.0) -> dict:
        addr = self.start_control_plane(port=port)
        if self.actors_per_player > 0:
            self.supervisor, _ = build_actor_fleets(
                self.player_ids, self.actors_per_player, coordinator=addr)
        try:
            self.spawn_learners(addr)
            codes = self.wait_learners(timeout_s=timeout_s)
            moves = self._reassign_step() if self.reassign else {}
            status = self.service.status()
            payoff = self.store.payoff_snapshot()
            off_diag = sum(
                1 for cell in payoff.get("cells", [])
                if cell.get("a") != cell.get("b")
                and cell.get("games", 0) > 0)
            digest = {
                "learner_rc": codes,
                "jobs_by_branch": status["jobs_by_branch"],
                "snapshot_mints": status["snapshot_mints"],
                "historical_players": status["historical_players"],
                "assignments_pending": status["assignments_pending"],
                "orphaned_jobs": status["orphaned_jobs"],
                "off_diagonal_pairs": off_diag,
                "matches_total": self.store.matches_total,
                "reassign_moves": moves,
            }
            ok = (all(c == 0 for c in codes.values())
                  and status["snapshot_mints"] >= 1
                  and off_diag >= 1)
            digest["ok"] = ok
            # analysis: allow(no-print) — the machine-parseable verdict line the acceptance harness greps for
            print("LEAGUE-RUN-DONE " + json.dumps(digest), flush=True)
            return digest
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        for proc in self.procs.values():
            if proc.poll() is None:
                proc.kill()
        if self.supervisor is not None:
            self.supervisor.stop()
            self.supervisor = None
        if self.arena_store_path and self.store is not None:
            self.store.save()
        if self.ha_state is not None:
            self.ha_state.final_snapshot()
            self.ha_state.stop()
            self.ha_state = None
        if self.server is not None:
            self.server.stop()
            self.server = None
        from ...arena import set_arena_store
        from .service import set_league_service

        set_arena_store(None)
        set_league_service(None)
