"""League runtime: the subsystem that RUNS a multi-learner league.

``service``   — coordinator-hosted, WAL-replayable matchmaking control
                plane (roster, PFSP jobs from the arena ledger, snapshot
                minting from checkpoint generations, assignment map).
``reassign``  — payoff-driven elastic actor rebalancing over the PR 12
                fleet supervisor.
``runner``    — the ``rl_train --type league-run`` launcher: one
                coordinator (league + arena + HA journal) plus N learner
                subprocesses, each an independent mesh.
"""
from .reassign import PayoffReassigner
from .service import (
    BRANCHES,
    LeagueService,
    get_league_service,
    set_league_service,
)

__all__ = [
    "BRANCHES",
    "LeagueService",
    "PayoffReassigner",
    "get_league_service",
    "set_league_service",
]
