"""Incremental ELO ladder (K=44) with payoff-consistency refit.

Role of the reference ELORating (reference: distar/ctools/worker/ladder/
elo.py:9-100+): incremental updates per game, plus an iterative refit that
finds ratings maximising consistency with the observed clipped payoff matrix
(the reference runs a fixed-point iteration over a discretised mmr grid; here
a simple gradient fixed-point on expected-vs-observed score, same objective).
"""
from __future__ import annotations

import math
from collections import defaultdict
from functools import partial
from typing import Dict

WIN, DRAW, LOSS = 1, 0, -1


class ELORating:
    def __init__(self, K: float = 44.0, init_elo: float = 1000.0, minimum_games: int = 0):
        self.K = K
        self.init_elo = init_elo
        self.minimum_games = minimum_games
        self.elos: Dict[str, float] = defaultdict(float)  # stored as offsets from init
        self.wins = defaultdict(partial(defaultdict, int))
        self.draws = defaultdict(partial(defaultdict, int))
        self.games = defaultdict(partial(defaultdict, int))
        self.game_count = 0

    def expected(self, p1: str, p2: str) -> float:
        return 1.0 / (1.0 + 10 ** ((self.elos[p2] - self.elos[p1]) / 400.0))

    def update(self, p1: str, p2: str, result: int) -> None:
        e = self.expected(p1, p2)
        if result == WIN:
            self.wins[p1][p2] += 1
            score = 1.0
        elif result == LOSS:
            self.wins[p2][p1] += 1
            score = 0.0
        else:
            self.draws[p1][p2] += 1
            self.draws[p2][p1] += 1
            score = 0.5
        self.games[p1][p2] += 1
        self.games[p2][p1] += 1
        self.elos[p1] += self.K * (score - e)
        self.elos[p2] -= self.K * (score - e)
        self.game_count += 1

    def ratings(self, start_from_zero: bool = True) -> Dict[str, float]:
        out = {k: v + self.init_elo for k, v in self.elos.items()}
        if start_from_zero and out:
            low = min(out.values())
            out = {k: v - low for k, v in out.items()}
        return out

    def refit(self, iterations: int = 200, lr: float = 20.0) -> Dict[str, float]:
        """Payoff-consistency refit: adjust ratings so expected scores match
        the observed (clipped) pairwise winrates over pairs with enough games."""
        players = list(self.elos.keys())
        r = {p: self.elos[p] for p in players}
        # `draws` may be absent on ladders unpickled from pre-draws journals
        draws = getattr(self, "draws", None) or defaultdict(partial(defaultdict, int))
        pairs = []
        for p1 in players:
            for p2 in players:
                if p1 != p2 and self.games[p1][p2] > self.minimum_games:
                    # draws score half — wins alone would undercount a player
                    # who converts losses into draws (50w/50d reads 0.5, not 0.75)
                    score = self.wins[p1][p2] + 0.5 * draws[p1][p2]
                    wr = score / max(self.games[p1][p2], 1)
                    pairs.append((p1, p2, min(max(wr, 0.1), 0.9)))
        if not pairs:
            return self.ratings()
        for _ in range(iterations):
            grad = defaultdict(float)
            for p1, p2, wr in pairs:
                e = 1.0 / (1.0 + 10 ** ((r[p2] - r[p1]) / 400.0))
                grad[p1] += wr - e
                grad[p2] -= wr - e
            for p in players:
                r[p] += lr * grad[p] / max(len(players) - 1, 1)
        low = min(r.values())
        return {p: v - low + self.init_elo for p, v in r.items()}

    def get_text(self) -> str:
        rows = sorted(self.ratings().items(), key=lambda kv: -kv[1])
        return "\n".join(f"{k:<40s} {v:>8.1f}" for k, v in rows)
