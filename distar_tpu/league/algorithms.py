"""Prioritized Fictitious Self-Play opponent weighting.

Same three weightings as the reference (reference: distar/ctools/worker/
league/algorithms.py:58-86): 'squared' (1-w)^2 favours opponents you lose to,
'variance' w(1-w) favours even matches, 'normal' min(0.5, 1-w).
"""
from __future__ import annotations

import numpy as np

WEIGHTINGS = {
    "squared": lambda x: (1 - x) ** 2,
    "variance": lambda x: x * (1 - x),
    "normal": lambda x: np.minimum(0.5, 1 - x),
}


def pfsp(win_rates: np.ndarray, weighting: str = "variance") -> np.ndarray:
    if weighting not in WEIGHTINGS:
        raise KeyError(f"invalid pfsp weighting: {weighting}")
    win_rates = np.asarray(win_rates, dtype=np.float64)
    assert win_rates.ndim == 1 and win_rates.shape[0] >= 1
    if win_rates.sum() < 1e-8:
        return np.full_like(win_rates, 1.0 / len(win_rates))
    w = WEIGHTINGS[weighting](win_rates)
    s = w.sum()
    if s < 1e-12:
        return np.full_like(win_rates, 1.0 / len(win_rates))
    return w / s
