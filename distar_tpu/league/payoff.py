"""Per-opponent match statistics with a 0.5 winrate prior below min games
(role of reference distar/ctools/worker/league/payoff.py)."""
from __future__ import annotations

from collections import defaultdict
from functools import partial
from typing import Dict

from .stats import WindowedMeter

DATA_KEYS = ("winrate", "game_steps", "game_iters", "game_duration")


def _stat_entry(warm_up_size: int) -> Dict[str, WindowedMeter]:
    return {k: WindowedMeter(warm_up_size) for k in DATA_KEYS}


class Payoff:
    def __init__(self, decay: float = 0.999, warm_up_size: int = 1000, min_win_rate_games: int = 1000):
        self._decay = decay
        self._warm_up_size = warm_up_size
        self._min_win_rate_games = min_win_rate_games
        # partial over a module-level fn keeps the defaultdict picklable
        # (league resume snapshots pickle whole players)
        self._record: Dict[str, Dict[str, WindowedMeter]] = defaultdict(
            partial(_stat_entry, warm_up_size)
        )

    def update(self, opponent_id: str, stat_info: Dict[str, float]) -> None:
        for k in DATA_KEYS:
            if k in stat_info:
                self._record[opponent_id][k].update(stat_info[k])

    def win_rate_opponent(self, opponent_id: str, use_prior: bool = True) -> float:
        meter = self._record[opponent_id]["winrate"]
        if use_prior and meter.count < self._min_win_rate_games:
            return 0.5
        return meter.val

    @property
    def pfsp_winrate_info_dict(self) -> Dict[str, float]:
        return {p: self.win_rate_opponent(p) for p in self._record}

    @property
    def stat_info_record(self):
        return self._record

    @property
    def game_count(self) -> Dict[str, int]:
        return {p: v["winrate"].count for p, v in self._record.items()}

    def get_text(self) -> str:
        rows = []
        for opp, stats in sorted(self._record.items()):
            rows.append(
                "{:<40s} ".format(opp)
                + " ".join(f"{stats[k].val:>10.3f}" for k in DATA_KEYS)
                + f" {stats['winrate'].count:>8d}"
            )
        header = "{:<40s} ".format("opponent") + " ".join(f"{k:>10s}" for k in DATA_KEYS) + f" {'games':>8s}"
        return "\n".join([header] + rows)
