"""Per-opponent match statistics with a 0.5 winrate prior below min games
(role of reference distar/ctools/worker/league/payoff.py)."""
from __future__ import annotations

from collections import defaultdict
from functools import partial
from typing import Dict

from .stats import WindowedMeter

DATA_KEYS = ("winrate", "game_steps", "game_iters", "game_duration")


def _stat_entry(warm_up_size: int) -> Dict[str, WindowedMeter]:
    return {k: WindowedMeter(warm_up_size) for k in DATA_KEYS}


def _decay_entry() -> Dict[str, float]:
    return {"wins": 0.0, "draws": 0.0, "losses": 0.0, "games": 0.0}


class Payoff:
    def __init__(self, decay: float = 0.999, warm_up_size: int = 1000, min_win_rate_games: int = 1000):
        self._decay = decay
        self._warm_up_size = warm_up_size
        self._min_win_rate_games = min_win_rate_games
        # partial over a module-level fn keeps the defaultdict picklable
        # (league resume snapshots pickle whole players)
        self._record: Dict[str, Dict[str, WindowedMeter]] = defaultdict(
            partial(_stat_entry, warm_up_size)
        )
        # reference payoff semantics: exponentially decayed per-opponent
        # result counters (multiply all by decay, then increment the bucket
        # for this game) — recency-weighted without a fixed window
        self._decayed: Dict[str, Dict[str, float]] = defaultdict(_decay_entry)

    def update(self, opponent_id: str, stat_info: Dict[str, float]) -> None:
        for k in DATA_KEYS:
            if k in stat_info:
                self._record[opponent_id][k].update(stat_info[k])
        if "winrate" in stat_info:
            rec = getattr(self, "_decayed", None)
            if rec is None:  # backfill payoffs unpickled from pre-decay journals
                rec = self._decayed = defaultdict(_decay_entry)
            entry = rec[opponent_id]
            for k in entry:
                entry[k] *= self._decay
            entry["games"] += 1.0
            score = float(stat_info["winrate"])
            if score >= 1.0:
                entry["wins"] += 1.0
            elif score <= 0.0:
                entry["losses"] += 1.0
            else:
                entry["draws"] += 1.0

    def decayed_win_rate(self, opponent_id: str) -> float:
        """Recency-weighted win rate (draws score half); 0.5 with no games."""
        rec = getattr(self, "_decayed", None) or {}
        entry = rec.get(opponent_id) if hasattr(rec, "get") else None
        if not entry or entry["games"] <= 0.0:
            return 0.5
        return (entry["wins"] + 0.5 * entry["draws"]) / entry["games"]

    def win_rate_opponent(self, opponent_id: str, use_prior: bool = True) -> float:
        meter = self._record[opponent_id]["winrate"]
        if use_prior and meter.count < self._min_win_rate_games:
            return 0.5
        return meter.val

    @property
    def pfsp_winrate_info_dict(self) -> Dict[str, float]:
        return {p: self.win_rate_opponent(p) for p in self._record}

    @property
    def stat_info_record(self):
        return self._record

    @property
    def game_count(self) -> Dict[str, int]:
        return {p: v["winrate"].count for p, v in self._record.items()}

    def get_text(self) -> str:
        rows = []
        for opp, stats in sorted(self._record.items()):
            rows.append(
                "{:<40s} ".format(opp)
                + " ".join(f"{stats[k].val:>10.3f}" for k in DATA_KEYS)
                + f" {stats['winrate'].count:>8d}"
            )
        header = "{:<40s} ".format("opponent") + " ".join(f"{k:>10s}" for k in DATA_KEYS) + f" {'games':>8s}"
        return "\n".join([header] + rows)
